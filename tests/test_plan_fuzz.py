"""Plan-space fuzzer (tools/plan_fuzz): deterministic generation, the
three-way differential (megakernel / vmap fusion / packed-numpy
oracle) clean on a seeded slice, and the committed tests/plan_corpus/
entries replaying clean. The heavyweight 300-case sweep runs in the
tools/check.sh plan-fuzz gate lane; tier-1 pins the machinery."""

import json
import os

import pytest

from tools.plan_fuzz import (
    DEFAULT_CORPUS, Harness, case_bytes, gen_case, render_query,
)

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def test_generation_is_deterministic():
    import hashlib
    def digest(seed, n):
        d = hashlib.sha256()
        for i in range(n):
            d.update(case_bytes(gen_case(seed, i)))
        return d.hexdigest()
    assert digest(0, 20) == digest(0, 20)
    assert digest(0, 20) != digest(1, 20)
    # (seed, index) child streams: a case is independent of its
    # position in the run.
    assert case_bytes(gen_case(3, 7)) == case_bytes(gen_case(3, 7))


def test_generator_covers_the_ir_surface():
    """Over a modest window the forest must exercise every node kind
    the lowering handles: both modes, all four folds, Not, cmp,
    between, the shared-operand flood, and absent rows."""
    kinds, modes = set(), set()
    shared_flood = 0

    def walk(t):
        kinds.add(t[0])
        if t[0] in ("and", "or", "xor", "diff", "not"):
            for s in t[1:]:
                walk(s)

    for i in range(60):
        case = gen_case(0, i)
        for mode, tree in case:
            modes.add(mode)
            walk(tree)
        # The Tanimoto tail: >=2 probes ANDing the SAME f row against
        # candidates (the shared-operand dedup the lowering must do).
        probes = [t for m, t in case
                  if t[0] == "and" and len(t) == 3
                  and t[1][0] == "row" and t[2][0] == "row"
                  and t[1][1] == "f" and t[2][1] == "f"]
        q_rows = [t[1][2] for t in probes]
        if any(q_rows.count(q) >= 2 for q in q_rows):
            shared_flood += 1
    assert modes == {"count", "rows"}
    for want in ("row", "cmp", "between", "not", "and", "or", "xor",
                 "diff"):
        assert want in kinds, (want, kinds)
    assert shared_flood > 0, "Tanimoto shared-operand flood never drawn"


def test_render_is_valid_pql():
    q = render_query("count", ["and", ["row", "f", 1],
                               ["cmp", "v", "gte", -3]])
    assert q == "Count(Intersect(Row(f=1), Row(v >= -3)))"
    q2 = render_query("rows", ["between", "v", -100, 500])
    assert q2 == "Row(-100 < v < 500)"
    q3 = render_query("count", ["not", ["row", "g", 2]])
    assert q3 == "Count(Not(Row(g=2)))"


def test_seeded_slice_differential_clean():
    """A seeded slice of the real fuzz loop: three-way bit-exact, all
    captured plans verified, every applied mutation rejected."""
    h = Harness(data_seed=2)
    try:
        for i in range(4):
            problems = h.check_case(gen_case(2, i), mutate_seed=2)
            assert not problems, (i, problems)
    finally:
        h.close()


def test_committed_corpus_replays_clean():
    """The smaller committed entries replay in tier-1 (the full
    corpus incl. the ~100-query BSI table runs in the check.sh
    lane)."""
    names = sorted(n for n in os.listdir(DEFAULT_CORPUS)
                   if n.endswith(".json"))
    assert names, "tests/plan_corpus must ship seed entries"
    light = [n for n in names
             if not n.startswith("bsi-boundaries")][:4]
    h = Harness(data_seed=0)
    try:
        for name in light:
            with open(os.path.join(DEFAULT_CORPUS, name)) as f:
                doc = json.load(f)
            assert doc.get("dataSeed") == 0
            problems = h.check_case(doc["queries"], mutate_seed=0)
            assert not problems, (name, problems)
    finally:
        h.close()


def test_corpus_names_pin_content():
    """Entry names carry the sha256[:12] of the exact file bytes (the
    append-only triage contract: regenerated-but-different files are
    visible in review)."""
    import hashlib
    for name in os.listdir(DEFAULT_CORPUS):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(DEFAULT_CORPUS, name), "rb") as f:
            blob = f.read()
        digest = hashlib.sha256(blob).hexdigest()[:12]
        assert name.rsplit("-", 1)[1] == f"{digest}.json", \
            f"{name}: content drifted from its digest"


def test_oracle_matches_direct_execution():
    """The packed-numpy oracle against execute_full directly — the
    leg-(c) semantics pinned without the batch machinery."""
    h = Harness(data_seed=1)
    try:
        trees = [
            ["count", ["row", "f", 1]],
            ["count", ["not", ["row", "f", 1]]],
            ["count", ["cmp", "v", "lte", 300]],
            ["count", ["cmp", "w", "eq", 3]],
            ["count", ["between", "z", -4096, 4096]],
            ["rows", ["diff", ["row", "f", 2], ["row", "g", 2]]],
        ]
        for mode, tree in trees:
            q = render_query(mode, tree)
            got = h.executor.execute_full("pf", q)["results"][0]
            exp = h.oracle.expected(mode, tree)
            assert got == exp, (q, got, exp)
    finally:
        h.close()


def test_harness_dataset_has_depth_diversity():
    """The three BSI fields land at distinct bit-depths (boundary
    depths are the point of the sweep)."""
    h = Harness(data_seed=0)
    try:
        idx = h.holder.index("pf")
        depths = {idx.field(f).bsi_groups[f].bit_depth
                  for f in ("v", "w", "z")}
    finally:
        h.close()
    assert len(depths) == 3, depths
