"""Multi-node cluster tests — the analog of the reference's
test.MustRunCluster (test/pilosa.go:243) and server/cluster_test.go: N real
servers with real HTTP on localhost, static topology (reference static
mode, cluster.go:1939)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.parallel.cluster import Cluster, Node, STATE_NORMAL
from pilosa_tpu.parallel import hashing
from pilosa_tpu.server import API, serve
from pilosa_tpu.utils.stats import MemStatsClient


class ClusterNode:
    def __init__(self, tmp_path, name, server_ssl=None, client_ssl=None):
        self.holder = Holder(str(tmp_path / name))
        self.holder.open()
        self.api = None
        self.server = None
        self.uri = None
        self.server_ssl = server_ssl
        self.client_ssl = client_ssl

    def start(self, peers, replica_n):
        # Bind first to learn the port, then build the cluster identity.
        self.api = API(self.holder, stats=MemStatsClient())
        self.server = serve(self.api, "localhost", 0, background=True,
                            ssl_context=self.server_ssl)
        scheme = "https" if self.server_ssl is not None else "http"
        self.uri = f"{scheme}://localhost:{self.server.server_address[1]}"
        return self.uri

    def attach_cluster(self, uris, replica_n, node_id=None):
        cluster = Cluster(Node(node_id or self.uri, self.uri),
                          replica_n=replica_n)
        for uri in uris:
            if uri != self.uri:
                cluster.add_node(Node(uri, uri))
        cluster.set_state(STATE_NORMAL)
        # Rebuild API with the cluster attached (same holder/server).
        api = API(self.holder, cluster=cluster, stats=MemStatsClient(),
                  client_ssl_context=self.client_ssl)
        self.api = api
        self.server.RequestHandlerClass.api = api
        self.cluster = cluster

    def stop(self):
        if self.api is not None and self.api.broadcaster is not None:
            self.api.broadcaster.stop()
        self.server.shutdown()
        self.server.server_close()
        self.holder.close()

    def stop_server_only(self):
        """Sever the listener but keep holder/cluster (a briefly-down
        node that will come back on the same port)."""
        self.server.shutdown()
        self.server.server_close()

    def restart_server(self, port):
        self.server = serve(self.api, "localhost", port, background=True)


def run_cluster(tmp_path, n, replica_n=1, server_ssl=None, client_ssl=None):
    nodes = [ClusterNode(tmp_path, f"n{i}", server_ssl=server_ssl,
                         client_ssl=client_ssl) for i in range(n)]
    uris = [nd.start(None, replica_n) for nd in nodes]
    for nd in nodes:
        nd.attach_cluster(uris, replica_n)
    return nodes


def req(uri, method, path, body=None, raw=False, ssl_ctx=None):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(uri + path, data=data, method=method)
    with urllib.request.urlopen(r, timeout=30, context=ssl_ctx) as resp:
        payload = resp.read()
        return payload if raw else json.loads(payload or b"{}")


def test_hashing_properties():
    # jump hash: stable, balanced-ish, minimal movement
    assert hashing.jump_hash(12345, 1) == 0
    a = [hashing.jump_hash(k, 5) for k in range(1000)]
    assert set(a) == {0, 1, 2, 3, 4}
    moved = sum(1 for k in range(1000)
                if hashing.jump_hash(k, 5) != hashing.jump_hash(k, 6))
    assert moved < 1000 * 0.4  # only ~1/6 should move
    # replica chain wraps the ring without duplicates
    nodes = hashing.partition_nodes(17, 4, 3)
    assert len(nodes) == len(set(nodes)) == 3


def test_cluster_query_write_fanout(tmp_path):
    nodes = run_cluster(tmp_path, 3)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ci", {"options": {}})
        req(base, "POST", "/index/ci/field/f", {"options": {}})
        # schema replicated to all nodes
        for nd in nodes:
            schema = req(nd.uri, "GET", "/schema")
            assert schema["indexes"][0]["name"] == "ci"

        # import bits across 6 shards via node 0; bits land on owners
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/ci/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        placed = [len(nd.holder.index("ci").available_shards())
                  for nd in nodes]
        assert sum(p > 0 for p in placed) > 1  # actually distributed

        # query from ANY node sees all bits
        for nd in nodes:
            res = req(nd.uri, "POST", "/index/ci/query", b"Count(Row(f=1))")
            assert res["results"] == [6], nd.uri
        res = req(base, "POST", "/index/ci/query", b"Row(f=1)")
        assert res["results"][0]["columns"] == cols

        # single Set routes to the owner and is visible cluster-wide
        res = req(nodes[1].uri, "POST", "/index/ci/query", b"Set(42, f=9)")
        assert res["results"] == [True]
        for nd in nodes:
            res = req(nd.uri, "POST", "/index/ci/query", b"Count(Row(f=9))")
            assert res["results"] == [1]

        # TopN across nodes
        res = req(base, "POST", "/index/ci/query", b"TopN(f, n=2)")
        assert res["results"][0][0] == {"id": 1, "count": 6}
    finally:
        for nd in nodes:
            nd.stop()


def test_cluster_profile_merges_node_fragments(tmp_path):
    """?profile=true on a cross-node query: the flag propagates to
    remote legs and the coordinator merges per-node profile fragments
    into one tree (profile.nodes keyed by node id)."""
    nodes = run_cluster(tmp_path, 3)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/cp", {"options": {}})
        req(base, "POST", "/index/cp/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/cp/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        res = req(base, "POST", "/index/cp/query?profile=true",
                  b"Count(Row(f=1))")
        assert res["results"] == [6]
        prof = res["profile"]
        assert prof["deviceSampled"] is True
        # The coordinator's own leg fills the root ops; every remote
        # node that served shards hangs its fragment off nodes[id].
        frags = prof.get("nodes", {})
        remote_ids = {nd.uri for nd in nodes[1:]}
        served_remotely = {nid for nid in frags if nid in remote_ids}
        assert prof["ops"] or served_remotely, prof
        for frag in frags.values():
            assert frag["deviceSampled"] is True
            assert frag["ops"], frag
            evals = [c for op in frag["ops"]
                     for c in op.get("children", [])
                     if c["name"].startswith("eval:")]
            assert any("deviceS" in e for e in evals), frag
        # An unprofiled cluster query carries no profile.
        res = req(base, "POST", "/index/cp/query", b"Count(Row(f=1))")
        assert "profile" not in res
    finally:
        for nd in nodes:
            nd.stop()


def _self_signed_cert(tmp_path):
    """PEM (cert_path, key_path) for CN/SAN localhost — EC P-256 (RSA
    keygen is seconds on this 1-vCPU box)."""
    import datetime
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
                critical=False)
            .sign(key, hashes.SHA256()))
    cert_path = tmp_path / "node.crt"
    key_path = tmp_path / "node.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    return str(cert_path), str(key_path)


def test_cluster_over_tls(tmp_path):
    """3-node cluster where client AND intra-cluster traffic ride HTTPS
    (VERDICT r3 missing #3; reference serves both over its TLS listener,
    server/server.go:244). Certificates verify against the self-signed
    cert as CA — no skip-verify — so this also proves real verification,
    and a plaintext client is rejected."""
    # Cert generation needs the cryptography wheel, which this image
    # doesn't carry — skip (not fail) where it's absent.
    pytest.importorskip("cryptography")
    from pilosa_tpu.utils.config import Config

    cert, key = _self_signed_cert(tmp_path)
    cfg = Config(tls_certificate=cert, tls_key=key,
                 tls_ca_certificate=cert)
    cfg.validate()
    assert cfg.scheme == "https"
    nodes = run_cluster(tmp_path, 3,
                        server_ssl=cfg.server_ssl_context(),
                        client_ssl=cfg.client_ssl_context())
    ctx = cfg.client_ssl_context()  # external client context
    try:
        base = nodes[0].uri
        assert base.startswith("https://")
        req(base, "POST", "/index/ti", {"options": {}}, ssl_ctx=ctx)
        req(base, "POST", "/index/ti/field/f", {"options": {}},
            ssl_ctx=ctx)
        for nd in nodes:  # schema broadcast crossed TLS node links
            schema = req(nd.uri, "GET", "/schema", ssl_ctx=ctx)
            assert schema["indexes"][0]["name"] == "ti"

        # import fans out to owners over TLS; queries gather over TLS
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/ti/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols}, ssl_ctx=ctx)
        placed = [len(nd.holder.index("ti").available_shards())
                  for nd in nodes]
        assert sum(p > 0 for p in placed) > 1  # actually distributed
        for nd in nodes:
            res = req(nd.uri, "POST", "/index/ti/query",
                      b"Count(Row(f=1))", ssl_ctx=ctx)
            assert res["results"] == [6], nd.uri

        # an unverified client must be refused by the TLS handshake
        import ssl as ssl_mod
        with pytest.raises((ssl_mod.SSLError, urllib.error.URLError)):
            req(base, "GET", "/schema")  # default context: unknown CA
    finally:
        for nd in nodes:
            nd.stop()


def test_tls_config_validation():
    from pilosa_tpu.utils.config import Config

    with pytest.raises(ValueError, match="set together"):
        Config(tls_certificate="x.pem").validate()
    with pytest.raises(ValueError, match="set together"):
        Config(tls_key="x.pem").validate()
    cfg = Config(tls_skip_verify=True)
    assert cfg.scheme == "http"  # skip-verify alone doesn't enable TLS
    ctx = cfg.client_ssl_context()
    assert ctx is not None and not ctx.check_hostname


def _wait(pred, timeout=30.0, every=0.1):
    import time
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(every)
    return False


def test_seed_join_triggers_resize(tmp_path):
    """A 4th node booted with ONLY a seed URI joins the cluster and
    triggers the rebalance with no operator call (VERDICT r3 missing #4;
    reference: memberlist seed join → join event → coordinator resize,
    gossip/gossip.go:364-420, cluster.go:1676-1715)."""
    nodes = run_cluster(tmp_path, 3)
    n4 = None
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/si", {"options": {}})
        req(base, "POST", "/index/si/field/f", {"options": {}})
        n_shards = 32  # enough that every node owns some w.h.p.
        cols = [s * SHARD_WIDTH + 3 for s in range(n_shards)]
        req(base, "POST", "/index/si/field/f/import",
            {"rowIDs": [1] * n_shards, "columnIDs": cols})

        # Boot node 4 knowing nothing but one seed.
        n4 = ClusterNode(tmp_path, "n3")
        n4.start(None, 1)
        n4.attach_cluster([n4.uri], 1)
        status = n4.api.join_via_seeds([nodes[0].uri])
        assert len(status["nodes"]) == 4

        allnodes = nodes + [n4]
        # Every node converges to 4 members and NORMAL (the resize job
        # pulls fragments, then resize-complete rides the retried
        # async broadcast).
        assert _wait(lambda: all(
            len(nd.cluster.nodes()) == 4
            and nd.cluster.state == STATE_NORMAL for nd in allnodes)), \
            [(nd.cluster.state, len(nd.cluster.nodes()))
             for nd in allnodes]
        # After the rebalance every owner HOLDS its shards (the joiner
        # pulled anything newly placed on it), and every node still
        # answers the full count.
        by_id = {nd.cluster.local.id: nd for nd in allnodes}

        def owners_hold():
            for s in range(n_shards):
                for owner in nodes[0].cluster.shard_nodes("si", s):
                    held = by_id[owner.id].holder.index(
                        "si").available_shards()
                    if s not in held:
                        return False
            return True

        assert _wait(owners_hold)
        for nd in allnodes:
            res = req(nd.uri, "POST", "/index/si/query",
                      b"Count(Row(f=1))")
            assert res["results"] == [n_shards], nd.uri
        # Rejoin is idempotent: no new resize, still 4 nodes, NORMAL.
        gen0 = nodes[0].cluster.resize_gen
        n4.api.join_via_seeds([nodes[0].uri])
        assert nodes[0].cluster.resize_gen == gen0
        assert nodes[0].cluster.state == STATE_NORMAL
        assert len(nodes[0].cluster.nodes()) == 4
    finally:
        for nd in nodes + ([n4] if n4 is not None else []):
            nd.stop()


def test_rejoin_with_new_uri_updates_peers(tmp_path):
    """A member with a stable node id that restarts on a DIFFERENT
    address rejoins as the same member: no ghost entry, no resize, and
    every peer learns the new URI (code-review r4: id==URI deployments
    can't express this; the CLI uses the holder's persisted .id for
    seed-joined nodes)."""
    nodes = run_cluster(tmp_path, 2)
    n3 = None
    try:
        n3 = ClusterNode(tmp_path, "n2")
        n3.start(None, 1)
        n3.attach_cluster([n3.uri], 1, node_id="stable-n3")
        n3.api.join_via_seeds([nodes[0].uri])
        allnodes = nodes + [n3]
        assert _wait(lambda: all(
            len(nd.cluster.nodes()) == 3
            and nd.cluster.state == STATE_NORMAL for nd in allnodes))

        # Restart the listener on a new port, same identity.
        n3.stop_server_only()
        n3.server = serve(n3.api, "localhost", 0, background=True)
        new_uri = f"http://localhost:{n3.server.server_address[1]}"
        n3.cluster.local.uri = new_uri
        n3.uri = new_uri
        gen0 = nodes[0].cluster.resize_gen
        status = n3.api.join_via_seeds([nodes[0].uri])
        assert len(status["nodes"]) == 3  # no ghost member
        assert nodes[0].cluster.resize_gen == gen0  # no resize
        # Every peer converges on the new URI for the stable id.
        assert _wait(lambda: all(
            any(n.id == "stable-n3" and n.uri == new_uri
                for n in nd.cluster.nodes())
            for nd in nodes))
    finally:
        for nd in nodes + ([n3] if n3 is not None else []):
            nd.stop()


def test_seed_join_prunes_stale_members(tmp_path):
    """A joiner carrying a stale persisted topology (a ghost member
    removed while it was down) adopts the seed's COMPLETE view: the
    ghost is dropped, not resurrected."""
    nodes = run_cluster(tmp_path, 2)
    n3 = None
    try:
        n3 = ClusterNode(tmp_path, "n2")
        n3.start(None, 1)
        n3.attach_cluster([n3.uri], 1, node_id="stable-g")
        n3.cluster.add_node(Node("ghost", "http://localhost:1"))
        n3.api.join_via_seeds([nodes[0].uri])
        allnodes = nodes + [n3]
        assert _wait(lambda: all(
            sorted(n.id for n in nd.cluster.nodes())
            == sorted([nodes[0].cluster.local.id,
                       nodes[1].cluster.local.id, "stable-g"])
            for nd in allnodes)), \
            [[n.id for n in nd.cluster.nodes()] for nd in allnodes]
        assert _wait(lambda: all(nd.cluster.state == STATE_NORMAL
                                 for nd in allnodes))
    finally:
        for nd in nodes + ([n3] if n3 is not None else []):
            nd.stop()


def test_async_broadcast_retries_briefly_down_peer(tmp_path):
    """A cluster message queued while the peer is down is delivered when
    it returns (VERDICT r3 missing #4: the reference's gossip layer
    retransmits async broadcasts, broadcast.go SendAsync)."""
    from pilosa_tpu.parallel.broadcast import AsyncBroadcaster

    nd = ClusterNode(tmp_path, "p0")
    nd.start(None, 1)
    nd.attach_cluster([nd.uri], 1)
    port = nd.server.server_address[1]
    bc = AsyncBroadcaster(ttl=60.0)
    try:
        nd.stop_server_only()
        bc.send(nd.uri, {"type": "set-coordinator",
                         "nodeID": nd.cluster.local.id})
        import time
        time.sleep(1.2)  # a delivery attempt fails while the peer is down
        assert bc.sent == 0
        nd.restart_server(port)
        assert bc.flush(timeout=20.0)
        assert bc.sent == 1 and bc.expired == 0
        # The message was applied, not just acknowledged.
        assert nd.cluster.local.is_coordinator
    finally:
        bc.stop()
        nd.stop()


def test_async_broadcast_expires_dead_peer():
    """Messages to a never-returning peer drop after the TTL instead of
    queueing forever."""
    from pilosa_tpu.parallel.broadcast import AsyncBroadcaster

    bc = AsyncBroadcaster(ttl=1.5)
    try:
        bc.send("http://localhost:1", {"type": "x"})  # port 1: refused
        assert bc.flush(timeout=20.0)
        assert bc.expired == 1 and bc.sent == 0
    finally:
        bc.stop()


def test_cluster_replica_failover(tmp_path):
    nodes = run_cluster(tmp_path, 3, replica_n=2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ci", {"options": {}})
        req(base, "POST", "/index/ci/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 7 for s in range(8)]
        req(base, "POST", "/index/ci/field/f/import",
            {"rowIDs": [1] * 8, "columnIDs": cols})
        res = req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert res["results"] == [8]

        # kill node 2; replicas on the remaining nodes must answer
        nodes[2].stop()
        res = req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert res["results"] == [8]
    finally:
        for nd in nodes[:2]:
            nd.stop()


def test_anti_entropy_heals_lagging_replica(tmp_path):
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ci", {"options": {}})
        req(base, "POST", "/index/ci/field/f", {"options": {}})
        # write only into node 0's holder directly (simulating a replica
        # that missed writes, like the paused node in the reference's
        # pumba clustertests)
        nodes[0].holder.index("ci").field("f").import_bits(
            np.array([1, 1], np.uint64), np.array([5, 6], np.uint64))
        assert nodes[1].holder.index("ci").field("f").available_shards() == []
        # one anti-entropy pass from node 0 pushes the missing fragment
        stats = req(base, "POST", "/internal/sync")
        assert stats["pushed"] > 0
        frag = nodes[1].holder.index("ci").field("f").view().fragment(0)
        assert frag is not None and frag.bit(1, 5) and frag.bit(1, 6)
    finally:
        for nd in nodes:
            nd.stop()


def test_anti_entropy_syncs_attrs(tmp_path):
    """Attr stores reconcile by block checksums during anti-entropy
    (reference holderSyncer.syncIndex/syncField, holder.go:730-824)."""
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ai", {"options": {}})
        req(base, "POST", "/index/ai/field/f", {"options": {}})
        # Write attrs only into node 0's local stores (a replica that
        # missed the broadcast while down).
        nodes[0].holder.index("ai").column_attr_store.set(
            7, {"city": "spokane"})
        nodes[0].holder.index("ai").field("f").row_attr_store.set(
            3, {"label": "x"})
        assert nodes[1].holder.index("ai").column_attr_store.get(7) == {}
        stats = req(base, "POST", "/internal/sync")
        assert stats["attrs_pushed"] > 0  # node 0 pushed its blocks
        assert nodes[1].holder.index("ai").column_attr_store.get(7) == \
            {"city": "spokane"}
        assert nodes[1].holder.index("ai").field("f").row_attr_store.get(
            3) == {"label": "x"}
        # And the reverse direction: node 1 pulls node-0-only attrs when
        # IT runs the sync pass.
        nodes[0].holder.index("ai").column_attr_store.set(8, {"n": 1})
        req(nodes[1].uri, "POST", "/internal/sync")
        assert nodes[1].holder.index("ai").column_attr_store.get(8) == \
            {"n": 1}
    finally:
        for nd in nodes:
            nd.stop()


def test_resize_pull_on_join(tmp_path):
    # start single node with data, then grow to 2 and run resize
    nodes = run_cluster(tmp_path, 1)
    base = nodes[0].uri
    req(base, "POST", "/index/ci", {"options": {}})
    req(base, "POST", "/index/ci/field/f", {"options": {}})
    # Enough shards that the newcomer owns at least one with
    # overwhelming probability under any port-derived node ids; the
    # assertions below still hold exactly if it happens to own none.
    n_shards = 16
    cols = [s * SHARD_WIDTH for s in range(n_shards)]
    req(base, "POST", "/index/ci/field/f/import",
        {"rowIDs": [1] * n_shards, "columnIDs": cols})

    newcomer = ClusterNode(tmp_path, "n9")
    newcomer.start(None, 1)
    try:
        # both sides learn the new topology
        req(base, "POST", "/internal/join",
            {"id": newcomer.uri, "uri": newcomer.uri})
        newcomer.attach_cluster([nodes[0].uri, newcomer.uri], 1)
        # newcomer pulls what it now owns
        req(newcomer.uri, "POST", "/cluster/resize/run")
        owned = [s for s in range(n_shards)
                 if newcomer.cluster.owns_shard("ci", s)]
        # `fetched` is indeterminate: the join-triggered background job
        # may have already pulled some fragments. Holdings are the
        # contract.
        assert newcomer.holder.index("ci").available_shards() == owned
        # cluster-wide query still complete from either node
        for uri in (base, newcomer.uri):
            r = req(uri, "POST", "/index/ci/query", b"Count(Row(f=1))")
            assert r["results"] == [n_shards]
    finally:
        newcomer.stop()
        nodes[0].stop()


def test_query_during_resize_window_no_undercount(tmp_path):
    """Queries issued WHILE the resize pull is in flight must not
    undercount: during RESIZING reads route via the pre-change placement
    (old owners still hold the data), and the new placement takes over
    only after every node's pull completes (reference holds the cluster
    in RESIZING and gates API methods on state, cluster.go:44-48,
    api.go:94)."""
    import threading
    import time

    nodes = run_cluster(tmp_path, 1)
    base = nodes[0].uri
    req(base, "POST", "/index/rz", {"options": {}})
    req(base, "POST", "/index/rz/field/f", {"options": {}})
    cols = [s * SHARD_WIDTH for s in range(6)]
    req(base, "POST", "/index/rz/field/f/import",
        {"rowIDs": [1] * 6, "columnIDs": cols})

    newcomer = ClusterNode(tmp_path, "n9")
    newcomer.start(None, 1)
    newcomer.attach_cluster([nodes[0].uri, newcomer.uri], 1)
    try:
        # Block the newcomer's pull so the resize window stays open.
        release = threading.Event()
        pulled = threading.Event()
        orig_pull = newcomer.api.resize_puller.pull_owned

        def slow_pull():
            release.wait(timeout=30)
            n = orig_pull()
            pulled.set()
            return n

        newcomer.api.resize_puller.pull_owned = slow_pull

        req(base, "POST", "/internal/join",
            {"id": newcomer.uri, "uri": newcomer.uri})
        # The window is open: base is RESIZING, newcomer owns shards it
        # has not pulled yet.
        assert req(base, "GET", "/status")["state"] == "RESIZING"
        assert any(newcomer.cluster.owns_shard("rz", s) for s in range(6))
        assert newcomer.holder.index("rz") is None or \
            newcomer.holder.index("rz").available_shards() == []
        # Queries from EITHER node during the window see every bit.
        for uri in (base, newcomer.uri):
            r = req(uri, "POST", "/index/rz/query", b"Count(Row(f=1))")
            assert r["results"] == [6], uri
        # Writes during the window are not lost either side of the move.
        req(base, "POST", "/index/rz/query", b"Set(99, f=1)")
        r = req(base, "POST", "/index/rz/query", b"Count(Row(f=1))")
        assert r["results"] == [7]

        # Close the window; the job finishes and placement flips.
        release.set()
        assert pulled.wait(timeout=30)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = {req(u, "GET", "/status")["state"]
                      for u in (base, newcomer.uri)}
            if states == {"NORMAL"}:
                break
            time.sleep(0.05)
        assert states == {"NORMAL"}
        owned = [s for s in range(6) if newcomer.cluster.owns_shard("rz", s)]
        held = newcomer.holder.index("rz").available_shards()
        assert set(owned) <= set(held)
        for uri in (base, newcomer.uri):
            r = req(uri, "POST", "/index/rz/query", b"Count(Row(f=1))")
            assert r["results"] == [7], uri
    finally:
        newcomer.stop()
        nodes[0].stop()


def test_failed_pull_leaves_cluster_resizing(tmp_path):
    """A node that cannot complete its pull keeps the cluster RESIZING:
    reads keep the safe pre-change placement until an operator aborts
    (reference keeps the cluster in RESIZING while the job is live,
    cluster.go:1458-1530)."""
    import time

    nodes = run_cluster(tmp_path, 1)
    base = nodes[0].uri
    req(base, "POST", "/index/fz", {"options": {}})
    req(base, "POST", "/index/fz/field/f", {"options": {}})
    cols = [s * SHARD_WIDTH for s in range(4)]
    req(base, "POST", "/index/fz/field/f/import",
        {"rowIDs": [1] * 4, "columnIDs": cols})

    newcomer = ClusterNode(tmp_path, "n9")
    newcomer.start(None, 1)
    newcomer.attach_cluster([nodes[0].uri, newcomer.uri], 1)
    try:
        import threading

        def broken_pull():
            raise RuntimeError("disk full")

        newcomer.api.resize_puller.pull_owned = broken_pull
        # The deterministic completion signal: the job's failure handler
        # logs "stays RESIZING". Wrap the coordinator's logger so the
        # test waits for the handler itself, not a timing guess.
        handled = threading.Event()
        orig_printf = nodes[0].api.logger.printf

        def recording_printf(fmt, *args):
            if "stays" in fmt and "RESIZING" in fmt:
                handled.set()
            return orig_printf(fmt, *args)

        nodes[0].api.logger.printf = recording_printf
        req(base, "POST", "/internal/join",
            {"id": newcomer.uri, "uri": newcomer.uri})
        assert handled.wait(timeout=15)
        # The job's failure handler ran; the cluster STAYS RESIZING and
        # reads stay complete via the pre-change placement.
        assert req(base, "GET", "/status")["state"] == "RESIZING"
        for uri in (base, newcomer.uri):
            r = req(uri, "POST", "/index/fz/query", b"Count(Row(f=1))")
            assert r["results"] == [4], uri
        # Operator abort adopts the new placement everywhere.
        res = req(base, "POST", "/cluster/resize/abort")
        assert res["aborted"] is True
        assert req(newcomer.uri, "GET", "/status")["state"] == "NORMAL"
    finally:
        newcomer.stop()
        nodes[0].stop()


def test_overlapping_resizes_finalize_only_latest(tmp_path):
    """A resize job superseded by a newer topology change must NOT adopt
    the new placement when it finishes first; only the newest job's
    completion ends RESIZING (generation guard + membership-tagged
    resize-complete)."""
    import threading
    import time

    nodes = run_cluster(tmp_path, 1)
    base = nodes[0].uri
    req(base, "POST", "/index/ov", {"options": {}})
    req(base, "POST", "/index/ov/field/f", {"options": {}})
    cols = [s * SHARD_WIDTH for s in range(6)]
    req(base, "POST", "/index/ov/field/f/import",
        {"rowIDs": [1] * 6, "columnIDs": cols})

    n1 = ClusterNode(tmp_path, "na")
    n1.start(None, 1)
    n1.attach_cluster([nodes[0].uri, n1.uri], 1)
    n2 = ClusterNode(tmp_path, "nb")
    n2.start(None, 1)
    try:
        # First join: n1's pull blocks until released.
        release1 = threading.Event()
        orig1 = n1.api.resize_puller.pull_owned

        def slow1():
            release1.wait(timeout=30)
            return orig1()

        n1.api.resize_puller.pull_owned = slow1
        req(base, "POST", "/internal/join", {"id": n1.uri, "uri": n1.uri})
        assert req(base, "GET", "/status")["state"] == "RESIZING"

        # Second join arrives mid-resize.
        n2.attach_cluster([nodes[0].uri, n1.uri, n2.uri], 1)
        req(base, "POST", "/internal/join", {"id": n2.uri, "uri": n2.uri})

        # Let job 1 finish: it is superseded, so the cluster must STAY
        # RESIZING (job 2's pulls — n2's among them — may not be done).
        release1.set()
        time.sleep(1.0)
        st = req(base, "GET", "/status")
        # Either job 2 also finished (fine: all pulls done) or the state
        # is still RESIZING; what must NEVER happen is NORMAL while n2
        # lacks its shards.
        if st["state"] == "NORMAL":
            owned = [s for s in range(6)
                     if n2.cluster.owns_shard("ov", s)]
            held = n2.holder.index("ov").available_shards() \
                if n2.holder.index("ov") else []
            assert set(owned) <= set(held)
        for uri in (base, n1.uri, n2.uri):
            r = req(uri, "POST", "/index/ov/query", b"Count(Row(f=1))")
            assert r["results"] == [6], uri
        # Eventually everything settles NORMAL with data in place.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = {req(u, "GET", "/status")["state"]
                      for u in (base, n1.uri, n2.uri)}
            if states == {"NORMAL"}:
                break
            time.sleep(0.1)
        assert states == {"NORMAL"}
        for uri in (base, n1.uri, n2.uri):
            r = req(uri, "POST", "/index/ov/query", b"Count(Row(f=1))")
            assert r["results"] == [6], uri
    finally:
        for nd in (n1, n2):
            nd.stop()
        nodes[0].stop()


def test_resize_abort_is_honest(tmp_path):
    """Abort cannot undo a pull-based resize; the response says so and
    the cluster adopts the new placement (divergence from reference
    api.go:1141, documented in the response note)."""
    nodes = run_cluster(tmp_path, 2)
    try:
        nodes[0].cluster.begin_resize()
        assert req(nodes[0].uri, "GET", "/status")["state"] == "RESIZING"
        # Schema mutations are rejected while RESIZING (reference
        # api.validate, api.go:76-99).
        with pytest.raises(urllib.error.HTTPError):
            req(nodes[0].uri, "POST", "/index/nope", {"options": {}})
        res = req(nodes[0].uri, "POST", "/cluster/resize/abort")
        assert res["aborted"] is True and "note" in res
        assert req(nodes[0].uri, "GET", "/status")["state"] == "NORMAL"
        res = req(nodes[0].uri, "POST", "/cluster/resize/abort")
        assert res["aborted"] is False
    finally:
        for nd in nodes:
            nd.stop()


def test_remove_live_node_pulls_its_data(tmp_path):
    """Removing an ALIVE node with replica_n=1: survivors must pull the
    removed node's exclusive shards from it (it stays reachable through
    the pre-resize snapshot) before the new placement takes over
    (reference sources resize instructions from pre-change owners,
    cluster.go:741-826)."""
    import time
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/rl", {"options": {}})
        req(base, "POST", "/index/rl/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 2 for s in range(8)]
        req(base, "POST", "/index/rl/field/f/import",
            {"rowIDs": [1] * 8, "columnIDs": cols})
        # node 1 must hold at least one shard exclusively
        assert nodes[1].holder.index("rl").available_shards()
        st = req(base, "POST", "/cluster/resize/remove-node",
                 {"id": nodes[1].uri})
        assert len(st["nodes"]) == 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if req(base, "GET", "/status")["state"] == "NORMAL":
                break
            time.sleep(0.05)
        assert req(base, "GET", "/status")["state"] == "NORMAL"
        # every bit now lives on the survivor
        assert sorted(nodes[0].holder.index("rl").available_shards()) == \
            list(range(8))
        r = req(base, "POST", "/index/rl/query", b"Count(Row(f=1))")
        assert r["results"] == [8]
    finally:
        for nd in nodes:
            nd.stop()


def test_keyed_cluster(tmp_path):
    nodes = run_cluster(tmp_path, 2)
    try:
        base = nodes[1].uri  # write via the NON-primary node
        req(base, "POST", "/index/ki", {"options": {"keys": True}})
        req(base, "POST", "/index/ki/field/f", {"options": {"keys": True}})
        req(base, "POST", "/index/ki/query",
            b"Set('alice', f='admin') Set('bob', f='admin')")
        for nd in nodes:
            res = req(nd.uri, "POST", "/index/ki/query", b"Row(f='admin')")
            assert sorted(res["results"][0]["keys"]) == ["alice", "bob"], \
                nd.uri
    finally:
        for nd in nodes:
            nd.stop()


def test_write_fails_when_no_replica_available(tmp_path):
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ci", {"options": {}})
        req(base, "POST", "/index/ci/field/f", {"options": {}})
        # find a column whose sole owner is node 1, then kill node 1
        target = None
        for col in range(0, 64 * SHARD_WIDTH, SHARD_WIDTH):
            owner = nodes[0].cluster.shard_nodes("ci", col // SHARD_WIDTH)[0]
            if owner.id != nodes[0].cluster.local.id:
                target = col
                break
        assert target is not None
        nodes[1].stop()
        with pytest.raises(urllib.error.HTTPError):
            req(base, "POST", "/index/ci/query",
                f"Set({target}, f=1)".encode())
    finally:
        nodes[0].stop()


def test_sync_creates_missing_schema(tmp_path):
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        # node 0 has schema+data node 1 never heard about
        nodes[0].holder.create_index("lone").create_field("f").import_bits(
            np.array([1], np.uint64), np.array([3], np.uint64))
        req(nodes[0].uri, "POST", "/internal/sync")
        f = nodes[1].holder.index("lone").field("f")
        assert f is not None and f.view().fragment(0).bit(1, 3)
    finally:
        for nd in nodes:
            nd.stop()


def test_heartbeat_marks_down_and_recovers(tmp_path):
    """Failure detector: N failed probes -> node DOWN + cluster DEGRADED
    + queries avoid the dead replica proactively; a successful probe
    marks it READY again (reference memberlist SWIM driving node state,
    gossip/gossip.go:246; DEGRADED cluster.go:522-533)."""
    from pilosa_tpu.parallel.heartbeat import Heartbeater

    nodes = run_cluster(tmp_path, 3, replica_n=2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/hb", {"options": {}})
        req(base, "POST", "/index/hb/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH for s in range(6)]
        req(base, "POST", "/index/hb/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})

        hb = Heartbeater(nodes[0].cluster, interval=0.1, suspect_after=2,
                         timeout=2.0)
        hb.probe_once()
        assert nodes[0].cluster.state == STATE_NORMAL

        victim_addr = nodes[2].server.server_address
        nodes[2].stop()
        hb.probe_once()
        assert nodes[0].cluster.state == STATE_NORMAL  # 1 failure: suspect
        hb.probe_once()
        st = req(base, "GET", "/status")
        assert st["state"] == "DEGRADED"
        down = [n for n in st["nodes"] if n["state"] == "DOWN"]
        assert [n["id"] for n in down] == [nodes[2].uri]
        # Proactive failover: routing never selects the down node.
        by_node = nodes[0].cluster.shards_by_node("hb", list(range(6)))
        assert nodes[2].uri not in by_node
        r = req(base, "POST", "/index/hb/query", b"Count(Row(f=1))")
        assert r["results"] == [6]

        # Node comes back on the same port: one good probe -> READY.
        revived = ClusterNode(tmp_path, "n2b")
        revived.api = nodes[2].api
        import http.server as _hs
        from pilosa_tpu.server.http import Handler
        handler = type("H", (Handler,), {"api": nodes[2].api})
        import threading as _t
        srv = _hs.ThreadingHTTPServer(victim_addr, handler)
        _t.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            hb.probe_once()
            assert nodes[0].cluster.state == STATE_NORMAL
            st = req(base, "GET", "/status")
            assert all(n["state"] == "READY" for n in st["nodes"])
        finally:
            srv.shutdown()
            srv.server_close()
    finally:
        for nd in nodes[:2]:
            nd.stop()


def test_heartbeat_probe_load_is_bounded_at_n20():
    """Rotating-subset prober at N=20: per-round probe count stays
    <= probes_per_round (+1 for the down slot) — O(N) cluster-wide
    instead of the previous every-peer N^2 mesh (VERDICT r2 weak #6;
    reference bounds this via memberlist SWIM, gossip/gossip.go:43,246).
    Failure detection latency is still suspect_after ROUNDS because
    suspects are re-probed every round, and recovery is still one
    round because a down peer gets the rotating extra slot."""
    from pilosa_tpu.parallel.cluster import Cluster, Node
    from pilosa_tpu.parallel.heartbeat import Heartbeater

    local = Node("n00", "http://h0:1")
    cluster = Cluster(local, replica_n=2)
    for i in range(1, 20):
        cluster.add_node(Node(f"n{i:02d}", f"http://h{i}:1"))
    cluster.state = "NORMAL"
    hb = Heartbeater(cluster, interval=0, suspect_after=3)

    probed = []
    dead = set()

    class _Cli:
        def status(self, uri):
            probed.append(uri)
            if uri in dead:
                from pilosa_tpu.parallel.client import ClientError
                raise ClientError("down")
            return {}

    hb.client = _Cli()

    # Healthy steady state: exactly probes_per_round probes per round,
    # and rotation covers every peer within ceil(19/2) rounds.
    for _ in range(10):
        hb.probe_once()
        assert hb.last_round_probes <= hb.probes_per_round
    assert set(probed) == {f"http://h{i}:1" for i in range(1, 20)}

    # Kill one: it becomes suspect once rotation hits it, then is
    # probed EVERY round, so DOWN lands suspect_after rounds later.
    dead.add("http://h7:1")
    rounds = 0
    while "n07" not in cluster.down_ids:
        hb.probe_once()
        rounds += 1
        assert hb.last_round_probes <= hb.probes_per_round + 1
        assert rounds < 20  # rotation reach + 3 suspect rounds
    assert cluster.state == "DEGRADED"

    # Down peers keep a single rotating probe slot; load stays bounded.
    for _ in range(5):
        hb.probe_once()
        assert hb.last_round_probes <= hb.probes_per_round + 1

    # Recovery: next round's down-slot probe marks it READY.
    dead.clear()
    hb.probe_once()
    assert "n07" not in cluster.down_ids
    assert cluster.state == "NORMAL"


def test_translate_replication_loop(tmp_path):
    """Replicas converge on the primary's translate log via the standing
    replication loop, without anti-entropy or a read-path fallback
    (reference replicate loop, translate.go:359-400)."""
    from pilosa_tpu.parallel.heartbeat import TranslateReplicationLoop

    nodes = run_cluster(tmp_path, 2)
    try:
        primary = sorted(nodes, key=lambda n: n.uri)[0]
        replica = next(n for n in nodes if n is not primary)
        req(primary.uri, "POST", "/index/tr", {"options": {"keys": True}})
        req(primary.uri, "POST", "/index/tr/field/f", {"options": {}})
        req(primary.uri, "POST", "/index/tr/query", b"Set('k1', f=1)")
        # The replica's local store may not know k1 yet (only via primary
        # fallback). One replication pass adopts the log directly.
        loop = TranslateReplicationLoop(replica.api, interval=0.0)
        loop.replicate_once()
        store = replica.holder.index("tr").column_translator
        assert store.translate_key("k1", create=False) is not None
    finally:
        for nd in nodes:
            nd.stop()


def test_max_writes_per_request(tmp_path):
    """(reference ErrTooManyWrites, executor.go:106; config
    max_writes_per_request server/config.go)."""
    nodes = run_cluster(tmp_path, 2)
    try:
        req(nodes[0].uri, "POST", "/index/mw", {"options": {}})
        req(nodes[0].uri, "POST", "/index/mw/field/f", {"options": {}})
        nodes[0].api.executor.max_writes_per_request = 3
        q = b"Set(1, f=1) Set(2, f=1) Set(3, f=1) Set(4, f=1)"
        with pytest.raises(urllib.error.HTTPError):
            req(nodes[0].uri, "POST", "/index/mw/query", q)
        # At the limit passes; reads don't count as writes.
        req(nodes[0].uri, "POST", "/index/mw/query",
            b"Set(1, f=1) Set(2, f=1) Set(3, f=1) Count(Row(f=1))")
    finally:
        for nd in nodes:
            nd.stop()


def test_translate_log_truncation_tolerated(tmp_path):
    from pilosa_tpu.core.translate import TranslateStore
    p = str(tmp_path / "keys")
    ts = TranslateStore(p)
    ts.open()
    ts.translate_key("alice")
    ts.translate_key("bob")
    ts.close()
    # torn tail: cut mid-record
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-3])
    ts2 = TranslateStore(p)
    ts2.open()  # must not raise
    assert ts2.translate_key("alice", create=False) == 1
    assert ts2.translate_key("bob", create=False) is None
    ts2.close()


def test_options_cluster_no_double_count(tmp_path):
    """Options(shards=[...]) must be consumed at the coordinator: with
    replication, forwarding the full shard list to every node would make
    replicated shards count twice."""
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        req(nodes[0].uri, "POST", "/index/oi", {})
        req(nodes[0].uri, "POST", "/index/oi/field/f", {})
        sets = " ".join(f"Set({c}, f=1)" for c in (1, 2, SHARD_WIDTH + 5))
        req(nodes[0].uri, "POST", "/index/oi/query", sets.encode())
        for nd in nodes:
            res = req(nd.uri, "POST", "/index/oi/query",
                      b"Options(Count(Row(f=1)), shards=[0, 1])")
            assert res["results"][0] == 3, (nd.uri, res)
            res = req(nd.uri, "POST", "/index/oi/query",
                      b"Options(Count(Row(f=1)), shards=[0])")
            assert res["results"][0] == 2, (nd.uri, res)
    finally:
        for nd in nodes:
            nd.stop()


def test_options_cluster_column_attrs(tmp_path):
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        req(nodes[0].uri, "POST", "/index/ai", {})
        req(nodes[0].uri, "POST", "/index/ai/field/f", {})
        req(nodes[0].uri, "POST", "/index/ai/query",
            b'Set(1, f=1) Set(2, f=1) SetColumnAttrs(2, kind="x")')
        for nd in nodes:
            res = req(nd.uri, "POST", "/index/ai/query",
                      b"Options(Row(f=1), columnAttrs=true)")
            assert res["results"][0]["columns"] == [1, 2], (nd.uri, res)
            assert res.get("columnAttrs") == \
                [{"id": 2, "attrs": {"kind": "x"}}], (nd.uri, res)
    finally:
        for nd in nodes:
            nd.stop()


def test_cluster_admin_remove_node_and_coordinator(tmp_path):
    """remove-node rebalances onto survivors; set-coordinator broadcasts
    (reference api.go:1084-1141, PostClusterResize* routes)."""
    nodes = run_cluster(tmp_path, 3, replica_n=2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/rm", {"options": {}})
        req(base, "POST", "/index/rm/field/f", {"options": {}})
        req(base, "POST", "/index/rm/query", b"Set(1, f=1) Set(2, f=1)")
        # owners of shard 0
        owners = req(base, "GET", "/internal/fragment/nodes?index=rm&shard=0")
        assert len(owners) == 2
        # set coordinator to node 1
        st = req(base, "POST", "/cluster/resize/set-coordinator",
                 {"id": nodes[1].uri})
        coords = [n for n in st["nodes"] if n.get("isCoordinator")]
        assert [c["id"] for c in coords] == [nodes[1].uri]
        # remove node 2 via node 0; survivors converge to 2-node topology
        st = req(base, "POST", "/cluster/resize/remove-node",
                 {"id": nodes[2].uri})
        assert len(st["nodes"]) == 2
        st1 = req(nodes[1].uri, "GET", "/status")
        assert len(st1["nodes"]) == 2
        # the removed node detached to a single-node topology
        st2 = req(nodes[2].uri, "GET", "/status")
        assert [n["id"] for n in st2["nodes"]] == [nodes[2].uri]
        # data still queryable after rebalance
        res = req(base, "POST", "/index/rm/query", b"Count(Row(f=1))")
        assert res["results"] == [2]
        # abort reports state without error
        assert "state" in req(base, "POST", "/cluster/resize/abort")
    finally:
        for nd in nodes:
            nd.stop()


def test_schema_sync_preserves_all_field_options(tmp_path):
    """maxColumns/noStandardView must survive anti-entropy schema
    creation — a replica without the declared bound would accept
    out-of-range writes the owner rejects."""
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        from pilosa_tpu.core.field import FieldOptions
        nodes[0].holder.create_index("sp").create_field(
            "fp", FieldOptions(max_columns=4096, cache_size=123))
        nodes[0].holder.index("sp").field("fp").import_bits(
            np.array([1], np.uint64), np.array([9], np.uint64))
        req(nodes[0].uri, "POST", "/internal/sync")
        f = nodes[1].holder.index("sp").field("fp")
        assert f is not None
        assert f.options.max_columns == 4096
        assert f.options.cache_size == 123
    finally:
        for nd in nodes:
            nd.stop()


def test_node_paused_during_import_heals_by_anti_entropy(tmp_path):
    """The reference's flagship clustertest (internal/clustertests/
    cluster_test.go:54-70, pumba pause): a replica unreachable during an
    import misses writes; once it is back, an anti-entropy pass brings
    it to parity."""
    import http.server as _hs
    import threading as _t

    from pilosa_tpu.server.http import Handler

    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/pz", {"options": {}})
        req(base, "POST", "/index/pz/field/f", {"options": {}})
        req(base, "POST", "/index/pz/field/f/import",
            {"rowIDs": [1, 1], "columnIDs": [1, 2]})

        # "Pause" node 1: stop serving, keep its holder/data intact.
        victim_addr = nodes[1].server.server_address
        nodes[1].server.shutdown()
        nodes[1].server.server_close()

        # Import lands only on node 0 (forward to node 1 fails silently,
        # healed later — reference importNode error tolerance).
        cols = [s * SHARD_WIDTH + 9 for s in range(4)]
        req(base, "POST", "/index/pz/field/f/import",
            {"rowIDs": [2] * 4, "columnIDs": cols})
        (before,) = req(base, "POST", "/index/pz/query",
                        b"Count(Row(f=2))")["results"]
        assert before == 4
        f1 = nodes[1].holder.index("pz").field("f")
        assert all(not fr.bit(2, c)
                   for c in cols
                   for v in [f1.view()] if v
                   for fr in [v.fragment(c // SHARD_WIDTH)] if fr)

        # "Unpause": serve again on the same port with the same holder.
        handler = type("H", (Handler,), {"api": nodes[1].api})
        srv = _hs.ThreadingHTTPServer(victim_addr, handler)
        _t.Thread(target=srv.serve_forever, daemon=True).start()
        nodes[1].server = srv
        # One anti-entropy pass from node 0 pushes the missed writes.
        stats = req(base, "POST", "/internal/sync")
        assert stats["pushed"] > 0
        for c in cols:
            fr = nodes[1].holder.index("pz").field("f").view() \
                .fragment(c // SHARD_WIDTH)
            assert fr is not None and fr.bit(2, c), c
        (after,) = req(nodes[1].uri, "POST", "/index/pz/query",
                       b"Count(Row(f=2))")["results"]
        assert after == 4
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass


def test_cluster_with_per_node_mesh_composes(tmp_path):
    """The two distribution layers compose: HTTP scatter-gather across
    nodes (the DCN analog) with each node's local executor running its
    shard subset SPMD over a device mesh (the ICI analog) — SURVEY §7
    step 6's layering, on the 8-virtual-device CPU platform."""
    import jax

    from pilosa_tpu.parallel import MeshContext

    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        # Rebuild each node's API with a 4-device mesh attached.
        for nd in nodes:
            mesh = MeshContext(jax.devices()[:4])
            api = API(nd.holder, mesh=mesh, cluster=nd.cluster,
                      stats=MemStatsClient())
            nd.api = api
            nd.server.RequestHandlerClass.api = api
        base = nodes[0].uri
        req(base, "POST", "/index/mm", {"options": {}})
        req(base, "POST", "/index/mm/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 3 for s in range(10)]
        req(base, "POST", "/index/mm/field/f/import",
            {"rowIDs": [1] * 10 + [2] * 10,
             "columnIDs": cols + [c + 1 for c in cols]})
        for nd in nodes:
            r = req(nd.uri, "POST", "/index/mm/query",
                    b"Count(Row(f=1)) Count(Intersect(Row(f=1), Row(f=2)))"
                    b" TopN(f, n=1)")
            assert r["results"][0] == 10, (nd.uri, r)
            assert r["results"][1] == 0
            assert r["results"][2][0]["count"] == 10
    finally:
        for nd in nodes:
            nd.stop()


def test_cluster_soak_random_schedule(tmp_path):
    """Deterministic soak: a seeded schedule of imports, point writes,
    membership changes (join + remove with resize jobs), anti-entropy
    passes, and per-node reads — every read from every node must match a
    host-side model at every step (the querygenerator + clustertests
    combination, internal/test/querygenerator.go +
    internal/clustertests/)."""
    import time

    rng = np.random.RandomState(1234)
    nodes = run_cluster(tmp_path, 3, replica_n=2)
    extra = None
    model = {}  # row -> set(cols)

    def wait_normal(uris, timeout=30):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(req(u, "GET", "/status")["state"] == "NORMAL"
                   for u in uris):
                return True
            time.sleep(0.1)
        return False

    def verify(uris):
        for row in sorted(model):
            want = len(model[row])
            for u in uris:
                r = req(u, "POST", "/index/sk/query",
                        f"Count(Row(f={row}))".encode())
                assert r["results"] == [want], (u, row, r, want)

    try:
        base = nodes[0].uri
        req(base, "POST", "/index/sk", {"options": {}})
        req(base, "POST", "/index/sk/field/f", {"options": {}})
        uris = [nd.uri for nd in nodes]
        for step in range(12):
            via = uris[rng.randint(len(uris))]
            if step == 3:
                # grow to 4 nodes via a real join + resize job
                # (membership steps are pinned so the schedule is
                # guaranteed to exercise BOTH resize directions under
                # data, whatever the seed does elsewhere)
                extra = ClusterNode(tmp_path, f"extra{step}")
                extra.start(None, 2)
                extra.attach_cluster(uris + [extra.uri], 2)
                req(base, "POST", "/internal/join",
                    {"id": extra.uri, "uri": extra.uri})
                assert wait_normal(uris + [extra.uri]), "join resize hung"
                uris = uris + [extra.uri]
            elif step == 8:
                # shrink back to 3
                req(base, "POST", "/cluster/resize/remove-node",
                    {"id": extra.uri})
                uris = [u for u in uris if u != extra.uri]
                assert wait_normal(uris), "remove resize hung"
                extra.stop()
                extra = None
            elif rng.rand() < 0.6:
                rows = rng.randint(0, 4, 30)
                cols = rng.randint(0, 4 * SHARD_WIDTH, 30)
                req(via, "POST", "/index/sk/field/f/import",
                    {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
                for r_, c_ in zip(rows.tolist(), cols.tolist()):
                    model.setdefault(r_, set()).add(c_)
            elif rng.rand() < 0.7:
                r_, c_ = int(rng.randint(0, 4)), int(
                    rng.randint(0, 4 * SHARD_WIDTH))
                req(via, "POST", "/index/sk/query",
                    f"Set({c_}, f={r_})".encode())
                model.setdefault(r_, set()).add(c_)
            else:
                req(via, "POST", "/internal/sync")
            verify(uris)
        req(base, "POST", "/internal/sync")
        verify(uris)
    finally:
        if extra is not None:
            extra.stop()
        for nd in nodes:
            nd.stop()


def test_seed_join_under_concurrent_imports(tmp_path):
    """Writers keep importing while a 4th node seed-joins and the
    cluster resizes: no write may fail and no bit may be lost — the
    write fan-out targets current ∪ pre-resize owners during the move
    (write_nodes), and the resize pulls cover the rest. The in-flight
    membership change is exactly when a lesser design undercounts."""
    import threading
    import time

    nodes = run_cluster(tmp_path, 3)
    n4 = None
    stop = threading.Event()
    imported: list = []
    errors: list = []

    def writer(k, uris):
        i = 0
        while not stop.is_set() and not errors:
            base = (i * 997 + k * 4_000_003) % (8 * SHARD_WIDTH)
            cols = [(base + j * 61) % (8 * SHARD_WIDTH) for j in range(40)]
            try:
                req(uris[i % len(uris)], "POST",
                    "/index/ji/field/f/import",
                    {"rowIDs": [1] * len(cols), "columnIDs": cols})
            except Exception as e:  # noqa: BLE001 — recorded, test fails
                errors.append(e)
                return
            imported.extend(cols)
            i += 1

    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ji", {"options": {}})
        req(base, "POST", "/index/ji/field/f", {"options": {}})
        uris = [nd.uri for nd in nodes]
        threads = [threading.Thread(target=writer, args=(k, uris))
                   for k in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # writes in flight before the join lands

        n4 = ClusterNode(tmp_path, "n3")
        n4.start(None, 1)
        n4.attach_cluster([n4.uri], 1)
        n4.api.join_via_seeds([base])
        allnodes = nodes + [n4]
        assert _wait(lambda: all(
            len(nd.cluster.nodes()) == 4
            and nd.cluster.state == STATE_NORMAL for nd in allnodes))
        time.sleep(0.3)  # writes continue against the new placement
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    try:
        assert not errors, errors
        want = len(set(imported))
        req(base, "POST", "/internal/sync")
        for nd in allnodes:
            res = req(nd.uri, "POST", "/index/ji/query",
                      b"Count(Row(f=1))")
            assert res["results"] == [want], (nd.uri, res, want)
    finally:
        for nd in nodes + ([n4] if n4 is not None else []):
            nd.stop()


def test_translate_primary_pinned_across_membership(tmp_path):
    """A joiner whose id sorts FIRST must not become the key allocator
    with an empty store (id collisions); removing the primary promotes
    the node that just caught up from it."""
    import time

    nodes = run_cluster(tmp_path, 2)
    newcomer = ClusterNode(tmp_path, "na")
    newcomer.start(None, 1)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ki", {"options": {"keys": True}})
        req(base, "POST", "/index/ki/field/f", {"options": {}})
        req(base, "POST", "/index/ki/query", b"Set('alice', f=1)")

        # Join with an id that sorts before every http:// URI.
        newcomer.attach_cluster([nodes[0].uri, nodes[1].uri], 1,
                                node_id="aaa-first")
        req(base, "POST", "/internal/join",
            {"id": "aaa-first", "uri": newcomer.uri})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if req(base, "GET", "/status")["state"] == "NORMAL":
                break
            time.sleep(0.1)
        st = req(base, "GET", "/status")
        # Primary stayed a pre-join member.
        assert st.get("translatePrimary") != "aaa-first"
        assert st.get("translatePrimary") in (nodes[0].uri, nodes[1].uri)
        # New key allocation still goes through the original primary:
        # 'bob' must get a FRESH id, not collide with 'alice'.
        req(nodes[1].uri, "POST", "/index/ki/query", b"Set('bob', f=1)")
        r = req(base, "POST", "/index/ki/query", b"Row(f=1)")
        assert sorted(r["results"][0]["keys"]) == ["alice", "bob"]

        # Remove the primary: the remover catches up and promotes itself.
        primary = st["translatePrimary"]
        via = nodes[0].uri if primary != nodes[0].uri else nodes[1].uri
        st2 = req(via, "POST", "/cluster/resize/remove-node",
                  {"id": primary})
        assert st2.get("translatePrimary") == via
        req(via, "POST", "/index/ki/query", b"Set('carol', f=1)")
        r = req(via, "POST", "/index/ki/query", b"Row(f=1)")
        assert sorted(r["results"][0]["keys"]) == ["alice", "bob", "carol"]
    finally:
        newcomer.stop()
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass


def test_cluster_queries_after_restart(tmp_path):
    """Restart a node (same data dir, same port): it reopens its
    fragments from disk, rejoins the topology, and serves the same
    results (reference TestClusterQueriesAfterRestart,
    server/server_test.go)."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server import API, serve
    from pilosa_tpu.utils.stats import MemStatsClient

    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/rs", {"options": {}})
        req(base, "POST", "/index/rs/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 3 for s in range(8)]
        req(base, "POST", "/index/rs/field/f/import",
            {"rowIDs": [1] * 8, "columnIDs": cols})
        (before,) = req(base, "POST", "/index/rs/query",
                        b"Count(Row(f=1))")["results"]
        assert before == 8

        # restart node 1: close everything, reopen from the same dir on
        # the same port, re-attach the same cluster identity
        port = nodes[1].server.server_address[1]
        uris = [nodes[0].uri, nodes[1].uri]
        nodes[1].server.shutdown()
        nodes[1].server.server_close()
        nodes[1].holder.close()

        nodes[1].holder = Holder(str(tmp_path / "n1"))
        nodes[1].holder.open()
        nodes[1].api = API(nodes[1].holder, stats=MemStatsClient())
        nodes[1].server = serve(nodes[1].api, "localhost", port,
                                background=True)
        nodes[1].attach_cluster(uris, replica_n=1)

        # both nodes answer with the full pre-restart count
        for uri in uris:
            (after,) = req(uri, "POST", "/index/rs/query",
                           b"Count(Row(f=1))")["results"]
            assert after == 8, uri
        # and writes keep working post-restart
        req(base, "POST", "/index/rs/query",
            f"Set({9 * SHARD_WIDTH}, f=1)".encode())
        (after,) = req(base, "POST", "/index/rs/query",
                       b"Count(Row(f=1))")["results"]
        assert after == 9
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass


def test_cluster_connection_burst(tmp_path):
    """Concurrent query burst through the coordinator (reference
    TestClusterExhaustingConnections, server/server_test.go): pooled
    internal connections + threaded handlers must survive parallel
    fan-out without fd exhaustion or cross-talk."""
    import threading as _t

    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/cb", {"options": {}})
        req(base, "POST", "/index/cb/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/cb/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        errors = []
        barrier = _t.Barrier(8)

        def worker():
            try:
                barrier.wait()
                for _ in range(25):
                    (cnt,) = req(base, "POST", "/index/cb/query",
                                 b"Count(Row(f=1))")["results"]
                    assert cnt == 6, cnt
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [_t.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
    finally:
        for nd in nodes:
            nd.stop()


def test_translate_replication_chains_from_predecessor(tmp_path):
    """Chained translate replication (reference
    setPrimaryTranslateStore(previousNode), cluster.go:1908-1935): each
    replica streams from its ring predecessor, so data flows
    primary -> middle -> last one hop per pass, and the primary serves
    ONE stream regardless of cluster size."""
    nodes = run_cluster(tmp_path, 3)
    try:
        order = sorted(nodes, key=lambda n: n.uri)
        primary, middle, last = order
        # Sanity: the ring predecessor of each is the node before it.
        assert middle.api._translate_source().id == primary.uri
        assert last.api._translate_source().id == middle.uri
        req(primary.uri, "POST", "/index/ch", {"options": {"keys": True}})
        req(primary.uri, "POST", "/index/ch/field/f", {"options": {}})
        req(primary.uri, "POST", "/index/ch/query", b"Set('kx', f=1)")

        def has_key(n):
            st = n.holder.index("ch").column_translator
            return st.translate_key("kx", create=False) is not None

        # last pulls from middle, which is still empty -> no key yet.
        last.api._sync_translate_stores()
        assert not has_key(last)
        # middle pulls from the primary -> adopts the key.
        middle.api._sync_translate_stores()
        assert has_key(middle)
        # now last's predecessor has it -> one more pass converges.
        last.api._sync_translate_stores()
        assert has_key(last)

        # Predecessor DOWN: the chain re-forms around it via the
        # primary fallback.
        req(primary.uri, "POST", "/index/ch/query", b"Set('ky', f=1)")
        last.cluster.down_ids.add(middle.uri)
        assert last.api._translate_source().id == primary.uri
        last.api._sync_translate_stores()
        st = last.holder.index("ch").column_translator
        assert st.translate_key("ky", create=False) is not None
    finally:
        for nd in nodes:
            nd.stop()


def test_chained_replica_serves_only_streamed_prefix(tmp_path):
    """A replica's served stream must be byte-stable for its successor:
    out-of-band adopted entries (primary-fallback lookups) have ids
    beyond the streamed prefix and must NOT be spliced into the served
    stream until the stream itself delivers them."""
    from pilosa_tpu.core.translate import TranslateStore
    primary = TranslateStore()
    for k in ("a", "b", "c", "d"):
        primary.translate_key(k)
    replica = TranslateStore()
    full = primary.read_log_from(0)
    # Stream only the first two records into the replica.
    two = 2 * (4 + 1 + 8)
    replica.apply_log(full[:two], resume=True)
    # Out-of-band adoption of a later allocation ('d', id 4).
    replica.apply_entries([("d", 4)])
    # The replica SERVES exactly the primary's first `two` bytes: a
    # successor at any offset <= two reads the true stream.
    assert replica.read_log_from(0) == full[:two]
    assert replica.read_log_from(replica.replica_offset) == b""
    # Streaming the rest closes the hole and extends the served prefix.
    replica.apply_log(full[two:], resume=True)
    assert replica.read_log_from(0) == full
    # A store that allocates locally (the primary, incl. a promoted
    # one) serves its whole id-ordered log.
    replica.translate_key("e")
    assert len(replica.read_log_from(0)) > len(full)


def test_restarted_replica_does_not_serve_stale_log(tmp_path):
    """After a restart a replica's served_limit is unknown; the serving
    endpoint must gate it to 0 (serve nothing) until the replica has
    re-streamed — not splice its possibly-hole-y disk log into a
    successor's stream."""
    nodes = run_cluster(tmp_path, 3)
    try:
        order = sorted(nodes, key=lambda n: n.uri)
        primary, middle, last = order
        req(primary.uri, "POST", "/index/rg", {"options": {"keys": True}})
        req(primary.uri, "POST", "/index/rg/field/f", {"options": {}})
        req(primary.uri, "POST", "/index/rg/query", b"Set('k1', f=1)")
        middle.api._sync_translate_stores()
        st = middle.holder.index("rg").column_translator
        assert st.served_limit == st.replica_offset > 0
        # Simulate restart: fresh store state, role unknown.
        st.served_limit = None
        # The HTTP-serving surface refuses to serve until re-streamed.
        assert middle.api.translate_data("rg") == b""
        assert st.served_limit == 0
        # Primary restart keeps serving (role known by pin).
        assert len(primary.api.translate_data("rg")) > 0
        # After re-streaming, the replica serves again.
        middle.api._sync_translate_stores()
        assert len(middle.api.translate_data("rg")) > 0
    finally:
        for nd in nodes:
            nd.stop()


def test_fragment_version_epoch_unique_across_recreate(tmp_path):
    """Version-keyed caches (view banks, merged row lists) must never
    be satisfied by a RECREATED fragment that restarted its version
    counter (fragments are popped/recreated across resizes)."""
    from pilosa_tpu.core.holder import Holder
    h = Holder(str(tmp_path / "d"))
    h.open()
    f = h.create_index("fe").create_field("ff")
    view = f.create_view_if_not_exists("standard")
    frag = view.create_fragment_if_not_exists(0)
    frag.set_bit(1, 1)
    v1 = frag.version
    merged = view.merged_row_ids((0,))
    assert merged == (1,)
    # Drop and recreate the fragment with different data (a resize
    # clean_unowned removes the files too).
    import os
    dropped = view.fragments.pop(0)
    dropped.close()
    os.unlink(dropped.path)
    frag2 = view.create_fragment_if_not_exists(0)
    frag2.set_bit(2, 2)
    assert frag2.version != v1
    assert view.merged_row_ids((0,)) == (2,)  # not the stale (1,)
    h.close()


def test_batch_query_cluster_path(tmp_path):
    """/batch/query on a clustered node: items execute via the fan-out
    executor, per-item errors isolate, HTTP round trip amortized."""
    nodes = run_cluster(tmp_path, 2)
    try:
        req(nodes[0].uri, "POST", "/index/bq", {"options": {}})
        req(nodes[0].uri, "POST", "/index/bq/field/f", {"options": {}})
        req(nodes[0].uri, "POST", "/index/bq/query",
            b"Set(1, f=6) Set(" + str(SHARD_WIDTH + 2).encode() + b", f=6)")
        res = req(nodes[0].uri, "POST", "/batch/query", {"queries": [
            {"index": "bq", "query": "Count(Row(f=6))"},
            {"index": "bq", "query": "Row(f=6)"},
            {"index": "nope", "query": "Count(Row(f=6))"},
            {"index": "bq"},
        ]})
        out = res["responses"]
        assert out[0] == {"results": [2]}
        assert out[1]["results"][0]["columns"] == [1, SHARD_WIDTH + 2]
        assert "error" in out[2] and "error" in out[3]
        # Identical answers through the other node (its own fan-out).
        res2 = req(nodes[1].uri, "POST", "/batch/query", {"queries": [
            {"index": "bq", "query": "Count(Row(f=6))"}]})
        assert res2["responses"][0] == {"results": [2]}
    finally:
        for nd in nodes:
            nd.stop()


def test_traceparent_round_trip_coordinator_to_remote(tmp_path):
    """W3C traceparent propagates across a coordinator→remote query
    leg: the trace id a client sends to the coordinator stamps the
    remote node's spans too (inject emits traceparent; extract adopts
    it), so one distributed query is one trace end to end."""
    from pilosa_tpu.utils.tracing import RecordingTracer

    nodes = run_cluster(tmp_path, 2)
    try:
        tracers = []
        for nd in nodes:
            rt = RecordingTracer()
            nd.api.tracer = rt
            # The internal client captured the tracer at API build
            # time; repoint it so outgoing legs inject the new one.
            nd.api._client.tracer = rt
            tracers.append(rt)
        base = nodes[0].uri
        req(base, "POST", "/index/tp", {"options": {}})
        req(base, "POST", "/index/tp/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/tp/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        trace_id = "f0" * 16
        r = urllib.request.Request(
            base + "/index/tp/query", data=b"Count(Row(f=1))",
            method="POST",
            headers={"traceparent": f"00-{trace_id}-{'ab' * 8}-01"})
        with urllib.request.urlopen(r, timeout=30) as resp:
            assert json.loads(resp.read())["results"] == [6]
        # Coordinator adopted the client's trace id...
        coord_roots = [s for s in tracers[0].finished
                       if s.name.startswith("API.Query")]
        assert coord_roots and all(s.trace_id == trace_id
                                   for s in coord_roots)
        # ...and the remote leg carried it over the node-to-node hop.
        remote_roots = [s for s in tracers[1].finished
                        if s.trace_id == trace_id]
        assert remote_roots, [s.trace_id for s in tracers[1].finished]
    finally:
        for nd in nodes:
            nd.stop()


def test_cluster_health_merges_nodes(tmp_path):
    """/cluster/health on any member fans out over the internal client
    and merges every node's self-report — memory, queue depth, jit and
    slow-query counters — plus liveness: a severed node shows up as
    healthy=false instead of vanishing from the document."""
    nodes = run_cluster(tmp_path, 3)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ch", {"options": {}})
        req(base, "POST", "/index/ch/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/ch/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        res = req(base, "POST", "/index/ch/query", b"Count(Row(f=1))")
        assert res["results"] == [6]

        doc = req(base, "GET", "/cluster/health")
        assert doc["totalNodes"] == 3
        assert doc["healthyNodes"] == 3
        assert len(doc["nodes"]) == 3
        ids = {n["id"] for n in doc["nodes"]}
        assert ids == {nd.uri for nd in nodes}
        for n in doc["nodes"]:
            assert n["healthy"] is True and n["down"] is False
            assert n["memory"]["totalBytes"] >= 0
            assert "queueDepth" in n["coalescer"]
            assert "jitCacheSize" in n["executor"]
            # Remote self-reports carry a staleness age; it is fresh.
            assert n["ageS"] < 30
        # The query above built at least one resident bank somewhere;
        # the fleet totals see it.
        assert doc["totals"]["memoryBytes"] > 0
        assert doc["totals"]["memoryBytes"] == sum(
            n["memory"]["totalBytes"] for n in doc["nodes"])

        # Sever node 2: the merge reports it unhealthy with the error,
        # and keeps merging the survivors.
        nodes[2].stop_server_only()
        nodes[0].api._client.drop_idle()
        doc = req(base, "GET", "/cluster/health")
        assert doc["totalNodes"] == 3
        assert doc["healthyNodes"] == 2
        dead = [n for n in doc["nodes"] if not n["healthy"]]
        assert len(dead) == 1 and dead[0]["id"] == nodes[2].uri
        assert "error" in dead[0]
    finally:
        nodes[2].holder.close()
        for nd in nodes[:2]:
            nd.stop()


def test_cluster_hotspots_merge_with_unreachable_node(tmp_path):
    """/cluster/hotspots mirrors the health plane's fan-out: one
    workload snapshot per member with fleet totals, and a severed
    node is REPORTED with its error instead of silently dropped."""
    from pilosa_tpu.utils.hotspots import WORKLOAD

    nodes = run_cluster(tmp_path, 3)
    try:
        WORKLOAD.reset()
        base = nodes[0].uri
        req(base, "POST", "/index/hs", {"options": {}})
        req(base, "POST", "/index/hs/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/hs/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        for _ in range(4):
            res = req(base, "POST", "/index/hs/query",
                      b"Count(Row(f=1))")
            assert res["results"] == [6]

        doc = req(base, "GET", "/cluster/hotspots")
        assert doc["totalNodes"] == 3
        assert doc["respondedNodes"] == 3
        assert {n["id"] for n in doc["nodes"]} == \
            {nd.uri for nd in nodes}
        for n in doc["nodes"]:
            assert n["healthy"] is True and n["down"] is False
            assert "totals" in n["hotspots"]
        # Fleet totals aggregate exactly what the nodes reported.
        assert doc["totals"]["fragmentReads"] == sum(
            n["hotspots"]["totals"]["fragmentReads"]
            for n in doc["nodes"])
        assert doc["totals"]["fragmentReads"] > 0

        # Sever node 2: reported unhealthy with the error, survivors
        # still merged — never dropped from the document.
        nodes[2].stop_server_only()
        nodes[0].api._client.drop_idle()
        doc = req(base, "GET", "/cluster/hotspots")
        assert doc["totalNodes"] == 3
        assert doc["respondedNodes"] == 2
        dead = [n for n in doc["nodes"] if not n["healthy"]]
        assert len(dead) == 1 and dead[0]["id"] == nodes[2].uri
        assert "error" in dead[0] and "hotspots" not in dead[0]
        assert doc["totals"]["fragmentReads"] == sum(
            n["hotspots"]["totals"]["fragmentReads"]
            for n in doc["nodes"] if "hotspots" in n)
    finally:
        WORKLOAD.reset()
        nodes[2].holder.close()
        for nd in nodes[:2]:
            nd.stop()


def test_cluster_timeline_stitches_nodes(tmp_path):
    """A coordinator→remote query leg produces ONE assembled timeline:
    /cluster/timeline/{trace} merges every member's slices for the
    trace id the W3C traceparent propagated — remote slices carry the
    remote node id and ride the coordinator's trace id, so a cross-
    node query reads as one Perfetto-loadable document."""
    from pilosa_tpu.utils.timeline import TIMELINE
    from pilosa_tpu.utils.tracing import RecordingTracer

    nodes = run_cluster(tmp_path, 2)
    try:
        TIMELINE.reset()
        for nd in nodes:
            rt = RecordingTracer()
            nd.api.tracer = rt
            nd.api._client.tracer = rt
            nd.api.profiler.tracer = rt
        base = nodes[0].uri
        req(base, "POST", "/index/ct", {"options": {}})
        req(base, "POST", "/index/ct/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/ct/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        trace_id = "e1" * 16
        r = urllib.request.Request(
            base + "/index/ct/query", data=b"Count(Row(f=1))",
            method="POST",
            headers={"traceparent": f"00-{trace_id}-{'ab' * 8}-01"})
        with urllib.request.urlopen(r, timeout=30) as resp:
            assert json.loads(resp.read())["results"] == [6]

        doc = req(base, "GET", f"/cluster/timeline/{trace_id}")
        assert doc["traceId"] == trace_id
        assert doc["totalNodes"] == 2
        assert doc["respondedNodes"] == 2
        by_id = {n["id"]: n for n in doc["nodes"]}
        assert set(by_id) == {nd.uri for nd in nodes}
        # The coordinator that assembled the doc is pid 0.
        assert by_id[nodes[0].uri]["pid"] == 0
        for n in doc["nodes"]:
            assert n["healthy"] is True and n["down"] is False

        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert xs
        # Every slice carries the shared trace id and its node id, and
        # the two nodes' slices sit in distinct pid tracks.
        assert all(e["args"]["trace"] == trace_id for e in xs)
        per_node = {e["args"]["node"] for e in xs}
        assert per_node == {nd.uri for nd in nodes}
        assert {e["pid"] for e in xs} == {0, 1}
        # The coordinator recorded the remote fan-out leg; the remote
        # recorded its own dispatch under the SAME trace.
        coord_names = {e["name"] for e in xs if e["pid"] == 0}
        remote_names = {e["name"] for e in xs if e["pid"] == 1}
        assert any(nm.startswith("remote:") for nm in coord_names), \
            coord_names
        assert "dispatch" in remote_names and "request" in remote_names
        # Every event validates against the Chrome trace-event shape.
        for ev in doc["traceEvents"]:
            for k in ("ph", "ts", "dur", "pid", "tid"):
                assert k in ev, ev
    finally:
        TIMELINE.reset()
        for nd in nodes:
            nd.stop()


def test_cluster_timeline_reports_unreachable_node(tmp_path):
    """A severed member is REPORTED in the assembled timeline with its
    error — never silently dropped — while the survivors' slices still
    merge (same contract as /cluster/health and /cluster/hotspots)."""
    from pilosa_tpu.utils.timeline import TIMELINE
    from pilosa_tpu.utils.tracing import RecordingTracer

    nodes = run_cluster(tmp_path, 3)
    try:
        TIMELINE.reset()
        for nd in nodes:
            rt = RecordingTracer()
            nd.api.tracer = rt
            nd.api._client.tracer = rt
        base = nodes[0].uri
        req(base, "POST", "/index/cu", {"options": {}})
        req(base, "POST", "/index/cu/field/f", {"options": {}})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        req(base, "POST", "/index/cu/field/f/import",
            {"rowIDs": [1] * 6, "columnIDs": cols})
        trace_id = "e2" * 16
        r = urllib.request.Request(
            base + "/index/cu/query", data=b"Count(Row(f=1))",
            method="POST",
            headers={"traceparent": f"00-{trace_id}-{'ab' * 8}-01"})
        with urllib.request.urlopen(r, timeout=30) as resp:
            assert json.loads(resp.read())["results"] == [6]

        nodes[2].stop_server_only()
        nodes[0].api._client.drop_idle()
        doc = req(base, "GET", f"/cluster/timeline/{trace_id}")
        assert doc["totalNodes"] == 3
        assert doc["respondedNodes"] == 2
        dead = [n for n in doc["nodes"] if not n["healthy"]]
        assert len(dead) == 1 and dead[0]["id"] == nodes[2].uri
        assert "error" in dead[0]
        # Survivors' slices still assembled under the trace.
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert xs and all(e["args"]["trace"] == trace_id for e in xs)
        live_ids = {n["id"] for n in doc["nodes"] if n["healthy"]}
        assert {e["args"]["node"] for e in xs} <= live_ids
    finally:
        TIMELINE.reset()
        nodes[2].holder.close()
        for nd in nodes[:2]:
            nd.stop()


# ---------------------------------------------------------------------
# Resilience plane (fan-out hardening + fault injection + placement
# epoch guard — docs/architecture.md "Resilience plane").


def _seed_bits(base, index="ci", field="f", shards=6):
    req(base, "POST", f"/index/{index}", {"options": {}})
    req(base, "POST", f"/index/{index}/field/{field}", {"options": {}})
    cols = [s * SHARD_WIDTH + 1 for s in range(shards)]
    req(base, "POST", f"/index/{index}/field/{field}/import",
        {"rowIDs": [1] * shards, "columnIDs": cols})
    return cols


def test_scatter_leg_nonclient_error_fails_over(tmp_path):
    """The silent-undercount regression (ISSUE 15 satellite 1): a
    non-ClientError from a scatter leg (here a stubbed ValueError — a
    torn-body JSON decode in production) must mark the leg failed and
    fail over, never merge short. Before the fix the exception killed
    the thread with `failed` still False and the merge undercounted."""
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        ce = nodes[0].api.cluster_executor
        real = ce.client.query_node_full

        def torn(uri, *a, **kw):
            raise ValueError("torn response body")
        ce.client.query_node_full = torn
        # replica_n=2: every shard also lives locally, so failover must
        # serve the exact answer with zero remote help.
        res = req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert res["results"] == [6]
        counters = nodes[0].api.stats.snapshot()["counters"]
        assert counters.get("cluster.partition_losses", 0) >= 1
        assert counters.get("cluster.failovers", 0) >= 1
        ce.client.query_node_full = real
        res = req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert res["results"] == [6]
    finally:
        for nd in nodes:
            nd.stop()


def test_marked_down_node_receives_zero_rpcs(tmp_path):
    """Pre-seeded exclusion (satellite 2): a node the failure detector
    marked down must receive ZERO query RPCs — proactive failover
    instead of paying a full client timeout per request."""
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        ce = nodes[0].api.cluster_executor
        calls = []
        real = ce.client.query_node_full

        def counting(uri, *a, **kw):
            calls.append(uri)
            return real(uri, *a, **kw)
        ce.client.query_node_full = counting
        down_id = nodes[1].api.cluster.local.id
        assert nodes[0].api.cluster.mark_down(down_id)
        for _ in range(5):
            res = req(base, "POST", "/index/ci/query",
                      b"Count(Row(f=1))")
            assert res["results"] == [6]
        assert nodes[1].uri not in calls, calls
        counters = nodes[0].api.stats.snapshot()["counters"]
        assert counters.get("cluster.excluded_nodes", 0) >= 5
        # Recovery: marked up again, RPCs resume.
        nodes[0].api.cluster.mark_up(down_id)
        for _ in range(5):
            req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert nodes[1].uri in calls
    finally:
        for nd in nodes:
            nd.stop()


def test_down_replicas_readmitted_as_last_resort(tmp_path):
    """A stale detector verdict must not fail a servable request: a
    shard whose every candidate is down-marked still routes to the
    down node as last resort rather than erroring (replica_n=1 ->
    node 1's shards have no other home)."""
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        down_id = nodes[1].api.cluster.local.id
        assert nodes[0].api.cluster.mark_down(down_id)
        res = req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert res["results"] == [6]  # served THROUGH the down-marked node
    finally:
        for nd in nodes:
            nd.stop()


def test_fanout_deadline_bounds_wedged_peer(tmp_path):
    """The per-request deadline budget: a wedged peer (stub sleeping
    far past it) fails the request within the budget instead of
    holding it for the flat client timeout."""
    import time as _t
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        ce = nodes[0].api.cluster_executor
        ce.configure(fanout_deadline_s=0.4, backoff_base_s=0.01,
                     backoff_cap_s=0.02)

        def wedged(uri, *a, **kw):
            _t.sleep(5.0)
            raise AssertionError("unreachable")
        ce.client.query_node_full = wedged
        t0 = _t.monotonic()
        with pytest.raises(urllib.error.HTTPError):
            req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert _t.monotonic() - t0 < 3.0  # not the 5 s stub, never 30 s
    finally:
        for nd in nodes:
            nd.stop()


def test_hedged_read_serves_from_replica(tmp_path):
    """Hedged reads: a leg slower than the configured latency quantile
    re-issues to the spare replica; first success wins, the settle
    latch keeps the merge exact (never double-counted)."""
    import time as _t
    nodes = run_cluster(tmp_path, 3, replica_n=2)
    try:
        base = nodes[0].uri
        c0 = nodes[0].api.cluster
        # Find a shard whose owners are exactly nodes 1 and 2 — the
        # hedge then has a single non-local alternative.
        ids = {nd.api.cluster.local.id: nd for nd in nodes}
        local_id = c0.local.id
        shard = next(
            s for s in range(64)
            if local_id not in [n.id for n in c0.shard_nodes("ci", s)])
        owners = [n.id for n in c0.shard_nodes("ci", shard)]
        slow_id, fast_id = owners[0], owners[1]
        req(base, "POST", "/index/ci", {"options": {}})
        req(base, "POST", "/index/ci/field/f", {"options": {}})
        req(base, "POST", "/index/ci/field/f/import",
            {"rowIDs": [1, 1], "columnIDs": [shard * SHARD_WIDTH + 1,
                                             shard * SHARD_WIDTH + 2]})
        ce = nodes[0].api.cluster_executor
        ce.configure(hedge_quantile=0.5)
        ce._leg_lat.extend([0.01] * 16)
        real = ce.client.query_node_full
        slow_uri = ids[slow_id].uri

        def slow_primary(uri, *a, **kw):
            if uri == slow_uri:
                _t.sleep(1.0)
            return real(uri, *a, **kw)
        ce.client.query_node_full = slow_primary
        t0 = _t.monotonic()
        res = req(base, "POST", "/index/ci/query",
                  b"Count(Row(f=1))")
        dur = _t.monotonic() - t0
        assert res["results"] == [2]  # exact: hedge merged exactly once
        assert dur < 0.9, dur  # answered from the hedge, not the sleeper
        counters = nodes[0].api.stats.snapshot()["counters"]
        assert counters.get("cluster.hedged_reads", 0) >= 1
    finally:
        for nd in nodes:
            nd.stop()


def test_mid_join_routing_never_targets_unpulled_joiner(tmp_path):
    """Chaos-harness regression (the live find): routing must make the
    RESIZING check atomically with the placement math. A join landing
    between a separate state read and shards_by_node once routed a
    shard to the unpulled joiner, which answered without error and the
    TopN merge silently lost one shard."""
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        c0 = nodes[0].api.cluster
        c0.begin_resize()
        c0.add_node(Node("zzz-unpulled-joiner", "http://127.0.0.1:1"))
        by_node, previous = c0.route_shards("ci", list(range(6)))
        assert previous is True
        assert "zzz-unpulled-joiner" not in by_node
        # Queries during the pinned window keep routing to data holders.
        res = req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")
        assert res["results"] == [6]
        res = req(base, "POST", "/index/ci/query", b"TopN(f, n=1)")
        assert res["results"] == [[{"id": 1, "count": 6}]]
    finally:
        for nd in nodes:
            nd.stop()


def test_placement_change_invalidates_cache_entries(tmp_path):
    """The placement epoch guard: eval-tier result-cache entries whose
    shard ownership moved in a resize are provably dropped at the
    adoption point (PR 10's epoch pattern keyed on placement)."""
    nodes = run_cluster(tmp_path, 1, replica_n=1)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        # Warm the eval tier (the second run records the hit path; the
        # first fills).
        for _ in range(3):
            res = req(base, "POST", "/index/ci/query",
                      b"Count(Row(f=1))")
            assert res["results"] == [6]
        api0 = nodes[0].api
        rc = api0.executor.result_cache
        eval_keys = [k for k in rc._entries
                     if isinstance(k, tuple) and k and k[0] == "eval"]
        assert eval_keys, "eval tier never filled"
        c0 = api0.cluster
        gen0 = c0.placement_gen
        c0.begin_resize()
        c0.add_node(Node("zzz-joiner", "http://127.0.0.1:1"))
        moved = api0._moved_shards()
        assert moved, "adding a member moved no shard ownership"
        c0.end_resize()
        api0._note_placement_change(moved)
        assert c0.placement_gen > gen0
        assert rc.placement_invalidations >= 1
        left = [k for k in rc._entries
                if isinstance(k, tuple) and k and k[0] == "eval"
                and any((k[1], int(s)) in moved for s in k[3])]
        assert not left, f"moved-shard entries survived: {left}"
        counters = api0.stats.snapshot()["counters"]
        assert counters.get("cluster.placement_invalidations", 0) >= 1
    finally:
        for nd in nodes:
            nd.stop()


def test_rank_cache_invalidate_shards_unit():
    from pilosa_tpu.core.cache import RankCacheStore, RankEntry

    class _View:
        index = "ci"
        field = "f"
        name = "standard"

    store = RankCacheStore(max_entries=8)
    v1, v2 = _View(), _View()
    v2.index = "other"
    store.put(v1, ("k1",), RankEntry({0: 1, 3: 2}, (1, 2), None, 16))
    store.put(v2, ("k2",), RankEntry({0: 1}, (1,), None, 8))
    assert store.invalidate_shards(set()) == 0
    assert store.invalidate_shards({("ci", 7)}) == 0
    assert store.invalidate_shards({("ci", 3)}) == 1  # v1 covers shard 3
    assert len(store) == 1 and store.placement_invalidations == 1
    assert store.invalidate_shards({("other", 0)}) == 1
    assert len(store) == 0
    assert store.snapshot()["placementInvalidations"] == 2


def test_cluster_lifecycle_events_and_timeline(tmp_path):
    """Kill/recovery verdicts and resize transitions are visible in
    the health plane and the cluster lifecycle timeline — the planes
    the chaos harness asserts against."""
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        c0 = nodes[0].api.cluster
        down_id = nodes[1].api.cluster.local.id
        assert c0.mark_down(down_id)
        assert c0.mark_up(down_id)
        c0.begin_resize()
        c0.end_resize()
        health = req(base, "GET", "/internal/health")
        kinds = [e["type"] for e in health["clusterEvents"]]
        for want in ("node-down", "node-up", "resize-begin",
                     "resize-complete"):
            assert want in kinds, (want, kinds)
        assert "failpoints" in health and "armed" in health["failpoints"]
        assert health["placementGen"] >= 1
        tl = req(base, "GET", "/cluster/timeline")
        got = {e["type"] for e in tl["events"]}
        assert {"node-down", "node-up"} <= got
        # Perfetto-loadable: instants carry ph/ts/pid and the observer.
        inst = [e for e in tl["traceEvents"] if e.get("ph") == "i"]
        assert inst and all("ts" in e and "pid" in e for e in inst)
        down_evs = [e for e in tl["events"] if e["type"] == "node-down"]
        assert any(e.get("node") == down_id for e in down_evs)
        assert all("observer" in e for e in tl["events"])
    finally:
        for nd in nodes:
            nd.stop()


def test_failpoint_5xx_kill_and_disarmed_identity(tmp_path):
    """A failpoint-killed peer (client.5xx scoped to its port) fails
    over bit-exactly; with everything disarmed the same queries serve
    identically — the disarmed-is-identical pin."""
    from pilosa_tpu.utils.failpoints import FAILPOINTS
    nodes = run_cluster(tmp_path, 2, replica_n=2)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        want = req(base, "POST", "/index/ci/query",
                   b"Count(Row(f=1)) Row(f=1)")["results"]
        port1 = nodes[1].uri.rsplit(":", 1)[1]
        FAILPOINTS.arm("client.5xx", f"partition(:{port1})")
        for _ in range(4):
            res = req(base, "POST", "/index/ci/query",
                      b"Count(Row(f=1)) Row(f=1)")
            assert res["results"] == want
        assert FAILPOINTS.snapshot()["sites"]["client.5xx"]["hits"] > 0
        FAILPOINTS.disarm_all()
        for _ in range(4):
            res = req(base, "POST", "/index/ci/query",
                      b"Count(Row(f=1)) Row(f=1)")
            assert res["results"] == want
    finally:
        FAILPOINTS.disarm_all()
        for nd in nodes:
            nd.stop()


def test_resize_puller_source_order_unit():
    """_source_order (satellite 4): pre-change owners first (they
    served every write of the ending epoch), then current owners,
    then any other holder."""
    from types import SimpleNamespace as NS

    from pilosa_tpu.parallel.syncer import ResizePuller
    n = {i: NS(id=f"n{i}", uri=f"u{i}") for i in range(4)}

    class FC:
        def shard_nodes(self, index, shard, previous=False):
            return [n[1], n[2]] if previous else [n[2], n[3]]

    rp = ResizePuller(holder=None, cluster=FC(), client=NS())
    order = rp._source_order("i", 0, [n[0], n[3], n[2], n[1]])
    assert [x.id for x in order] == ["n1", "n2", "n3", "n0"]
    # Holders missing from either placement keep their position at the
    # tail; placement nodes not holding the shard are skipped.
    order = rp._source_order("i", 0, [n[0], n[3]])
    assert [x.id for x in order] == ["n3", "n0"]


def test_resize_puller_regain_ownership_refreshes(tmp_path):
    """Satellite 4, the regain-ownership path: a node re-acquiring a
    shard must REFRESH from the authoritative pre-change owner
    (replace_with_bytes — never trust the stale local copy, which may
    resurrect bits cleared while it wasn't an owner)."""
    from types import SimpleNamespace as NS

    import numpy as np

    from pilosa_tpu.parallel.syncer import ResizePuller

    # Authoritative copy: bits (0,2),(0,3).
    h_auth = Holder(str(tmp_path / "auth"))
    h_auth.open()
    fa = h_auth.create_index("ri",
                             track_existence=False).create_field("rf")
    fa.import_bits(np.array([0, 0], np.uint64),
                   np.array([2, 3], np.uint64))
    auth_bytes = fa.view().fragment(0).write_bytes()
    h_auth.close()

    # Local stale copy: bit (0,1) — cleared upstream while this node
    # wasn't an owner.
    h = Holder(str(tmp_path / "local"))
    h.open()
    idx = h.create_index("ri", track_existence=False)
    f = idx.create_field("rf")
    f.import_bits(np.array([0], np.uint64), np.array([1], np.uint64))

    class Client:
        def views(self, uri, index, field):
            return ["standard"]

        def retrieve_shard(self, uri, index, field, view, shard):
            return auth_bytes

    class FC:
        def owns_shard(self, index, shard):
            return True

    rp = ResizePuller(h, FC(), client=Client())
    peer = NS(id="peer", uri="u-peer")
    # Held and NOT refreshing (was already an owner): untouched.
    assert rp._maybe_pull(peer, idx, 0, refresh=False) == 0
    frag = f.view().fragment(0)
    assert sorted(frag.row_columns(0).tolist()) == [1]
    # Regained ownership: refresh replaces with the authoritative copy.
    assert rp._maybe_pull(peer, idx, 0, refresh=True) == 1
    frag = f.view().fragment(0)
    assert sorted(frag.row_columns(0).tolist()) == [2, 3]
    h.close()


def test_pull_owned_regain_sets_refresh(tmp_path):
    """_pull_owned_locked computes refresh=not was_owner: a node in
    the CURRENT owner set but not the PREVIOUS one pulls with
    refresh=True; a previous-epoch owner pulls refresh=False."""
    from types import SimpleNamespace as NS

    from pilosa_tpu.parallel.syncer import ResizePuller

    h = Holder(str(tmp_path / "h"))
    h.open()
    h.create_index("ri").create_field("rf")

    local = NS(id="me", uri="u-me")
    peer = NS(id="peer", uri="u-peer")

    class Client:
        def schema(self, uri):
            return {"indexes": [{"name": "ri", "options": {},
                                 "fields": [{"name": "rf",
                                             "options": {}}],
                                 "shards": [0]}]}

    class FC:
        def __init__(self, was_owner):
            self.local = local
            self.was_owner = was_owner

        def known_nodes(self):
            return [local, peer]

        def owns_shard(self, index, shard):
            return True

        def shard_nodes(self, index, shard, previous=False):
            if previous:
                return [local, peer] if self.was_owner else [peer]
            return [local]

    seen = []
    for was_owner in (True, False):
        rp = ResizePuller(h, FC(was_owner), client=Client())
        rp._maybe_pull = lambda p, idx, s, refresh=False: (
            seen.append(refresh), 0)[1]
        rp.pull_owned()
    assert seen[0] is False   # previous owner: copy is current
    assert seen[-1] is True   # regained: must refresh
    h.close()
