"""tools/sarif_merge: per-tool runs concatenate under one SARIF
envelope (the single CI artifact check.sh uploads), absent
availability-gated inputs skip cleanly, malformed inputs fail."""

import json

import pytest

from tools.sarif_merge import main, merge_documents


def _doc(tool, n_results=0):
    return {
        "$schema": "s", "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": tool, "rules": []}},
            "results": [{"ruleId": f"{tool}-R", "level": "error",
                         "message": {"text": str(i)}}
                        for i in range(n_results)],
        }],
    }


def test_runs_concatenate_in_argument_order(tmp_path):
    a = tmp_path / "a.sarif"
    b = tmp_path / "b.sarif"
    out = tmp_path / "merged.sarif"
    a.write_text(json.dumps(_doc("graftlint", 2)))
    b.write_text(json.dumps(_doc("planverify", 1)))
    assert main([str(a), str(b), "--output", str(out)]) == 0
    merged = json.loads(out.read_text())
    names = [r["tool"]["driver"]["name"] for r in merged["runs"]]
    assert names == ["graftlint", "planverify"]
    assert merged["version"] == "2.1.0"
    assert sum(len(r["results"]) for r in merged["runs"]) == 3


def test_absent_inputs_skip_without_failing(tmp_path, capsys):
    a = tmp_path / "a.sarif"
    out = tmp_path / "merged.sarif"
    a.write_text(json.dumps(_doc("planverify")))
    rc = main([str(a), str(tmp_path / "missing.sarif"),
               "--output", str(out)])
    assert rc == 0
    assert "absent" in capsys.readouterr().out
    assert len(json.loads(out.read_text())["runs"]) == 1


def test_malformed_input_fails(tmp_path):
    bad = tmp_path / "bad.sarif"
    out = tmp_path / "merged.sarif"
    bad.write_text("{}")
    assert main([str(bad), "--output", str(out)]) == 2


def test_merge_documents_preserves_run_objects():
    d1, d2 = _doc("a", 1), _doc("b")
    merged = merge_documents([d1, d2])
    assert merged["runs"][0] is d1["runs"][0]
    assert merged["runs"][1] is d2["runs"][0]


def test_empty_merge_is_valid_sarif(tmp_path):
    out = tmp_path / "merged.sarif"
    with pytest.raises(SystemExit):
        main(["--output", str(out)])  # inputs are required
    merged = merge_documents([])
    assert merged["runs"] == []
