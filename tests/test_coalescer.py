"""Serving-path query coalescer (server/coalescer.py): threaded stress
against a live PilosaHTTPServer asserting result-equivalence vs the
direct path, per-request error isolation, deadline ejection, 429 at
queue capacity, and the new observability surface. Rides alongside
test_concurrency.py (in-process races) — here the races cross the HTTP
boundary, which is the layer the coalescer lives at."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.server import API, serve
from pilosa_tpu.server.coalescer import QueryCoalescer
from pilosa_tpu.utils.stats import MemStatsClient

N_THREADS = 8
N_QUERIES = 6


def post(base, path, body, timeout=30):
    """(status, raw_bytes, headers) for a POST; 4xx captured, not raised."""
    data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(base + path, data=data, method="POST")
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def seed_data(holder):
    idx = holder.create_index("c")
    f = idx.create_field("f")
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 8, 4000).astype(np.uint64)
    cols = rng.integers(0, 3 * 2**20, 4000).astype(np.uint64)
    f.import_bits(rows, cols)
    idx.add_existence(cols)


@pytest.fixture
def pair(tmp_path):
    """Two identically-seeded live servers: one coalesced, one direct.
    Yields (coalesced_base, direct_base, coalesced_api)."""
    servers, holders, coalescers = [], [], []
    bases = []
    for name, with_coal in (("coal", True), ("direct", False)):
        h = Holder(str(tmp_path / name))
        h.open()
        seed_data(h)
        api = API(h, stats=MemStatsClient())
        if with_coal:
            api.coalescer = QueryCoalescer(
                api.executor, window_s=0.002, max_batch=32,
                stats=api.stats, tracer=api.tracer)
            api.coalescer.start()
            coalescers.append(api.coalescer)
            capi = api
        srv = serve(api, "localhost", 0, background=True)
        servers.append(srv)
        holders.append(h)
        bases.append(f"http://localhost:{srv.server_address[1]}")
    yield bases[0], bases[1], capi
    for srv in servers:
        srv.shutdown()
        srv.server_close()
    for c in coalescers:
        c.stop()
    for h in holders:
        h.close()


QUERIES = ([f"Count(Row(f={r}))" for r in range(8)]
           + [f"Row(f={r})" for r in range(4)]
           + ["TopN(f, n=3)", "Count(Union(Row(f=0), Row(f=1)))",
              "Count(Intersect(Row(f=2), Row(f=3)))"])


def test_coalesced_byte_identical_to_direct_threaded(pair):
    """N client threads x M queries against the coalesced server; every
    response body must be byte-identical to the direct server's answer
    for the same query."""
    coal, direct, _api = pair
    want = {q: post(direct, "/index/c/query", q.encode()) for q in QUERIES}
    for q, (st, _, _) in want.items():
        assert st == 200, q
    errors = []
    barrier = threading.Barrier(N_THREADS)

    def worker(tid):
        try:
            barrier.wait()
            for i in range(N_QUERIES):
                q = QUERIES[(tid * N_QUERIES + i) % len(QUERIES)]
                st, body, _ = post(coal, "/index/c/query", q.encode())
                assert st == 200, (q, body)
                assert body == want[q][1], (q, body, want[q][1])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_error_isolation_across_batchmates(pair):
    """Bad queries (unknown field) racing good ones: each bad request
    gets ITS 400; good batchmates still answer 200 with exact results."""
    coal, direct, _api = pair
    good = "Count(Row(f=1))"
    want = post(direct, "/index/c/query", good.encode())[1]
    errors = []
    barrier = threading.Barrier(N_THREADS)

    def worker(tid):
        try:
            barrier.wait()
            for i in range(N_QUERIES):
                if (tid + i) % 2:
                    st, body, _ = post(coal, "/index/c/query",
                                       b"Count(Row(nope=1))")
                    assert st == 400, (st, body)
                    assert b"error" in body
                else:
                    st, body, _ = post(coal, "/index/c/query",
                                       good.encode())
                    assert st == 200 and body == want, (st, body)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_writes_flush_and_stay_exact(pair):
    """Write-containing queries ride the coalescer (immediate flush, no
    dedup) while readers hammer the same field; no lost writes."""
    coal, _direct, _api = pair
    errors = []
    barrier = threading.Barrier(4)

    def writer(tid):
        try:
            barrier.wait()
            for i in range(20):
                st, body, _ = post(
                    coal, "/index/c/query",
                    f"Set({4 * 2**20 + tid * 1000 + i}, f={20 + tid})"
                    .encode())
                assert st == 200, body
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            barrier.wait()
            for _ in range(20):
                st, body, _ = post(coal, "/index/c/query",
                                   b"Count(Row(f=20))")
                assert st == 200, body
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(3)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for tid in range(3):
        st, body, _ = post(coal, "/index/c/query",
                           f"Count(Row(f={20 + tid}))".encode())
        assert json.loads(body)["results"] == [20], (tid, body)


def test_dedup_identical_queries_one_execution(pair):
    """Identical read-only queries landing in one window execute once
    and fan out; a long window + barrier makes the batch deterministic."""
    coal, direct, api = pair
    api.coalescer.window_s = 0.25  # hold the window open for the burst
    try:
        want = post(direct, "/index/c/query", b"Count(Row(f=5))")[1]
        results, errors = [], []
        barrier = threading.Barrier(12)

        def worker():
            try:
                barrier.wait()
                results.append(post(coal, "/index/c/query",
                                    b"Count(Row(f=5))"))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert all(st == 200 and body == want
                   for st, body, _ in results), results
        snap = api.stats.snapshot()
        assert snap["counters"].get("coalescer.deduped", 0) > 0
        assert snap["histograms"]["coalescer.batch_size"]["count"] >= 1
    finally:
        api.coalescer.window_s = 0.002


class _GatedExecutor:
    """Delegating executor whose execute paths block on a release event
    — pins the dispatcher mid-batch so queue-capacity and deadline
    behavior become deterministic."""

    def __init__(self, inner):
        self._inner = inner
        self.started = threading.Event()
        self.release = threading.Event()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _gate(self):
        self.started.set()
        assert self.release.wait(30), "gate never released"

    def execute_full(self, *a, **kw):
        self._gate()
        return self._inner.execute_full(*a, **kw)

    def execute_batch_shaped(self, *a, **kw):
        self._gate()
        return self._inner.execute_batch_shaped(*a, **kw)


@pytest.fixture
def gated(tmp_path):
    """Live server whose coalescer has a tiny queue + deadline and a
    gated executor. Yields (base, gate, api)."""
    h = Holder(str(tmp_path / "g"))
    h.open()
    seed_data(h)
    api = API(h, stats=MemStatsClient())
    gate = _GatedExecutor(api.executor)
    api.coalescer = QueryCoalescer(
        gate, window_s=0.0005, max_batch=8, max_queue=2,
        deadline_s=0.2, stats=api.stats, tracer=api.tracer)
    api.coalescer.start()
    srv = serve(api, "localhost", 0, background=True)
    yield f"http://localhost:{srv.server_address[1]}", gate, api
    gate.release.set()
    srv.shutdown()
    srv.server_close()
    api.coalescer.stop()
    h.close()


def test_overload_429_and_deadline_ejection(gated):
    base, gate, api = gated
    results = {}

    def bg(name):
        def run():
            results[name] = post(base, "/index/c/query",
                                 b"Count(Row(f=1))")
        t = threading.Thread(target=run)
        t.start()
        return t

    # First request: dispatcher claims it and blocks inside the gate.
    t1 = bg("inflight")
    assert gate.started.wait(10), "dispatcher never started the batch"
    # Two more fill the bounded pending queue (max_queue=2)...
    t2, t3 = bg("q1"), bg("q2")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        depth = api.stats.snapshot()["gauges"].get(
            "coalescer.queue_depth", 0)
        if depth >= 2:
            break
        time.sleep(0.01)
    # ...so the next submit is rejected up front: 429 + Retry-After.
    st, body, headers = post(base, "/index/c/query", b"Count(Row(f=1))")
    assert st == 429, (st, body)
    assert "Retry-After" in headers, headers
    assert b"capacity" in body
    # The two queued requests outlive their 200 ms queue deadline while
    # the dispatcher stays pinned: ejected with 408, never dispatched.
    t2.join(timeout=10)
    t3.join(timeout=10)
    assert results["q1"][0] == 408, results["q1"]
    assert results["q2"][0] == 408, results["q2"]
    snap = api.stats.snapshot()
    assert snap["counters"].get("coalescer.deadline_ejected", 0) >= 2
    assert snap["counters"].get("coalescer.rejected", 0) >= 1
    # Release the gate: the in-flight request completes normally.
    gate.release.set()
    t1.join(timeout=10)
    assert results["inflight"][0] == 200, results["inflight"]


def test_stats_and_metrics_surface(pair):
    """The acceptance-named stats reach both /debug/vars (expvar) and
    /metrics (Prometheus text)."""
    coal, _direct, api = pair
    barrier = threading.Barrier(6)

    def worker():
        barrier.wait()
        for _ in range(4):
            post(coal, "/index/c/query", b"Count(Row(f=1))")

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with urllib.request.urlopen(coal + "/debug/vars") as resp:
        snap = json.loads(resp.read())
    assert "coalescer.queue_depth" in snap["gauges"]
    assert "coalescer.batch_size" in snap["histograms"]
    assert snap["counters"].get("coalescer.admitted", 0) >= 24
    assert any(k.startswith("coalescer.flush.")
               for k in snap["counters"]), snap["counters"]
    with urllib.request.urlopen(coal + "/metrics") as resp:
        text = resp.read().decode()
    assert "pilosa_coalescer_queue_depth" in text
    # occupancy is unitless: no _seconds suffix on the summary
    assert "pilosa_coalescer_batch_size_bucket{" in text
    assert "pilosa_coalescer_batch_size_seconds" not in text
    assert "pilosa_coalescer_flush_" in text


def test_graceful_stop_drains_and_degrades(pair):
    """stop() executes everything already admitted, and later requests
    fall back to the direct path (same answers, no errors)."""
    coal, direct, api = pair
    want = post(direct, "/index/c/query", b"Count(Row(f=2))")[1]
    st, body, _ = post(coal, "/index/c/query", b"Count(Row(f=2))")
    assert st == 200 and body == want
    api.coalescer.stop()
    st, body, _ = post(coal, "/index/c/query", b"Count(Row(f=2))")
    assert st == 200 and body == want


def test_single_request_degrades_to_direct_path(pair):
    """A lone request (batch of one) takes the execute_full path and
    matches the direct server exactly."""
    coal, direct, _api = pair
    for q in ("Count(Row(f=3))", "TopN(f, n=2)"):
        assert (post(coal, "/index/c/query", q.encode())[1]
                == post(direct, "/index/c/query", q.encode())[1]), q


def test_config_coalescer_section(tmp_path):
    """[coalescer] TOML table flattens onto the coalescer_* fields; env
    spelling stays flat."""
    from pilosa_tpu.utils.config import load_config

    p = tmp_path / "c.toml"
    p.write_text('bind = "localhost:1"\n'
                 "[coalescer]\n"
                 "enabled = false\n"
                 "window-ms = 3.5\n"
                 "max_batch = 16\n")
    cfg = load_config(str(p))
    assert cfg.coalescer_enabled is False
    assert cfg.coalescer_window_ms == 3.5
    assert cfg.coalescer_max_batch == 16
    assert cfg.coalescer_max_queue == 256  # untouched default
    with pytest.raises(ValueError, match="unknown config key"):
        p.write_text("[coalescer]\nnot_a_key = 1\n")
        load_config(str(p))


# ---------------------------------------------- pipelined error paths
#
# The RTT-hiding pipelined dispatcher (PR 11) splits a flush into a
# begin half on the dispatcher thread and a _ShapedInFlight drain on
# the finalizer thread. A drain that THROWS must propagate to exactly
# the in-flight batch's requests, must not wedge the depth-1 double
# buffer, and must not leak into the next batch — pinned here (this
# file also runs under PILOSA_TPU_LOCK_CHECK=1 in the check.sh
# lock-order lane, so the error paths hold the lock discipline too).


@pytest.fixture
def plex(tmp_path):
    """In-process executor over the seeded index (the pipelined paths
    under test live below the HTTP layer)."""
    from pilosa_tpu.executor import Executor
    h = Holder(str(tmp_path / "pl"))
    h.open()
    seed_data(h)
    ex = Executor(h)
    ex.result_cache.enabled = False
    yield ex
    h.close()


def _pl_burst(co, queries, timeout=60):
    """Submit every query from its own thread; returns ({i: result},
    {i: exception}) with no worker left hanging."""
    results, errors = {}, {}
    barrier = threading.Barrier(len(queries))

    def worker(i, q):
        try:
            barrier.wait()
            results[i] = co.submit("c", q)
        except Exception as e:  # noqa: BLE001 — the subject under test
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i, q))
               for i, q in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), \
        "a submitter wedged — the pipeline lost its batch"
    return results, errors


_PL_QUERIES = [f"Count(Row(f={r % 8}))" if r % 2 else f"Row(f={r % 8})"
               for r in range(16)]


def test_pipelined_finalizer_exception_propagates_and_recovers(plex):
    """A finalizer-thread exception in the _ShapedInFlight drain lands
    on that batch's requests as per-request errors, the depth-1 buffer
    clears, and the very next burst serves correctly."""
    from pilosa_tpu.executor import Executor

    direct = {i: plex.execute_full("c", q)
              for i, q in enumerate(_PL_QUERIES)}
    orig_finish = Executor.execute_batch_shaped_finish
    state = {"boom": True}

    def failing_finish(self, sh):
        if state["boom"]:
            state["boom"] = False
            raise RuntimeError("injected drain failure")
        return orig_finish(self, sh)

    Executor.execute_batch_shaped_finish = failing_finish
    co = QueryCoalescer(plex, window_s=0.005, max_batch=8,
                        stats=MemStatsClient(), pipeline=True)
    co.start()
    try:
        results, errors = _pl_burst(co, _PL_QUERIES)
        assert co.pipelined_flushes >= 1
        assert errors, "the failing drain must surface somewhere"
        for i, e in errors.items():
            assert "injected drain failure" in str(e), (i, e)
        # Requests outside the failed batch are untouched — correct
        # results, not errors.
        for i, res in results.items():
            assert res == direct[i], (i, _PL_QUERIES[i])
        # The double buffer is clear (not wedged) ...
        with co._pl_cond:
            assert co._pl_pending is None
        # ... and the next burst is fully correct: the error did not
        # leak forward.
        results2, errors2 = _pl_burst(co, _PL_QUERIES)
        assert not errors2, errors2
        assert results2 == direct
        assert co.pipelined_flushes >= 2
    finally:
        # Restore FIRST: a stop() that raises (the wedge this test
        # exists to catch) must not leak the patch into later tests.
        Executor.execute_batch_shaped_finish = orig_finish
        co.stop()


def test_pipelined_drain_failure_respects_batch_boundaries(plex):
    """While batch K's drain fails on the finalizer, batch K+1 has
    already dispatched (the overlap the pipeline exists for): K+1's
    requests must still resolve correctly — errors stay inside K."""
    from pilosa_tpu.executor import Executor

    direct = {i: plex.execute_full("c", q)
              for i, q in enumerate(_PL_QUERIES)}
    orig_begin = Executor.execute_batch_shaped_begin
    orig_finish = Executor.execute_batch_shaped_finish
    second_begin = threading.Event()
    state = {"begins": 0, "doomed": None}
    lock = threading.Lock()

    def tagged_begin(self, reqs, profiles=None):
        sh = orig_begin(self, reqs, profiles=profiles)
        with lock:
            state["begins"] += 1
            if state["begins"] == 1:
                state["doomed"] = sh
            elif state["begins"] == 2:
                second_begin.set()
        return sh

    def gated_finish(self, sh):
        if sh is state["doomed"]:
            # Hold the drain until the NEXT batch is in flight, then
            # fail: the overlap window is provably open.
            second_begin.wait(timeout=30)
            raise RuntimeError("injected drain failure")
        return orig_finish(self, sh)

    Executor.execute_batch_shaped_begin = tagged_begin
    Executor.execute_batch_shaped_finish = gated_finish
    co = QueryCoalescer(plex, window_s=0.005, max_batch=4,
                        stats=MemStatsClient(), pipeline=True)
    co.start()
    try:
        results, errors = _pl_burst(co, _PL_QUERIES)
        assert second_begin.is_set(), \
            "test premise: a second batch dispatched during the drain"
        assert errors, "the doomed batch's requests must error"
        for i, e in errors.items():
            assert "injected drain failure" in str(e), (i, e)
        for i, res in results.items():
            assert res == direct[i], (i, _PL_QUERIES[i])
        with co._pl_cond:
            assert co._pl_pending is None
    finally:
        Executor.execute_batch_shaped_begin = orig_begin
        Executor.execute_batch_shaped_finish = orig_finish
        co.stop()


def test_pipelined_finalizer_base_exception_wrapped(plex):
    """A non-Exception BaseException from the drain must not kill the
    finalizer silently: items resolve with a CoalescerStopped wrapper
    and the loop keeps draining subsequent batches."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.server.coalescer import CoalescerStopped

    orig_finish = Executor.execute_batch_shaped_finish
    state = {"boom": True}

    def failing_finish(self, sh):
        if state["boom"]:
            state["boom"] = False
            raise SystemExit("injected non-Exception failure")
        return orig_finish(self, sh)

    Executor.execute_batch_shaped_finish = failing_finish
    co = QueryCoalescer(plex, window_s=0.005, max_batch=8,
                        stats=MemStatsClient(), pipeline=True)
    co.start()
    try:
        results, errors = _pl_burst(co, _PL_QUERIES)
        assert errors
        for e in errors.values():
            assert isinstance(e, CoalescerStopped), e
        results2, errors2 = _pl_burst(co, _PL_QUERIES[:8])
        assert not errors2, errors2
        assert len(results2) == 8
    finally:
        Executor.execute_batch_shaped_finish = orig_finish
        co.stop()
