"""Generation-keyed cross-request result cache + device rank cache
(executor/result_cache.py, core/cache.RANK_CACHE, ROADMAP item 3):
request/eval tier hit semantics, implicit write invalidation through
fragment generations ([read, write, read] incl. fusion and a two-node
cluster), bit-exactness against the cache-off path, the hardened
RankedCache/LRUCache/NopCache units, rank-cache hit/patch/rebuild
legs, and the ledger/metrics/hotspots/health surfaces."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.cache import (
    LRUCache, NopCache, RANK_CACHE, RankedCache,
)
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.result_cache import ResultCache
from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text


@pytest.fixture(autouse=True)
def _reset_rank_cache():
    """RANK_CACHE is process-wide (the LEDGER/WORKLOAD convention):
    every test starts empty with defaults and leaves them behind."""
    RANK_CACHE.clear()
    RANK_CACHE.configure(enabled=True, max_entries=64)
    yield
    RANK_CACHE.clear()
    RANK_CACHE.configure(enabled=True, max_entries=64)


def _seed(h):
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 8, 4000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 4000).astype(np.uint64)
    f.import_bits(rows, cols)
    g.import_bits(rows[::2], cols[::2])
    idx.create_field("v", FieldOptions(type="int", min=0, max=10000))
    vcols = rng.integers(0, 2 * SHARD_WIDTH, 500).astype(np.uint64)
    idx.field("v").import_values(
        vcols, rng.integers(0, 10000, 500).astype(np.int64))
    idx.add_existence(cols)
    return idx


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    _seed(h)
    executor = Executor(h)
    yield executor
    h.close()


def count_dispatches(monkeypatch):
    """Stub Executor._call_program — the single funnel every compiled
    program invocation passes through (the test_fusion idiom)."""
    calls = []
    orig = Executor._call_program

    def stub(self, fn, *args):
        calls.append(fn)
        return orig(self, fn, *args)

    monkeypatch.setattr(Executor, "_call_program", stub)
    return calls


# ------------------------------------------------- core/cache.py units


def test_ranked_cache_add_top_and_zero_removal():
    c = RankedCache(size=4)
    for r, n in [(1, 10), (2, 20), (3, 5)]:
        c.add(r, n)
    assert c.top() == [(2, 20), (1, 10), (3, 5)]
    c.add(3, 0)  # zero count removes
    assert c.top() == [(2, 20), (1, 10)]
    assert len(c) == 2


def test_ranked_cache_recalculate_prunes_to_size_and_saturates():
    c = RankedCache(size=4)  # threshold factor 1.1 -> prune above 4
    for r in range(10):
        c.add(r, r + 1)
    # The 5th add crossed the bound: _recalculate keeps exactly the
    # top-`size` by (count desc, row asc) and latches `saturated`, so
    # rows 5..9 (added after) were refused.
    assert c.top() == [(4, 5), (3, 4), (2, 3), (1, 2)]
    assert c.saturated
    c.add(50, 100)
    assert 50 not in c.counts, "saturated latch refuses further adds"
    # invalidate() resets the latch.
    c.invalidate()
    assert len(c) == 0 and not c.saturated
    c.add(50, 1)
    assert c.counts[50] == 1


def test_ranked_cache_invalidate_rebinds_not_clears():
    """invalidate() must REBIND counts (O(1)) — a lock-free reader
    holding the old dict keeps a consistent snapshot."""
    c = RankedCache(size=8)
    c.add(1, 5)
    before = c.counts
    c.invalidate()
    assert before == {1: 5}, "reader snapshot must survive invalidate"
    assert c.counts == {} and c.counts is not before


def test_ranked_cache_concurrent_adds_and_invalidates():
    c = RankedCache(size=64)
    errs = []

    def worker(base):
        try:
            for i in range(500):
                c.add(base + (i % 80), i + 1)
                if i % 97 == 0:
                    c.invalidate()
                c.top()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(b,))
          for b in (0, 100, 200, 300)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs


def test_lru_cache_recency_and_eviction():
    c = LRUCache(size=3)
    for r in (1, 2, 3):
        c.add(r, r * 10)
    assert c.get(1) == 10  # touch 1 -> 2 is now oldest
    c.add(4, 40)
    assert c.get(2) == 0, "least-recently-used entry evicted"
    assert sorted(c.ids()) == [1, 3, 4]
    assert c.top() == [(4, 40), (3, 30), (1, 10)]
    c.invalidate()
    assert len(c) == 0


def test_nop_cache_stores_nothing():
    c = NopCache()
    c.add(1, 10)
    assert c.top() == [] and len(c) == 0


# ------------------------------------------- ResultCache (store) units


def test_result_cache_hit_miss_and_generation_drop():
    rc = ResultCache(max_bytes=1 << 20)
    rc.fill("k", gen=(1,), value="v", nbytes=100)
    assert rc.lookup("k", (1,)) == "v"
    assert rc.hits["eval"] == 1
    # Stale generation: dropped immediately, not just missed.
    assert rc.lookup("k", (2,)) is None
    assert rc.invalidations == 1 and len(rc) == 0
    assert rc.lookup("k", (2,)) is None
    assert rc.misses["eval"] == 2


def test_result_cache_lru_byte_budget_and_oversized_refusal():
    rc = ResultCache(max_bytes=250)
    for i in range(3):
        rc.fill(i, (0,), i, nbytes=100)
    assert len(rc) == 2 and rc.bytes == 200, "byte budget evicts LRU"
    assert rc.evictions == 1
    assert rc.lookup(0, (0,)) is None  # 0 was the LRU victim
    # One oversized value must not flush the whole cache.
    rc.fill("big", (0,), "x", nbytes=10_000)
    assert len(rc) == 2 and rc.lookup("big", (0,)) is None
    rc.clear()
    assert rc.bytes == 0 and len(rc) == 0


def test_result_cache_configure_shrink_updates_ledger():
    from pilosa_tpu.utils.memledger import LEDGER
    c = ResultCache(max_bytes=100)
    try:
        c.fill("a", 1, "va", 40)
        c.fill("b", 1, "vb", 40)
        assert c.bytes == 80
        c.configure(max_bytes=50)
        assert c.bytes == 40 and c.evictions == 1
        ent = [e for e in LEDGER.entries("result_cache")
               if e.get("entries") is not None and e["bytes"] == c.bytes]
        assert ent, "ledger must reflect the post-shrink bytes"
    finally:
        c.clear()


def test_result_cache_request_tier_validator():
    rc = ResultCache(max_bytes=1 << 20)
    rc.fill("rk", gen={"dep": 1}, value={"results": [1]}, nbytes=50,
            tier="request")
    assert rc.lookup_request("rk", lambda d: d["dep"] == 1) \
        == {"results": [1]}
    assert rc.hits["request"] == 1
    # Failed revalidation drops the entry.
    assert rc.lookup_request("rk", lambda d: False) is None
    assert rc.invalidations == 1 and len(rc) == 0


def test_result_cache_env_kill_switch(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_RESULT_CACHE", "0")
    rc = ResultCache()
    assert not rc.enabled
    rc.configure(enabled=True)  # config can never re-enable past env
    assert not rc.enabled
    rc.fill("k", (1,), "v", 10)
    assert rc.lookup("k", (1,)) is None


def test_rank_cache_env_kill_switch(monkeypatch):
    from pilosa_tpu.core.cache import RankCacheStore
    monkeypatch.setenv("PILOSA_TPU_RANK_CACHE", "0")
    store = RankCacheStore()
    assert not store.enabled
    store.configure(enabled=True)
    assert not store.enabled


# --------------------------------------------------- eval-tier caching


def test_eval_tier_repeat_serves_without_dispatch(ex, monkeypatch):
    direct = [ex.execute("i", f"Count(Row(f={r}))")[0] for r in range(4)]
    calls = count_dispatches(monkeypatch)
    again = [ex.execute("i", f"Count(Row(f={r}))")[0] for r in range(4)]
    assert again == direct, "cached counts must be bit-identical"
    assert calls == [], "warm repeats must not dispatch anything"
    assert ex.result_cache.hits["eval"] == 4


def test_eval_tier_row_results_bit_identical(ex, monkeypatch):
    direct = ex.execute("i", "Row(f=3)")[0].columns().tolist()
    calls = count_dispatches(monkeypatch)
    cached = ex.execute("i", "Row(f=3)")[0]
    assert cached.columns().tolist() == direct
    assert cached.count() == len(direct)
    assert calls == []


def test_eval_tier_whitespace_variant_hits(ex):
    ex.execute("i", "Count(Row(f=1))")
    h0 = ex.result_cache.hits["eval"]
    # Different request text, same staged fingerprint: the eval tier
    # keys on the semantic (sig, rows, params) identity, not the PQL
    # spelling.
    ex.execute("i", "Count( Row( f = 1 ) )")
    assert ex.result_cache.hits["eval"] == h0 + 1


def test_read_write_read_generation_invalidation(ex, tmp_path):
    """The satellite invalidation contract: [read, write, read] — the
    second read must MISS (generation bump) and match the uncached
    result bit-exactly."""
    h2 = Holder(str(tmp_path / "ref"))
    h2.open()
    _seed(h2)
    ref = Executor(h2)
    ref.result_cache.enabled = False
    try:
        (c0,) = ex.execute("i", "Count(Row(f=5))")
        assert c0 == ref.execute("i", "Count(Row(f=5))")[0]
        free_col = 2 * SHARD_WIDTH - 7
        m0 = ex.result_cache.misses["eval"]
        ex.execute("i", f"Set({free_col}, f=5)")
        ref.execute("i", f"Set({free_col}, f=5)")
        (c1,) = ex.execute("i", "Count(Row(f=5))")
        assert ex.result_cache.misses["eval"] == m0 + 1, \
            "post-write read must miss, not serve the stale entry"
        assert ex.result_cache.invalidations >= 1
        assert c1 == c0 + 1 == ref.execute("i", "Count(Row(f=5))")[0]
    finally:
        h2.close()


def test_read_write_read_through_fusion_under_lock_check(
        tmp_path, monkeypatch):
    """The same contract through the FUSION path (execute_batch) with
    the lock-order checker live: the head read may serve from cache,
    the tail read must observe the write."""
    monkeypatch.setenv("PILOSA_TPU_LOCK_CHECK", "1")
    from pilosa_tpu.utils.locks import (
        lock_order_violations, reset_lock_order,
    )
    reset_lock_order()
    h = Holder(str(tmp_path / "lc"))
    h.open()
    _seed(h)
    e = Executor(h)
    try:
        (c0,) = e.execute("i", "Count(Row(f=5))")
        assert e.result_cache.hits["eval"] == 0
        free_col = 2 * SHARD_WIDTH - 11
        out = e.execute_batch([
            ("i", "Count(Row(f=5))", None),       # warm: cache hit
            ("i", f"Set({free_col}, f=5)", None),
            ("i", "Count(Row(f=5))", None),       # must miss + re-eval
        ])
        assert out[0][0][0] == c0
        assert e.result_cache.hits["eval"] == 1
        assert out[2][0][0] == c0 + 1, "tail read must observe the write"
        # And the refreshed fill is immediately servable.
        assert e.execute("i", "Count(Row(f=5))")[0] == c0 + 1
        assert e.result_cache.hits["eval"] == 2
        assert lock_order_violations() == []
    finally:
        h.close()
        reset_lock_order()


def test_fully_hitting_group_never_launches(ex, monkeypatch):
    """A fused group whose members ALL hit the eval tier never forms,
    let alone launches — zero dispatches, zero fused groups."""
    queries = [f"Count(Row(f={r}))" for r in range(6)]
    direct = [ex.execute("i", q)[0] for q in queries]  # warm the tier
    calls = count_dispatches(monkeypatch)
    fd0 = ex.fused_dispatches
    out = ex.execute_batch([("i", q, None) for q in queries])
    assert [r[0][0] for r in out] == direct
    assert calls == []
    assert ex.fused_dispatches == fd0
    assert ex.result_cache.hits["eval"] >= len(queries)


def test_eval_tier_same_named_fields_across_indexes_coexist(ex):
    """Two indexes with same-named fields and matching bank shapes
    must hold SEPARATE eval-tier entries: without the index name in
    the key they'd collide and evict each other on every lookup
    (generations always differ via process-unique fragment epochs), so
    alternating traffic would run at a 0% hit ratio."""
    h = ex.holder
    idx2 = h.create_index("j")
    f2 = idx2.create_field("f")
    rng = np.random.default_rng(7)  # the _seed layout, shifted rows
    rows = rng.integers(0, 8, 4000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 4000).astype(np.uint64)
    f2.import_bits(rows, cols)
    idx2.add_existence(cols)
    a0 = ex.execute("i", "Count(Row(f=1))")[0]
    b0 = ex.execute("j", "Count(Row(f=1))")[0]
    inv0 = ex.result_cache.invalidations
    h0 = ex.result_cache.hits["eval"]
    for _ in range(2):
        assert ex.execute("i", "Count(Row(f=1))")[0] == a0
        assert ex.execute("j", "Count(Row(f=1))")[0] == b0
    assert ex.result_cache.hits["eval"] == h0 + 4
    assert ex.result_cache.invalidations == inv0, \
        "alternating indexes must not evict each other's entries"


def test_eval_tier_shard_restriction_is_part_of_the_key(ex):
    full = ex.execute("i", "Count(Row(f=2))")[0]
    only0 = ex.execute("i", "Count(Row(f=2))", shards=[0])[0]
    assert only0 != full, "seed data spans two shards"
    # Repeat each: both must hit their OWN entry, not each other's.
    assert ex.execute("i", "Count(Row(f=2))")[0] == full
    assert ex.execute("i", "Count(Row(f=2))", shards=[0])[0] == only0


# ------------------------------------------------ request-tier caching


def test_request_tier_execute_full_hits_and_write_invalidates(
        ex, monkeypatch):
    r0 = ex.execute_full("i", "Count(Row(f=1))")
    calls = count_dispatches(monkeypatch)
    assert ex.execute_full("i", "Count(Row(f=1))") == r0
    assert ex.result_cache.hits["request"] == 1
    assert calls == []
    free_col = 2 * SHARD_WIDTH - 13
    ex.execute("i", f"Set({free_col}, f=1)")
    r1 = ex.execute_full("i", "Count(Row(f=1))")
    assert r1["results"][0] == r0["results"][0] + 1
    assert ex.result_cache.hits["request"] == 1, \
        "post-write repeat must revalidate and miss"


def test_request_tier_row_attr_mutation_invalidates(ex):
    """Row-attr writes do NOT bump fragment generations — the request
    tier must still invalidate through the attr store's own stamp."""
    ex.execute("i", 'SetRowAttrs(f, 1, cat="x")')
    r0 = ex.execute_full("i", "Row(f=1)")
    assert r0["results"][0]["attrs"] == {"cat": "x"}
    assert ex.execute_full("i", "Row(f=1)") == r0  # hit
    h0 = ex.result_cache.hits["request"]
    ex.execute("i", 'SetRowAttrs(f, 1, cat="y")')
    r1 = ex.execute_full("i", "Row(f=1)")
    assert r1["results"][0]["attrs"] == {"cat": "y"}
    assert ex.result_cache.hits["request"] == h0, \
        "attr-stale entry must not serve"


def test_request_tier_excludes_non_staged_calls(ex):
    for q in ("TopN(f, n=2)", 'Min(field="v")', 'Sum(field="v")'):
        r0 = ex.execute_full("i", q)
        assert ex.execute_full("i", q) == r0
    assert ex.result_cache.hits["request"] == 0, \
        "only the Count/bitmap family rides the request tier"


def test_forced_profile_bypasses_lookup_but_still_fills(ex):
    from pilosa_tpu.utils.profile import QueryProfile
    ex.execute_full("i", "Count(Row(f=4))")  # warm both tiers
    prof = QueryProfile("i", "Count(Row(f=4))")
    prof.forced = True
    r = ex.execute_full("i", "Count(Row(f=4))", profile=prof)
    assert ex.result_cache.hits["request"] == 0
    # The forced profile's tree must describe a REAL execution.
    evals = [n for op in prof.ops for n in op.children
             if n.name.startswith("eval:")]
    assert evals and "cacheHit" not in evals[0].attrs
    assert r["results"][0] == ex.execute("i", "Count(Row(f=4))")[0]


def test_sampled_profile_hit_gets_cache_attribution(ex):
    from pilosa_tpu.utils.profile import QueryProfile
    ex.execute_full("i", "Count(Row(f=6))")
    prof = QueryProfile("i", "Count(Row(f=6))")  # forced=False default
    ex.execute_full("i", "Count(Row(f=6))", profile=prof)
    assert ex.result_cache.hits["request"] == 1
    ops = [op for op in prof.ops if op.name == "cache"]
    assert ops and ops[0].attrs["cacheHit"] is True


# ------------------------------------------------- device rank cache


@pytest.fixture
def topn_ex(tmp_path, monkeypatch):
    """Executor over a field with known TopN standings, with the host
    fragment-cache warm path disabled so filterless TopN deterministically
    reaches the device rank cache."""
    h = Holder(str(tmp_path / "t"))
    h.open()
    idx = h.create_index("t")
    f = idx.create_field("tf")
    rows, cols = [], []
    # row r gets (20 - 2r) columns, spread over two shards.
    for r in range(8):
        for c in range(20 - 2 * r):
            rows.append(r)
            cols.append(c * 3 + (SHARD_WIDTH if c % 2 else 0))
    f.import_bits(np.asarray(rows, np.uint64),
                  np.asarray(cols, np.uint64))
    idx.add_existence(np.asarray(cols, np.uint64))
    monkeypatch.setattr(Executor, "_topn_cached_counts",
                        lambda self, view, shards: None)
    e = Executor(h)
    yield e
    h.close()


def test_rank_cache_rebuild_then_hit_bit_identical(topn_ex):
    e = topn_ex
    RANK_CACHE.configure(enabled=False)
    baseline = e.execute("t", "TopN(tf, n=3)")[0].pairs
    baseline_all = e.execute("t", "TopN(tf)")[0].pairs
    RANK_CACHE.configure(enabled=True)
    assert e.execute("t", "TopN(tf, n=3)")[0].pairs == baseline
    assert e.rank_cache_rebuilds == 1
    # Warm: the unrestricted top-k leg and the fetch leg both hit.
    assert e.execute("t", "TopN(tf, n=3)")[0].pairs == baseline
    assert e.execute("t", "TopN(tf)")[0].pairs == baseline_all
    assert e.rank_cache_hits == 2
    assert len(RANK_CACHE) == 1


def test_rank_cache_patch_after_small_write(topn_ex):
    e = topn_ex
    assert e.execute("t", "TopN(tf, n=3)")[0].pairs  # build the vector
    assert e.rank_cache_rebuilds == 1
    # One written row: versions move, rows_changed_since names it ->
    # the incremental gather+scatter patch, not a rebuild.
    e.execute("t", "Set(299, tf=7)")
    RANK_CACHE.configure(enabled=False)
    expect = e.execute("t", "TopN(tf, n=8)")[0].pairs
    RANK_CACHE.configure(enabled=True)
    got = e.execute("t", "TopN(tf, n=8)")[0].pairs
    assert got == expect
    assert e.rank_cache_patches == 1
    assert e.rank_cache_rebuilds == 1, "small churn must not rebuild"
    assert (7, 7) in got  # row 7 had 6 columns, now 7


def test_rank_cache_threshold_and_filter_paths(topn_ex):
    e = topn_ex
    RANK_CACHE.configure(enabled=False)
    thr = e.execute("t", "TopN(tf, n=8, threshold=15)")[0].pairs
    filt = e.execute("t", "TopN(tf, Row(tf=0), n=2)")[0].pairs
    RANK_CACHE.configure(enabled=True)
    assert e.execute("t", "TopN(tf, n=8, threshold=15)")[0].pairs == thr
    assert all(c >= 15 for _, c in thr) and thr
    # Filtered TopN needs real bitmaps: it must BYPASS the rank cache.
    consults0 = (e.rank_cache_hits + e.rank_cache_rebuilds
                 + e.rank_cache_patches)
    assert e.execute("t", "TopN(tf, Row(tf=0), n=2)")[0].pairs == filt
    assert (e.rank_cache_hits + e.rank_cache_rebuilds
            + e.rank_cache_patches) == consults0, \
        "filtered call must not consult the rank cache"


def test_rank_cache_lru_eviction_and_ledger_accounting(topn_ex):
    from pilosa_tpu.utils.memledger import LEDGER
    e = topn_ex
    e.execute("t", "TopN(tf, n=3)")
    ents = LEDGER.entries("rank_cache")
    assert len(ents) == 1 and ents[0]["bytes"] > 0
    assert LEDGER.snapshot()["categories"]["rank_cache"]["bytes"] \
        == ents[0]["bytes"]
    # Entry-count LRU: shrink the bound, insert another key.
    RANK_CACHE.configure(max_entries=1)
    e.execute("t", "TopN(tf, n=3)", shards=[0])
    assert len(RANK_CACHE) == 1 and RANK_CACHE.evictions == 1
    assert len(LEDGER.entries("rank_cache")) == 1, \
        "evicted vector must leave the ledger"
    # View close drops the remaining entries + ledger rows.
    e.holder.index("t").field("tf").view("standard").close()
    assert len(RANK_CACHE) == 0
    assert LEDGER.entries("rank_cache") == []


def test_rank_cache_append_grown_bank_stays_exact(tmp_path, monkeypatch):
    """An append-grown bank (_patch_bank places a NEW mid-range row at
    the END) breaks the slots-ascend-with-row-id layout: the device
    top-k leg must refuse it (its index tie-break would misattribute
    counts to sorted-position rows) and the rank entry built for the
    old layout must read as misaligned — rebuild, never a wrong-slot
    patch."""
    h = Holder(str(tmp_path / "ag"))
    h.open()
    idx = h.create_index("ag")
    f = idx.create_field("af")
    rows, cols = [], []
    for r, n_cols in ((0, 5), (5, 4), (10, 3)):
        for c in range(n_cols):
            rows.append(r)
            cols.append(c * 2)
    f.import_bits(np.asarray(rows, np.uint64),
                  np.asarray(cols, np.uint64))
    idx.add_existence(np.asarray(cols, np.uint64))
    monkeypatch.setattr(Executor, "_topn_cached_counts",
                        lambda self, view, shards: None)
    e = Executor(h)
    try:
        assert e.execute("ag", "TopN(af)")[0].pairs == \
            [(0, 5), (5, 4), (10, 3)]
        assert e.rank_cache_rebuilds == 1
        # New row 7 sorts BETWEEN cached rows but appends at the bank's
        # end: slot order is now (0, 5, 10, 7).
        e.execute("ag", "Set(100, af=7)")
        RANK_CACHE.configure(enabled=False)
        expect = e.execute("ag", "TopN(af, n=4)")[0].pairs
        RANK_CACHE.configure(enabled=True)
        assert expect == [(0, 5), (5, 4), (10, 3), (7, 1)]
        got = e.execute("ag", "TopN(af, n=4)")[0].pairs
        assert got == expect, \
            "append-grown layout must not swap rows 7 and 10"
        assert e.rank_cache_patches == 0, \
            "old-layout entry must not be patched with new-layout slots"
        assert e.rank_cache_rebuilds == 2
        # Warm repeats on the grown layout stay exact (host-merge leg).
        assert e.execute("ag", "TopN(af, n=4)")[0].pairs == expect
        assert e.rank_cache_hits == 1
    finally:
        h.close()


def test_rank_cache_fragment_recreation_forces_rebuild(topn_ex):
    """A fragment recreated in-process (pop + reload across a resize)
    starts a fresh version epoch with empty _row_versions, so
    rows_changed_since() cannot name writes made in the OLD
    incarnation. Both the rank-cache patch leg and the bank patch must
    detect the epoch change and rebuild — an attribution-based patch
    would silently keep pre-recreation counts."""
    from pilosa_tpu.core.fragment import Fragment
    e = topn_ex
    assert e.execute("t", "TopN(tf, n=8)")[0].pairs  # build the vector
    # A write the old incarnation attributes...
    e.execute("t", "Set(299, tf=7)")
    view = e.holder.index("t").field("tf").view("standard")
    for frag in view.fragments.values():
        # ...then simulate recreation: fresh epoch, attribution gone.
        frag._row_versions.clear()
        frag.version = next(Fragment._VERSION_EPOCH) << 48
    # And one post-recreation write providing a non-empty (but
    # incomplete) changed-rows set for the old-epoch entry.
    e.execute("t", "Set(301, tf=0)")
    RANK_CACHE.configure(enabled=False)
    expect = e.execute("t", "TopN(tf, n=8)")[0].pairs
    RANK_CACHE.configure(enabled=True)
    got = e.execute("t", "TopN(tf, n=8)")[0].pairs
    assert got == expect, "epoch change must rebuild, not under-patch"
    assert e.rank_cache_patches == 0
    assert (7, 7) in got and (0, 21) in got


def test_request_fill_racing_write_cannot_validate_stale(ex, monkeypatch):
    """Stamp-then-read: a write landing AFTER the dependency stamps
    are captured but BEFORE the banks are read leaves the stored stamp
    behind the current one, so the pre-write response filled into the
    cache can never validate — the repeat must miss and observe the
    write (with read-then-stamp ordering the stale response would
    validate forever)."""
    h = ex.holder
    orig = Executor._get_bank
    fired = []

    def racing(self, idx, key, shards, rows_needed=None):
        bank = orig(self, idx, key, shards, rows_needed=rows_needed)
        if not fired:
            fired.append(1)
            h.index("i").field("f").import_bits(
                np.asarray([1], np.uint64),
                np.asarray([2 * SHARD_WIDTH - 23], np.uint64))
        return bank

    monkeypatch.setattr(Executor, "_get_bank", racing)
    r0 = ex.execute_full("i", "Count(Row(f=1))")
    monkeypatch.setattr(Executor, "_get_bank", orig)
    r1 = ex.execute_full("i", "Count(Row(f=1))")
    assert ex.result_cache.hits["request"] == 0, \
        "the stale fill must fail validation, not hit"
    assert r1["results"][0] == r0["results"][0] + 1


def test_rank_cache_disabled_sweeps_identically(topn_ex):
    e = topn_ex
    warm = e.execute("t", "TopN(tf, n=4)")[0].pairs
    assert e.rank_cache_rebuilds == 1
    RANK_CACHE.configure(enabled=False)
    assert e.execute("t", "TopN(tf, n=4)")[0].pairs == warm
    assert e.rank_cache_rebuilds + e.rank_cache_hits == 1, \
        "disabled store must not be consulted"


# -------------------------------------------------- two-node cluster


def test_cluster_two_node_read_write_read(tmp_path):
    """Interleaved [read, write, read] across two real nodes: the
    second read must miss (generation bump on the owning node) and
    match the uncached result bit-exactly."""
    from tests.test_cluster import req, run_cluster
    nodes = run_cluster(tmp_path, 2)
    try:
        base = nodes[0].uri
        req(base, "POST", "/index/ci", {"options": {}})
        req(base, "POST", "/index/ci/field/f", {"options": {}})
        for col in range(0, 40, 2):
            req(base, "POST", "/index/ci/query",
                body=f"Set({col}, f=1)".encode())
        r0 = req(base, "POST", "/index/ci/query",
                 body=b"Count(Row(f=1))")
        assert r0["results"][0] == 20
        # Warm repeat: some node's eval tier serves it.
        assert req(base, "POST", "/index/ci/query",
                   body=b"Count(Row(f=1))") == r0
        hits0 = sum(n.api.executor.result_cache.hits["eval"]
                    for n in nodes)
        misses0 = sum(n.api.executor.result_cache.misses["eval"]
                      for n in nodes)
        assert hits0 >= 1
        # Write THROUGH THE OTHER NODE (routed to the shard owner).
        req(nodes[1].uri, "POST", "/index/ci/query",
            body=b"Set(41, f=1)")
        r1 = req(base, "POST", "/index/ci/query",
                 body=b"Count(Row(f=1))")
        assert r1["results"][0] == 21, "second read must see the write"
        assert sum(n.api.executor.result_cache.misses["eval"]
                   for n in nodes) > misses0, \
            "post-write read must miss the eval tier somewhere"
    finally:
        for n in nodes:
            n.stop()


# ------------------------------------------------------ HTTP surfaces


def test_http_surfaces_metrics_hotspots_health_memory(tmp_path):
    from pilosa_tpu.server import API, serve
    h = Holder(str(tmp_path / "s"))
    h.open()
    _seed(h)
    api = API(h, stats=MemStatsClient())
    srv = serve(api, "localhost", 0, background=True)
    base = f"http://localhost:{srv.server_address[1]}"

    def get(path):
        return json.loads(urllib.request.urlopen(
            base + path, timeout=30).read())

    try:
        for _ in range(4):
            for r in range(4):
                body = f"Count(Row(f={r}))".encode()
                urllib.request.urlopen(
                    base + "/index/i/query", data=body).read()
        rc = api.executor.result_cache
        assert rc.hits["request"] + rc.hits["eval"] >= 12

        # /metrics: event-time counters + scrape-time gauges.
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "pilosa_result_cache_hits_total" in text
        assert "pilosa_result_cache_misses_total" in text
        assert "pilosa_result_cache_bytes" in text
        assert "pilosa_rank_cache_entries" in text

        # /debug/hotspots: observed hit ratio joined against the
        # estimator's predicted savings — same fingerprints, one doc.
        doc = get("/debug/hotspots")
        assert doc["resultCache"]["hits"] >= 12
        assert doc["resultCache"]["hitRatio"] > 0.5
        obs = doc["opportunity"]["observed"]
        assert obs["hits"] == doc["resultCache"]["hits"]
        assert "predictedTotalEstSavedS" in obs
        assert "rankCache" in doc

        # /internal/health: cache stanzas ride the health document.
        health = get("/internal/health")
        assert health["resultCache"]["enabled"]
        assert health["resultCache"]["hits"] >= 12
        assert {"hits", "patches", "rebuilds"} \
            <= set(health["rankCache"])

        # /debug/memory: cached host bytes are ledgered (category
        # result_cache, HOST side) and the totals stay provable.
        mem = get("/debug/memory")
        assert mem["totalBytes"] == sum(
            c["bytes"] for c in mem["categories"].values())
        # The category totals THIS cache's bytes (plus any other live
        # embedded executor's — each instance is owner-scoped).
        assert rc.bytes > 0
        assert mem["categories"]["result_cache"]["bytes"] >= rc.bytes
    finally:
        srv.shutdown()
        srv.server_close()
        h.close()


def test_prometheus_counter_names(ex):
    stats = MemStatsClient()
    ex.result_cache.stats = stats
    ex.execute("i", "Count(Row(f=1))")
    ex.execute("i", "Count(Row(f=1))")
    ex.result_cache.publish(stats)
    text = prometheus_text(stats)
    assert "pilosa_result_cache_hits_total 1" in text
    assert "pilosa_result_cache_eval_hits_total 1" in text
    assert "pilosa_result_cache_hit_ratio" in text


def test_timeline_cache_lane_slice_on_hit(ex):
    from pilosa_tpu.utils.profile import QueryProfile
    from pilosa_tpu.utils.timeline import TIMELINE
    TIMELINE.configure(enabled=True, sample_every=1)
    try:
        ex.execute_full("i", "Count(Row(f=2))")
        tl = TIMELINE.begin(None, "i")
        prof = QueryProfile("i", "Count(Row(f=2))")
        prof.timeline = tl
        ex.execute_full("i", "Count(Row(f=2))", profile=prof)
        TIMELINE.finish(tl)
        (req,) = TIMELINE.requests(last=1)
        assert any(name == "cache" for name, *_ in req.events), \
            req.events
    finally:
        TIMELINE.reset()
