"""Binary wire codec (server/wire.py) tests: roundtrip fidelity with the
JSON shapes the HTTP layer speaks, bulk integer packing, error handling."""

import json

import numpy as np
import pytest

from pilosa_tpu.server import wire


@pytest.mark.parametrize("v", [
    None, True, False, 0, -1, 42, 2**62, -(2**62), 3.5, -0.0,
    "", "héllo", b"", b"\x00\xffraw",
    [], [1, 2, 3], [0, 2**20, 2**40], list(range(1000)),
    [-5, 7, -9], ["a", 1, None, True],
    {}, {"a": 1}, {"results": [{"columns": [1, 2, 3], "attrs": {"x": "y"}}]},
    {"rows": [1, 1, 2], "columns": [5, 6, 7], "shard": 0},
    [{"id": 3, "count": 2}, {"id": 4, "count": 1}],
    {"nested": {"deep": [[1], [2, 3], []]}},
])
def test_roundtrip(v):
    assert wire.loads(wire.dumps(v)) == v


def test_u64_range_values():
    big = 2**64 - 1
    assert wire.loads(wire.dumps(big)) == big
    assert wire.loads(wire.dumps([big, 1])) == [big, 1]
    assert wire.loads(wire.dumps([big])) == [big]  # 1-elem list stays a list


def test_numpy_arrays_decode_to_lists():
    a = np.array([1, 5, 9], dtype=np.uint64)
    assert wire.loads(wire.dumps(a)) == [1, 5, 9]
    b = np.array([-3, 0, 3], dtype=np.int32)
    assert wire.loads(wire.dumps(b)) == [-3, 0, 3]
    assert wire.loads(wire.dumps({"columns": a})) == {"columns": [1, 5, 9]}


def test_matches_json_semantics_on_query_response():
    resp = {"results": [{"columns": list(range(500)),
                         "keys": [str(i) for i in range(3)]},
                        [{"id": 1, "count": 9}],
                        7,
                        {"value": -12, "count": 4},
                        True]}
    assert wire.loads(wire.dumps(resp)) == json.loads(json.dumps(resp))


def test_bool_first_list_uses_generic_path():
    assert wire.loads(wire.dumps([True, False])) == [True, False]


def test_bad_magic_rejected():
    with pytest.raises(wire.WireError):
        wire.loads(b"nope")
    with pytest.raises(wire.WireError):
        wire.loads(b"")


def test_truncated_rejected():
    data = wire.dumps({"columns": list(range(100))})
    with pytest.raises(wire.WireError):
        wire.loads(data[:-5])


def test_trailing_bytes_rejected():
    with pytest.raises(wire.WireError):
        wire.loads(wire.dumps(1) + b"x")


def test_mixed_numeric_lists_round_trip_exactly():
    assert wire.loads(wire.dumps([1, 2.5])) == [1, 2.5]
    assert wire.loads(wire.dumps([1, True])) == [1, True]
    assert wire.loads(wire.dumps([0, None, 3])) == [0, None, 3]


def test_oversize_int_raises_typeerror():
    with pytest.raises(TypeError):
        wire.dumps(1 << 70)
    with pytest.raises(TypeError):
        wire.dumps(-(1 << 63) - 1)


def test_truncated_headers_raise_wireerror():
    for bad in (b"PW1\x00\x07", b"PW1\x00\x08\x01\x00\x00",
                b"PW1\x00\x03\x01", b"PW1\x00\x05\xff\xff\xff\xff"):
        with pytest.raises(wire.WireError):
            wire.loads(bad)


def test_bulk_packing_is_compact():
    cols = list(range(100_000))
    w = wire.dumps({"columns": cols})
    j = json.dumps({"columns": cols}).encode()
    assert len(w) < len(j) * 1.5  # 8B/int vs ~6.9B avg JSON digits+comma
    assert wire.loads(w) == {"columns": cols}
