"""OS-process-level cluster fault injection — the analog of the
reference's docker+pumba cluster tests
(/root/reference/internal/clustertests/cluster_test.go:54-70, which
pauses a node 10 s mid-import and asserts anti-entropy heals it, and
Dockerfile-clustertests:17-19): three REAL `pilosa-tpu server`
processes on localhost, faults injected with real signals.

- SIGSTOP one node mid-import (the pumba pause): imports keep landing
  (fan-out to the frozen peer is swallowed and healed later), then the
  node resumes and anti-entropy converges every replica.
- SIGKILL the same node mid-import: its oplog may tear mid-record;
  restart on the same data dir must recover the torn tail, rejoin the
  static topology, and resync via anti-entropy.

Convergence is asserted the way the fragment syncer itself reasons:
identical per-block checksums on every owning replica, plus identical
query results through every node."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

N_NODES = 3
REPLICAS = 2
N_SHARDS = 4
ROWS = 3


def _free_ports(n):
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _req(port, method, path, body=None, timeout=30):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                               data=data, method=method)
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


class ProcCluster:
    def __init__(self, tmp_path):
        self.tmp = tmp_path
        self.ports = _free_ports(N_NODES)
        self.uris = [f"http://127.0.0.1:{p}" for p in self.ports]
        self.procs = [None] * N_NODES
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        self.env = dict(os.environ)
        # CPU jax in the children; never let a dead axon tunnel hang
        # server boot (axon monkeypatches get_backend even under
        # JAX_PLATFORMS=cpu — see .claude/skills/verify/SKILL.md).
        self.env["JAX_PLATFORMS"] = "cpu"
        self.env["PYTHONPATH"] = repo
        for i in range(N_NODES):
            d = tmp_path / f"node{i}"
            d.mkdir(exist_ok=True)
            peers = ", ".join(f'"{u}"' for u in self.uris)
            (d / "config.toml").write_text(
                f'bind = "127.0.0.1:{self.ports[i]}"\n'
                f"cluster_peers = [{peers}]\n"
                f"cluster_replicas = {REPLICAS}\n"
                "anti_entropy_interval = 2.0\n"
                "heartbeat_interval = 1.0\n"
                "translate_replication_interval = 1.0\n"
                'metric_service = "none"\n'
                "metric_poll_interval = 0\n")

    def start(self, i):
        d = self.tmp / f"node{i}"
        log = open(d / "server.log", "ab")
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "-d", str(d), "-c", str(d / "config.toml"),
             "--platform", "cpu"],
            stdout=log, stderr=log, env=self.env)

    def start_all(self):
        for i in range(N_NODES):
            self.start(i)
        deadline = time.time() + 120
        for i, port in enumerate(self.ports):
            while True:
                try:
                    _req(port, "GET", "/status", timeout=5)
                    break
                except (urllib.error.URLError, OSError):
                    if time.time() > deadline:
                        raise RuntimeError(
                            f"node {i} never became ready; log:\n" +
                            (self.tmp / f"node{i}" / "server.log")
                            .read_text()[-2000:])
                    if self.procs[i].poll() is not None:
                        raise RuntimeError(
                            f"node {i} exited rc={self.procs[i].returncode}"
                            ":\n" + (self.tmp / f"node{i}" / "server.log")
                            .read_text()[-2000:])
                    time.sleep(0.5)

    def stop_all(self):
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in self.procs:
            if p is not None:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)


@pytest.fixture
def cluster(tmp_path):
    c = ProcCluster(tmp_path)
    c.start_all()
    yield c
    c.stop_all()


class Importer(threading.Thread):
    """Continuously imports bits through node0 until stopped, retrying
    on transient failures (the reference's import client retries
    through the pause the same way). Tracks exactly which bits landed
    (an import batch either succeeds as a whole or is retried)."""

    def __init__(self, port):
        super().__init__(daemon=True)
        self.port = port
        self.stop_evt = threading.Event()
        self.landed = set()  # (row, col)
        self.batches = 0
        self.next_col = 0

    def run(self):
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        while not self.stop_evt.is_set():
            batch = []
            for _ in range(40):
                shard = self.next_col % N_SHARDS
                col = shard * SHARD_WIDTH + (self.next_col // N_SHARDS)
                batch.append((self.next_col % ROWS, col))
                self.next_col += 1
            body = {"rowIDs": [r for r, _ in batch],
                    "columnIDs": [c for _, c in batch]}
            while not self.stop_evt.is_set():
                try:
                    _req(self.port, "POST",
                         "/index/ci/field/cf/import", body, timeout=60)
                    self.landed.update(batch)
                    self.batches += 1
                    break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.5)
            time.sleep(0.05)

    def stop(self):
        self.stop_evt.set()
        self.join(timeout=90)


def wait_converged(c, up_ports, want_counts, deadline_s=90):
    """Until deadline: every row Count agrees with `want_counts`
    through every live node, and every owning replica reports
    identical fragment block checksums for every shard."""
    q = " ".join(f"Count(Row(cf={r}))" for r in range(ROWS))
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            ok = True
            for port in up_ports:
                res = _req(port, "POST", "/index/ci/query",
                           q.encode())["results"]
                if res != want_counts:
                    ok = False
                    last = (port, res, want_counts)
                    break
            if ok:
                checked = 0
                for shard in range(N_SHARDS):
                    sums = set()
                    nodes = _req(up_ports[0], "GET",
                                 f"/internal/fragment/nodes?index=ci"
                                 f"&shard={shard}")
                    owner_ports = [c.ports[c.uris.index(n["uri"])]
                                   for n in nodes
                                   if c.ports[c.uris.index(n["uri"])]
                                   in up_ports]
                    assert owner_ports, (shard, nodes, up_ports)
                    for port in owner_ports:
                        blocks = _req(
                            port, "GET",
                            f"/internal/fragment/blocks?index=ci&field=cf"
                            f"&view=standard&shard={shard}")["blocks"]
                        if not blocks:
                            # e.g. a restarted node pre-resync: retry,
                            # don't abort — this is the state the loop
                            # exists to wait out.
                            sums.add(f"empty:{port}")
                            continue
                        sums.add(json.dumps(blocks, sort_keys=True))
                    checked += len(owner_ports)
                    if len(sums) > 1:
                        ok = False
                        last = ("blocks", shard)
                        break
                # Replica pairs must actually have been compared: with
                # all nodes up every shard has REPLICAS owners.
                if ok and len(up_ports) == N_NODES:
                    assert checked == N_SHARDS * REPLICAS, checked
            if ok:
                return
        except (urllib.error.URLError, OSError) as e:
            last = repr(e)
        time.sleep(1.0)
    raise AssertionError(f"cluster did not converge: {last}")


@pytest.mark.slow
@pytest.mark.timeout(540)
def test_pause_and_kill_mid_import(cluster):
    c = cluster
    _req(c.ports[0], "POST", "/index/ci", {})
    _req(c.ports[0], "POST", "/index/ci/field/cf", {})
    # Schema must reach every node before imports fan out.
    for port in c.ports:
        deadline = time.time() + 30
        while time.time() < deadline:
            idxs = {i["name"] for i in _req(port, "GET",
                                            "/schema")["indexes"]}
            if "ci" in idxs:
                break
            time.sleep(0.5)

    imp = Importer(c.ports[0])
    imp.start()
    try:
        # Let some data land everywhere first.
        deadline = time.time() + 60
        while imp.batches < 3 and time.time() < deadline:
            time.sleep(0.5)
        assert imp.batches >= 3

        # --- Fault 1: SIGSTOP node2 for 10 s mid-import (pumba pause,
        # cluster_test.go:54-70). Its sockets stay open; fan-out legs
        # stall on the frozen peer and are swallowed, healed later.
        victim = c.procs[2]
        victim.send_signal(signal.SIGSTOP)
        time.sleep(10)
        victim.send_signal(signal.SIGCONT)
        # Imports kept flowing during the pause.
        b0 = imp.batches
        deadline = time.time() + 60
        while imp.batches < b0 + 2 and time.time() < deadline:
            time.sleep(0.5)
        assert imp.batches >= b0 + 2

        # --- Fault 2: SIGKILL node2 mid-import — torn oplog tail risk.
        victim.kill()
        victim.wait(timeout=30)
        b0 = imp.batches
        deadline = time.time() + 90
        while imp.batches < b0 + 2 and time.time() < deadline:
            time.sleep(0.5)
        assert imp.batches >= b0 + 2, "imports stalled after node kill"
    finally:
        imp.stop()

    from collections import Counter
    by_row = Counter(r for r, _ in imp.landed)
    want = [by_row.get(r, 0) for r in range(ROWS)]

    # Survivors converge while node2 is dead (its replicas have a live
    # second owner at REPLICAS=2).
    wait_converged(c, [c.ports[0], c.ports[1]], want)

    # Restart node2 on its kill-torn data dir: torn-tail recovery +
    # rejoin + anti-entropy resync to full convergence.
    c.start(2)
    deadline = time.time() + 120
    while True:
        try:
            _req(c.ports[2], "GET", "/status", timeout=5)
            break
        except (urllib.error.URLError, OSError):
            if time.time() > deadline:
                log = (c.tmp / "node2" / "server.log").read_text()[-2000:]
                raise RuntimeError("node2 failed to restart:\n" + log)
            time.sleep(0.5)
    wait_converged(c, c.ports, want, deadline_s=120)

    # --- Keyed translation across real processes: writes through
    # DIFFERENT nodes (non-primaries adopt allocations out-of-band),
    # then the chained replication loops converge every node's served
    # log to a byte-prefix of the primary's (the chain invariant,
    # cluster.go:1908-1935).
    _req(c.ports[0], "POST", "/index/tk", {"options": {"keys": True}})
    _req(c.ports[0], "POST", "/index/tk/field/kf", {})
    time.sleep(1)  # schema broadcast
    for i, key in enumerate(("alpha", "beta", "gamma")):
        _req(c.ports[i], "POST", "/index/tk/query",
             f"Set('{key}', kf=1)".encode())
    for port in c.ports:
        res = _req(port, "POST", "/index/tk/query", b"Count(Row(kf=1))")
        assert res["results"] == [3], (port, res)
    import urllib.request as _ur
    deadline = time.time() + 60
    while True:
        logs = []
        for port in c.ports:
            with _ur.urlopen(f"http://127.0.0.1:{port}/internal/"
                             "translate/data?index=tk&offset=0",
                             timeout=10) as r:
                logs.append(r.read())
        full = max(logs, key=len)
        if all(len(lg) > 0 and full.startswith(lg) for lg in logs) \
                and sum(len(lg) == len(full) for lg in logs) == len(logs):
            break
        if time.time() > deadline:
            raise AssertionError(
                f"translate logs did not converge: {[len(x) for x in logs]}")
        time.sleep(1)
