"""Per-query execution profiler tests (utils/profile.py + executor/
server wiring): profile tree shape, device-fence sampling policy, the
slow-query ring, /debug/queries + ?profile=true HTTP surfaces, and the
pilosa_executor_* metrics feed."""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.server.api import API
from pilosa_tpu.utils.profile import Profiler, QueryProfile
from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text


def _seed_two_shards(holder, index="p"):
    """Index with two set fields holding the same bits in 2 shards."""
    idx = holder.create_index(index)
    f = idx.create_field("f")
    g = idx.create_field("g")
    cols = np.array([1, 2, SHARD_WIDTH + 3], np.uint64)
    f.import_bits(np.full(3, 1, np.uint64), cols)
    g.import_bits(np.full(3, 1, np.uint64), cols)
    idx.add_existence(cols)
    return idx


def _walk(node):
    yield node
    for c in node.get("children", []):
        yield from _walk(c)


def test_profile_tree_count_intersect_two_shards(tmp_holder):
    """Acceptance: a profiled Count(Intersect(Row, Row)) over >= 2
    shards returns per-op device time, jit cache hit/miss, and
    transfer-byte fields."""
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    resp = api.query("p", "Count(Intersect(Row(f=1), Row(g=1)))",
                     profile=True)
    assert resp["results"] == [3]
    p = resp["profile"]
    assert p["deviceSampled"] is True
    assert p["durS"] > 0
    assert p["jit"]["hits"] + p["jit"]["misses"] >= 1
    assert p["ops"] and p["ops"][0]["name"] == "Count"
    op = p["ops"][0]
    assert op["dispatchS"] >= 0 and op["materializeS"] >= 0
    assert op["d2hBytes"] > 0  # the fetched per-shard counts
    evals = [n for n in _walk(op) if n["name"].startswith("eval:")]
    assert evals, op
    ev = evals[0]
    assert ev["jit"] in ("hit", "miss")
    assert ev["shards"] == 2
    assert "deviceS" in ev and ev["deviceS"] >= 0
    assert ev.get("h2dBytes", 0) >= 0
    # Warm repeat: same shape -> jit cache hit recorded.
    p2 = api.query("p", "Count(Intersect(Row(f=1), Row(g=2)))",
                   profile=True)["profile"]
    ev2 = [n for op2 in p2["ops"] for n in _walk(op2)
           if n["name"].startswith("eval:")][0]
    assert ev2["jit"] == "hit"


def test_no_fence_without_sampling_profile(tmp_holder, monkeypatch):
    """Acceptance: profiling disabled adds no block_until_ready fences
    on the hot path."""
    import pilosa_tpu.executor.executor as ex

    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    fences = []
    real = ex._fence_device
    monkeypatch.setattr(ex, "_fence_device",
                        lambda out: fences.append(1) or real(out))
    api.query("p", "Count(Row(f=1))")
    assert fences == []  # passive profile: zero fences
    api.query("p", "Count(Row(f=1))", profile=True)
    assert fences  # forced profile fences


def test_sample_every_fences_one_in_n(tmp_holder, monkeypatch):
    import pilosa_tpu.executor.executor as ex

    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    # Sampled fences require the repeats to DISPATCH; the result
    # cache would serve queries 2-6 without any device work.
    api.executor.result_cache.enabled = False
    api.profiler.configure(sample_every=3)
    fences = []
    monkeypatch.setattr(ex, "_fence_device",
                        lambda out: fences.append(1) or 0.0)
    for _ in range(6):
        api.query("p", "Count(Row(f=1))")
    assert len(fences) == 2  # queries 3 and 6


def test_device_seconds_carries_sampled_label(tmp_holder):
    """Satellite (ISSUE 18): pilosa_executor_device_seconds is fed
    ONLY by 1-in-N sampled fences, so the series carries an explicit
    sampled="true" label and the live fence rate exports beside it —
    a dashboard scaling device time must multiply by the rate."""
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.executor.result_cache.enabled = False
    api.profiler.configure(sample_every=2)
    for _ in range(4):
        api.query("p", "Count(Row(f=1))")
    prom = prometheus_text(api.stats)
    line = next(l for l in prom.splitlines()
                if l.startswith("pilosa_executor_device_seconds{"))
    assert 'sampled="true"' in line, line
    # No unlabeled twin series: one family, one label shape.
    assert "pilosa_executor_device_seconds{quantile" not in prom
    assert "pilosa_executor_device_sample_every 2" in prom
    # The recorder learned the rate through Profiler.configure.
    from pilosa_tpu.utils.roofline import ROOFLINE
    assert ROOFLINE.sample_every == 2


def test_retrace_counter_and_metrics(tmp_holder):
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    before = api.executor.jit_compiles
    api.query("p", "Count(Row(f=1))")
    assert api.executor.jit_compiles > before  # cold shape: a retrace
    first = api.executor.jit_compiles
    api.query("p", "Count(Row(g=1))")  # same shape: no retrace
    assert api.executor.jit_compiles == first
    prom = prometheus_text(api.stats)
    assert "pilosa_executor_retrace_total" in prom
    assert "pilosa_executor_plan_seconds" in prom
    assert "pilosa_executor_materialize_seconds" in prom


def test_slow_query_ring_structured_record(tmp_holder):
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.long_query_time = 1e-9  # everything is slow
    api.query("p", "Count(Row(f=1))")
    recs = api.profiler.slow_queries()
    assert recs
    rec = recs[0]
    assert rec["index"] == "p"
    assert rec["query"] == "Count(Row(f=1))"
    assert rec["durS"] > 0 and rec["kind"] == "query"
    # Structured per-op breakdown rides along.
    assert rec["profile"]["ops"][0]["name"] == "Count"
    # Ring is bounded and most-recent-first.
    api.profiler.configure(ring_size=2)
    for i in range(4):
        api.query("p", f"Count(Row(f={i}))")
    recs = api.profiler.slow_queries()
    assert len(recs) == 2
    assert recs[0]["query"] == "Count(Row(f=3))"


def test_ring_records_errors(tmp_holder):
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.long_query_time = 1e-9
    with pytest.raises(Exception):
        api.query("p", "Count(Row(nope=1))")
    recs = api.profiler.slow_queries()
    assert any("error" in r for r in recs)


def test_http_profile_and_debug_queries(live_server):
    """?profile=true embeds the tree (through the coalescer);
    GET /debug/queries serves the structured slow-query ring."""
    base, api, holder = live_server
    _seed_two_shards(holder, index="hp")
    api.long_query_time = 1e-9

    def req(method, path, body=None):
        data = body if isinstance(body, (bytes, type(None))) \
            else json.dumps(body).encode()
        r = urllib.request.Request(base + path, data=data, method=method)
        with urllib.request.urlopen(r, timeout=30) as resp:
            return json.loads(resp.read() or b"{}")

    res = req("POST", "/index/hp/query?profile=true",
              b"Count(Intersect(Row(f=1), Row(g=1)))")
    assert res["results"] == [3]
    p = res["profile"]
    assert p["deviceSampled"] is True
    assert p["ops"][0]["name"] == "Count"
    # Through the live_server coalescer the profile records its batch.
    assert p.get("coalesced", {}).get("batch", 1) >= 1
    dbg = req("GET", "/debug/queries")
    assert isinstance(dbg["retraces"], int)
    assert dbg["queries"], dbg
    assert dbg["queries"][0]["index"] == "hp"
    # Unprofiled query: no profile key in the response.
    res = req("POST", "/index/hp/query", b"Count(Row(f=1))")
    assert "profile" not in res
    # /metrics carries the executor series.
    r = urllib.request.Request(base + "/metrics")
    with urllib.request.urlopen(r, timeout=30) as resp:
        prom = resp.read().decode()
    assert "pilosa_executor_" in prom


def test_coalesced_dedup_skips_forced_profiles(tmp_holder):
    """Forced profiles never share a deduped response dict — each gets
    its own execution."""
    from pilosa_tpu.server.coalescer import QueryCoalescer

    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    coal = QueryCoalescer(api.executor, window_s=0.02, stats=api.stats)
    coal.start()
    api.coalescer = coal
    try:
        import threading
        results = []

        def go():
            results.append(api.query_coalesced(
                "p", "Count(Row(f=1))", profile=True))

        threads = [threading.Thread(target=go) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r["results"] == [3] for r in results)
        profiles = [r["profile"] for r in results]
        assert all(p["ops"] for p in profiles)  # each really executed
    finally:
        coal.stop()


def test_profile_reused_across_executes_keeps_per_op_attribution(
        tmp_holder):
    """The cluster path runs one executor.execute() per PQL call
    against the SAME profile: finalize indices must rebase per dispatch
    run, or call 2's materialize data would overwrite call 1's op."""
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    prof = api.profiler.begin("p", "reused", force=True)
    api.executor.execute("p", "Row(f=1)", profile=prof)
    api.executor.execute("p", "Count(Row(f=1))", profile=prof)
    assert [op.name for op in prof.ops] == ["Row", "Count"]
    for op in prof.ops:
        assert "materializeS" in op.attrs, op.to_json()
    assert prof.ops[1].attrs.get("d2hBytes", 0) > 0  # Count's fetch


def test_profile_merge_node_fragments():
    p = QueryProfile("i", "Count(Row(f=1))", forced=True)
    p.add_node_fragment("node-a", {"ops": [{"name": "Count"}]})
    p.add_node_fragment("node-b", {"ops": []})
    out = p.to_json()
    assert set(out["nodes"]) == {"node-a", "node-b"}
    assert out["nodes"]["node-a"]["ops"][0]["name"] == "Count"


def test_profiler_observe_never_raises_without_sinks():
    prof = Profiler()
    p = prof.begin("i", "Count(Row(f=1))")
    prof.observe("i", "Count(Row(f=1))", 0.5, profile=p,
                 long_query_time=0.1, logger=None)
    assert prof.slow_queries()[0]["durS"] == 0.5


def test_batch_query_slow_record(tmp_holder):
    _seed_two_shards(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.long_query_time = 1e-9
    out = api.query_batch([{"index": "p", "query": "Count(Row(f=1))"}])
    assert out[0]["results"] == [3]
    assert any(r["kind"] == "batch" for r in api.profiler.slow_queries())
