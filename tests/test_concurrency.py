"""Concurrency smoke tests — the analog of the reference's race-detector
CI (`go test -race`, CHANGELOG.md:19): hammer the API from several
threads and assert no exceptions, lost writes, or torn reads. Python
threads interleave at bytecode granularity, which is exactly the
dict-mutation / cache-rebuild interleaving the per-structure locks
(fragment._lock, view._lock) must survive."""

import threading

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor

N_THREADS = 6
N_OPS = 40


@pytest.fixture
def world(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("c")
    idx.create_field("f")
    yield Executor(h), h
    h.close()


def test_concurrent_writes_and_queries(world):
    ex, h = world
    errors = []
    barrier = threading.Barrier(N_THREADS)

    def writer(tid):
        try:
            barrier.wait()
            for i in range(N_OPS):
                col = tid * 10_000 + i
                ex.execute("c", f"Set({col}, f={tid})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            barrier.wait()
            for _ in range(N_OPS):
                ex.execute("c", "Count(Row(f=1)) TopN(f, n=3)")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(N_THREADS - 2)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # no lost writes: every thread's bits all present
    for tid in range(N_THREADS - 2):
        (cnt,) = ex.execute("c", f"Count(Row(f={tid}))")
        assert cnt == N_OPS, (tid, cnt)


def test_concurrent_bulk_import_and_topn(world):
    """Imports racing trimmed-bank TopN sweeps: widths grow while banks
    rebuild; results must always reflect a consistent snapshot."""
    ex, h = world
    f = h.index("c").field("f")
    errors = []
    stop = threading.Event()

    def importer():
        try:
            rng = np.random.default_rng(0)
            for i in range(10):
                cols = rng.integers(0, (i + 1) * 100_000, 500,
                                    dtype=np.uint64)
                f.import_bits(np.full(500, 1, np.uint64), cols)
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            stop.set()

    def querier():
        try:
            while not stop.is_set():
                (res,) = ex.execute("c", "TopN(f, n=1)")
                if res.pairs:
                    assert res.pairs[0][0] == 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=importer),
          threading.Thread(target=querier),
          threading.Thread(target=querier)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    # final state exact
    (cnt,) = ex.execute("c", "Count(Row(f=1))")
    assert cnt == f.view().fragment(0).row_count(1) + sum(
        fr.row_count(1) for s, fr in f.view().fragments.items() if s != 0)


def test_concurrent_queries_under_tiny_bank_budget(world):
    """Queries racing while the global bank budget constantly evicts
    other threads' cached banks: results must stay exact (evicted banks
    are rebuilt; a query holding a device array keeps it alive via its
    own reference regardless of cache eviction)."""
    import pilosa_tpu.core.view as view_mod

    ex, h = world
    idx = h.index("c")
    for fname in ("a", "b", "d"):
        f = idx.create_field(fname)
        f.import_bits(np.repeat(np.arange(4, dtype=np.uint64), 25),
                      np.tile(np.arange(25, dtype=np.uint64) * 7, 4))
    idx.add_existence(np.arange(200, dtype=np.uint64))
    want = {}
    for fname in ("a", "b", "d"):
        (want[fname],) = ex.execute("c", f"Count(Row({fname}=2))")

    orig = view_mod.BANK_BUDGET
    view_mod.BANK_BUDGET = view_mod.BankBudget(1 << 16)  # ~one bank
    for fname in ("a", "b", "d"):
        view = idx.field(fname).view()
        for key in list(view._bank_cache):
            orig.forget(view, key)  # keep the global budget's accounting
        view._bank_cache.clear()
    errors = []

    def worker(fname):
        try:
            for _ in range(N_OPS):
                (got,) = ex.execute("c", f"Count(Row({fname}=2))")
                assert got == want[fname], (fname, got, want[fname])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(fn,))
                   for fn in ("a", "b", "d") for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert view_mod.BANK_BUDGET.evictions > 0
    finally:
        view_mod.BANK_BUDGET = orig


def test_concurrent_writes_with_snapshot_pressure(tmp_path):
    """Tiny MaxOpN forces a snapshot every few ops while writers and
    readers run — the reference's snapshot-under-load interleaving
    (fragment.go:1769 incrementOpN -> snapshot)."""
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("s")
    idx.create_field("f")
    ex = Executor(h)
    ex.execute("s", "Set(0, f=0)")
    frag = idx.field("f").view().fragment(0)
    frag.max_op_n = 5
    errors = []
    barrier = threading.Barrier(4)

    def writer(tid):
        try:
            barrier.wait()
            for i in range(30):
                ex.execute("s", f"Set({tid * 1000 + i}, f={tid})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            barrier.wait()
            for _ in range(30):
                ex.execute("s", "Count(Row(f=1))")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(3)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for tid in range(3):
        (cnt,) = ex.execute("s", f"Count(Row(f={tid}))")
        assert cnt == 30, (tid, cnt)  # tid 0's col 0 covers the seed Set
    # durability: reopen from disk and recount
    h.close()
    h2 = Holder(str(tmp_path))
    h2.open()
    ex2 = Executor(h2)
    for tid in range(3):
        (cnt,) = ex2.execute("s", f"Count(Row(f={tid}))")
        assert cnt == 30, (tid, cnt)
    h2.close()


def test_concurrent_key_allocation(tmp_path):
    """Racing Set() calls with overlapping string keys must allocate one
    id per key (reference TranslateFile get-or-create under lock,
    translate.go:266)."""
    h = Holder(str(tmp_path))
    h.open()
    h.create_index("k", keys=True)
    from pilosa_tpu.core.field import FieldOptions
    h.index("k").create_field("f", FieldOptions(keys=True))
    ex = Executor(h)
    errors = []
    barrier = threading.Barrier(N_THREADS)
    keys = [f"user{n}" for n in range(20)]

    def writer(tid):
        try:
            barrier.wait()
            for i, k in enumerate(keys):
                ex.execute("k", f"Set('{k}', f='tag{i % 5}')")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    idx = h.index("k")
    ids = [idx.column_translator.translate_keys([k])[0] for k in keys]
    assert len(set(ids)) == len(keys)  # one id per key, no dup alloc
    for i in range(5):
        (res,) = ex.execute("k", f"Row(f='tag{i}')")
        assert len(res.columns()) == 4  # 20 keys / 5 tags
    h.close()
