"""GL009 pass fixture: the snapshot-under-the-lock / block-after
pattern, plus the call shapes that LOOK like sinks but are not
(str.join, os.path.join, Condition.wait)."""
import os
import time
from urllib.request import urlopen

from pilosa_tpu.utils.locks import make_condition, make_lock


class PoliteSender:
    def __init__(self):
        self._lock = make_lock("PoliteSender._lock")
        self._cond = make_condition("PoliteSender._cond")
        self._pending = []

    def deliver(self, uri):
        with self._lock:
            batch = list(self._pending)
            del self._pending[:]
        # Blocking work happens AFTER the lock is released.
        for msg in batch:
            urlopen(uri, data=msg).read()
        time.sleep(0.01)

    def describe(self, parts):
        with self._lock:
            # str.join / os.path.join are not thread joins.
            label = ", ".join(parts)
            return os.path.join("/tmp", label)

    def await_work(self):
        with self._cond:
            # Condition.wait RELEASES the lock it waits on — lock-order
            # business (GL002), not a blocking hazard.
            self._cond.wait(timeout=1.0)
            return list(self._pending)
