"""GL003 fail: host syncs on device values in a hot-path function."""
import jax
import jax.numpy as jnp
import numpy as np


def leaky_count(words):
    acc = jnp.bitwise_and(words, words)
    host = np.asarray(acc)          # device fetch mid-pipeline
    total = int(jnp.sum(acc))       # blocking scalar transfer
    jax.block_until_ready(acc)      # explicit sync
    return host, total


def leaky_item(words):
    s = jnp.sum(words)
    return s.item()                 # device->host scalar


def leaky_closure(words, register_callback):
    # The callback closes over `total`, which is only device-tainted
    # AFTER the def — closures see the final binding, so the .item()
    # inside is still a device sync (end-of-scope taint inheritance).
    def cb():
        return total.item()
    total = jnp.sum(words)
    register_callback(cb)
