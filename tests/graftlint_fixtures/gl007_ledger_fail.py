"""GL007 fail fixture: device arrays parked on instance state with no
path to a LEDGER.register — /debug/memory totals go dark for them."""
import jax.numpy as jnp


class BankHolder:
    def __init__(self):
        self._bank = None
        self._scratch = None

    def cache_bank(self, words):
        # Direct store, no registration anywhere in this class.
        self._bank = jnp.asarray(words)

    def stage(self, words):
        # Helper indirection must NOT satisfy the rule when the helper
        # never registers either.
        self._scratch = jnp.zeros((4, 8))
        self._note()

    def _note(self):
        return "noted, but never registered"
