"""GL003 pass: syncs only at annotated materialization boundaries (or
on host-only data)."""
import jax.numpy as jnp
import numpy as np


# graftlint: materialize — fixture materialization point.
def finalize_count(words):
    acc = jnp.bitwise_and(words, words)
    return int(np.asarray(jnp.sum(acc)))


def host_only(positions):
    arr = np.asarray(positions, dtype=np.uint64)  # host list marshalling
    return arr.shape[0]


def stays_on_device(words, other):
    return jnp.bitwise_or(words, other)
