"""GL005 fail: word-corrupting dtypes in a word-kernel file."""
import jax.numpy as jnp
import numpy as np


def promote(words):
    w = words.astype(jnp.int64)           # x64-off silently truncates
    f = words.astype(np.float32)          # float destroys bit patterns
    z = jnp.zeros(words.shape)            # dtype-less: defaults float
    return w, f, z


def full_no_dtype(shape):
    return np.full(shape, 0xFFFF)    # full's dtype is positional arg 2
