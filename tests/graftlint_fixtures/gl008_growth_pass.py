"""GL008 pass fixture: every growing container shows a bound — ring
(deque maxlen), LRU eviction, len() cap, fixed literal keys, a
draining AugAssign, or a reset path."""
from collections import OrderedDict, deque


class BoundedRecorder:
    def __init__(self):
        self._ring = deque(maxlen=256)
        self._lru = OrderedDict()
        self._capped = {}
        self._totals = {}
        self._dirty = set()
        self._batch = []

    def observe(self, key, value):
        self._ring.append((key, value))
        self._lru[key] = value
        while len(self._lru) > 128:
            self._lru.popitem(last=False)

    def admit(self, key, value):
        if len(self._capped) < 64:
            self._capped[key] = value

    def count(self, n):
        # Literal subscript keys cannot grow past the number of
        # distinct literals in the source: a fixed-field record.
        self._totals["reads"] = self._totals.get("reads", 0) + n

    def stage(self, items):
        self._dirty |= items
        self._batch.append(items)

    def drain(self):
        consumed = set(self._dirty)
        self._dirty -= consumed
        return consumed

    def flush(self):
        out, self._batch = self._batch, []
        return out
