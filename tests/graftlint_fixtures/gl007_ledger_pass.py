"""GL007 pass fixture: every long-lived device store reaches a ledger
registration — directly, through helper indirection (the call graph
follows it), or is annotated transient."""
import jax.numpy as jnp

from pilosa_tpu.utils.memledger import LEDGER


class RegisteredHolder:
    def __init__(self):
        self._bank = None
        self._positions = None
        self._tmp = None

    def cache_bank(self, words):
        # Direct registration in the assigning function.
        self._bank = jnp.asarray(words)
        LEDGER.register("bank", "fixture", int(self._bank.nbytes))

    def cache_positions(self, pos):
        # Registration via helper indirection: the interprocedural
        # call graph follows cache_positions -> _install.
        self._positions = jnp.asarray(pos)
        self._install("positions", self._positions)

    def _install(self, key, arr):
        LEDGER.register("bank", key, int(arr.nbytes))

    def stage_scratch(self, words):
        # graftlint: transient — replaced within the same request;
        # never outlives the call that stages it.
        self._tmp = jnp.asarray(words)
        return self._tmp
