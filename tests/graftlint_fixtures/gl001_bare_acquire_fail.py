"""GL001 fail: acquire() without a structural try/finally release."""
import threading

_LOCK = threading.Lock()  # also a GL001 factory finding when scoped
STATE = 0


def bad_bare():
    global STATE
    _LOCK.acquire()
    STATE += 1          # an exception here leaks the lock forever
    _LOCK.release()


def bad_conditional(timeout):
    if _LOCK.acquire(timeout=timeout):
        _LOCK.release()
