"""GL011 fail fixture: foreign symbols called through a ctypes handle
without full argtypes/restype declarations.

`nat_count` declares only restype (argtypes missing -> default int
conversion truncates the pointer argument on LP64); `nat_load` declares
neither (its pointer-sized return value is ALSO mangled to c_int);
`memcpy` is fully declared on a DIFFERENT handle (libc), which must not
license the same-named symbol on `lib`.
"""

import ctypes

lib = ctypes.CDLL("libnat_fixture.so")
lib.nat_count.restype = ctypes.c_uint64

libc = ctypes.CDLL(None)
libc.memcpy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                        ctypes.c_size_t]
libc.memcpy.restype = ctypes.c_void_p


def count(buf: bytes) -> int:
    data = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    return int(lib.nat_count(data, len(buf)))


def load(buf: bytes) -> int:
    data = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    return int(lib.nat_load(data, len(buf)))


def cross_handle(buf: bytes) -> None:
    data = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    lib.memcpy(data, data, len(buf))  # declared on libc, called on lib
