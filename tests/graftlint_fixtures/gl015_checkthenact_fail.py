"""GL015 fail fixture: check-then-act across lock scopes — a stale
guard used under a re-acquisition, one passed into a call that takes
the lock again, and an early-return guard ahead of placement math."""
from pilosa_tpu.utils.locks import make_lock


class Registry:
    def __init__(self):
        self._lock = make_lock("Registry._lock")
        self.state = "NORMAL"
        self.items = {}

    def _place(self, previous):
        with self._lock:
            return dict(self.items) if previous else {}

    def route(self):
        # Guard read under one acquisition, consumed by a helper that
        # re-acquires: the resize-routing race shape.
        with self._lock:
            previous = self.state == "RESIZING"
        return self._place(previous)

    def bump(self):
        # Stale index used under a separate acquisition.
        with self._lock:
            n = len(self.items)
        with self._lock:
            self.items[n] = "x"

    def fan_out(self):
        # Early-return guard: the check and the placement math run
        # under different acquisitions.
        with self._lock:
            quiet = self.state == "NORMAL"
        if not quiet:
            return {}
        return self._place(False)
