"""GL006 fail fixture: jit build sites invisible to the retrace
counter — no _note_jit_compile anywhere in the enclosing scope."""
import functools

import jax


@jax.jit  # module-scope decorator build: flagged
def _module_kernel(x):
    return x + 1


@functools.partial(jax.jit, static_argnames=("flag",))  # flagged
def _module_kernel2(x, *, flag=False):
    return x if flag else -x


class Runner:
    _cache = {}

    def kernel_for(self, shape):
        # Cached, but the compile is never noted: the retrace counter
        # stays flat while signature churn burns compiles — flagged.
        fn = self._cache.get(shape)
        if fn is None:
            fn = jax.jit(lambda x: x * 2)
            self._cache[shape] = fn
        return fn
