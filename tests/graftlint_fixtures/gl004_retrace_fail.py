"""GL004 fail: traced scalar/tuple call sites + import-time jnp."""
import jax
import jax.numpy as jnp

_IMPORT_TIME = jnp.zeros(8, dtype=jnp.uint32)  # device alloc at import


@jax.jit
def shifted(words, n):
    return words << n


def caller(words):
    return shifted(words, 3)        # literal scalar traced per call


def caller_tuple(words):
    return shifted(words, (1, 2))   # fresh tuple positional
