"""GL013 passing fixture: unique literal names at module level; local
registries (test fixtures) are out of scope. Expected findings: 0."""

from pilosa_tpu.utils.failpoints import FAILPOINTS, FailpointRegistry

_FP_OK = FAILPOINTS.register("fixture.pass_site")


def test_scoped_registry():
    # A LOCAL registry may register wherever it likes — only the
    # process-wide FAILPOINTS carries the catalog contract.
    reg = FailpointRegistry()
    return reg.register("fixture.local")
