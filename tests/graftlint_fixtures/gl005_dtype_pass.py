"""GL005 pass: the word dtype lattice (uint words, i32 accumulators,
bool masks)."""
import jax
import jax.numpy as jnp
import numpy as np


def word_ops(words):
    w = words.astype(jnp.uint32)
    acc = jax.lax.population_count(w).astype(jnp.int32)
    mask = jnp.zeros(words.shape, dtype=jnp.bool_)
    host = np.zeros(16, dtype=np.uint64)
    return w, acc, mask, host


def positional_dtype(shape, dt):
    a = np.zeros(shape, np.uint32)   # recognizable positional dtype
    b = np.zeros(shape, dt)          # unresolvable expression: left alone
    return a, b
