"""GL002 fail: ABBA — Alpha.step holds Alpha._lock_a then calls
Beta.poke (takes Beta._lock_b); Beta.drain holds Beta._lock_b and calls
Alpha.kick (takes Alpha._lock_a)."""
from pilosa_tpu.utils.locks import make_lock


class Alpha:
    def __init__(self, beta):
        self._lock_a = make_lock("Alpha._lock_a")
        self.beta = beta

    def step(self):
        with self._lock_a:
            self.beta.poke()

    def kick(self):
        with self._lock_a:
            return 1


class Beta:
    def __init__(self, alpha):
        self._lock_b = make_lock("Beta._lock_b")
        self.alpha = alpha

    def poke(self):
        with self._lock_b:
            return 2

    def drain(self):
        with self._lock_b:
            self.alpha.kick()
