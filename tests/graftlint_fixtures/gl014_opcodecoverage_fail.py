"""GL014 fail fixture: an opcode table whose coverage tables drifted.

Three violations, one of each shape the rule detects:

- ``"newop"`` is in OP_NAMES but has no OPCODE_MUTATIONS entry — the
  classic "shipped an opcode without fuzzer teeth" gap.
- ``"ghost"`` has a coverage row but is not a real opcode — a stale
  row left behind by a rename, hiding the table's true coverage.
- ``"or"`` maps to ``"flip_bits"`` which is not in PLAN_MUTATIONS —
  the sweep would never apply it, so the row vouches for nothing.

Both tables live in this one file so the single-file fixture harness
exercises the cross-file rule (opcode_table_paths and
mutation_table_paths both point at the gl014 fixture prefix).
"""

OP_NAMES = ("and", "or", "newop")

PLAN_MUTATIONS = ("opcode", "src_range")

OPCODE_MUTATIONS = {
    "and": ("opcode", "src_range"),
    "or": ("flip_bits",),
    "ghost": ("opcode",),
}
