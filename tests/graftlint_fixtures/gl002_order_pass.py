"""GL002 pass: strict one-way order Alpha._lock_a -> Beta._lock_b (and a
reentrant self-hold, which is fine for an RLock)."""
from pilosa_tpu.utils.locks import make_lock, make_rlock


class Alpha:
    def __init__(self, beta):
        self._lock_a = make_rlock("Alpha._lock_a")
        self.beta = beta

    def step(self):
        with self._lock_a:
            self.beta.poke()

    def snapshot(self):
        with self._lock_a:
            return self.inner()

    def inner(self):
        with self._lock_a:  # reentrant: no self-deadlock finding
            return 1


class Beta:
    def __init__(self):
        self._lock_b = make_lock("Beta._lock_b")

    def poke(self):
        with self._lock_b:
            return 2
