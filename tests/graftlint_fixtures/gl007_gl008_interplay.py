"""Suppression-interplay fixture: a `disable=GL007` on a line that
ALSO violates GL008 must silence only GL007 — suppressions are
(rule, line)-keyed, not line-keyed."""
import jax.numpy as jnp


class InterplayHolder:
    def __init__(self):
        self._buf = None
        self._log = []

    def stage(self, words, key):
        self._buf = jnp.asarray(words); self._log.append(key)  # graftlint: disable=GL007
