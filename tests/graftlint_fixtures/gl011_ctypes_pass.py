"""GL011 pass fixture: every called symbol is fully declared, through
the same idioms native.py uses — a central bind step on an annotated
handle, an annotated-return loader, and a handle alias.
"""

from typing import Optional

import ctypes

_lib: Optional[ctypes.CDLL] = None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.nat_count.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                              ctypes.c_uint64]
    lib.nat_count.restype = ctypes.c_uint64
    lib.nat_load.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                             ctypes.c_uint64]
    lib.nat_load.restype = ctypes.c_void_p
    lib.nat_free.argtypes = [ctypes.c_void_p]
    lib.nat_free.restype = None
    return lib


def load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None:
        _lib = _bind(ctypes.CDLL("libnat_fixture.so"))
    return _lib


def count(buf: bytes) -> int:
    lib = load()
    assert lib is not None
    data = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    return int(lib.nat_count(data, len(buf)))


def round_trip(buf: bytes) -> None:
    lib = load()
    assert lib is not None
    alias = lib  # alias still resolves to the same declared handle
    data = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    handle = alias.nat_load(data, len(buf))
    alias.nat_free(handle)
