"""GL001 fail: module-level mutable dict mutated without any lock."""
from pilosa_tpu.utils.locks import make_lock

_CACHE = {}
_LOCK = make_lock("fixture._LOCK")


def put(key, value):
    _CACHE[key] = value     # racy: no lock held


def get(key):
    return _CACHE.get(key)  # racy read of mutated state


def put_in_file_cm(key, path):
    with open(path) as f:      # a context manager is NOT a lock
        _CACHE[key] = f.read()
