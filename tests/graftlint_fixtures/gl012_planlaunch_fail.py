"""GL012 fail fixture: a plan buffer (.instrs) reaches the
_call_program funnel with no path to verify_plan."""
import jax.numpy as jnp


class BadLauncher:
    def launch(self, executor, plan, banks):
        # The handoff marker: the plan buffer is read and uploaded...
        instrs_dev = jnp.asarray(plan.instrs)
        widths_dev = jnp.asarray(plan.widths)
        # ...and dispatched without ever passing the checker.
        return executor._call_program(plan.fn, banks, widths_dev,
                                      instrs_dev)


class AlsoBad:
    def helper_does_not_verify(self, plan):
        return plan.n_instrs

    def launch(self, executor, plan):
        self.helper_does_not_verify(plan)
        buf = plan.instrs
        return executor._call_program(plan.fn, buf)
