"""GL014 pass fixture: opcode and coverage tables in lockstep.

Every OP_NAMES entry has a non-empty OPCODE_MUTATIONS row, every row
names a real opcode, and every listed kind exists in PLAN_MUTATIONS —
the invariant the real pair (pilosa_tpu/ops/megakernel.py and
tools/planverify.py) maintains.
"""

OP_NAMES = ("and", "or", "thresh")

PLAN_MUTATIONS = ("opcode", "src_range", "thresh_off_by_one")

OPCODE_MUTATIONS = {
    "and": ("opcode", "src_range"),
    "or": ("opcode",),
    "thresh": ("opcode", "thresh_off_by_one"),
}
