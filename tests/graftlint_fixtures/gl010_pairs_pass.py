"""GL010 pass fixture: exception-safe closers — try/finally, a context
manager on the opener, weakref.finalize, and the evict-then-install
idiom (closer BEFORE opener is not a bracket)."""
import weakref

from pilosa_tpu.utils.memledger import LEDGER
from pilosa_tpu.utils.stats import MemStatsClient
from pilosa_tpu.utils.timeline import TIMELINE

STATS = MemStatsClient()


def risky(payload):
    return payload["key"]


def ledger_pair_finally(arr):
    LEDGER.register("bank", "k", int(arr.nbytes))
    try:
        return risky(arr)
    finally:
        LEDGER.unregister("bank", "k")


def timeline_pair_cm(payload):
    with TIMELINE.begin("req"):
        return risky(payload)


def gauge_pair_finally(payload):
    STATS.inc("inflight")
    try:
        return risky(payload)
    finally:
        STATS.dec("inflight")


def ledger_pair_finalized(owner, arr):
    LEDGER.register("bank", "k", int(arr.nbytes))
    weakref.finalize(owner, LEDGER.unregister, "bank", "k")
    return owner


def evict_then_install(arr):
    # unregister BEFORE register: the cache-replacement idiom, not an
    # open/close bracket (nothing to balance on the exception edge).
    LEDGER.unregister("bank", "old")
    LEDGER.register("bank", "new", int(arr.nbytes))
    return arr
