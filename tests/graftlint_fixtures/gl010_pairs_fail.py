"""GL010 fail fixture: open/close effect pairs balanced only on the
fall-through path — one raise between them leaks the effect."""
from pilosa_tpu.utils.memledger import LEDGER
from pilosa_tpu.utils.stats import MemStatsClient
from pilosa_tpu.utils.timeline import TIMELINE

STATS = MemStatsClient()


def risky(payload):
    return payload["key"]


def ledger_pair(arr):
    LEDGER.register("bank", "k", int(arr.nbytes))
    out = risky(arr)  # a raise here orphans the ledger row
    LEDGER.unregister("bank", "k")
    return out


def timeline_pair(payload):
    handle = TIMELINE.begin("req")
    out = risky(payload)  # a raise leaves the timeline open forever
    TIMELINE.finish(handle)
    return out


def gauge_pair(payload):
    STATS.inc("inflight")
    out = risky(payload)  # a raise leaves the gauge high for good
    STATS.dec("inflight")
    return out
