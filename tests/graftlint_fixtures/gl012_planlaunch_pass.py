"""GL012 pass fixture: launch sites that reach verify_plan — lexically
or through a helper the call graph resolves — before the funnel."""
import jax.numpy as jnp

from pilosa_tpu.ops.megakernel import verify_plan


class DirectLauncher:
    def launch(self, executor, plan, banks, n_shards, w_mega):
        verify_plan(plan, n_shards, w_mega)
        instrs_dev = jnp.asarray(plan.instrs)
        return executor._call_program(plan.fn, banks, instrs_dev)


def _checked(plan, n_shards, w_mega):
    verify_plan(plan, n_shards, w_mega)


class HelperLauncher:
    """The call-graph leg: verification delegated to a module helper."""

    def launch(self, executor, plan, banks, n_shards, w_mega):
        _checked(plan, n_shards, w_mega)
        instrs_dev = jnp.asarray(plan.instrs)
        return executor._call_program(plan.fn, banks, instrs_dev)


class NoPlanInvolved:
    """A funnel call with no plan buffer in sight must not flag."""

    def dispatch(self, executor, fn, bank, idxs):
        return executor._call_program(fn, bank, idxs)
