"""GL006 pass fixture: every jit build site notes its compile (the
tracked-cache idiom), or carries a justified suppression."""
import jax


class Runner:
    def __init__(self):
        self._jit_cache = {}
        self.jit_compiles = 0

    def _note_jit_compile(self):
        self.jit_compiles += 1

    def kernel_for(self, shape):
        fn = self._jit_cache.get(shape)
        if fn is None:
            self._note_jit_compile()
            fn = jax.jit(lambda x: x * 2)
            self._jit_cache[shape] = fn
        return fn

    def nested_build(self, shape):
        # The note may sit in the enclosing function while the build
        # hides in a helper closure.
        def build():
            return jax.jit(lambda x: x + 1)
        self._note_jit_compile()
        return build()


# graftlint: disable=GL006 — process-global compile-once probe kernel
_PROBE = jax.jit(lambda x: x)
