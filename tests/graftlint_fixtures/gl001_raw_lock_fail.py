"""GL001 fail (factory sub-rule): raw threading primitives invisible to
PILOSA_TPU_LOCK_CHECK."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition()
