"""GL015 pass fixture: the safe shapes — check and act under ONE
acquisition, snapshot-then-send with no re-acquire, and double-checked
fill (the second critical section re-validates before acting)."""
from pilosa_tpu.utils.locks import make_lock


def send(payload):
    return payload


class Registry:
    def __init__(self):
        self._lock = make_lock("Registry._lock")
        self.state = "NORMAL"
        self.items = {}

    def _place_locked(self, previous):
        # Callers hold the lock; no acquisition here.
        return dict(self.items) if previous else {}

    def route(self):
        # Check and act atomically: one critical section.
        with self._lock:
            previous = self.state == "RESIZING"
            return self._place_locked(previous)

    def publish(self):
        # Snapshot under the lock, send after — nothing re-acquires.
        with self._lock:
            snap = dict(self.items)
        return send(snap)

    def fill(self, key):
        # Double-checked: the stale probe only gates the attempt; the
        # second critical section re-reads before mutating.
        with self._lock:
            cur = self.items.get(key)
        if cur is not None:
            return cur
        built = object()
        with self._lock:
            fresh = self.items.get(key)
            if fresh is None:
                self.items[key] = built
                fresh = built
        return fresh
