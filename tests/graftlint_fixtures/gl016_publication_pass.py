"""GL016 pass fixture: the safe shapes — stores under the lock, a
lock-held helper (every call site inside the critical section), and an
attribute never consumed under the lock."""
from pilosa_tpu.utils.locks import make_lock


class Stats:
    def __init__(self):
        self._lock = make_lock("Stats._lock")
        self.total = 0
        self.label = ""

    def snapshot(self):
        with self._lock:
            return self.total

    def bump(self, n):
        with self._lock:
            self._bump_held(n)

    def rebase(self):
        with self._lock:
            self._bump_held(0)

    def _bump_held(self, n):
        # Both call sites hold the lock: synchronized by callers.
        self.total += n

    def rename(self, s):
        # Never read under the lock — not this rule's business.
        self.label = s
