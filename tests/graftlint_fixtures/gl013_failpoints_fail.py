"""GL013 fixtures: duplicate registration, in-function registration,
computed name. Expected findings: 3."""

from pilosa_tpu.utils.failpoints import FAILPOINTS

_FP_A = FAILPOINTS.register("fixture.site_a")
_FP_DUP = FAILPOINTS.register("fixture.site_a")  # duplicate name

_NAME = "fixture." + "computed"
_FP_C = FAILPOINTS.register(_NAME)  # not a string literal


def lazy_register():
    # registered per call — the second call raises at runtime
    return FAILPOINTS.register("fixture.lazy")
