"""GL001 pass: locks built through the factory."""
from pilosa_tpu.utils.locks import make_condition, make_rlock


class Worker:
    def __init__(self):
        self._lock = make_rlock("Worker._lock")
        self._cond = make_condition("Worker._cond")
