"""GL002 fail: non-reentrant Lock re-acquired through a helper call."""
from pilosa_tpu.utils.locks import make_lock


class Counter:
    def __init__(self):
        self._lock = make_lock("Counter._lock")
        self.n = 0

    def bump(self):
        with self._lock:
            return self.read()  # read() re-takes the plain Lock

    def read(self):
        with self._lock:
            return self.n
