"""GL009 fail fixture: blocking work under a lock — directly in the
`with` body, and through a helper the call graph resolves."""
import subprocess
import time
from urllib.request import urlopen

from pilosa_tpu.utils.locks import make_lock


class ConvoyedSender:
    def __init__(self):
        self._lock = make_lock("ConvoyedSender._lock")
        self._peers = []

    def deliver(self, msg):
        with self._lock:
            # Direct: sleeping while every other sender waits.
            time.sleep(0.5)
            self._peers.append(msg)

    def push(self, uri, payload):
        with self._lock:
            # Transitive: _post blocks on network I/O.
            self._post(uri, payload)

    def _post(self, uri, payload):
        return urlopen(uri, data=payload).read()

    def rebuild(self):
        with self._lock:
            # Transitive: a child process wait under the lock.
            self._make()

    def _make(self):
        return subprocess.run(["make"], capture_output=True)

    def finish(self, worker):
        with self._lock:
            # Direct: joining a thread while holding the lock.
            worker.join()
