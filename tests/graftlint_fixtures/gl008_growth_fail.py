"""GL008 fail fixture: long-lived accumulators with no bound in scope
— the quiet-leak shape (raw `self._seen[key] = v` on a request path)."""


class LeakyRecorder:
    def __init__(self):
        self._seen = {}
        self._events = []
        self._ids = set()

    def observe(self, key, value):
        # Dict grows per request key: no eviction, cap, ring, or reset
        # anywhere in the class.
        self._seen[key] = value

    def log(self, event):
        self._events.append(event)

    def mark(self, rid):
        self._ids.add(rid)
