"""GL004 pass: statics declared, arrays built lazily."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(1,))
def shifted(words, n):
    return words << n


def caller(words):
    return shifted(words, 3)        # position 1 is static: fine


def lazy_table():
    return jnp.zeros(8, dtype=jnp.uint32)  # inside a function: fine


class Kernels:
    @functools.partial(jax.jit, static_argnums=(1,))
    def shifted_m(self, n, words):
        return words << n

    def caller(self, words):
        # argnum 1 (= call-site position 0 after self) IS static.
        return self.shifted_m(3, words)
