"""GL001 pass: every access to the mutated module dict holds the lock;
read-only module constants need no lock."""
from pilosa_tpu.utils.locks import make_lock

_CACHE = {}
_LOCK = make_lock("fixture._LOCK")
_CONSTANT_TABLE = {"a": 1, "b": 2}  # never mutated: no findings


def put(key, value):
    with _LOCK:
        _CACHE[key] = value


def get(key):
    with _LOCK:
        return _CACHE.get(key)


def lookup(key):
    return _CONSTANT_TABLE.get(key)
