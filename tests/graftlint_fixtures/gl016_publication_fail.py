"""GL016 fail fixture: attributes read under the class lock but
assigned outside it — plain store, augmented store, and a helper whose
call sites do NOT all hold the lock."""
from pilosa_tpu.utils.locks import make_lock


class Stats:
    def __init__(self):
        self._lock = make_lock("Stats._lock")
        self.total = 0
        self.rate = 0.0
        self.label = ""

    def snapshot(self):
        with self._lock:
            return (self.total, self.rate, self.label)

    def bump(self, n):
        self.total += n  # unsynchronized publication

    def set_rate(self, r):
        self.rate = r  # unsynchronized publication

    def rename(self, s):
        self._apply_label(s)  # caller does NOT hold the lock

    def _apply_label(self, s):
        self.label = s
