"""GL001 pass: with-statement and both accepted try/finally shapes."""
from pilosa_tpu.utils.locks import make_lock

_LOCK = make_lock("fixture._LOCK")


def good_with():
    with _LOCK:
        return 1


def good_acquire_then_try():
    _LOCK.acquire()
    try:
        return 2
    finally:
        _LOCK.release()


def good_acquire_inside_try():
    try:
        _LOCK.acquire()
        return 3
    finally:
        _LOCK.release()
