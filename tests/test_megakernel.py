"""Heterogeneous staged-query megakernel (ops/megakernel.py +
executor/megakernel.py) and RTT-hiding pipelined dispatch
(server/coalescer.py): a mixed-signature batch must collapse to
exactly ONE plan-buffer launch with per-query results bit-identical to
the unfused/unpipelined path, the kill switches must restore the
per-group / serial paths exactly, and the dispatch-gap analyzer's
``pilosa_device_idle_ratio`` must strictly drop when pipelining
overlaps batch K+1's plan/H2D with batch K's drain. Launch counts are
asserted deterministically through the ``Executor._call_program``
funnel stub (the tests/test_fusion.py idiom)."""

import threading

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor import megakernel as megamod
from pilosa_tpu.ops.bitset import SHARD_WIDTH

N_ROWS = 16


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(23)
    rows = rng.integers(0, N_ROWS, 6000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 6000).astype(np.uint64)
    f.import_bits(rows, cols)
    g.import_bits(rows[::2], cols[::2])
    # Negative min: BSI base-value offsets are in play, so the lowered
    # plane scans run against offset-encoded predicates like the
    # traced path does.
    idx.create_field("v", FieldOptions(type="int", min=-500, max=10000))
    vcols = rng.integers(0, 2 * SHARD_WIDTH, 900).astype(np.uint64)
    idx.field("v").import_values(
        vcols, rng.integers(-500, 10000, 900).astype(np.int64))
    idx.add_existence(cols)
    executor = Executor(h)
    # Exact launch counts are the subject; the result cache would
    # serve repeats and zero them out (cache-ON interplay is pinned in
    # tests/test_result_cache.py).
    executor.result_cache.enabled = False
    # The default is `auto` (TPU-only — the launch collapse loses on
    # CPU where launches are ~free); force it ON so the CPU test run
    # exercises the megakernel path.
    prev = megamod.MEGAKERNEL_ENABLED
    megamod.MEGAKERNEL_ENABLED = True
    yield executor
    megamod.MEGAKERNEL_ENABLED = prev
    h.close()


def count_dispatches(monkeypatch):
    calls = []
    orig = Executor._call_program

    def stub(self, fn, *args):
        calls.append(fn)
        return orig(self, fn, *args)

    monkeypatch.setattr(Executor, "_call_program", stub)
    return calls


MIXED = ([("i", f"Count(Row(f={r}))", None) for r in (1, 2, 3)]
         + [("i", f"Row(g={r})", None) for r in (4, 5)]
         + [("i", "Count(Intersect(Row(f=6), Row(g=7)))", None)]
         + [("i", "Count(Row(v > 300))", None)]
         + [("i", "Row(v < 9000)", None)])


def test_mixed_signatures_collapse_to_one_launch(ex, monkeypatch):
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in MIXED]
    calls = count_dispatches(monkeypatch)
    jc0 = ex.jit_compiles
    shaped = ex.execute_batch_shaped(MIXED)
    assert shaped == direct
    assert len(calls) == 1, "a mixed batch must be ONE launch"
    assert ex.mega_launches == 1
    assert ex.mega_queries == len(MIXED)
    assert ex.mega_plan_entries > 0
    assert ex.mega_plan_bytes > 0
    # The per-group vmap path never ran.
    assert ex.fused_dispatches == 0
    assert ex.jit_compiles == jc0 + 1, "one interpreter compile"
    # Same composition again: same capacities -> cached program, one
    # more launch, zero new compiles.
    assert ex.execute_batch_shaped(MIXED) == direct
    assert len(calls) == 2
    assert ex.jit_compiles == jc0 + 1
    assert ex.mega_launches == 2


def test_kill_switch_restores_per_group_fusion(ex, monkeypatch):
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in MIXED]
    monkeypatch.setattr(megamod, "MEGAKERNEL_ENABLED", False)
    calls = count_dispatches(monkeypatch)
    shaped = ex.execute_batch_shaped(MIXED)
    assert shaped == direct, "kill switch must not change results"
    assert ex.mega_launches == 0
    assert len(calls) == 5, "5 signature groups under the fallback"
    assert ex.fused_dispatches >= 1


OPS = [
    "Count(Row(f=1))",
    "Row(f=2)",
    "Count(Union(Row(f=1), Row(g=2), Row(f=3)))",
    "Count(Intersect(Row(f=4), Row(g=4)))",
    "Count(Difference(Row(f=5), Row(g=5)))",
    "Count(Xor(Row(f=6), Row(g=6)))",
    "Not(Row(f=7))",
    "Count(Not(Row(g=8)))",
    "Row(f=999)",                      # absent row -> zero-slot leaf
    "Count(Row(v > 300))",
    "Count(Row(v >= 300))",
    "Count(Row(v < 4000))",
    "Count(Row(v <= 4000))",
    "Count(Row(v == 1234))",
    "Count(Row(v != 1234))",
    "Count(Row(v == -800))",           # out of range -> zeros leaf
    "Count(Row(v != -800))",           # out of range -> not-null
    "Count(Row(-100 < v < 500))",      # between
    "Row(v > -499)",
    "Count(Intersect(Row(f=1), Row(v > 2000)))",
]


def test_every_opcode_bit_identical(ex, monkeypatch):
    """Every lowerable op family, mixed in one batch: AND/OR/XOR/
    ANDNOT folds, existence-Not, zero leaves, and the whole BSI
    comparison table (the host-value-specialized plane scans) must
    match the traced per-group programs bit for bit."""
    reqs = [("i", q, None) for q in OPS]
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in reqs]
    calls = count_dispatches(monkeypatch)
    shaped = ex.execute_batch_shaped(reqs)
    assert shaped == direct
    assert len(calls) == 1
    assert ex.mega_queries == len(OPS)


def test_unlowerable_shift_falls_back_beside_megakernel(ex, monkeypatch):
    reqs = ([("i", f"Count(Row(f={r}))", None) for r in (1, 2)]
            + [("i", f"Row(g={r})", None) for r in (3, 4)]
            + [("i", "Count(Shift(Row(f=5), n=3))", None)])
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in reqs]
    calls = count_dispatches(monkeypatch)
    shaped = ex.execute_batch_shaped(reqs)
    assert shaped == direct
    # One megakernel launch for the 4 lowerable evals + one solo
    # program for the Shift (no mega opcode for word carries).
    assert len(calls) == 2
    assert ex.mega_launches == 1
    assert ex.mega_queries == 4


def test_write_fences_megakernel_batches(ex, monkeypatch):
    (c0,) = ex.execute("i", "Count(Row(f=5))")
    r0 = ex.execute("i", "Row(g=5)")[0].columns().tolist()
    calls = count_dispatches(monkeypatch)
    free_col = 2 * SHARD_WIDTH - 7
    out = ex.execute_batch([
        ("i", "Count(Row(f=5))", None),
        ("i", "Row(g=5)", None),
        ("i", f"Set({free_col}, f=5)", None),
        ("i", "Count(Row(f=5))", None),
        ("i", "Row(g=5)", None),
    ])
    assert out[0][0][0] == c0, "head read sees pre-write state"
    assert out[1][0][0].columns().tolist() == r0
    assert out[2][0][0] is True
    assert out[3][0][0] == c0 + 1, "tail read observes the write"
    assert out[4][0][0].columns().tolist() == r0
    # Two mega launches (head pair, tail pair) split by the fence.
    assert len(calls) == 2
    assert ex.mega_launches == 2
    assert ex.mega_queries == 4


def test_single_signature_batches_keep_vmap_fusion(ex, monkeypatch):
    """A homogeneous batch is already one (vmapped) launch — the
    interpreter must not take it."""
    queries = [f"Count(Row(f={r}))" for r in range(8)]
    direct = [ex.execute("i", q)[0] for q in queries]
    calls = count_dispatches(monkeypatch)
    out = ex.execute_batch([("i", q, None) for q in queries])
    assert [r[0][0] for r in out] == direct
    assert len(calls) == 1
    assert ex.fused_dispatches == 1
    assert ex.mega_launches == 0


def test_slab_budget_falls_back_per_group(ex, monkeypatch):
    monkeypatch.setattr(megamod, "MEGA_MAX_BYTES", 1)
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in MIXED]
    calls = count_dispatches(monkeypatch)
    assert ex.execute_batch_shaped(MIXED) == direct
    assert ex.mega_launches == 0
    assert len(calls) == 5


def test_profile_attribution_mega_fields(ex):
    from pilosa_tpu.utils.profile import QueryProfile
    # The Intersect contributes real plan instructions (a gather-only
    # launch legitimately has planEntries == 0).
    reqs = ([("i", f"Count(Row(f={r}))", None) for r in (1, 2)]
            + [("i", "Count(Intersect(Row(f=3), Row(g=3)))", None)])
    profs = [QueryProfile("i", q) for _, q, _ in reqs]
    ex.execute_batch(reqs, profiles=profs)
    seen = set()
    for p in profs:
        evals = [n for op in p.ops for n in op.children
                 if n.name.startswith("eval:")]
        assert evals, p.ops
        node = evals[0]
        assert node.attrs["megaBatch"] == 3
        assert node.attrs["planEntries"] > 0
        assert node.attrs["planBytes"] > 0
        assert node.attrs["jit"] in ("hit", "miss")
        seen.add(node.attrs["megaIndex"])
        assert p.fused_batch == 3
    assert seen == {0, 1, 2}, "each member gets its own launch lane"


def test_post_dispatch_failure_isolates_per_member(ex, monkeypatch):
    """An async device failure surfacing AFTER the launch (at the
    sampled _fence_device inside attribution) must land on the
    cohort's members as per-request errors — the _FuseGroup.run
    isolation contract — and leave the executor serving."""
    from pilosa_tpu.executor import executor as exmod
    from pilosa_tpu.utils.profile import QueryProfile

    def boom(out):
        raise RuntimeError("simulated async device failure")

    monkeypatch.setattr(exmod, "_fence_device", boom)
    profs = [QueryProfile("i", "q", sample_device=True)
             for _ in range(2)]
    out = ex.execute_batch_shaped(
        [("i", "Count(Row(f=1))", None), ("i", "Row(g=2)", None)],
        profiles=profs)
    assert all(isinstance(r, Exception) for r in out), out
    monkeypatch.undo()
    assert ex.execute("i", "Count(Row(f=1))")[0] >= 0


def test_shared_operand_rows_share_one_slab_register(ex):
    """The Tanimoto shape: N Count(Intersect(Row(fp=Q), Row(fp=c)))
    probes share the query row Q — the lowering must gather it ONCE
    per launch, not once per referencing entry."""
    from pilosa_tpu.ops.megakernel import Lowering
    bank = object()
    low = Lowering()
    ir = (("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2))
    for c in (5, 6, 7):
        low.add_entry(ir, [bank], [3, c], [], 8, "count")
    plan = low.finish()
    # Slots: shared Q row (slot 3) once + three distinct candidates.
    assert sorted(plan.slots[0].tolist()) == [3, 5, 6, 7]


def test_error_isolation_beside_megakernel(ex, monkeypatch):
    calls = count_dispatches(monkeypatch)
    out = ex.execute_batch([
        ("i", "Count(Row(f=1))", None),
        ("i", "Count(Row(nosuch=1))", None),  # plan-time error
        ("i", "Row(g=2)", None),
    ])
    assert isinstance(out[1], Exception)
    assert out[0][0][0] == ex.execute("i", "Count(Row(f=1))")[0]
    assert out[2][0][0].columns().tolist() == \
        ex.execute("i", "Row(g=2)")[0].columns().tolist()
    assert ex.mega_queries == 2


# -------------------------------------------------------------------- mesh


@pytest.fixture
def mesh4():
    import jax
    from pilosa_tpu.parallel import MeshContext
    assert len(jax.devices()) >= 4
    return MeshContext(jax.devices()[:4])


def _mesh_ex(holder, mesh):
    executor = Executor(holder, mesh=mesh)
    executor.result_cache.enabled = False
    return executor


def test_mesh_cohort_single_launch_and_counters(ex, mesh4, monkeypatch):
    """A mixed batch on a mesh executor is ONE SPMD launch: the plan
    verifies against the MeshSpec, the mesh counters move, and every
    result matches the single-device executor bit for bit."""
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in MIXED]
    mex = _mesh_ex(ex.holder, mesh4)
    calls = count_dispatches(monkeypatch)
    shaped = mex.execute_batch_shaped(MIXED)
    assert shaped == direct, "mesh cohort results differ"
    assert len(calls) == 1, "a mesh mixed batch must be ONE launch"
    assert mex.mesh_launches == 1
    assert mex.mega_launches == 1
    assert mex.plan_verify_passes >= 1, "mesh plan must be verified"
    assert mex.mesh_collective_bytes > 0
    # Same composition again: cached partitioned program, one more
    # mesh launch, no recompile.
    assert mex.execute_batch_shaped(MIXED) == direct
    assert mex.mesh_launches == 2


def test_mesh_kill_switch_bit_identical(ex, mesh4, monkeypatch):
    """PILOSA_TPU_MESH=0 (module attr MESH_ENABLED) restores the
    pre-mesh behavior exactly: no collector under the mesh, no mesh
    launches, identical bytes."""
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in MIXED]
    mex = _mesh_ex(ex.holder, mesh4)
    monkeypatch.setattr(megamod, "MESH_ENABLED", False)
    shaped = mex.execute_batch_shaped(MIXED)
    assert shaped == direct, "kill switch must not change results"
    assert mex.mesh_launches == 0
    assert mex.mega_launches == 0


def test_mesh_count_reduce_path_zero_host_partials(ex, mesh4):
    """The acceptance's d2h claim: under the mesh epilogue a Count
    lane's device->host transfer is the FINAL uint32 answer (4 bytes),
    never the [S] per-shard partial vector — the in-kernel psum left
    nothing for the host to reduce. Asserted through the profiler's
    real d2h accounting (transfer_nbytes over the pending arrays)."""
    from pilosa_tpu.utils.profile import QueryProfile
    mex = _mesh_ex(ex.holder, mesh4)
    reqs = [("i", f"Count(Row(f={r}))", None) for r in (1, 2)] \
        + [("i", "Count(Intersect(Row(f=3), Row(g=3)))", None)]
    profs = [QueryProfile("i", q) for _, q, _ in reqs]
    out = mex.execute_batch(reqs, profiles=profs)
    assert not any(isinstance(r, Exception) for r in out), out
    assert mex.mesh_launches == 1
    for p in profs:
        assert p.d2h_bytes == 4, (
            f"count reduce path moved {p.d2h_bytes} host bytes — "
            f"expected the 4-byte final answer only")
    # The unmeshed path on the same queries moves the per-shard
    # partials (n_shards * 4 per lane) — the contrast that proves the
    # reduce moved on device.
    profs2 = [QueryProfile("i", q) for _, q, _ in reqs]
    ex.execute_batch(reqs, profiles=profs2)
    for p in profs2:
        assert p.d2h_bytes > 4


def test_mesh_burst_bit_identical(ex, mesh4):
    """The acceptance burst: a 64-thread mixed-signature burst through
    the pipelined coalescer on a mesh executor is byte-identical to
    the same burst with the mesh cohort path killed."""
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient

    queries = _mixed_queries(64)
    direct = {i: ex.execute_full("i", q) for i, q in enumerate(queries)}

    def burst(executor):
        co = QueryCoalescer(executor, window_s=0.005, max_batch=8,
                            stats=MemStatsClient(), pipeline=True)
        co.start()
        results, errors = {}, []
        try:
            _burst(co, queries, results, errors)
        finally:
            co.stop()
        assert not errors, errors
        return results

    mex_on = _mesh_ex(ex.holder, mesh4)
    on = burst(mex_on)
    assert mex_on.mesh_launches >= 1, "burst must take the mesh path"

    megamod.MESH_ENABLED = False
    try:
        mex_off = _mesh_ex(ex.holder, mesh4)
        off = burst(mex_off)
        assert mex_off.mesh_launches == 0
    finally:
        megamod.MESH_ENABLED = True

    assert on == off == direct, \
        "mesh on/off burst responses must be byte-identical"


# --------------------------------------------------------------- pipelined


def _burst(co, queries, results, errors):
    barrier = threading.Barrier(len(queries))

    def worker(i, q):
        try:
            barrier.wait()
            results[i] = co.submit("i", q)
        except Exception as e:  # noqa: BLE001
            errors.append((q, e))

    threads = [threading.Thread(target=worker, args=(i, q))
               for i, q in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)


def _mixed_queries(n):
    qs = []
    for k in range(n):
        r = k % N_ROWS
        qs.append([f"Count(Row(f={r}))", f"Row(g={r})",
                   f"Count(Intersect(Row(f={r}), Row(g={r})))",
                   f"Count(Union(Row(f={r}), Row(g={r})))"][k % 4])
    return qs


def test_pipelined_coalescer_bit_identical(ex):
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient
    queries = _mixed_queries(48)
    direct = {i: ex.execute_full("i", q) for i, q in enumerate(queries)}
    co = QueryCoalescer(ex, window_s=0.005, max_batch=8,
                        stats=MemStatsClient(), pipeline=True)
    assert co.pipeline
    co.start()
    results, errors = {}, []
    try:
        _burst(co, queries, results, errors)
    finally:
        co.stop()
    assert not errors, errors
    assert results == direct, "pipelined responses differ from direct"
    assert co.pipelined_flushes >= 1
    assert ex.mega_launches >= 1


def test_pipeline_kill_switch_serial_path(ex):
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient
    queries = _mixed_queries(24)
    direct = {i: ex.execute_full("i", q) for i, q in enumerate(queries)}
    co = QueryCoalescer(ex, window_s=0.005, max_batch=8,
                        stats=MemStatsClient(), pipeline=False)
    assert not co.pipeline
    co.start()
    results, errors = {}, []
    try:
        _burst(co, queries, results, errors)
    finally:
        co.stop()
    assert not errors, errors
    assert results == direct
    assert co.pipelined_flushes == 0


def test_pipelined_write_observes_sequencing(ex):
    """A write arriving among pipelined read flushes barriers: the
    post-write read must observe it (sequential semantics per item)."""
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient
    co = QueryCoalescer(ex, window_s=0.002, max_batch=8,
                        stats=MemStatsClient(), pipeline=True)
    co.start()
    try:
        results, errors = {}, []
        _burst(co, _mixed_queries(16), results, errors)
        assert not errors, errors
        (c0,) = ex.execute("i", "Count(Row(f=3))")
        free_col = 2 * SHARD_WIDTH - 11
        assert co.submit("i", f"Set({free_col}, f=3)")["results"] == [True]
        assert co.submit("i", "Count(Row(f=3))")["results"] == [c0 + 1]
    finally:
        co.stop()


def test_idle_ratio_strictly_decreases_with_pipeline(ex, monkeypatch):
    """The satellite acceptance, split into its two real claims so
    neither rides the wall clock:

    * **Functional leg** (real coalescer, injected §5-floor latency):
      a pipelined burst actually overlaps — ``pipelined_flushes``
      fires, every query answers, and the gap analyzer saw the
      dispatches. No ratio assertion here: single-run wall-clock
      ratios are thread-scheduler noise on CPU, the exact flake the
      old median-of-3 version papered over.
    * **Scoring leg** (the synthetic-latency harness's deterministic
      clock): the two schedules the pipeline chooses between are fed
      to the analyzer as explicit intervals — serial alternates a
      20 ms dispatch with a 3 ms drain that is pure idle; pipelined
      lands batch K+1's dispatch inside batch K's drain so busy
      intervals cover the gaps — and ``gap_summary(now_pc=...)``
      must score the pipelined schedule strictly lower. Pure interval
      math on an explicit clock: deterministic on any machine."""
    import time as time_mod

    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient
    from pilosa_tpu.utils.timeline import TIMELINE

    queries = _mixed_queries(32)
    # Warm every compiled variant so no burst pays tracing time.
    for q in queries:
        ex.execute_full("i", q)
    ex.execute_batch_shaped([("i", q, None) for q in queries[:8]])

    orig_call = Executor._call_program

    def rtt_call(self, fn, *args):
        def slow_fn(*a):
            time_mod.sleep(0.005)
            return fn(*a)
        return orig_call(self, slow_fn, *args)

    orig_shape = Executor.shape_response

    def slow_shape(self, *a, **k):
        time_mod.sleep(0.002)
        return orig_shape(self, *a, **k)

    monkeypatch.setattr(Executor, "_call_program", rtt_call)
    monkeypatch.setattr(Executor, "shape_response", slow_shape)

    def run(pipeline):
        TIMELINE.reset()
        co = QueryCoalescer(ex, window_s=0.002, max_batch=8,
                            stats=MemStatsClient(), pipeline=pipeline)
        co.start()
        results, errors = {}, []
        try:
            _burst(co, queries, results, errors)
        finally:
            co.stop()
        assert not errors, errors
        assert len(results) == len(queries)
        assert TIMELINE.gap_summary()["dispatches"] >= 2
        return co.pipelined_flushes

    assert run(False) == 0
    assert run(True) >= 1

    # Deterministic scoring: 8 batches of the §5-floor schedule.
    dispatch_s, drain_s, batches = 0.020, 0.003, 8

    def ratio(overlapped):
        TIMELINE.reset()
        t = 0.0
        for _ in range(batches):
            TIMELINE.note_dispatch(t, dispatch_s)
            # Serial: every drain is idle between dispatches.
            # Pipelined: the next dispatch starts inside the drain.
            t += dispatch_s if overlapped else dispatch_s + drain_s
        gap = TIMELINE.gap_summary(now_pc=t)
        assert gap["dispatches"] == batches
        return gap["idleRatio"]

    serial_ratio = ratio(False)
    pipe_ratio = ratio(True)
    TIMELINE.reset()
    assert pipe_ratio < serial_ratio, (
        f"pipelined idle ratio {pipe_ratio:.3f} must drop below the "
        f"serial {serial_ratio:.3f}")
