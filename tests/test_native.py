"""Native C++ host-runtime library (native/pilosa_native.cpp) tests.

Cross-checks the native roaring codec against the pure-Python reference
semantics in storage/roaring.py: identical parse results, byte-identical
serialization, identical error behavior on corrupt input."""

import struct

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.storage.roaring import (
    Bitmap, encode_op, OP_ADD, OP_ADD_BATCH, OP_REMOVE, OP_REMOVE_BATCH,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable")


def _python_bitmap(data: bytes, tolerate_torn_tail: bool = False) -> Bitmap:
    """Force the pure-Python reader regardless of native availability."""
    b = Bitmap.__new__(Bitmap)
    b.__init__()
    with native.force_python():
        b.read_bytes(data, tolerate_torn_tail=tolerate_torn_tail)
    return b


def _mixed_bitmap() -> Bitmap:
    rng = np.random.default_rng(7)
    b = Bitmap()
    # array container
    b.add_batch(rng.choice(1 << 16, 300, replace=False).astype(np.uint64))
    # bitmap container
    b.add_batch((1 << 16) + rng.choice(1 << 16, 50000,
                                       replace=False).astype(np.uint64))
    # run container
    b.add_batch(np.arange(5 << 16, (5 << 16) + 20000, dtype=np.uint64))
    # full container (cardinality 65536 → card-1 wraps to uint16 max)
    b.add_batch(np.arange(9 << 16, 10 << 16, dtype=np.uint64))
    return b


def test_native_parse_matches_python():
    data = _mixed_bitmap().write_bytes()
    keys, words, op_n, _ = native.roaring_load(data)
    pb = _python_bitmap(data)
    assert keys == sorted(pb.containers)
    assert op_n == 0
    from pilosa_tpu.storage.roaring import _as_dense
    for i, k in enumerate(keys):
        assert np.array_equal(words[i], _as_dense(pb.containers[k]))


def test_native_serialize_byte_identical():
    b = _mixed_bitmap()
    keys = sorted(b.containers)
    nk = np.array(keys, dtype=np.uint64)
    nw = np.stack([b.containers[k] for k in keys])
    with native.force_python():
        python_bytes = b.write_bytes()
    assert native.roaring_serialize(nk, nw) == python_bytes


def test_native_ops_replay():
    b = _mixed_bitmap()
    data = b.write_bytes()
    data += encode_op(OP_ADD, (20 << 16) + 5)
    data += encode_op(OP_ADD_BATCH,
                      values=np.array([1, 2, (21 << 16) + 3], dtype=np.uint64))
    data += encode_op(OP_REMOVE, (20 << 16) + 5)
    data += encode_op(OP_REMOVE_BATCH, values=np.array([2], dtype=np.uint64))
    keys, words, op_n, _ = native.roaring_load(data)
    pb = _python_bitmap(data)
    assert op_n == 6  # 1 add + 3 batch-adds + 1 remove + 1 batch-remove
    assert keys == sorted(pb.containers)
    from pilosa_tpu.storage.roaring import _as_dense
    for i, k in enumerate(keys):
        assert np.array_equal(words[i], _as_dense(pb.containers[k]))
    # container 20<<16 emptied by the remove op must not be materialized
    assert (20 << 16) >> 16 not in keys


def test_native_rejects_bad_magic():
    with pytest.raises(ValueError, match="magic"):
        native.roaring_load(struct.pack("<HHI", 999, 0, 0))


def test_native_rejects_corrupt_op_checksum():
    data = Bitmap([1, 2, 3]).write_bytes()
    op = bytearray(encode_op(OP_ADD, 42))
    op[9] ^= 0xFF  # flip a checksum byte
    with pytest.raises(ValueError, match="checksum"):
        native.roaring_load(data + bytes(op))


def test_native_empty_bitmap_roundtrip():
    data = Bitmap().write_bytes()
    keys, words, op_n, _ = native.roaring_load(data)
    assert keys == [] and words.shape == (0, 1024) and op_n == 0


def test_native_fnv1a32_matches_python():
    from pilosa_tpu.storage.roaring import _FNV_OFFSET, _FNV_PRIME

    def py_fnv(*chunks):
        h = _FNV_OFFSET
        for chunk in chunks:
            for byte in chunk:
                h = ((h ^ byte) * _FNV_PRIME) & 0xFFFFFFFF
        return h

    cases = [(b"",), (b"\x00",), (b"hello",), (b"abc", b"defgh"),
             (bytes(range(256)),), (np.arange(1000, dtype="<u8")
                                    .tobytes(),)]
    for chunks in cases:
        assert native.fnv1a32(chunks) == py_fnv(*chunks)


def test_popcount_kernels_match_numpy():
    rng = np.random.default_rng(3)
    a = rng.integers(0, 2**63, 2048, dtype=np.uint64)
    b = rng.integers(0, 2**63, 2048, dtype=np.uint64)
    assert native.popcount(a) == int(np.bitwise_count(a).sum())
    assert native.intersection_count(a, b) == \
        int(np.bitwise_count(a & b).sum())
    rows = a.reshape(8, -1)
    assert np.array_equal(native.row_popcounts(rows),
                          np.bitwise_count(rows).sum(axis=1))


def test_bitmap_roundtrip_through_native_paths():
    """Full loop: Python-built bitmap → native serialize → native parse."""
    b = _mixed_bitmap()
    b2 = Bitmap.from_bytes(b.write_bytes())
    assert sorted(b.containers) == sorted(b2.containers)
    assert b.count() == b2.count()
    assert np.array_equal(b.slice(), b2.slice())


def test_build_masks_matches_python_scatter():
    """direct_add_n produces identical storage with and without the
    native mask builder."""
    rng = np.random.default_rng(5)
    positions = np.unique(rng.integers(0, 40 << 16, 20000, dtype=np.uint64))
    a = Bitmap()
    a.direct_add_n(positions)  # native path (len >= 4096)
    b = Bitmap()
    orig = native.build_masks
    native.build_masks = lambda *args: None
    try:
        b.direct_add_n(positions)
    finally:
        native.build_masks = orig
    assert sorted(a.containers) == sorted(b.containers)
    for k in a.containers:
        assert np.array_equal(a.containers[k], b.containers[k])
    assert a.count() == b.count() == len(positions)
    # incremental merge into existing containers, both paths
    more = np.unique(rng.integers(0, 40 << 16, 20000, dtype=np.uint64))
    a.direct_add_n(more)
    native.build_masks = lambda *args: None
    try:
        b.direct_add_n(more)
    finally:
        native.build_masks = orig
    assert a.count() == b.count() == len(np.union1d(positions, more))
    for k in a.containers:
        assert np.array_equal(a.containers[k], b.containers[k])


def test_scatter_rows_bound_filtering():
    out = np.zeros((3, 8), np.uint64)
    ok = native.scatter_rows(
        np.array([0, 511, 512, 63], np.uint16),   # 512 = first out-of-range
        np.array([3, 1], np.uint64),
        np.array([2, 0], np.uint64), 8, out)
    if not ok:
        return  # native unavailable: nothing to check
    assert out[2][0] & 1 and out[2][7] >> 63
    assert not (out[2][0] >> 1) & 1  # 512 filtered (>= 8*64)
    assert out[0][0] == np.uint64(1) << 63


def test_torn_tail_tolerated_both_codecs():
    """A record torn at EOF (crash mid-append) is dropped, not fatal;
    everything before it replays (divergence from the reference, which
    refuses to open — op.UnmarshalBinary roaring.go:3659)."""
    b = Bitmap([1, 2, 3])
    data = b.write_bytes()
    data += encode_op(OP_ADD, 42)
    good_len = len(data)
    data += encode_op(OP_ADD_BATCH,
                      values=np.arange(10, dtype=np.uint64))[:-5]
    # native
    keys, words, op_n, dropped = native.roaring_load(data)
    assert op_n == 1 and dropped == len(data) - good_len
    # python fallback (opt-in tolerance)
    pb = _python_bitmap(data, tolerate_torn_tail=True)
    assert pb.op_n == 1 and pb.tail_dropped == len(data) - good_len
    assert pb.contains(42)
    # short torn head (< 13 bytes) also tolerated
    data2 = b.write_bytes() + encode_op(OP_ADD, 7)[:6]
    _, _, op_n, dropped = native.roaring_load(data2)
    assert op_n == 0 and dropped == 6
    pb2 = _python_bitmap(data2, tolerate_torn_tail=True)
    assert pb2.op_n == 0 and pb2.tail_dropped == 6


def test_torn_tail_fail_hard_by_default():
    """Wire-received bytes (imports, Bitmap.from_bytes) keep fail-hard
    semantics: a truncated payload errors instead of half-applying."""
    data = Bitmap([1, 2, 3]).write_bytes() + encode_op(OP_ADD, 42)[:-5]
    with pytest.raises(ValueError, match="truncated|out of bounds"):
        Bitmap.from_bytes(data)          # native path
    with pytest.raises(ValueError, match="truncated|out of bounds"):
        _python_bitmap(data)             # python path


def test_torn_tail_mid_log_corruption_still_fatal():
    """A checksum mismatch on a COMPLETE record is corruption, not a torn
    write — both codecs must still refuse it."""
    data = Bitmap([1]).write_bytes()
    op = bytearray(encode_op(OP_ADD, 42))
    op[9] ^= 0xFF
    data = data + bytes(op) + encode_op(OP_ADD, 43)
    with pytest.raises(ValueError, match="checksum"):
        native.roaring_load(data)
    with pytest.raises(ValueError, match="checksum"):
        _python_bitmap(data)


def test_fragment_truncates_torn_tail_on_open(tmp_path):
    """Fragment.open drops the torn bytes from the file so later appends
    start at a clean boundary, and the fragment keeps working."""
    import os
    from pilosa_tpu.core.fragment import Fragment

    p = str(tmp_path / "f")
    f = Fragment(p, "i", "f", "standard", 0)
    f.open()
    for c in range(50):
        f.set_bit(1, c)
    f.close()
    size = os.path.getsize(p)
    with open(p, "r+b") as fh:
        fh.truncate(size - 3)

    f2 = Fragment(p, "i", "f", "standard", 0)
    f2.open()
    assert f2.row_count(1) == 49        # last torn Set dropped
    assert os.path.getsize(p) == size - 3 - 10  # torn record removed
    assert os.path.getsize(p + ".torn") == 10   # bytes preserved, not lost
    f2.set_bit(1, 49)                   # appends work after truncation
    f2.close()
    f3 = Fragment(p, "i", "f", "standard", 0)
    f3.open()
    assert f3.row_count(1) == 50
    f3.close()


def test_parallel_import_build_matches_serial():
    """pn_import_build and pn_serialize_groups parallelize over threads
    (VERDICT r3 next #5; reference: errgroup-parallel import,
    api.go:878-888). Output must be byte-identical at any thread count
    — the stripe order is deterministic. Runs each count in a fresh
    subprocess because the thread count is latched on first native
    call."""
    import os
    import subprocess
    import sys

    code = r"""
import hashlib, sys
import numpy as np
sys.path.insert(0, %(repo)r)
from pilosa_tpu import native
assert native.available()
rng = np.random.default_rng(7)
# Dense-scatter shape, big enough for the parallel scatter gate
# (>= 2^20 pairs) and multi-stripe count/payload passes.
n = 1_600_000
rows = rng.integers(0, 2, n, dtype=np.uint64)
cols = rng.integers(0, 1 << 20, n, dtype=np.uint64)
keys, words, counts, payload, nbits = native.import_build(rows, cols, 20)
# Grouped-serialize shape: >4096 groups so its stripe fill splits.
gkeys = np.arange(6000, dtype=np.uint64)
glows = np.tile(np.arange(3, dtype=np.uint16), 6000)
gbounds = np.arange(0, 3 * 6000 + 1, 3, dtype=np.uint64)
gp = native.serialize_groups(gkeys, glows, gbounds)
print(hashlib.sha256(payload).hexdigest(), int(nbits), len(keys),
      hashlib.sha256(gp).hexdigest())
""" % {"repo": os.path.dirname(os.path.dirname(os.path.abspath(__file__)))}
    outs = {}
    for threads in ("1", "4"):
        env = {**os.environ, "PILOSA_NATIVE_THREADS": threads}
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stderr
        outs[threads] = p.stdout.strip()
        assert outs[threads]
    assert outs["1"] == outs["4"]


def test_crash_point_fuzz_reopen_prefix_semantics(tmp_path):
    """Randomized crash-point fuzz: build a fragment through mixed
    single-bit ops and bulk imports, then truncate the file at MANY
    random byte offsets within the op-log region and reopen each
    prefix. Every reopen must either succeed with a bit-state equal to
    some PREFIX of the applied operations (torn tail dropped), and
    appends must work afterwards — no offset may corrupt silently or
    crash (reference: ops-log replay, roaring.go:1100-1126; our
    torn-tail sidecar recovery)."""
    import os

    import numpy as np

    from pilosa_tpu.core.fragment import Fragment

    rng = np.random.default_rng(77)
    p = str(tmp_path / "f")
    f = Fragment(p, "i", "f", "standard", 0)
    f.open()
    # Operation log we replay host-side: (kind, payload)
    states = []  # cumulative set(positions) AFTER each op
    cur: set = set()

    def snap():
        states.append(set(cur))

    snap()  # state after zero ops
    for step in range(12):
        if rng.random() < 0.5:
            r, c = int(rng.integers(0, 4)), int(rng.integers(0, 3000))
            f.set_bit(r, c)
            cur.add((r, c))
        else:
            rows = rng.integers(0, 4, 25)
            cols = rng.integers(0, 3000, 25)
            f.bulk_import(rows.astype(np.uint64), cols.astype(np.uint64))
            cur.update(zip(rows.tolist(), cols.tolist()))
        snap()
    f.close()
    size = os.path.getsize(p)
    full = open(p, "rb").read()

    prefix_counts = sorted({len(s) for s in states})
    for trial in range(40):
        cut = int(rng.integers(1, size + 1))
        fp = str(tmp_path / f"cut{trial}")
        with open(fp, "wb") as fh:
            fh.write(full[:cut])
        g = Fragment(fp, "i", "f", "standard", 0)
        try:
            g.open()
        except ValueError:
            # Acceptable only for cuts INSIDE the snapshot section
            # (mid-file corruption is fail-hard by design); op-log cuts
            # must recover.
            assert cut <= g.storage.snapshot_bytes or \
                g.storage.snapshot_bytes == 0, \
                (cut, size, g.storage.snapshot_bytes)
            continue
        # Count-based prefix check (order-insensitive): the recovered
        # bit-set must be exactly one of the cumulative states.
        total = sum(g.row_count(r) for r in range(4))
        assert total in prefix_counts, (cut, total, prefix_counts)
        # The recovered fragment accepts new appends.
        g.set_bit(3, 2999)
        assert g.bit(3, 2999)
        g.close()


# ---------------------------------------------------- sanitizer variants


def test_unknown_san_variant_yields_none(monkeypatch):
    """An unrecognized PILOSA_TPU_NATIVE_SAN must NOT fall back to the
    uninstrumented library — that would fake a green sanitized run."""
    monkeypatch.setenv("PILOSA_TPU_NATIVE_SAN", "bogus")
    assert native.load() is None
    assert not native.available()


def test_load_cache_is_keyed_on_san_variant(monkeypatch):
    """A variant requested AFTER another was first loaded must not be
    served that cached library (regression: a single _tried/_lib pair
    pinned whatever variant touched load() first for process life)."""
    base_lib = native.load()
    base = native.active_san()
    # The counterpart variant must be loadable WITHOUT a runtime
    # preload, whatever leg this test runs under: plain and ubsan both
    # qualify (dlopen'ing the asan .so into a process that did not
    # preload libasan hard-aborts — "runtime does not come first").
    other = "ubsan" if base != "ubsan" else ""
    monkeypatch.setenv("PILOSA_TPU_NATIVE_SAN", other)
    got = native.load()
    assert got is not base_lib or base_lib is None
    monkeypatch.setenv("PILOSA_TPU_NATIVE_SAN", base)
    assert native.load() is base_lib


def test_staged_bytes_uses_exact_malloc_block_under_san(monkeypatch):
    """Under a sanitizer the input staging path must round-trip through
    the exact-size libc malloc block (where ASan redzones sit)."""
    monkeypatch.setenv("PILOSA_TPU_NATIVE_SAN", "ubsan")
    data = bytes(range(256)) * 3
    staged = native._StagedBytes(data)
    with staged as ptr:
        assert staged._raw is not None  # malloc path, not ctypes copy
        assert bytes(ptr[i] for i in range(len(data))) == data
    assert staged._raw is None  # freed on exit
