"""AttrStore: append-log write path, torn-tail recovery, compaction,
block checksums (reference attr.go:80-119, boltdb/attrstore.go)."""

import json
import os

import pytest

from pilosa_tpu.core import attrs as attrs_mod
from pilosa_tpu.core.attrs import ATTR_BLOCK_SIZE, AttrStore


@pytest.fixture
def store(tmp_path):
    s = AttrStore(str(tmp_path / "d" / ".attrs"))
    s.open()
    yield s
    s.close()


def reopened(s):
    s.close()
    s2 = AttrStore(s.path)
    s2.open()
    return s2


def test_set_get_merge_delete(store):
    store.set(1, {"a": 1, "b": "x"})
    store.set(1, {"b": None, "c": [1, 2]})
    assert store.get(1) == {"a": 1, "c": [1, 2]}
    store.set(1, {"a": None, "c": None})
    assert store.get(1) == {}
    assert 1 not in store.attrs  # fully-emptied ids drop


def test_log_append_and_replay(store):
    store.set(5, {"k": "v"})
    store.set_bulk({6: {"x": 1}, 7: {"y": 2}})
    store.set(6, {"x": None, "z": 3})
    # The write path appended (no snapshot rewrite yet).
    assert os.path.getsize(store.path + ".log") > 0
    assert not os.path.exists(store.path)
    s2 = reopened(store)
    assert s2.get(5) == {"k": "v"}
    assert s2.get(6) == {"z": 3}
    assert s2.get(7) == {"y": 2}
    s2.close()


def test_torn_tail_truncated(store):
    store.set(1, {"a": 1})
    store.set(2, {"b": 2})
    store.close()
    with open(store.path + ".log", "ab") as f:
        f.write(b'{"3": {"c":')  # crash mid-append
    s2 = AttrStore(store.path)
    s2.open()
    assert s2.get(1) == {"a": 1} and s2.get(2) == {"b": 2}
    assert s2.get(3) == {}
    # The torn bytes are gone; further writes replay cleanly.
    s2.set(4, {"d": 4})
    s3 = reopened(s2)
    assert s3.get(4) == {"d": 4}
    s3.close()


def test_compaction_folds_log(store, monkeypatch):
    monkeypatch.setattr(attrs_mod, "LOG_COMPACT_ENTRIES", 10)
    for i in range(25):
        store.set(i, {"n": i})
    # Two compactions happened; log is small, snapshot holds the rest.
    assert os.path.exists(store.path)
    with open(store.path + ".log") as f:
        assert len(f.read().strip().splitlines()) < 10
    s2 = reopened(store)
    assert all(s2.get(i) == {"n": i} for i in range(25))
    s2.close()


def test_legacy_snapshot_only_store_opens(tmp_path):
    path = str(tmp_path / ".attrs")
    with open(path, "w") as f:
        json.dump({"9": {"old": True}}, f)
    s = AttrStore(path)
    s.open()
    assert s.get(9) == {"old": True}
    s.set(10, {"new": 1})
    s2 = reopened(s)
    assert s2.get(9) == {"old": True} and s2.get(10) == {"new": 1}
    s2.close()


def test_blocks_diff_after_log_writes(store):
    store.set(3, {"a": 1})
    store.set(ATTR_BLOCK_SIZE + 3, {"a": 1})
    b1 = dict(store.blocks())
    store.set(3, {"a": 2})
    b2 = dict(store.blocks())
    assert b1[0] != b2[0]          # changed block's checksum moved
    assert b1[1] == b2[1]          # untouched block unchanged
    assert store.block_data(1) == {ATTR_BLOCK_SIZE + 3: {"a": 1}}


def test_oplog_survives_process_kill(tmp_path):
    """Op appends are unbuffered (one write syscall each, Go file-write
    semantics): bits written through the executor are durable on disk
    even if the process dies WITHOUT close() — modeled by opening a
    second holder on the same dir while the first is still open."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    h = Holder(str(tmp_path / "d"))
    h.open()
    h.create_index("k").create_field("f")
    Executor(h).execute("k", "Set(1, f=3) Set(9, f=3)")
    # No h.close() — the "killed" process's buffers never flush.
    h2 = Holder(str(tmp_path / "d"))
    h2.open()
    (row,) = Executor(h2).execute("k", "Row(f=3)")
    assert row.columns().tolist() == [1, 9]
    h2.close()


def test_write_cost_flat_in_store_size(tmp_path):
    """The VERDICT r4 #6 criterion: per-write cost must not grow with
    store size (the old path re-serialized the whole store per set).
    Compare per-write time at 100 ids vs 10k ids — allow generous
    noise, fail only on the old O(store) blow-up."""
    import time
    s = AttrStore(str(tmp_path / ".attrs"))
    s.open()

    def time_writes(base, n=50):
        t0 = time.perf_counter()
        for i in range(n):
            s.set(base + i, {"v": i})
        return (time.perf_counter() - t0) / n

    for i in range(100):
        s.set(i, {"v": i, "pad": "x" * 50})
    small = time_writes(10_000)
    for i in range(10_000):
        s.attrs.setdefault(20_000 + i, {"v": i, "pad": "x" * 50})
    big = time_writes(50_000)
    s.close()
    assert big < small * 20 + 1e-3, (small, big)
