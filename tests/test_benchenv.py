"""Tests for the bench-environment helpers (pilosa_tpu/utils/benchenv.py):
the hold-for-device gate, its deadline contract, the partial-record
handler's exit status, and the persistent compile-cache knob. These are
the pieces the round-4 TPU suite's retry correctness rests on
(benches/run_tpu_suite_r04b.sh marks a leg done only on rc==0)."""

import json
import os
import signal
import subprocess
import sys

import pytest

from pilosa_tpu.utils import benchenv


@pytest.fixture
def hold_env(monkeypatch):
    """Clean slate for the hold gate's env knobs."""
    for k in ("PILOSA_BENCH_HOLD_FOR_TPU", "PILOSA_BENCH_HOLD_MAX_S",
              "PILOSA_BENCH_PLATFORM"):
        monkeypatch.delenv(k, raising=False)
    return monkeypatch


def _forbid_probe(monkeypatch):
    def boom(timeout_s=75.0):  # pragma: no cover - failure path
        raise AssertionError("probe_device_once must not be called")
    monkeypatch.setattr(benchenv, "probe_device_once", boom)


def test_hold_gate_off_by_default(hold_env):
    _forbid_probe(hold_env)
    benchenv.hold_for_tpu("t")  # returns without probing


@pytest.mark.parametrize("val", ["0", "false", "FALSE", ""])
def test_hold_gate_off_values(hold_env, val):
    _forbid_probe(hold_env)
    hold_env.setenv("PILOSA_BENCH_HOLD_FOR_TPU", val)
    benchenv.hold_for_tpu("t")


def test_hold_noop_in_smoke_mode(hold_env):
    """A PILOSA_BENCH_PLATFORM smoke run must never hold: the probe
    asserts a non-cpu platform, so holding would always hit deadline."""
    _forbid_probe(hold_env)
    hold_env.setenv("PILOSA_BENCH_HOLD_FOR_TPU", "1")
    hold_env.setenv("PILOSA_BENCH_PLATFORM", "cpu")
    benchenv.hold_for_tpu("t")


def test_hold_returns_when_device_answers(hold_env):
    hold_env.setenv("PILOSA_BENCH_HOLD_FOR_TPU", "1")
    hold_env.setattr(benchenv, "probe_device_once",
                     lambda timeout_s=75.0: (True, ""))
    before = signal.getsignal(signal.SIGTERM)
    benchenv.hold_for_tpu("t")
    assert signal.getsignal(signal.SIGTERM) is before


def test_hold_deadline_exits_tempfail(hold_env):
    """Deadline with the device unreachable must EXIT (75), not proceed:
    a dead axon tunnel makes the first in-process device op stall
    forever, which would burn the leg's whole timeout."""
    hold_env.setenv("PILOSA_BENCH_HOLD_FOR_TPU", "1")
    hold_env.setenv("PILOSA_BENCH_HOLD_MAX_S", "0")
    hold_env.setattr(benchenv, "probe_device_once",
                     lambda timeout_s=75.0: (False, "down"))
    before = signal.getsignal(signal.SIGTERM)
    with pytest.raises(SystemExit) as exc:
        benchenv.hold_for_tpu("t")
    assert exc.value.code == 75
    # The partial-record disarm is restored even on the exit path.
    assert signal.getsignal(signal.SIGTERM) is before


def test_hold_disarms_sigterm_while_waiting(hold_env):
    """During the hold, SIGTERM must be at SIG_DFL (no partial record
    can be meaningful before the query phase)."""
    hold_env.setenv("PILOSA_BENCH_HOLD_FOR_TPU", "1")
    seen = {}

    def probe(timeout_s=75.0):
        seen["handler"] = signal.getsignal(signal.SIGTERM)
        return True, ""

    hold_env.setattr(benchenv, "probe_device_once", probe)
    prev = signal.signal(signal.SIGTERM, lambda s, f: None)
    try:
        benchenv.hold_for_tpu("t")
        assert seen["handler"] is signal.SIG_DFL
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_partial_record_handler_exits_143():
    """SIGTERM during a bench leg: parseable partial line on stdout,
    exit 143 — so an rc==0-based suite done-marker never counts a
    partial-only leg as completed."""
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r);"
         "from pilosa_tpu.utils.benchenv import"
         " install_partial_record_handler;"
         "install_partial_record_handler('m', 'u');"
         "print('READY', flush=True);"
         "import time; time.sleep(30)" % os.path.dirname(
             os.path.dirname(os.path.abspath(__file__)))],
        stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "READY"
    proc.terminate()
    out, _ = proc.communicate(timeout=15)
    assert proc.returncode == 143
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["partial"] is True and rec["metric"] == "m"


@pytest.mark.parametrize("val", ["0", "false", "False", ""])
def test_enable_compile_cache_disable(monkeypatch, val):
    monkeypatch.setenv("PILOSA_BENCH_COMPILE_CACHE", val)
    import jax

    before = jax.config.jax_compilation_cache_dir
    benchenv.enable_compile_cache()
    assert jax.config.jax_compilation_cache_dir == before


def test_enable_compile_cache_default_skipped_on_cpu(monkeypatch):
    """XLA:CPU AOT cache entries can mismatch the loading host's machine
    features (observed SIGILL-risk warnings); the DEFAULT cache dir must
    only arm for device runs. Under the test conftest jax_platforms is
    'cpu', which is exactly the cpu-first config that must stay off."""
    monkeypatch.delenv("PILOSA_BENCH_COMPILE_CACHE", raising=False)
    import jax

    assert jax.config.jax_platforms.split(",")[0] == "cpu"
    before = jax.config.jax_compilation_cache_dir
    benchenv.enable_compile_cache()
    assert jax.config.jax_compilation_cache_dir == before


def test_enable_compile_cache_explicit_dir_honored(monkeypatch, tmp_path):
    """An explicitly set PILOSA_BENCH_COMPILE_CACHE is an operator
    opt-in: honored even on a cpu platform (e.g. validating cache
    behavior in a smoke run)."""
    monkeypatch.setenv("PILOSA_BENCH_COMPILE_CACHE", str(tmp_path))
    import jax

    before = jax.config.jax_compilation_cache_dir
    try:
        benchenv.enable_compile_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


# ---------------------------------------------------------------- round 4:
# contention stamps / quiet gate (benchenv.measurement_context)

def test_quiet_wait_budget_env(monkeypatch):
    from pilosa_tpu.utils import benchenv
    monkeypatch.delenv("PILOSA_BENCH_WAIT_QUIET_S", raising=False)
    assert benchenv.quiet_wait_budget_s(30.0) == 30.0
    monkeypatch.setenv("PILOSA_BENCH_WAIT_QUIET_S", "7.5")
    assert benchenv.quiet_wait_budget_s() == 7.5
    # Empty and garbage values mean the default, never a crash.
    monkeypatch.setenv("PILOSA_BENCH_WAIT_QUIET_S", "")
    assert benchenv.quiet_wait_budget_s(11.0) == 11.0
    monkeypatch.setenv("PILOSA_BENCH_WAIT_QUIET_S", "nope")
    assert benchenv.quiet_wait_budget_s(11.0) == 11.0


def test_measurement_context_fields(monkeypatch):
    from pilosa_tpu.utils import benchenv
    ctx = benchenv.measurement_context(wait_quiet_s=0)
    assert set(ctx) == {"loadavg_1m", "trivial_fetch_ms",
                        "waited_quiet_s"}
    assert ctx["trivial_fetch_ms"] >= 0
    assert ctx["waited_quiet_s"] == 0.0


def test_trivial_probe_compiles_once():
    """The quiet-gate loop polls this; a compile per poll would inflate
    the contention signal it measures, so the jitted probe is cached."""
    from pilosa_tpu.utils import benchenv
    benchenv.trivial_fetch_ms(samples=1)
    probe = benchenv._trivial_probe
    assert probe is not None
    benchenv.trivial_fetch_ms(samples=1)
    assert benchenv._trivial_probe is probe


def test_bench_sidecar_carry_tolerates_corrupt_payload(tmp_path,
                                                       monkeypatch):
    """A malformed sidecar (zero/absent tpu_s_per_call, wrong JSON
    shape) must yield carry=None, never an exception — sidecar_carry
    runs BEFORE the provisional record prints (code-review r5)."""
    import importlib.util
    import json as _json
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    side = tmp_path / "side.json"
    monkeypatch.setattr(bench, "LAST_GOOD_TPU_PATH", str(side))
    import time as _time
    for payload in (
        {"measured_at_unix": _time.time(),
         "payload": {"tpu_s_per_call": 0}},           # zero divisor
        {"measured_at_unix": _time.time(), "payload": {}},  # absent
        {"payload": None},                             # wrong shape
        "not a dict",
    ):
        side.write_text(_json.dumps(payload))
        assert bench.sidecar_carry(1e9, 1 << 30) is None
    side.write_text("{garbage")
    assert bench.sidecar_carry(1e9, 1 << 30) is None
    # A healthy sidecar still carries.
    side.write_text(_json.dumps({
        "measured_at_unix": _time.time(), "bits": 1 << 30,
        "payload": {"tpu_s_per_call": 0.5}}))
    got = bench.sidecar_carry(1e9, 1 << 30)
    assert got is not None and got["value"] == (1 << 30) / 0.5
