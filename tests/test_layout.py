"""Adaptive hybrid bank layout (core/layout.py, view.SparseBank, the
megakernel OP_EXPAND path): bit-identity across layouts and paths,
the re-layout pass's ledger-provable byte deltas, demotion-ranked
BankBudget eviction, the true-live-density demotion quadrants, and
the cache-interaction invariants (spurious miss allowed, stale hit
never — the PR 10 epoch-guard pattern exercised by its third
invalidation source)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from pilosa_tpu.core import layout as layout_mod
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.layout import LayoutManager, demotion_scores
from pilosa_tpu.core.view import BankBudget, SparseBank
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.memledger import LEDGER


@pytest.fixture(autouse=True)
def _fresh_workload():
    WORKLOAD.reset()
    yield
    WORKLOAD.reset()


def _build(tmp, sparse_rows=600, seed=5, two_shards=True):
    """Holder with a narrow sparse-eligible field "s" (many near-empty
    rows), a dense field "f", and existence."""
    h = Holder(tmp)
    h.open()
    idx = h.create_index("i")
    rng = np.random.default_rng(seed)
    s_rows = np.repeat(np.arange(sparse_rows, dtype=np.uint64), 2)
    s_cols = rng.integers(0, 4096, 2 * sparse_rows).astype(np.uint64)
    if two_shards:
        # A second shard's worth of sparse bits for multi-shard plans.
        half = len(s_cols) // 2
        s_cols[half:] += SHARD_WIDTH
    idx.create_field("s").import_bits(s_rows, s_cols)
    f_rows = rng.integers(0, 8, 6000).astype(np.uint64)
    f_cols = rng.integers(0, 2 * SHARD_WIDTH, 6000).astype(np.uint64)
    idx.create_field("f").import_bits(f_rows, f_cols)
    idx.add_existence(np.concatenate([s_cols, f_cols]))
    return h, idx


QUERIES = (
    ["Count(Row(s={r}))".format(r=r) for r in range(6)]
    + ["Row(s=2)", "Row(s=999)", "Count(Row(s=9999))",
       "Count(Intersect(Row(s=1), Row(f=1)))",
       "Count(Union(Row(s=2), Row(s=3), Row(f=2)))",
       "Count(Difference(Row(f=3), Row(s=3)))",
       "Count(Xor(Row(s=4), Row(f=4)))",
       "Count(Not(Row(s=5)))"]
)


def _results(ex, queries):
    out = []
    for q in queries:
        res = ex.execute("i", q)
        out.append([r.columns() if hasattr(r, "columns") else r
                    for r in res])
    return repr(out)


def test_sparse_layout_bit_identity_unfused_and_fused(tmp_path):
    h, idx = _build(str(tmp_path))
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        dense = _results(ex, QUERIES)
        view = idx.field("s").view("standard")
        assert view.set_layout("sparse")
        assert _results(ex, QUERIES) == dense
        # Fused (vmap) batch path, sparse operands stacked by idxs.
        reqs = [("i", q, None) for q in QUERIES]
        from pilosa_tpu.executor import megakernel as megamod
        prev = megamod.MEGAKERNEL_ENABLED
        try:
            megamod.MEGAKERNEL_ENABLED = False
            fused = ex.execute_batch_shaped(reqs)
            view.set_layout("dense")
            assert ex.execute_batch_shaped(reqs) == fused
        finally:
            megamod.MEGAKERNEL_ENABLED = prev
    finally:
        h.close()


def test_megakernel_expand_launch_bit_identity(tmp_path):
    h, idx = _build(str(tmp_path))
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        idx.field("s").view("standard").set_layout("sparse")
        reqs = [("i", q, None) for q in QUERIES]
        from pilosa_tpu.executor import megakernel as megamod
        prev = megamod.MEGAKERNEL_ENABLED
        try:
            megamod.MEGAKERNEL_ENABLED = True
            on = ex.execute_batch_shaped(reqs)
            assert ex.mega_launches >= 1
            # Every launch passed the plan-IR gate (conftest pins
            # PILOSA_TPU_PLAN_VERIFY=on), OP_EXPAND included.
            assert ex.plan_verify_rejects == 0
            assert ex.plan_verify_passes >= 1
            megamod.MEGAKERNEL_ENABLED = False
            off = ex.execute_batch_shaped(reqs)
        finally:
            megamod.MEGAKERNEL_ENABLED = prev
        assert on == off
    finally:
        h.close()


def test_sparse_bank_write_invalidation(tmp_path):
    """Version discipline: a write after the sparse bank build makes
    the cached bank read stale and rebuild — the new bit must appear
    (spurious miss allowed, stale hit never)."""
    h, idx = _build(str(tmp_path), two_shards=False)
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        view = idx.field("s").view("standard")
        view.set_layout("sparse")
        before = ex.execute("i", "Count(Row(s=1))")[0]
        idx.field("s").set_bit(1, 4000)
        after = ex.execute("i", "Count(Row(s=1))")[0]
        assert after == before + 1
        idx.field("s").clear_bit(1, 4000)
        assert ex.execute("i", "Count(Row(s=1))")[0] == before
    finally:
        h.close()


def test_result_cache_no_stale_hit_across_relayout(tmp_path):
    """Satellite: promote/demote between two identical queries with
    the result cache ON — results bit-identical (relayout moves
    representation, never data), and a write after the flip still
    invalidates (the generation guard is layout-independent)."""
    h, idx = _build(str(tmp_path), two_shards=False)
    try:
        ex = Executor(h)
        assert ex.result_cache.enabled
        view = idx.field("s").view("standard")
        q = "Count(Row(s=3))"
        r1 = ex.execute("i", q)[0]
        view.set_layout("sparse")    # invalidation source #3
        r2 = ex.execute("i", q)[0]
        assert r2 == r1
        idx.field("s").set_bit(3, 4001)
        assert ex.execute("i", q)[0] == r1 + 1
        view.set_layout("dense")
        assert ex.execute("i", q)[0] == r1 + 1
    finally:
        h.close()


def test_relayout_under_lock_check_subprocess(tmp_path):
    """The satellite's LOCK_CHECK leg: demote/promote racing queries
    under the runtime lock-order checker — no cycle in the acquisition
    graph (BankBudget -> Ledger/Workload scoring included), results
    bit-identical."""
    script = r"""
import os, tempfile, threading
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.utils.locks import lock_order_violations

with tempfile.TemporaryDirectory() as d:
    h = Holder(d); h.open()
    idx = h.create_index("i")
    rows = np.repeat(np.arange(200, dtype=np.uint64), 2)
    cols = np.random.default_rng(0).integers(0, 4096, 400).astype(np.uint64)
    idx.create_field("s").import_bits(rows, cols)
    idx.add_existence(cols)
    ex = Executor(h)
    view = idx.field("s").view("standard")
    want = ex.execute("i", "Count(Row(s=1))")[0]
    stop = threading.Event()
    errs = []
    def flipper():
        m = 0
        while not stop.is_set():
            view.set_layout("sparse" if m % 2 == 0 else "dense")
            m += 1
    def querier():
        try:
            for _ in range(40):
                got = ex.execute("i", "Count(Row(s=1))")[0]
                assert got == want, (got, want)
        except Exception as e:
            errs.append(e)
    t1 = threading.Thread(target=flipper)
    qs = [threading.Thread(target=querier) for _ in range(3)]
    t1.start(); [t.start() for t in qs]
    [t.join() for t in qs]; stop.set(); t1.join()
    assert not errs, errs
    assert not lock_order_violations(), lock_order_violations()
    h.close()
print("LOCK_CHECK_OK")
"""
    env = dict(os.environ)
    env["PILOSA_TPU_LOCK_CHECK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "LOCK_CHECK_OK" in proc.stdout


def test_relayout_pass_ledger_delta_and_promotion(tmp_path):
    h, idx = _build(str(tmp_path), sparse_rows=1500)
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        want = _results(ex, QUERIES[:4])
        before = LEDGER.total_bytes(device_only=True)
        assert before > 0
        mgr = LayoutManager(h, min_bytes=1024)
        WORKLOAD.reset()  # cold heat map: "s" demotes
        summary = mgr.relayout_once()
        assert summary["ran"] and summary["demoted"] >= 1, summary
        assert summary["deltaBytes"] < 0, summary
        snap = mgr.snapshot()
        assert snap["demotions"] >= 1 and snap["sparseViews"] >= 1
        assert snap["bytesReclaimed"] > 0
        assert _results(ex, QUERIES[:4]) == want
        # Heat the sparse view back up -> the next pass promotes.
        for _ in range(30):
            ex.execute("i", "Count(Row(s=1))")
        s2 = mgr.relayout_once()
        assert s2["promoted"] >= 1, s2
        assert idx.field("s").view("standard").layout_mode == "dense"
        assert _results(ex, QUERIES[:4]) == want
    finally:
        h.close()


def test_demote_compacts_point_write_densified_storage(tmp_path):
    """A view built from point Set()s (every written row's container
    densified for mutation) must still demote: the pass runs
    Fragment.optimize_storage (the Bitmap.optimize model) before the
    positions gather — found by the live-server drive, pinned here."""
    h = Holder(str(tmp_path))
    h.open()
    try:
        idx = h.create_index("i")
        f = idx.create_field("sp")
        for r in range(300):
            f.set_bit(r, (r * 7) % 4096)
            f.set_bit(r, (r * 13) % 4096)
        ex = Executor(h)
        ex.result_cache.enabled = False
        want = ex.execute("i", "Count(Row(sp=5))")[0]
        ex.execute("i", "Count(Row(sp=1))")  # materialize the bank
        WORKLOAD.reset()
        mgr = LayoutManager(h, min_bytes=1024)
        summary = mgr.relayout_once()
        assert summary["demoted"] == 1, summary
        assert mgr.demote_failures == 0
        assert idx.field("sp").view("standard").layout_mode == "sparse"
        assert ex.execute("i", "Count(Row(sp=5))")[0] == want
        f.set_bit(5, 4000)
        assert ex.execute("i", "Count(Row(sp=5))")[0] == want + 1
    finally:
        h.close()


def test_kill_switch_disables_sparse_planning(tmp_path):
    h, idx = _build(str(tmp_path), two_shards=False)
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        view = idx.field("s").view("standard")
        view.set_layout("sparse")
        from pilosa_tpu.pql.parser import parse_string
        call = parse_string("Row(s=1)").calls[0]
        prev = layout_mod.HYBRID_LAYOUT_ENABLED
        try:
            layout_mod.HYBRID_LAYOUT_ENABLED = False
            staged = ex._stage_tree(idx, call, [0], "row")
            # Dense program: no (pos, starts) pairs among operands.
            assert not any(isinstance(a, tuple)
                           for a in staged.bank_arrays)
            mgr = LayoutManager(h)
            assert mgr.relayout_once() == {"ran": False,
                                           "reason": "disabled"}
        finally:
            layout_mod.HYBRID_LAYOUT_ENABLED = prev
        staged = ex._stage_tree(idx, call, [0], "row")
        assert any(isinstance(a, tuple) for a in staged.bank_arrays)
    finally:
        h.close()


def test_sparse_bank_too_dense_self_heals(tmp_path):
    """A view marked sparse whose data is actually dense: the build
    bails, the view self-heals to dense, and the query still answers
    from the dense path."""
    h = Holder(str(tmp_path))
    h.open()
    try:
        idx = h.create_index("i")
        f = idx.create_field("d")
        rng = np.random.default_rng(2)
        # A few rows with ~60% of a 4096-col window set: dense-encoded
        # containers dominate and rows_positions bails.
        for r in range(4):
            cols = rng.choice(4096, size=2500,
                              replace=False).astype(np.uint64)
            f.import_bits(np.full(2500, r, np.uint64), cols)
        idx.add_existence(np.arange(4096, dtype=np.uint64))
        ex = Executor(h)
        ex.result_cache.enabled = False
        view = f.view("standard")
        dense = ex.execute("i", "Count(Row(d=1))")[0]
        view.set_layout("sparse")
        assert ex.execute("i", "Count(Row(d=1))")[0] == dense
        assert view.layout_mode == "dense"  # self-healed
    finally:
        h.close()


# --------------------------------------------------------- verify_plan


def _xpair(rows, positions=256):
    return (np.zeros(positions, np.uint32),
            np.zeros(rows + 1, np.int32))


def test_verify_plan_expand_typing():
    low = mk.Lowering()
    xp = _xpair(16)
    bank = np.zeros((8, 2, 8), np.uint32)
    low.add_entry((("slot", 0, 0), ("xslot", 1, 1), ("fold", "and", 2)),
                  [bank, xp], [1, 3], [], 8, "count")
    plan = low.finish()
    assert plan.n_xslots == 1
    mk.verify_plan(plan, 2, 8)  # clean

    # OP_EXPAND importing a non-expand register.
    from tools.planverify import clone_plan
    p = clone_plan(plan)
    for i in range(p.n_instrs):
        if int(p.instrs[i, 0]) == mk.OP_EXPAND:
            p.instrs[i, 2] = 0  # dense slot
            break
    with pytest.raises(mk.PlanVerifyError, match="not an expand"):
        mk.verify_plan(p, 2, 8)

    # A bitwise opcode reading the expand register directly.
    p = clone_plan(plan)
    for i in range(p.n_instrs):
        if int(p.instrs[i, 0]) == mk.OP_AND:
            p.instrs[i, 2] = p.n_slots  # the expand register
            break
    with pytest.raises(mk.PlanVerifyError, match="only through"):
        mk.verify_plan(p, 2, 8)

    # Sparse gather index past the starts table.
    p = clone_plan(plan)
    p.xslots[0][0] = 99
    with pytest.raises(mk.PlanVerifyError, match="starts table"):
        mk.verify_plan(p, 2, 8)

    # Writing an expand register.
    p = clone_plan(plan)
    p.instrs[0, 1] = p.n_slots
    with pytest.raises(mk.PlanVerifyError, match="read-only"):
        mk.verify_plan(p, 2, 8)


def test_plan_mutations_cover_expand_kinds():
    from tools.planverify import PLAN_MUTATIONS, mutate_plan
    low = mk.Lowering()
    xp = _xpair(16)
    ir = (("xslot", 0, 0), ("xslot", 0, 1), ("fold", "or", 2))
    low.add_entry(ir, [xp], [2, 5], [], 8, "count")
    plan = low.finish()
    mk.verify_plan(plan, 2, 8)
    applied = 0
    for ki, kind in enumerate(PLAN_MUTATIONS):
        rng = np.random.default_rng([7, ki])
        mutated = mutate_plan(rng, plan, kind, w_mega=8)
        if mutated is None:
            continue
        applied += 1
        with pytest.raises(mk.PlanVerifyError):
            mk.verify_plan(mutated, 2, 8)
    assert applied >= 8  # the expand kinds applied on this plan


# ------------------------------------------------- eviction + density


def test_bank_budget_evicts_sparsest_coldest_first(tmp_path):
    """Pinning: under pressure the demotion-ranked victim (sparse,
    cold) goes before an OLDER dense-hot bank — score beats LRU, and
    LRU still breaks ties."""
    h, idx = _build(str(tmp_path), sparse_rows=1200, two_shards=False)
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        # Materialize both dense banks (ledger rows carry liveDensity).
        ex.execute("i", "Count(Row(f=1))")
        ex.execute("i", "Count(Row(s=1))")
        fview = idx.field("f").view("standard")
        sview = idx.field("s").view("standard")
        fkey = next(k for k in fview._bank_cache)
        skey = next(k for k in sview._bank_cache)
        f_nb = LEDGER.entry_info(("bank",), (id(fview), fkey))["bytes"]
        s_nb = LEDGER.entry_info(("bank",), (id(sview), skey))["bytes"]
        # Keep "f" hot, "s" cold.
        WORKLOAD.reset()
        for _ in range(50):
            WORKLOAD.record_read("i", "f", "standard", [0, 1])
        scores = demotion_scores({(id(fview), fkey): (fview, f_nb),
                                  (id(sview), skey): (sview, s_nb)})
        assert scores[(id(sview), skey)] > scores[(id(fview), fkey)]
        # HOT admitted FIRST (LRU would evict it); ranking must evict
        # the sparse-cold bank instead.
        budget = BankBudget(f_nb + s_nb)
        budget.admit(fview, fkey, nbytes=f_nb)
        budget.admit(sview, skey, nbytes=s_nb)
        budget.admit(fview, ("trigger",), nbytes=16)
        assert skey not in sview._bank_cache, "sparse-cold must evict"
        assert fkey in fview._bank_cache, "dense-hot must survive"
    finally:
        h.close()


def test_live_density_reaches_quadrants(tmp_path):
    """A full-width-but-sparse bank scores demotable: its ledger row
    carries the sampled live-bit density and the hotspots quadrant
    density reflects it (pad share alone would call it dense)."""
    h, idx = _build(str(tmp_path), sparse_rows=1024, two_shards=False)
    try:
        ex = Executor(h)
        ex.result_cache.enabled = False
        ex.execute("i", "Count(Row(s=1))")
        entry = next(e for e in LEDGER.entries("bank")
                     if e.get("field") == "s")
        assert 0 < entry["liveDensity"] < 0.05, entry
        # 1024 rows + zero slot pad to 2048 -> pad share alone says
        # ~50% dense; the LIVE density must drag the quadrant down.
        banks = WORKLOAD.snapshot(
            top_k=10, bank_entries=[entry])["opportunity"]["banks"]
        assert banks and banks[0]["quadrant"].startswith("sparse-")
        assert banks[0]["density"] < 0.05
        assert banks[0]["demotionScore"] > 0
    finally:
        h.close()


# ------------------------------------------------ config + surfaces


def test_config_layout_keys(tmp_path):
    from pilosa_tpu.utils.config import load_config
    p = tmp_path / "c.toml"
    p.write_text("[layout]\nenabled = false\ninterval_s = 7.5\n"
                 "demote_density = 0.1\nmin_bytes = 4096\n"
                 "promote_rate = 2.0\n")
    cfg = load_config(str(p))
    assert cfg.layout_enabled is False
    assert cfg.layout_interval_s == 7.5
    assert cfg.layout_demote_density == 0.1
    assert cfg.layout_min_bytes == 4096
    assert cfg.layout_promote_rate == 2.0
    p.write_text("layout_demote_density = 1.5\n")
    with pytest.raises(ValueError):
        load_config(str(p))


def test_health_and_memory_layout_stanza(tmp_path):
    from pilosa_tpu.server.api import API
    from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text
    h, idx = _build(str(tmp_path), two_shards=False)
    try:
        api = API(h, stats=MemStatsClient())
        api.query("i", "Count(Row(s=1))")
        mem = api.debug_memory()
        assert mem["totalBytes"] == sum(
            c["bytes"] for c in mem["categories"].values())
        assert "layout" in mem and "sparseViews" in mem["layout"]
        health = api.node_health()
        for k in ("enabled", "sparseViews", "demotions", "promotions",
                  "relayoutRuns", "bytesReclaimed"):
            assert k in health["layout"], health["layout"]
        api.refresh_memory_gauges()
        met = prometheus_text(api.stats)
        assert "pilosa_layout_sparse_views" in met
    finally:
        h.close()


def test_sparse_bank_structure(tmp_path):
    h, idx = _build(str(tmp_path))
    try:
        view = idx.field("s").view("standard")
        bank = view.sparse_bank((0, 1))
        assert isinstance(bank, SparseBank)
        pos, starts = bank.arrays
        assert int(starts[-1]) == int(starts[bank.n_rows])
        # Absent rows resolve to the empty zero slot.
        z = bank.slot(10**6)
        assert z == bank.zero_slot
        s0, s1 = int(starts[z]), int(starts[z + 1])
        assert s0 == s1
        # Cached: same versions alias the same object.
        assert view.sparse_bank((0, 1)) is bank
        # Compact: resident bytes well under the dense equivalent.
        from pilosa_tpu.core.view import bank_capacity
        dense_bytes = (bank_capacity(bank.n_rows) * 2
                       * view.trimmed_words() * 4)
        assert bank.nbytes < dense_bytes / 10
    finally:
        h.close()
