"""HTTP surface tests (reference server/handler_test.go
TestHandler_Endpoints) — a real server on a random port."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.storage import Bitmap


@pytest.fixture
def server(live_server):
    base, api, _h = live_server
    yield base, api


def req(base, method, path, body=None, raw=False):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(base + path, data=data, method=method)
    with urllib.request.urlopen(r) as resp:
        payload = resp.read()
        return resp.status, payload if raw else json.loads(payload or b"{}")


def test_end_to_end_http(server):
    base, _ = server
    # create index + fields
    st, _ = req(base, "POST", "/index/myidx", {"options": {}})
    assert st == 200
    st, _ = req(base, "POST", "/index/myidx/field/f", {"options": {}})
    assert st == 200
    st, _ = req(base, "POST", "/index/myidx/field/n",
                {"options": {"type": "int", "min": 0, "max": 100}})
    assert st == 200

    # write + query via PQL
    st, res = req(base, "POST", "/index/myidx/query",
                  b"Set(1, f=10) Set(2, f=10) Set(1, n=42)")
    assert res["results"] == [True, True, True]
    st, res = req(base, "POST", "/index/myidx/query", b"Row(f=10)")
    assert res["results"][0]["columns"] == [1, 2]
    st, res = req(base, "POST", "/index/myidx/query",
                  {"query": "Count(Row(f=10))"})
    assert res["results"] == [2]
    st, res = req(base, "POST", "/index/myidx/query", b"TopN(f, n=1)")
    assert res["results"][0] == [{"id": 10, "count": 2}]
    st, res = req(base, "POST", "/index/myidx/query", b'Sum(field="n")')
    assert res["results"][0] == {"value": 42, "count": 1}

    # bulk import (JSON body)
    st, _ = req(base, "POST", "/index/myidx/field/f/import",
                {"rowIDs": [7, 7], "columnIDs": [100, 200]})
    assert st == 200
    st, res = req(base, "POST", "/index/myidx/query", b"Row(f=7)")
    assert res["results"][0]["columns"] == [100, 200]

    # roaring import (raw bytes)
    bm = Bitmap(np.array([3 * 2**20 + 5], dtype=np.uint64))  # row 3, col 5
    st, _ = req(base, "POST", "/index/myidx/field/f/import-roaring/0",
                bm.write_bytes())
    st, res = req(base, "POST", "/index/myidx/query", b"Row(f=3)")
    assert res["results"][0]["columns"] == [5]

    # schema / status / version / shards-max
    st, schema = req(base, "GET", "/schema")
    names = [f["name"] for f in schema["indexes"][0]["fields"]]
    assert names == ["f", "n"]
    st, status = req(base, "GET", "/status")
    assert status["state"] == "NORMAL"
    st, v = req(base, "GET", "/version")
    assert "version" in v
    st, sm = req(base, "GET", "/internal/shards/max")
    assert sm["standard"]["myidx"] == 0

    # export + fragment sync endpoints
    st, csv = req(base, "GET", "/export?index=myidx&field=f&shard=0", raw=True)
    assert b"10,1" in csv
    st, blocks = req(base, "GET",
                     "/internal/fragment/blocks?index=myidx&field=f&shard=0")
    assert blocks["blocks"]
    st, frag = req(base, "GET",
                   "/internal/fragment/data?index=myidx&field=f&shard=0",
                   raw=True)
    got = Bitmap.from_bytes(frag)
    assert got.count() > 0

    # delete field then index
    st, _ = req(base, "DELETE", "/index/myidx/field/n")
    st, schema = req(base, "GET", "/schema")
    assert [f["name"] for f in schema["indexes"][0]["fields"]] == ["f"]
    st, _ = req(base, "DELETE", "/index/myidx")
    st, schema = req(base, "GET", "/schema")
    assert schema["indexes"] == []


def test_http_errors(server):
    base, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/nosuch/query", b"Row(f=1)")
    assert e.value.code == 404 or e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "GET", "/no/such/route")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "DELETE", "/index/nosuch")
    assert e.value.code == 404
    # malformed PQL
    req(base, "POST", "/index/i2", {})
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/i2/query", b"Row(")
    assert e.value.code == 400


def test_column_keys_http(server):
    base, _ = server
    req(base, "POST", "/index/keyed", {"options": {"keys": True}})
    req(base, "POST", "/index/keyed/field/f",
        {"options": {"keys": True}})
    st, res = req(base, "POST", "/index/keyed/query",
                  b"Set('alice', f='admin') Set('bob', f='admin')")
    assert res["results"] == [True, True]
    st, res = req(base, "POST", "/index/keyed/query", b"Row(f='admin')")
    assert sorted(res["results"][0]["keys"]) == ["alice", "bob"]
    # import with keys
    st, _ = req(base, "POST", "/index/keyed/field/f/import",
                {"rowKeys": ["user"], "columnKeys": ["carol"]})
    st, res = req(base, "POST", "/index/keyed/query", b"Row(f='user')")
    assert res["results"][0]["keys"] == ["carol"]


def test_translation_scoping(server):
    """Attr values never get key-translated; unkeyed fields reject string
    rows; keys stay aligned with columns."""
    base, api = server
    req(base, "POST", "/index/k2", {"options": {"keys": True}})
    req(base, "POST", "/index/k2/field/city", {"options": {"keys": True}})
    req(base, "POST", "/index/k2/field/plain", {"options": {}})
    # attr named like a keyed field must stay a string
    req(base, "POST", "/index/k2/query",
        b"Set('c1', plain=1) SetRowAttrs(plain, 1, city=\"nyc\")")
    assert api.holder.index("k2").field("plain").row_attr_store.get(1) == \
        {"city": "nyc"}
    # string row on unkeyed field errors instead of silently allocating
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/k2/query", b"Row(plain='oops')")
    assert e.value.code == 400
    # raw ids on a keyed field are rejected unless explicitly allowed
    # (reference api.go:836-860 + ignoreKeyCheck escape hatch)
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/k2/field/city/import",
            {"rowIDs": [1], "columnIDs": [99]})
    assert e.value.code == 400
    # keys align with columns even for raw-id imports
    req(base, "POST", "/index/k2/field/city/import?ignoreKeyCheck=true",
        {"rowIDs": [1], "columnIDs": [99]})  # bypasses the translator
    req(base, "POST", "/index/k2/query", b"Set('alice', city='a')")
    st, res = req(base, "POST", "/index/k2/query",
                  b"Union(Row(city='a'), Row(city=1))")
    r = res["results"][0]
    assert len(r["keys"]) == len(r["columns"])


def test_rows_previous_key(server):
    base, _ = server
    req(base, "POST", "/index/k3", {"options": {"keys": True}})
    req(base, "POST", "/index/k3/field/f", {"options": {"keys": True}})
    req(base, "POST", "/index/k3/query",
        b"Set('c1', f='apple') Set('c2', f='banana')")
    st, res = req(base, "POST", "/index/k3/query", b"Rows(f, previous='apple')")
    assert res["results"][0]["keys"] == ["banana"]


def test_query_url_exec_options(server):
    """columnAttrs/excludeColumns as URL args, reference PostQuery
    optional args (http/handler.go:186)."""
    base, _ = server
    req(base, "POST", "/index/u", {})
    req(base, "POST", "/index/u/field/f", {})
    req(base, "POST", "/index/u/query", b"Set(7, f=1)")
    st, res = req(base, "POST", "/index/u/query?excludeColumns=true",
                  b"Row(f=1)")
    assert st == 200 and res["results"][0]["columns"] == []
    st, res = req(base, "POST", "/index/u/query", b"Row(f=1)")
    assert res["results"][0]["columns"] == [7]


def test_unknown_query_args_rejected(server):
    """Unknown query-string args get 400 (reference queryArgValidator,
    http/handler.go:171-235)."""
    import urllib.error
    base, _ = server
    req(base, "POST", "/index/v", {})
    req(base, "POST", "/index/v/field/f", {})
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(base, "POST", "/index/v/query?bogus=1", b"Count(Row(f=1))")
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(base, "GET", "/export?index=v&field=f&bad=2")
    assert ei.value.code == 400


def test_export_csv_translates_keys(server):
    """Export writes keys, not raw ids, for keyed fields/indexes
    (reference api.ExportCSV per-bit translation, api.go:430-500)."""
    base, _ = server
    req(base, "POST", "/index/ek", {"options": {"keys": True}})
    req(base, "POST", "/index/ek/field/tag", {"options": {"keys": True}})
    req(base, "POST", "/index/ek/query", b"Set('alice', tag='red')")
    st, body = req(base, "GET", "/export?index=ek&field=tag&shard=0",
                   raw=True)
    assert st == 200 and body.decode().strip() == "red,alice"


def test_export_csv_quoting_and_fallback(server):
    """Keys with commas are csv-quoted; unmapped ids fall back to the
    decimal id instead of 'None'."""
    base, _ = server
    req(base, "POST", "/index/eq", {"options": {"keys": True}})
    req(base, "POST", "/index/eq/field/tag", {"options": {"keys": True}})
    req(base, "POST", "/index/eq/query", b"Set('a,b', tag='red')")
    # raw-id bit with no key mapping, via the escape hatch
    req(base, "POST", "/index/eq/field/tag/import?ignoreKeyCheck=true",
        {"rowIDs": [55], "columnIDs": [7]})
    st, body = req(base, "GET", "/export?index=eq&field=tag&shard=0",
                   raw=True)
    lines = sorted(body.decode().strip().split("\n"))
    assert 'red,"a,b"' in lines
    assert "55,7" in lines


def test_parse_error_with_url_options_is_400(server):
    base, _ = server
    req(base, "POST", "/index/pe", {})
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/pe/query?excludeColumns=true", b"Row(")
    assert e.value.code == 400
    # boolean URL args: explicit false stays off
    req(base, "POST", "/index/pe/field/f", {})
    req(base, "POST", "/index/pe/query", b"Set(3, f=1)")
    st, res = req(base, "POST", "/index/pe/query?excludeColumns=false",
                  b"Row(f=1)")
    assert res["results"][0]["columns"] == [3]


def test_prometheus_metrics_endpoint(server):
    base, _ = server
    req(base, "POST", "/index/pm", {})
    req(base, "POST", "/index/pm/field/f", {})
    req(base, "POST", "/index/pm/query", b"Set(1, f=2)")
    req(base, "POST", "/index/pm/query", b"Count(Row(f=2))")
    st, body = req(base, "GET", "/metrics", raw=True)
    text = body.decode()
    assert st == 200
    assert "# TYPE pilosa_query_total counter" in text
    assert "pilosa_query_total" in text
