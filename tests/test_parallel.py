"""Distribution tests over the 8-device virtual CPU mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.parallel import MeshContext, ShardPlacement


@pytest.fixture
def mesh8():
    import jax
    assert len(jax.devices()) >= 8
    return MeshContext(jax.devices()[:8])


def test_placement_padding():
    p = ShardPlacement(4)
    assert p.pad([0, 1, 2, 3]) == [0, 1, 2, 3]
    assert p.pad([0, 1, 2, 3, 7]) == [0, 1, 2, 3, 7, 8, 9, 10]
    assert p.pad([]) == [0, 1, 2, 3]
    assert len(p.pad([5])) == 4


def test_sharded_query_matches_local(tmp_path, mesh8):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    rng = np.random.default_rng(3)
    # 10 shards (not divisible by 8 — exercises padding)
    cols = rng.choice(10 * SHARD_WIDTH, size=20000, replace=False).astype(np.uint64)
    rows = np.arange(20000, dtype=np.uint64) % 5
    f.import_bits(rows, cols)
    idx.add_existence(cols)

    local = Executor(h)
    dist = Executor(h, mesh=mesh8)

    queries = [
        "Count(Row(f=0))",
        "Count(Intersect(Row(f=0), Row(f=1)))",
        "Count(Union(Row(f=0), Row(f=1), Row(f=2)))",
        "Count(Not(Row(f=3)))",
    ]
    with mesh8.mesh:
        for q in queries:
            (a,) = local.execute("i", q)
            (b,) = dist.execute("i", q)
            assert a == b, q

        (tn_l,) = local.execute("i", "TopN(f, n=3)")
        (tn_d,) = dist.execute("i", "TopN(f, n=3)")
        assert tn_l.pairs == tn_d.pairs

        (row_l,) = local.execute("i", "Row(f=2)")
        (row_d,) = dist.execute("i", "Row(f=2)")
        np.testing.assert_array_equal(row_l.columns(), row_d.columns())
    h.close()


def test_sharded_bank_placement(tmp_path, mesh8):
    """Bank arrays really are split over the mesh shard axis."""
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    cols = np.arange(0, 8 * SHARD_WIDTH, SHARD_WIDTH, dtype=np.uint64) + 5
    f.import_bits(np.zeros(8, np.uint64), cols)
    ex = Executor(h, mesh=mesh8)
    with mesh8.mesh:
        ex.execute("i", "Count(Row(f=0))")
    view = f.view()
    bank = view.device_bank(tuple(range(8)), mesh=mesh8)
    assert len(bank.array.sharding.device_set) == 8
    h.close()


def test_bsi_sharded(tmp_path, mesh8):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    n = idx.create_field("n", FieldOptions(type="int", min=-5, max=100))
    cols = np.arange(0, 9 * SHARD_WIDTH, 1000, dtype=np.uint64)
    vals = (np.arange(len(cols)) % 106 - 5).astype(np.int64)
    n.import_values(cols, vals)
    local = Executor(h)
    dist = Executor(h, mesh=mesh8)
    with mesh8.mesh:
        for q in ["Count(Row(n > 50))", 'Sum(field="n")', 'Min(field="n")',
                  'Max(field="n")']:
            (a,) = local.execute("i", q)
            (b,) = dist.execute("i", q)
            av = (a.value, a.count) if hasattr(a, "value") else a
            bv = (b.value, b.count) if hasattr(b, "value") else b
            assert av == bv, q
    h.close()


def test_replicated_mesh(tmp_path):
    import jax
    mesh = MeshContext(jax.devices()[:8], replicas=2)
    assert mesh.n_shard_devices == 4
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    f.import_bits(np.zeros(100, np.uint64),
                  np.arange(100, dtype=np.uint64) * 40000)
    ex = Executor(h, mesh=mesh)
    with mesh.mesh:
        (c,) = ex.execute("i", "Count(Row(f=0))")
    assert c == 100
    h.close()


def test_graft_entry_contract():
    import __graft_entry__ as ge
    import jax
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert len(out) == 4
    ge.dryrun_multichip(8)


def test_pad_does_not_alias_excluded_shards(tmp_path, mesh8):
    """Padding a shard subset must not pull in real excluded shards."""
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    # shards 0..9 each hold one bit in row 0
    cols = (np.arange(10, dtype=np.uint64) * SHARD_WIDTH) + 7
    f.import_bits(np.zeros(10, np.uint64), cols)
    ex = Executor(h, mesh=mesh8)
    with mesh8.mesh:
        (c,) = ex.execute("i", "Count(Row(f=0))", shards=[0, 1])
    assert c == 2  # not 8: shards 2..7 are excluded, padding must skip them
    h.close()


def test_store_does_not_create_phantom_shards(tmp_path, mesh8):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    f.import_bits(np.zeros(3, np.uint64),
                  np.array([0, SHARD_WIDTH, 2 * SHARD_WIDTH], np.uint64))
    ex = Executor(h, mesh=mesh8)
    with mesh8.mesh:
        ex.execute("i", "Store(Row(f=0), g=1)")
    assert idx.field("g").available_shards() == [0, 1, 2]
    h.close()


def test_int_field_range_guard(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    with pytest.raises(ValueError, match="63 bits"):
        idx.create_field("big", FieldOptions(type="int", min=-2**62,
                                             max=2**62))
    idx.create_field("ok", FieldOptions(type="int", min=0, max=2**40))
    h.close()


def test_chunked_topn_under_mesh(tmp_path, mesh8, monkeypatch):
    """The over-budget TopN stream (chunk banks, host-block + HBM-LRU
    caches) must agree with local execution when sharded over the mesh,
    and repeat queries must agree after cache warm-up."""
    from pilosa_tpu.executor import executor as executor_mod

    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("ct")
    f = idx.create_field("f")
    rng = np.random.default_rng(5)
    cols = rng.choice(10 * SHARD_WIDTH, size=30000,
                      replace=False).astype(np.uint64)
    rows = np.arange(30000, dtype=np.uint64) % 300
    f.import_bits(rows, cols)
    monkeypatch.setattr(executor_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(executor_mod, "TOPN_CHUNK_ROWS", 64)

    local = Executor(h)
    dist = Executor(h, mesh=mesh8)
    q = "TopN(f, Row(f=0), n=10)"
    with mesh8.mesh:
        (a,) = local.execute("ct", q)
        (b,) = dist.execute("ct", q)
        assert a.pairs == b.pairs
        (b2,) = dist.execute("ct", q)   # warm: cached chunk banks
        assert b2.pairs == b.pairs
        # write between queries: caches must invalidate
        dist.execute("ct", "Set(10000000, f=0) Set(10000000, f=1)")
        (b3,) = dist.execute("ct", q)
        (a3,) = local.execute("ct", q)
        assert b3.pairs == a3.pairs != b.pairs
    h.close()
