"""Tracing: span recording, OTLP/HTTP JSON export wire format.

Reference: /root/reference/tracing/tracing.go:18-56 (opentracing facade)
and the Jaeger wiring in server/config.go:110-118. The rebuild exports
OTLP/HTTP JSON (Jaeger >=1.35 and the OTel collector ingest it
natively); these tests capture real export POSTs and assert the wire
shape field by field.
"""

import http.server
import json
import threading

import numpy as np
import pytest

from pilosa_tpu.utils.tracing import (
    ExportingTracer,
    RecordingTracer,
    spans_to_otlp,
)


class _Capture(http.server.BaseHTTPRequestHandler):
    captured = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        type(self).captured.append(
            (self.path, dict(self.headers), json.loads(body)))
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *a):
        pass


@pytest.fixture
def capture_server():
    _Capture.captured = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Capture)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/v1/traces", \
        _Capture.captured
    srv.shutdown()
    srv.server_close()


def test_spans_to_otlp_wire_shape():
    tr = RecordingTracer()
    with tr.span("API.Query", index="i") as root:
        with tr.span("executor.Execute"):
            pass
    doc = spans_to_otlp(tr.finished, "svc")
    (rs,) = doc["resourceSpans"]
    attrs = {a["key"]: a["value"]["stringValue"]
             for a in rs["resource"]["attributes"]}
    assert attrs["service.name"] == "svc"
    (ss,) = rs["scopeSpans"]
    spans = ss["spans"]
    assert [s["name"] for s in spans] == ["API.Query", "executor.Execute"]
    parent, child = spans
    # Hex ids at OTLP JSON widths; child links to parent; trace shared.
    assert len(parent["traceId"]) == 32 and len(parent["spanId"]) == 16
    int(parent["traceId"], 16), int(parent["spanId"], 16)
    assert child["parentSpanId"] == parent["spanId"]
    assert child["traceId"] == parent["traceId"]
    assert "parentSpanId" not in parent
    # Nanos ride as strings (uint64 JSON mapping) and are ordered.
    for s in spans:
        assert isinstance(s["startTimeUnixNano"], str)
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    assert {a["key"]: a["value"]["stringValue"]
            for a in parent["attributes"]} == {"index": "i"}
    assert root.span_id == parent["spanId"]


def test_exporting_tracer_posts_batches(capture_server):
    endpoint, captured = capture_server
    tr = ExportingTracer(endpoint, service_name="pilosa-test",
                         batch_size=2, flush_interval=3600)
    with tr.span("a"):
        pass
    assert not captured  # below batch size, nothing shipped yet
    with tr.span("b"):
        with tr.span("b.child"):
            pass
    tr.flush()
    assert len(captured) == 1
    path, headers, doc = captured[0]
    assert path == "/v1/traces"
    assert headers["Content-Type"] == "application/json"
    names = [s["name"] for s in
             doc["resourceSpans"][0]["scopeSpans"][0]["spans"]]
    assert names == ["a", "b", "b.child"]


def test_failed_spans_still_export(capture_server):
    """Spans whose traced block raised must still reach the exporter —
    failed-request traces are the ones operators need."""
    endpoint, captured = capture_server
    tr = ExportingTracer(endpoint, batch_size=1, flush_interval=3600)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("query failed")
    tr.flush()
    names = [s["name"] for _, _, doc in captured
             for s in doc["resourceSpans"][0]["scopeSpans"][0]["spans"]]
    assert names == ["boom"]


def test_inject_emits_w3c_traceparent():
    """inject speaks traceparent (00-<trace>-<span>-01): the root
    span's trace id, the innermost open span as parent."""
    from pilosa_tpu.utils.tracing import parse_traceparent
    tr = RecordingTracer()
    headers = {}
    with tr.span("root") as root:
        with tr.span("child") as child:
            tr.inject(headers)
    tp = headers["traceparent"]
    ver, tid, sid, flags = tp.split("-")
    assert (ver, flags) == ("00", "01")
    assert tid == root.trace_id and len(tid) == 32
    assert sid == child.span_id and len(sid) == 16
    assert parse_traceparent(tp) == root.trace_id
    # The legacy header rides along (same id) for the one-release
    # window, so a not-yet-upgraded receiver still correlates.
    assert headers["X-Trace-Id"] == root.trace_id


def test_extract_traceparent_round_trip():
    """A trace id injected by one tracer is adopted by another through
    the traceparent header — the same id stamps both sides' spans."""
    a, b = RecordingTracer(), RecordingTracer()
    headers = {}
    with a.span("client"):
        a.inject(headers)
    b.extract(headers)
    with b.span("server"):
        pass
    assert b.finished[0].trace_id == a.finished[0].trace_id


def test_extract_accepts_legacy_header():
    """X-Trace-Id still extracts (one-release compatibility window for
    mixed-version clusters)."""
    tr = RecordingTracer()
    tid = "ab" * 16
    tr.extract({"X-Trace-Id": tid})
    with tr.span("s"):
        pass
    assert tr.finished[0].trace_id == tid


def test_extract_prefers_traceparent_and_rejects_malformed():
    from pilosa_tpu.utils.tracing import parse_traceparent
    # traceparent wins over the legacy header when both are present.
    tr = RecordingTracer()
    tp_tid = "cd" * 16
    tr.extract({"traceparent": f"00-{tp_tid}-{'12' * 8}-01",
                "X-Trace-Id": "ab" * 16})
    with tr.span("s"):
        pass
    assert tr.finished[0].trace_id == tp_tid
    # Malformed traceparents parse to None instead of poisoning.
    for bad in ("junk", "00-short-1212121212121212-01",
                f"00-{'0' * 32}-{'12' * 8}-01",       # all-zero trace
                f"ff-{'cd' * 16}-{'12' * 8}-01",      # reserved version
                f"00-{'zz' * 16}-{'12' * 8}-01",      # non-hex
                f"00-{'cd' * 16}-{'0' * 16}-01",      # all-zero span
                f"00-{'cd' * 16}-{'12' * 8}-zz",      # non-hex flags
                f"00-{'cd' * 16}-{'12' * 8}-01-x"):   # v00 extra field
        assert parse_traceparent(bad) is None, bad
    # ... and a malformed traceparent falls back to the legacy header.
    tr2 = RecordingTracer()
    tr2.extract({"traceparent": "junk", "X-Trace-Id": "ab" * 16})
    with tr2.span("s"):
        pass
    assert tr2.finished[0].trace_id == "ab" * 16


def test_non_hex_trace_header_is_sanitized():
    """Client-settable X-Trace-Id must not poison the OTLP batch: a
    non-hex value re-hashes deterministically to 32 hex chars."""
    tr = RecordingTracer()
    tr.extract({"X-Trace-Id": "req-abc!!"})
    with tr.span("s"):
        pass
    tid = tr.finished[0].trace_id
    assert len(tid) == 32
    int(tid, 16)
    # Deterministic: a second node extracting the same junk correlates.
    tr2 = RecordingTracer()
    tr2.extract({"X-Trace-Id": "req-abc!!"})
    with tr2.span("s"):
        pass
    assert tr2.finished[0].trace_id == tid


def test_export_failure_drops_without_raising():
    tr = ExportingTracer("http://127.0.0.1:9/v1/traces")  # nothing there
    with tr.span("doomed"):
        pass
    assert tr.flush() is False
    assert tr.flush() is True  # dropped, not retried


def test_live_query_spans_reach_exporter(tmp_path, capture_server):
    """Spans from a real API.Query land in the OTLP payload (VERDICT r2
    missing #4: 'spans from a live query visible in an exporter-format
    fixture')."""
    endpoint, captured = capture_server
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server.api import API

    tr = ExportingTracer(endpoint, service_name="pilosa-test",
                         batch_size=1, flush_interval=3600)
    holder = Holder(str(tmp_path))
    holder.open()
    api = API(holder, tracer=tr)
    api.create_index("ti", {})
    api.create_field("ti", "f", {})
    api.import_bits("ti", "f",
                    np.array([1, 1], np.uint64),
                    np.array([3, 9], np.uint64))
    res = api.query("ti", "Count(Row(f=1))")
    assert res["results"] == [2]
    tr.flush()
    all_spans = [s for _, _, doc in captured
                 for s in doc["resourceSpans"][0]["scopeSpans"][0]["spans"]]
    by_name = {s["name"]: s for s in all_spans}
    assert "API.Query" in by_name
    attrs = {a["key"]: a["value"]["stringValue"]
             for a in by_name["API.Query"]["attributes"]}
    assert attrs.get("index") == "ti"
    holder.close()


def test_span_duration_immune_to_clock_step(monkeypatch):
    """Regression (PR 7 satellite): Span previously stamped start/end
    with two time.time() reads, so an NTP step mid-span corrupted the
    duration. Durations are now perf_counter deltas with ONE wall
    anchor per trace for export timestamps."""
    import time as _time

    tr = RecordingTracer()
    wall = [_time.time()]
    monkeypatch.setattr(_time, "time", lambda: wall[0])
    with tr.span("outer") as outer:
        with tr.span("inner") as inner:
            wall[0] -= 3600.0  # the clock steps BACK an hour mid-span
    # Durations stay tiny and non-negative despite the step...
    assert 0.0 <= inner.duration() < 5.0
    assert 0.0 <= outer.duration() < 5.0
    # ...and the derived wall end never precedes the start.
    assert outer.end >= outer.start
    # OTLP export anchors every span of the trace on the ROOT's wall
    # clock: the child's offset from the root is monotonic, so end >=
    # start holds and the child nests inside the parent window.
    doc = spans_to_otlp(tr.finished, "svc")
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    parent, child = spans
    for s in spans:
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    assert int(child["startTimeUnixNano"]) >= \
        int(parent["startTimeUnixNano"])
    assert int(child["endTimeUnixNano"]) <= int(parent["endTimeUnixNano"])


def test_extract_without_headers_clears_stale_thread_id():
    """Handler threads are reused across keep-alive requests: a request
    with NO trace headers must clear the previous request's adopted id
    instead of stitching unrelated requests into one trace."""
    tr = RecordingTracer()
    tr.extract({"X-Trace-Id": "ab" * 16})
    assert tr.current_trace_id() == "ab" * 16
    tr.extract({})  # next request on the same thread, no headers
    assert tr.current_trace_id() is None


def test_inject_falls_back_to_adopted_thread_id():
    """Scatter-gather worker threads have no open span; after adopt()
    their outgoing requests still inject the coordinator's trace id
    (the fix that made cross-node stitching deterministic instead of
    relying on a stale-thread-local side channel)."""
    from pilosa_tpu.utils.tracing import parse_traceparent

    tr = RecordingTracer()
    headers = {}
    tr.inject(headers)
    assert "traceparent" not in headers  # nothing to propagate
    tr.adopt("cd" * 16)
    tr.inject(headers)
    assert parse_traceparent(headers["traceparent"]) == "cd" * 16
    assert headers["X-Trace-Id"] == "cd" * 16


def test_tracer_ring_registers_with_memory_ledger():
    """The finished-span ring registers its bytes under the ledger's
    `telemetry` category (host RAM: excluded from deviceBytes), and
    the registration tracks ring churn."""
    from pilosa_tpu.utils.memledger import MemoryLedger

    ledger = MemoryLedger()
    tr = RecordingTracer(keep=4)
    with tr.span("a", big="x" * 100):
        pass
    tr.register_memory(ledger)
    tot = ledger.totals()["telemetry"]
    assert tot["bytes"] > 100 and tot["count"] == 1
    first = tot["bytes"]
    for _ in range(20):  # churn past `keep`: bytes stay bounded
        with tr.span("b"):
            pass
    tr.register_memory(ledger)
    tot = ledger.totals()["telemetry"]
    assert tot["count"] == 1  # re-registered in place, no growth
    assert 0 < tot["bytes"] < first + 4 * 1000
    snap = ledger.snapshot()
    assert snap["deviceBytes"] == 0  # telemetry is host RAM
    assert snap["totalBytes"] == tot["bytes"]


def test_tracer_dump_writes_recent_spans():
    tr = RecordingTracer()
    with tr.span("API.Query", index="i"):
        pass
    lines = []

    class _Log:
        def printf(self, fmt, *args):
            lines.append(fmt % args if args else fmt)

    assert tr.dump(_Log()) == 1
    assert any("API.Query" in ln for ln in lines)


# ---------------------------------------------------------------------------
# Head sampling (reference SamplerType/SamplerParam, server/config.go:110-118)

def test_sampler_const_zero_exports_nothing(capture_server):
    endpoint, captured = capture_server
    tr = ExportingTracer(endpoint, sampler_type="const", sampler_param=0,
                         batch_size=1, flush_interval=3600)
    for _ in range(5):
        with tr.span("q"):
            pass
    tr.flush()
    assert not captured
    # Local recording still works for /debug introspection.
    assert len(tr.finished) == 5


def test_sampler_probabilistic_is_deterministic_on_trace_id():
    tr = ExportingTracer("http://unused", sampler_type="probabilistic",
                         sampler_param=0.5)
    from pilosa_tpu.utils.tracing import Span
    decisions = {}
    for i in range(64):
        s = Span("q", trace_id=f"{i:032x}", attrs={})
        d = tr._sampled(s)
        # Same trace id -> same decision, on every node.
        assert tr._sampled(Span("other", trace_id=s.trace_id,
                                attrs={})) == d
        decisions[s.trace_id] = d
    kept = sum(decisions.values())
    assert 10 < kept < 54  # ~50%, generous bounds


def test_sampler_probabilistic_fraction(capture_server):
    endpoint, captured = capture_server
    tr = ExportingTracer(endpoint, sampler_type="probabilistic",
                         sampler_param=0.25, batch_size=10**6,
                         flush_interval=3600)
    n = 400
    for _ in range(n):
        with tr.span("q"):
            pass
    with tr._pending_lock:
        kept = len(tr._pending)
    assert 0.1 * n < kept < 0.45 * n  # ~25%, generous bounds


def test_sampler_ratelimiting_caps_rate():
    tr = ExportingTracer("http://unused", sampler_type="ratelimiting",
                         sampler_param=2.0)
    from pilosa_tpu.utils.tracing import Span
    burst = sum(tr._sampled(Span("q", "t" * 32, {})) for _ in range(50))
    assert burst <= 2  # bucket starts with param tokens, refills slowly


def test_sampler_unknown_type_rejected():
    with pytest.raises(ValueError):
        ExportingTracer("http://unused", sampler_type="bogus")


def test_sampler_config_keys(tmp_path):
    from pilosa_tpu.utils.config import load_config
    p = tmp_path / "c.toml"
    p.write_text('tracing-sampler-type = "probabilistic"\n'
                 "tracing-sampler-param = 0.01\n")
    cfg = load_config(str(p))
    assert cfg.tracing_sampler_type == "probabilistic"
    assert cfg.tracing_sampler_param == 0.01
