"""Request-lifecycle timeline plane (utils/timeline.py): the
dispatch-gap analyzer's idle-ratio math, ring bounds and sampling, the
Chrome trace-event export shape, wall-anchor skew immunity, the HTTP
surfaces (/debug/timeline, /cluster/timeline, the SLO histograms), the
memory-ledger registration, and the zero-new-fences acceptance bar."""

import json
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.server.api import API
from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text
from pilosa_tpu.utils.timeline import (
    LANE_DISPATCH, LANE_NAMES, LANE_PLAN, TIMELINE, TimelineRecorder,
)


@pytest.fixture(autouse=True)
def _reset_timeline():
    """The recorder is process-wide (like hotspots.WORKLOAD): every
    test starts clean and leaves defaults behind."""
    TIMELINE.reset()
    TIMELINE.configure(enabled=True, ring=256, sample_every=1,
                       gap_window_s=60.0)
    yield
    TIMELINE.reset()
    TIMELINE.configure(enabled=True, ring=256, sample_every=1,
                       gap_window_s=60.0)


def _seed(holder):
    idx = holder.create_index("tl")
    cols = np.array([1, 2, SHARD_WIDTH + 3], np.uint64)
    idx.create_field("f").import_bits(np.full(3, 1, np.uint64), cols)
    idx.add_existence(cols)
    return idx


# ------------------------------------------------- dispatch-gap analyzer


def test_idle_ratio_exact_math():
    rec = TimelineRecorder(gap_window_s=100.0)
    # Three dispatches at t=0..1, 2..3, 4..5: busy 3s over span 5s.
    for s in (0.0, 2.0, 4.0):
        rec.note_dispatch(s, 1.0)
    gap = rec.gap_summary(now_pc=5.0)
    assert gap["dispatches"] == 3
    assert gap["busyS"] == pytest.approx(3.0)
    assert gap["idleS"] == pytest.approx(2.0)
    assert gap["idleRatio"] == pytest.approx(2.0 / 5.0)
    assert gap["largestGapS"] == pytest.approx(1.0)
    assert 0.0 <= gap["idleRatio"] <= 1.0


def test_idle_ratio_overlapping_dispatches_merge():
    """Overlapping enqueue intervals (pipelined dispatch) must not
    double-count busy time — coverage is an interval union."""
    rec = TimelineRecorder(gap_window_s=100.0)
    rec.note_dispatch(0.0, 2.0)
    rec.note_dispatch(1.0, 2.0)   # overlaps the first
    rec.note_dispatch(5.0, 1.0)
    gap = rec.gap_summary(now_pc=6.0)
    assert gap["busyS"] == pytest.approx(4.0)   # [0,3] + [5,6]
    assert gap["idleRatio"] == pytest.approx(2.0 / 6.0)


def test_idle_ratio_degenerate_cases():
    rec = TimelineRecorder(gap_window_s=10.0)
    assert rec.idle_ratio(now_pc=0.0) == 0.0          # no dispatches
    rec.note_dispatch(0.0, 0.5)
    assert rec.idle_ratio(now_pc=1.0) == 0.0          # one dispatch
    # Dispatches older than the window fall out of the analysis.
    rec.note_dispatch(0.6, 0.2)
    assert rec.gap_summary(now_pc=100.0)["dispatches"] == 0


def test_note_dispatch_disabled_is_noop():
    rec = TimelineRecorder()
    rec.enabled = False
    rec.note_dispatch(0.0, 1.0)
    assert rec.dispatches_total == 0
    assert rec.begin("t" * 32) is None


# -------------------------------------------------- ring / sampling / cap


def test_ring_bound_and_sampling():
    rec = TimelineRecorder(ring=4, sample_every=1)
    for i in range(10):
        req = rec.begin(f"{i:032x}")
        assert req is not None
        rec.finish(req)
    assert rec.ring_count() == 4
    assert rec.requests_recorded == 10
    # 1-in-2 sampling: roughly half skip (deterministic counter).
    rec2 = TimelineRecorder(ring=64, sample_every=2)
    got = [rec2.begin("a" * 32) for _ in range(10)]
    assert sum(1 for r in got if r is not None) == 5
    assert rec2.requests_skipped == 5


def test_note_serialize_cannot_attach_to_previous_request():
    """Review regression: if a request's serialize hook never fires
    (error path, broken pipe), the NEXT request on the thread must not
    attach its serialize slice to the already-published timeline —
    begin() invalidates the thread's post-finish handle."""
    rec = TimelineRecorder(sample_every=2)
    assert rec.begin("0" * 32) is None   # seq 1: skipped
    a = rec.begin("a" * 32)              # seq 2: sampled
    rec.finish(a)                        # serialize hook never fires
    assert rec.begin("b" * 32) is None   # seq 3: unsampled request B
    rec.note_serialize(0.0, 1.0)         # B's serialize: must go nowhere
    assert all(name != "serialize" for name, *_ in a.events)


def test_event_cap_counts_drops():
    rec = TimelineRecorder()
    req = rec.begin("b" * 32)
    for i in range(rec.MAX_EVENTS_PER_REQUEST + 10):
        rec.event(req, "plan", LANE_PLAN, float(i), 0.001)
    assert len(req.events) == rec.MAX_EVENTS_PER_REQUEST
    assert req.dropped == 10
    rec.event(None, "plan", LANE_PLAN, 0.0, 0.0)  # None handle: no-op


# ------------------------------------------------------ export shape


def test_snapshot_chrome_trace_event_shape():
    rec = TimelineRecorder()
    req = rec.begin("c" * 32, index="i1")
    rec.event(req, "plan", LANE_PLAN, req.t0_pc + 0.001, 0.002)
    rec.event(req, "dispatch", LANE_DISPATCH, req.t0_pc + 0.003, 0.004,
              shards=2)
    rec.finish(req)
    doc = rec.snapshot(node_id="node-a")
    evs = doc["traceEvents"]
    # Every event — metadata included — carries the full shape.
    for ev in evs:
        for k in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert k in ev, ev
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"plan", "dispatch", "request"}
    disp = next(e for e in xs if e["name"] == "dispatch")
    assert disp["tid"] == LANE_DISPATCH
    assert disp["dur"] == pytest.approx(4000.0)       # µs
    assert disp["args"]["trace"] == "c" * 32
    assert disp["args"]["shards"] == 2
    # ts is wall-anchored: within the request's wall window.
    assert abs(disp["ts"] / 1e6 - req.t0_wall) < 1.0
    # Metadata names the process and every stage lane.
    metas = [e for e in evs if e["ph"] == "M"]
    assert {"process_name", "thread_name"} == {e["name"] for e in metas}
    assert any(e["args"]["name"] == "node-a" for e in metas)
    assert {e["args"]["name"] for e in metas
            if e["name"] == "thread_name"} == set(LANE_NAMES.values())
    # Request-level slice nests everything under one trace.
    root = next(e for e in xs if e["name"] == "request")
    assert root["args"]["index"] == "i1"
    summary = doc["summary"]
    assert summary["requests"] == 1
    assert 0.0 <= summary["deviceIdleRatio"] <= 1.0


def test_bandwidth_counter_track_shape():
    """Roofline plane counter tracks: note_bandwidth exports two
    Perfetto ph:"C" samples (launch_bytes_per_s + roofline_fraction)
    with the full event shape, bounded by MAX_COUNTER_SAMPLES."""
    rec = TimelineRecorder()
    rec.note_bandwidth(2.5e9, 0.8)
    rec.note_bandwidth(1.0e9, 0.3)
    doc = rec.snapshot()
    cs = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert len(cs) == 4                      # 2 samples x 2 tracks
    for ev in cs:
        for k in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert k in ev, ev
    by_name = {}
    for ev in cs:
        by_name.setdefault(ev["name"], []).append(ev)
    assert set(by_name) == {"launch_bytes_per_s", "roofline_fraction"}
    assert [e["args"]["bytes_per_s"]
            for e in by_name["launch_bytes_per_s"]] == [2.5e9, 1.0e9]
    assert [e["args"]["fraction"]
            for e in by_name["roofline_fraction"]] == [0.8, 0.3]
    assert doc["summary"]["counterSamples"] == 2
    # Bounded ring: the counter deque never outgrows the cap.
    for _ in range(rec.MAX_COUNTER_SAMPLES + 50):
        rec.note_bandwidth(1.0, 0.5)
    assert len(rec.counter_samples()) == rec.MAX_COUNTER_SAMPLES
    assert rec.counters_total == 2 + rec.MAX_COUNTER_SAMPLES + 50
    rec.reset()
    assert len(rec.counter_samples()) == 0


def test_snapshot_filters_last_and_trace():
    rec = TimelineRecorder()
    for i in range(6):
        req = rec.begin(f"{i:032x}")
        rec.finish(req)
    assert rec.snapshot(last=2)["summary"]["requests"] == 2
    doc = rec.snapshot(trace_id=f"{3:032x}")
    assert doc["summary"]["requests"] == 1
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert all(e["args"]["trace"] == f"{3:032x}" for e in xs)


def test_wall_anchor_immune_to_clock_step(monkeypatch):
    """One wall-clock read per request: an NTP step AFTER begin() must
    not move any event timestamp or duration (they are perf_counter
    offsets from the anchor)."""
    rec = TimelineRecorder()
    real_time = time.time
    wall = [real_time()]
    monkeypatch.setattr(time, "time", lambda: wall[0])
    req = rec.begin("d" * 32)
    t = req.t0_pc
    rec.event(req, "plan", LANE_PLAN, t + 0.010, 0.005)
    wall[0] += 3600.0  # the clock steps one hour mid-request
    rec.event(req, "dispatch", LANE_DISPATCH, t + 0.020, 0.005)
    rec.finish(req)
    xs = {e["name"]: e for e in rec.snapshot()["traceEvents"]
          if e["ph"] == "X"}
    anchor_us = req.t0_wall * 1e6
    assert xs["plan"]["ts"] == pytest.approx(anchor_us + 10_000, abs=1)
    # The post-step event still exports 10ms later, not an hour later.
    assert xs["dispatch"]["ts"] - xs["plan"]["ts"] == \
        pytest.approx(10_000, abs=1)
    assert xs["request"]["dur"] < 1e6  # the request did not "take" 1h


# ------------------------------------------------------- live wiring


def test_query_records_stage_slices(tmp_holder):
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.query("tl", "Count(Row(f=1))")
    reqs = TIMELINE.requests()
    assert len(reqs) == 1
    names = [name for name, *_ in reqs[0].events]
    assert "plan" in names and "dispatch" in names \
        and "materialize" in names and "request" in names
    assert "device" not in names  # unsampled: no device slice
    assert TIMELINE.dispatches_total >= 1


def test_profiled_query_gains_device_slice(tmp_holder):
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.query("tl", "Count(Row(f=1))", profile=True)
    names = [name for name, *_ in TIMELINE.requests()[-1].events]
    assert "device" in names  # rides the profiler's sampled fence


def test_zero_new_fences_on_unsampled_path(tmp_holder, monkeypatch):
    """Acceptance: the timeline plane adds NO block_until_ready fences
    on the unsampled hot path — wall timestamps of host-side events
    only (same bar as PR 3's profiler and PR 6's recorder)."""
    import pilosa_tpu.executor.executor as ex

    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    # Every repeat must DISPATCH (dispatches_total >= 8 below); the
    # result cache would serve 6 of the 8 without any device work.
    api.executor.result_cache.enabled = False
    fences = []
    monkeypatch.setattr(ex, "_fence_device",
                        lambda out: fences.append(1) or 0.0)
    for i in range(8):
        api.query("tl", f"Count(Row(f={i % 2}))")
    assert fences == []
    # ...and it recorded the full stage set while staying fence-free.
    assert TIMELINE.requests_recorded == 8
    assert TIMELINE.dispatches_total >= 8


def test_timeline_disabled_records_nothing(tmp_holder):
    _seed(tmp_holder)
    TIMELINE.configure(enabled=False)
    api = API(tmp_holder, stats=MemStatsClient())
    api.query("tl", "Count(Row(f=1))")
    assert TIMELINE.requests_recorded == 0
    assert TIMELINE.dispatches_total == 0


def test_embedded_queries_get_distinct_trace_ids(tmp_holder):
    """Review regression: library (non-HTTP) callers have no per-
    request extract() reset, so the minted trace id must be dropped at
    request end — N queries on one thread are N traces, not one."""
    from pilosa_tpu.utils.tracing import RecordingTracer

    _seed(tmp_holder)
    tracer = RecordingTracer()
    api = API(tmp_holder, stats=MemStatsClient(), tracer=tracer)
    api.query("tl", "Count(Row(f=1))")
    api.query("tl", "Count(Row(f=1))")
    assert len({r.trace_id for r in TIMELINE.requests()}) == 2
    assert len({s.trace_id for s in tracer.finished}) == 2
    assert tracer.current_trace_id() is None  # nothing sticks around


def test_endpoint_label_is_bounded():
    """Review regression: unknown paths under /internal/ and /cluster/
    fold into "other" like everything else — the known internal routes
    are a fixed whitelist, not a prefix grant."""
    from pilosa_tpu.server.http import endpoint_label

    assert endpoint_label("/internal/health") == "/internal/health"
    assert endpoint_label("/cluster/resize/abort") == \
        "/cluster/resize/abort"
    assert endpoint_label("/index/i1/query") == "/index/{index}/query"
    assert endpoint_label("/cluster/timeline/abc123") == \
        "/cluster/timeline/{trace}"
    for probe in ("/internal/zz-random", "/cluster/zz-random",
                  "/internal/fragment/bogus", "/xyz"):
        assert endpoint_label(probe) == "other", probe


def test_trace_id_links_profiler_and_timeline(tmp_holder):
    """The slow-query ring's traceId opens the same request in the
    timeline: both stamp the ONE id the tracer minted."""
    from pilosa_tpu.utils.tracing import RecordingTracer

    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient(),
              tracer=RecordingTracer())
    api.long_query_time = 1e-9  # everything is "slow"
    api.query("tl", "Count(Row(f=1))")
    rec = api.profiler.slow_queries()[0]
    assert rec["traceId"]
    doc = api.debug_timeline(trace=rec["traceId"])
    assert doc["summary"]["requests"] == 1


# ------------------------------------------------------- HTTP surfaces


@pytest.fixture
def live_api(tmp_holder):
    from pilosa_tpu.server import serve
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.tracing import RecordingTracer

    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient(),
              tracer=RecordingTracer())
    # These tests assert plan/dispatch/materialize slices on repeated
    # queries; the result cache would answer the repeats with a single
    # `cache` slice instead. Cache-ON timeline attribution is pinned
    # in tests/test_result_cache.py.
    api.executor.result_cache.enabled = False
    api.coalescer = QueryCoalescer(api.executor, window_s=0.0005,
                                   stats=api.stats, tracer=api.tracer)
    api.coalescer.start()
    srv = serve(api, "localhost", 0, background=True)
    base = f"http://localhost:{srv.server_address[1]}"
    yield api, base
    srv.shutdown()
    srv.server_close()
    api.coalescer.stop()


def _get(base, path):
    return json.loads(urllib.request.urlopen(base + path,
                                             timeout=30).read())


def test_debug_timeline_http_surface(live_api):
    api, base = live_api
    for i in range(12):
        r = urllib.request.urlopen(
            base + "/index/tl/query",
            data=f"Count(Row(f={i % 3}))".encode()).read()
        assert "results" in json.loads(r)
    doc = _get(base, "/debug/timeline?last=6")
    for ev in doc["traceEvents"]:
        for k in ("ph", "ts", "dur", "pid", "tid"):
            assert k in ev, ev
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    assert {"queue", "plan", "dispatch", "materialize", "serialize",
            "request"} <= names
    s = doc["summary"]
    assert s["requests"] == 6
    assert 0.0 <= s["deviceIdleRatio"] <= 1.0
    assert s["dispatchGap"]["dispatches"] > 0
    assert s["stageMedianS"]["dispatch"] > 0
    # ?trace= narrows to one request; the single-node /cluster/timeline
    # wraps the same events with node attribution.
    tid = next(e["args"]["trace"] for e in xs if e["name"] == "request")
    one = _get(base, f"/debug/timeline?trace={tid}")
    assert one["summary"]["requests"] == 1
    merged = _get(base, f"/cluster/timeline/{tid}")
    assert merged["respondedNodes"] == merged["totalNodes"] == 1
    mx = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert mx and all(e["args"]["node"] for e in mx)
    # The idle-ratio gauge is on /metrics.
    met = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "pilosa_device_idle_ratio" in met


def test_slo_histograms_per_endpoint(live_api):
    api, base = live_api
    urllib.request.urlopen(base + "/index/tl/query",
                           data=b"Count(Row(f=1))").read()
    urllib.request.urlopen(base + "/schema").read()
    try:
        urllib.request.urlopen(base + "/definitely/not/a/route").read()
    except urllib.error.HTTPError as e:
        assert e.code == 404
        e.read()
    # The SLO observation runs in the handler's finally block, AFTER
    # the response body went out — poll until all three landed.
    met = ""
    for _ in range(200):
        met = urllib.request.urlopen(base + "/metrics").read().decode()
        if ('endpoint="/schema"' in met
                and 'endpoint="/index/{index}/query"' in met
                and 'endpoint="other",status="404"' in met):
            break
        time.sleep(0.01)
    assert '# TYPE pilosa_http_request_seconds histogram' in met
    assert 'endpoint="/index/{index}/query"' in met
    assert 'endpoint="/schema"' in met
    # Unknown paths fold into "other" with their status label — a
    # scanner cannot mint series.
    assert 'endpoint="other",status="404"' in met
    # Cumulative-bucket invariants hold for the query endpoint family.
    lines = [ln for ln in met.splitlines()
             if ln.startswith("pilosa_http_request_seconds_bucket")
             and 'endpoint="/schema"' in ln and 'status="200"' in ln]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts) and counts[-1] >= 1


def test_slow_non_query_endpoint_cross_links_ring(live_api):
    api, base = live_api
    api.long_query_time = 1e-9
    urllib.request.urlopen(base + "/schema").read()
    # The SLO observation runs in the handler's finally block, AFTER
    # the response body went out — the client can get here first.
    recs = []
    for _ in range(200):
        recs = [r for r in api.profiler.slow_queries()
                if r.get("kind") == "http"]
        if recs:
            break
        time.sleep(0.01)
    assert recs, api.profiler.slow_queries()
    assert recs[0]["query"] == "GET /schema"


def test_telemetry_rings_in_memory_ledger(live_api):
    api, base = live_api
    urllib.request.urlopen(base + "/index/tl/query",
                           data=b"Count(Row(f=1))").read()
    mem = _get(base, "/debug/memory")
    tel = mem["categories"].get("telemetry")
    assert tel is not None and tel["bytes"] > 0
    # At least two registered rings: this API's tracer span ring + the
    # process-wide timeline ring (earlier tests' tracers may not be
    # collected yet — their owner-scoped entries purge on GC).
    assert tel["count"] >= 2
    # Telemetry is host RAM: counted in totalBytes, not deviceBytes.
    assert mem["totalBytes"] == sum(
        c["bytes"] for c in mem["categories"].values())
    assert mem["deviceBytes"] <= mem["totalBytes"] - tel["bytes"]


def test_dump_and_drain(tmp_holder):
    """drain_telemetry writes the timeline + tracer rings to the log on
    shutdown (the SIGTERM post-mortem path)."""
    from pilosa_tpu.cli.main import drain_telemetry
    from pilosa_tpu.utils.tracing import RecordingTracer

    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient(),
              tracer=RecordingTracer())
    api.query("tl", "Count(Row(f=1))")

    lines = []

    class _Log:
        def printf(self, fmt, *args):
            lines.append(fmt % args if args else fmt)

    drain_telemetry(api, logger=_Log())
    assert any("timeline:" in ln for ln in lines), lines
    assert any("tracer:" in ln for ln in lines), lines


def test_config_timeline_keys(tmp_path):
    from pilosa_tpu.utils.config import load_config
    p = tmp_path / "c.toml"
    p.write_text("[timeline]\nenabled = false\nring = 64\n"
                 "sample_every = 4\ngap_window_s = 30.0\n")
    cfg = load_config(str(p))
    assert cfg.timeline_enabled is False
    assert cfg.timeline_ring == 64
    assert cfg.timeline_sample_every == 4
    assert cfg.timeline_gap_window_s == 30.0
    with pytest.raises(ValueError):
        load_config(None, {"timeline_ring": 0})
