"""prometheus_text exposition invariants (utils/stats.py) — label
escaping, the `_size` no-`_seconds`-suffix rule, and the one-TYPE-line-
per-metric invariant — plus StatsdStatsClient.close() thread join."""

import threading

from pilosa_tpu.utils.stats import (
    MemStatsClient, StatsdStatsClient, prometheus_text,
)


def test_prom_label_escaping():
    """Tag values with backslashes and double quotes must escape per
    the text exposition format, never break the label syntax."""
    stats = MemStatsClient()
    stats.with_tags('path:C:\\tmp', 'msg:say "hi"').count("esc", 2)
    out = prometheus_text(stats)
    line = next(l for l in out.splitlines()
                if l.startswith("pilosa_esc_total{"))
    assert 'path="C:\\\\tmp"' in line
    assert 'msg="say \\"hi\\""' in line
    assert line.endswith(" 2")


def test_prom_size_metrics_have_no_seconds_suffix():
    """Unitless distributions must not claim seconds: histograms
    (batch/group sizes) export bare _bucket/_sum/_count names, and a
    `*_size` timing stays suffix-free too."""
    stats = MemStatsClient()
    stats.histogram("coalescer.batch_size", 4)
    stats.timing("queue.wait_size", 3)
    stats.timing("coalescer.request", 0.25)
    out = prometheus_text(stats)
    assert 'pilosa_coalescer_batch_size_bucket{le="4"} 1' in out
    assert "pilosa_coalescer_batch_size_seconds" not in out
    assert "pilosa_queue_wait_size{" in out
    assert "pilosa_queue_wait_size_seconds" not in out
    assert "pilosa_coalescer_request_seconds{" in out


def test_prom_histogram_bucket_invariants():
    """fusion_group_size is a REAL cumulative histogram: fixed pow2
    buckets 1,2,4,...,64,+Inf; _bucket counts monotone non-decreasing;
    le="+Inf" == _count; _sum is the observation total."""
    stats = MemStatsClient()
    for v in (1, 1, 2, 3, 5, 64, 200):
        stats.histogram("executor.fusion_group_size", v)
    snap = stats.snapshot()["histograms"]["executor.fusion_group_size"]
    assert list(snap["buckets"]) == ["1", "2", "4", "8", "16", "32",
                                     "64", "+Inf"]
    # Cumulative counts: 2 at le=1, +1 at le=2, +1 at le=4 (v=3),
    # +1 at le=8 (v=5), +1 at le=64, +1 only past every bound (v=200).
    assert snap["buckets"] == {"1": 2, "2": 3, "4": 4, "8": 5,
                               "16": 5, "32": 5, "64": 6, "+Inf": 7}
    cum = list(snap["buckets"].values())
    assert cum == sorted(cum)  # monotone non-decreasing
    assert snap["count"] == snap["buckets"]["+Inf"] == 7
    assert snap["sum"] == 1 + 1 + 2 + 3 + 5 + 64 + 200

    out = prometheus_text(stats)
    assert "# TYPE pilosa_executor_fusion_group_size histogram" in out
    assert 'pilosa_executor_fusion_group_size_bucket{le="+Inf"} 7' in out
    assert "pilosa_executor_fusion_group_size_count 7" in out
    assert "pilosa_executor_fusion_group_size_sum 276" in out


def test_prom_histogram_labels_ride_buckets():
    """A tagged histogram keeps its labels beside le= on every bucket
    line (tags must not fold into the metric name)."""
    stats = MemStatsClient()
    stats.with_tags("index:i1").histogram("executor.fusion_group_size", 2)
    out = prometheus_text(stats)
    assert ('pilosa_executor_fusion_group_size_bucket'
            '{index="i1",le="2"} 1') in out
    assert 'pilosa_executor_fusion_group_size_count{index="i1"} 1' in out


def test_prom_one_type_line_per_metric():
    stats = MemStatsClient()
    stats.count("q", 1)
    stats.with_tags("index:a").count("q", 1)
    stats.with_tags("index:b").count("q", 1)
    stats.gauge("depth", 3)
    stats.with_tags("index:a").gauge("depth", 5)
    stats.timing("lat", 0.1)
    stats.with_tags("index:a").timing("lat", 0.2)
    out = prometheus_text(stats)
    type_lines = [l for l in out.splitlines() if l.startswith("# TYPE ")]
    names = [l.split()[2] for l in type_lines]
    assert len(names) == len(set(names)), names
    # Every series name that appears has exactly one TYPE declaration.
    assert names.count("pilosa_q_total") == 1
    assert names.count("pilosa_depth") == 1
    assert names.count("pilosa_lat_seconds") == 1
    # Samples with different label sets still share the one TYPE line.
    q_samples = [l for l in out.splitlines()
                 if l.startswith("pilosa_q_total")]
    assert len(q_samples) == 3


def test_prom_families_stay_contiguous_under_name_interleave():
    """The exposition format requires every family's samples to form
    ONE contiguous group under exactly one # TYPE line. Raw-key
    sorting breaks that whenever another family name sorts between a
    family's untagged and tagged spellings ('fragment.reads' <
    'fragment.reads_dedup' < 'fragment.reads{index=...}' since
    '_' < '{') — the second group then rode TYPE-less behind a
    different family. Families must group by name, not by raw key."""
    stats = MemStatsClient()
    stats.count("fragment.reads", 7)
    stats.count("fragment.reads_dedup", 1)  # sorts BETWEEN the two
    stats.with_tags("index:i1").count("fragment.reads", 3)
    out = prometheus_text(stats)
    lines = out.splitlines()
    fam = [i for i, l in enumerate(lines)
           if l.startswith("pilosa_fragment_reads_total")
           or l == "# TYPE pilosa_fragment_reads_total counter"]
    # TYPE + both samples, contiguous.
    assert len(fam) == 3
    assert fam == list(range(fam[0], fam[0] + 3))
    assert lines[fam[0]] == "# TYPE pilosa_fragment_reads_total counter"
    type_lines = [l.split()[2] for l in lines
                  if l.startswith("# TYPE ")]
    assert type_lines.count("pilosa_fragment_reads_total") == 1


def test_prom_new_workload_counter_families():
    """The workload-plane counter families export with one TYPE line
    each and proper label escaping (the invariants of this module
    extended to pilosa_fragment_{reads,writes}_total and
    pilosa_query_repeat_ratio)."""
    stats = MemStatsClient()
    stats.count("fragment.reads", 5)
    stats.with_tags('index:a"b').count("fragment.reads", 2)
    stats.count("fragment.writes", 4)
    stats.gauge("query.repeat_ratio", 0.9375)
    out = prometheus_text(stats)
    lines = out.splitlines()
    for fam, typ in (("pilosa_fragment_reads_total", "counter"),
                     ("pilosa_fragment_writes_total", "counter"),
                     ("pilosa_query_repeat_ratio", "gauge")):
        types = [l for l in lines if l == f"# TYPE {fam} {typ}"]
        assert len(types) == 1, (fam, out)
        # Samples directly follow their single TYPE line.
        i = lines.index(types[0])
        assert lines[i + 1].startswith(fam), (fam, lines[i:i + 2])
    assert "pilosa_fragment_reads_total 5" in out
    assert 'pilosa_fragment_reads_total{index="a\\"b"} 2' in out
    assert "pilosa_query_repeat_ratio 0.9375" in out


def test_prom_tagged_names_stay_bounded():
    """Tags become labels, never part of the metric name (cardinality
    control)."""
    stats = MemStatsClient()
    stats.with_tags("index:i1").count("query", 1)
    out = prometheus_text(stats)
    assert 'pilosa_query_total{index="i1"} 1' in out
    assert "i1_total" not in out


def test_statsd_close_joins_flush_thread():
    """close() must stop AND join the periodic flush thread (it was
    previously a fire-and-forget daemon that could race the final
    flush)."""
    before = threading.active_count()
    c = StatsdStatsClient("localhost:1")  # UDP, nothing listening
    t = c._shared["thread"]
    assert t.is_alive()
    c.count("x", 1)
    c.close()
    t.join(timeout=5)
    assert not t.is_alive()
    assert threading.active_count() <= before + 1


def test_statsd_close_via_tagged_clone():
    """with_tags clones share the flush thread; close() through a clone
    stops it too."""
    c = StatsdStatsClient("localhost:1")
    clone = c.with_tags("a:b")
    clone.close()
    assert not c._shared["thread"].is_alive() or \
        c._shared["thread"].join(timeout=5) is None
    assert c._shared["stop"].is_set()
