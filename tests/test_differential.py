"""Three-way differential oracle: native codec == Python codec == device.

The roaring container lattice is implemented three times — the native
C++ parser (native/pilosa_native.cpp), the Python reference codecs
(storage/roaring.py), and the packed-word device ops
(ops/bitset.py + executor/bsi.py). The bulk-ingest path moves bits
through all three; these property tests pin that they agree bit-exactly
on generated bitmaps, that serialize∘parse is the identity through
every reader/writer pairing, and that ``optimize()`` is idempotent.

The byte-level adversarial version of this oracle is
tools/roaring_fuzz.py (replayed from tests/fuzz_corpus/); this suite
covers the *valid-input* space plus the device leg the fuzzer cannot
reach.
"""

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.executor import bsi
from pilosa_tpu.ops.bitset import (
    SHARD_WIDTH, b_and, b_or, count_and, count_or, pack_positions,
    popcount, unpack_positions,
)
from pilosa_tpu.storage.roaring import Bitmap, _as_dense


def _rand_positions(rng, n, hi=SHARD_WIDTH):
    return np.unique(rng.integers(0, hi, size=n, dtype=np.uint64))


def _force_python_bitmap(data: bytes) -> Bitmap:
    with native.force_python():
        return Bitmap.from_bytes(data)


def _native_positions(data: bytes) -> np.ndarray:
    """Sorted positions per the native parser."""
    loaded = native.roaring_load(data)
    assert loaded is not None, "native library unavailable"
    keys, words, _, _ = loaded
    out = []
    for i, k in enumerate(keys):
        bits = np.unpackbits(words[i].view(np.uint8), bitorder="little")
        pos = np.nonzero(bits)[0].astype(np.uint64)
        out.append(np.uint64(k << 16) + pos)
    return np.concatenate(out) if out else np.empty(0, dtype=np.uint64)


needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native library unavailable")


# ------------------------------------------------- parse agreement


@needs_native
@pytest.mark.parametrize("seed,n", [(1, 50), (2, 5000), (3, 60000),
                                    (4, 200000)])
def test_three_way_positions_agree(seed, n):
    """storage bytes -> native parse == python parse == device words."""
    rng = np.random.default_rng(seed)
    pos = _rand_positions(rng, n)
    data = Bitmap(pos).write_bytes()

    np.testing.assert_array_equal(_native_positions(data), pos)
    np.testing.assert_array_equal(_force_python_bitmap(data).slice(), pos)

    # Device leg: pack -> popcount on device == host cardinality, and
    # the packed words round-trip back to the same positions.
    words = pack_positions(pos)
    assert int(popcount(words)) == len(pos)
    np.testing.assert_array_equal(unpack_positions(words), pos)


@needs_native
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_three_way_algebra_agree(seed):
    """AND/OR through device ops == roaring set algebra (both codecs)
    == numpy set ops."""
    rng = np.random.default_rng(seed)
    a_pos = _rand_positions(rng, 30000)
    b_pos = _rand_positions(rng, 30000)
    a_bytes = Bitmap(a_pos).write_bytes()
    b_bytes = Bitmap(b_pos).write_bytes()

    # Three parses of each operand must agree before we even compare ops.
    for data, pos in ((a_bytes, a_pos), (b_bytes, b_pos)):
        np.testing.assert_array_equal(_native_positions(data), pos)
        np.testing.assert_array_equal(
            _force_python_bitmap(data).slice(), pos)

    aw, bw = pack_positions(a_pos), pack_positions(b_pos)
    want_and = np.intersect1d(a_pos, b_pos)
    want_or = np.union1d(a_pos, b_pos)

    np.testing.assert_array_equal(
        unpack_positions(np.asarray(b_and(aw, bw))), want_and)
    np.testing.assert_array_equal(
        unpack_positions(np.asarray(b_or(aw, bw))), want_or)
    assert int(count_and(aw, bw)) == len(want_and)
    assert int(count_or(aw, bw)) == len(want_or)

    ba = _force_python_bitmap(a_bytes)
    bb = _force_python_bitmap(b_bytes)
    np.testing.assert_array_equal(ba.intersect(bb).slice(), want_and)
    np.testing.assert_array_equal(ba.union(bb).slice(), want_or)
    assert ba.intersection_count(bb) == len(want_and)

    # Native word kernels over the dense u64 view.
    a64 = np.ascontiguousarray(aw).view(np.uint64)
    b64 = np.ascontiguousarray(bw).view(np.uint64)
    assert native.intersection_count(a64, b64) == len(want_and)
    assert native.popcount(a64) == len(a_pos)


@needs_native
def test_three_way_bsi_sum_agrees(seed=21, cols=4000, depth=12):
    """BSI bit planes built from roaring-serialized rows: device
    sum/eq == host arithmetic (the pack_positions -> BSI path)."""
    rng = np.random.default_rng(seed)
    col_ids = _rand_positions(rng, cols)
    values = rng.integers(0, 1 << depth, size=len(col_ids),
                          dtype=np.uint64)

    planes = []
    for bit in range(depth):
        plane_pos = col_ids[(values >> np.uint64(bit)) & np.uint64(1) == 1]
        # Round-trip every plane through the storage codec (both
        # readers) before packing: the ingest path a plane actually
        # takes into HBM.
        data = Bitmap(plane_pos).write_bytes()
        np.testing.assert_array_equal(_native_positions(data), plane_pos)
        np.testing.assert_array_equal(
            _force_python_bitmap(data).slice(), plane_pos)
        planes.append(pack_positions(plane_pos))
    planes.append(pack_positions(col_ids))  # not-null plane
    stack = np.stack(planes)[:, None, :]    # [depth+1, S=1, W]

    # sum_count returns per-plane counts; the 2^bit weighting happens
    # host-side over exact ints (see its docstring).
    plane_counts, count = bsi.sum_count(stack)
    plane_counts = np.asarray(plane_counts)
    total = sum(int(plane_counts[bit]) << bit for bit in range(depth))
    assert total == int(values.sum())
    assert int(np.asarray(count)) == len(col_ids)

    probe = int(values[0])
    eq_mask = np.asarray(bsi.eq(stack, probe))[0]
    np.testing.assert_array_equal(
        unpack_positions(eq_mask), col_ids[values == probe])


# ------------------------------------------- round-trip + optimize


@pytest.mark.parametrize("seed,n", [(31, 10), (32, 3000), (33, 150000)])
def test_serialize_parse_identity_both_writers(seed, n):
    """parse(write(b)) == b through the python writer and (when
    available) the native-path writer, read by both readers."""
    rng = np.random.default_rng(seed)
    pos = _rand_positions(rng, n, hi=1 << 24)
    b = Bitmap(pos)

    with native.force_python():
        py_bytes = b.write_bytes()
        np.testing.assert_array_equal(
            Bitmap.from_bytes(py_bytes).slice(), pos)

    if native.available():
        nat_bytes = b.write_bytes()
        np.testing.assert_array_equal(_native_positions(nat_bytes), pos)
        np.testing.assert_array_equal(
            _force_python_bitmap(nat_bytes).slice(), pos)


@pytest.mark.parametrize("seed", [41, 42])
def test_optimize_preserves_state_and_is_idempotent(seed):
    rng = np.random.default_rng(seed)
    # A mix that crosses the array/dense threshold in both directions.
    pos = np.concatenate([
        _rand_positions(rng, 100, hi=1 << 16),
        (1 << 16) + _rand_positions(rng, 60000, hi=1 << 16),
        (5 << 16) + _rand_positions(rng, 4096, hi=1 << 16),
    ])
    b = Bitmap(np.unique(pos))
    before = b.slice()
    b.optimize()
    np.testing.assert_array_equal(b.slice(), before)
    assert b.optimize() == 0  # second pass converts nothing
    np.testing.assert_array_equal(b.slice(), before)
    # Serialization unaffected by in-memory encoding.
    np.testing.assert_array_equal(
        Bitmap.from_bytes(b.write_bytes()).slice(), before)


@needs_native
def test_full_and_empty_container_boundaries():
    """Cardinality-65536 (card-1 wraps u16) and near-empty containers
    through all three implementations."""
    pos = np.concatenate([
        np.arange(1 << 16, dtype=np.uint64),          # full container 0
        np.array([(3 << 16) + 7], dtype=np.uint64),   # singleton
    ])
    data = Bitmap(pos).write_bytes()
    np.testing.assert_array_equal(_native_positions(data), pos)
    np.testing.assert_array_equal(_force_python_bitmap(data).slice(), pos)
    words = pack_positions(pos)
    assert int(popcount(words)) == len(pos)
    np.testing.assert_array_equal(unpack_positions(words), pos)
