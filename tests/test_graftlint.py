"""graftlint: fixture corpus per rule + runtime lock-order checker.

Each rule has at least one failing and one passing fixture under
tests/graftlint_fixtures/ (that directory is excluded from normal lint
discovery; here every file is linted explicitly with a Config whose
scope knobs point at it). The second half unit-tests the
PILOSA_TPU_LOCK_CHECK=1 runtime: DebugLock order-graph recording, cycle
raising, condition wait bookkeeping, and a coalescer smoke run under
the checker.
"""

import os
import threading

import pytest

from tools.graftlint import Config, lint_files, lint_paths

FIXTURES = os.path.join(os.path.dirname(__file__), "graftlint_fixtures")


def fixture_config() -> Config:
    """Point every path-scoped rule at the fixture dir. Rules with a
    sibling-rule blast radius (GL003 taint, GL007-GL010) are scoped to
    their own fixture files so each fixture exercises ONE rule."""
    return Config(
        hot_paths=("graftlint_fixtures/gl003",),
        word_dtype_paths=("graftlint_fixtures/gl005",),
        state_paths=("graftlint_fixtures/",),
        factory_paths=("graftlint_fixtures/",),
        jit_tracked_paths=("graftlint_fixtures/gl006",),
        ledger_paths=("graftlint_fixtures/gl007",),
        growth_paths=("graftlint_fixtures/gl008",
                      "graftlint_fixtures/gl007_gl008"),
        lock_block_paths=("graftlint_fixtures/gl009",),
        effect_paths=("graftlint_fixtures/gl010",),
        ctypes_paths=("graftlint_fixtures/gl011",),
        plan_paths=("graftlint_fixtures/gl012",),
        failpoint_paths=("graftlint_fixtures/gl013",),
        opcode_table_paths=("graftlint_fixtures/gl014",),
        mutation_table_paths=("graftlint_fixtures/gl014",),
        atomicity_paths=("graftlint_fixtures/gl015",),
        publication_paths=("graftlint_fixtures/gl016",),
    )


def codes_for(filename, config=None):
    findings = lint_files([os.path.join(FIXTURES, filename)],
                          config or fixture_config())
    return [f.code for f in findings]


# ------------------------------------------------------------ per-rule


@pytest.mark.parametrize("fail_fixture,pass_fixture,code", [
    ("gl001_bare_acquire_fail.py", "gl001_bare_acquire_pass.py", "GL001"),
    ("gl001_module_state_fail.py", "gl001_module_state_pass.py", "GL001"),
    ("gl001_raw_lock_fail.py", "gl001_raw_lock_pass.py", "GL001"),
    ("gl002_cycle_fail.py", "gl002_order_pass.py", "GL002"),
    ("gl002_self_deadlock_fail.py", "gl002_order_pass.py", "GL002"),
    ("gl003_hostsync_fail.py", "gl003_hostsync_pass.py", "GL003"),
    ("gl004_retrace_fail.py", "gl004_retrace_pass.py", "GL004"),
    ("gl005_dtype_fail.py", "gl005_dtype_pass.py", "GL005"),
    ("gl006_jitsite_fail.py", "gl006_jitsite_pass.py", "GL006"),
    ("gl007_ledger_fail.py", "gl007_ledger_pass.py", "GL007"),
    ("gl008_growth_fail.py", "gl008_growth_pass.py", "GL008"),
    ("gl009_blocking_fail.py", "gl009_blocking_pass.py", "GL009"),
    ("gl010_pairs_fail.py", "gl010_pairs_pass.py", "GL010"),
    ("gl011_ctypes_fail.py", "gl011_ctypes_pass.py", "GL011"),
    ("gl012_planlaunch_fail.py", "gl012_planlaunch_pass.py", "GL012"),
    ("gl013_failpoints_fail.py", "gl013_failpoints_pass.py", "GL013"),
    ("gl014_opcodecoverage_fail.py", "gl014_opcodecoverage_pass.py",
     "GL014"),
    ("gl015_checkthenact_fail.py", "gl015_checkthenact_pass.py", "GL015"),
    ("gl016_publication_fail.py", "gl016_publication_pass.py", "GL016"),
])
def test_rule_fixtures(fail_fixture, pass_fixture, code):
    fail_codes = codes_for(fail_fixture)
    assert code in fail_codes, \
        f"{fail_fixture}: expected a {code} finding, got {fail_codes}"
    pass_codes = codes_for(pass_fixture)
    assert code not in pass_codes, \
        f"{pass_fixture}: expected no {code}, got {pass_codes}"


def test_gl012_counts_and_callgraph_leg():
    """Both unverified launchers in the fail fixture flag (direct and
    helper-that-does-not-verify); the pass fixture's call-graph leg
    (verify delegated to a module helper) stays clean — pinned by the
    parametrized pair above, counted exactly here."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl012_planlaunch_fail.py")],
        fixture_config())
    gl12 = [f for f in findings if f.code == "GL012"]
    assert len(gl12) == 2, gl12
    assert all("verify_plan" in f.message for f in gl12)


def test_gl013_counts_and_kinds():
    """Exactly three findings in the fail fixture — duplicate name,
    computed name, in-function registration — and local
    FailpointRegistry instances stay out of scope (the pass fixture's
    test-scoped registry, pinned by the parametrized pair)."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl013_failpoints_fail.py")],
        fixture_config())
    gl13 = [f for f in findings if f.code == "GL013"]
    assert len(gl13) == 3, gl13
    msgs = " | ".join(f.message for f in gl13)
    assert "registered twice" in msgs
    assert "string literal" in msgs
    assert "inside a function" in msgs


def test_gl014_counts_and_kinds():
    """Exactly three findings in the fail fixture — uncovered opcode,
    stale coverage row, unknown mutation kind — and the rule stays
    silent when either table is outside the lint scope (partial-path
    runs fall back to planverify's PV003 runtime check)."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl014_opcodecoverage_fail.py")],
        fixture_config())
    gl14 = [f for f in findings if f.code == "GL014"]
    assert len(gl14) == 3, gl14
    msgs = " | ".join(f.message for f in gl14)
    assert "'newop' has no OPCODE_MUTATIONS entry" in msgs
    assert "'ghost' names no opcode" in msgs
    assert "'flip_bits' which is not in PLAN_MUTATIONS" in msgs
    # Scope miss on either table => no findings, not false positives.
    cfg = fixture_config()
    cfg.mutation_table_paths = ("graftlint_fixtures/elsewhere",)
    assert codes_for("gl014_opcodecoverage_fail.py", cfg) == []


def test_gl015_counts_and_kinds():
    """Exactly three findings in the fail fixture — guard handed to a
    re-acquiring helper (the resize-routing shape), stale index used
    under a separate acquisition, early-return guard ahead of placement
    math — and each names the stale local."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl015_checkthenact_fail.py")],
        fixture_config())
    gl15 = [f for f in findings if f.code == "GL015"]
    assert len(gl15) == 3, gl15
    msgs = " | ".join(f.message for f in gl15)
    assert "`previous`" in msgs and "re-acquires the lock" in msgs
    assert "`n` was computed" in msgs
    assert "`quiet`" in msgs


def test_gl016_counts_and_kinds():
    """Exactly three findings in the fail fixture — augmented store,
    plain store, and a helper whose call sites do not all hold the
    lock — each naming the attribute and the witnessing reader."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl016_publication_fail.py")],
        fixture_config())
    gl16 = [f for f in findings if f.code == "GL016"]
    assert len(gl16) == 3, gl16
    msgs = " | ".join(f.message for f in gl16)
    assert "`self.total`" in msgs
    assert "`self.rate`" in msgs
    assert "`self.label`" in msgs
    assert "snapshot" in msgs


def test_gl001_context_manager_is_not_a_lock():
    """`with open(path):` around a racy mutation must still flag."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl001_module_state_fail.py")],
        fixture_config())
    lines = {f.line for f in findings if f.code == "GL001"}
    src = open(os.path.join(FIXTURES,
                            "gl001_module_state_fail.py")).read()
    cm_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                   if "f.read()" in ln)
    assert cm_line in lines


def test_gl003_counts_every_sync_form():
    # asarray fetch, int() transfer, block_until_ready, .item(), and
    # the closure-over-later-taint case (a def lexically BEFORE the
    # device assignment still sees its final binding).
    assert codes_for("gl003_hostsync_fail.py").count("GL003") >= 5


def test_gl004_flags_both_call_and_import_time():
    assert codes_for("gl004_retrace_fail.py").count("GL004") >= 3


def test_gl006_flags_decorator_partial_and_cached_call():
    # module-scope @jax.jit, functools.partial(jax.jit, ...), and an
    # un-noted cached build inside a method: three distinct site forms.
    assert codes_for("gl006_jitsite_fail.py").count("GL006") >= 3


def test_gl007_flags_direct_and_unregistering_helper():
    # Direct store + a store whose helper never registers: two sites.
    assert codes_for("gl007_ledger_fail.py").count("GL007") == 2


def test_gl008_flags_dict_list_and_set_growth():
    assert codes_for("gl008_growth_fail.py").count("GL008") == 3


def test_gl009_flags_direct_and_transitive_sinks():
    # sleep + join directly under the lock, network + subprocess
    # through one level of helper indirection: four sites.
    assert codes_for("gl009_blocking_fail.py").count("GL009") == 4


def test_gl010_flags_every_pair_kind():
    # ledger register/unregister, TIMELINE.begin/finish, gauge inc/dec.
    assert codes_for("gl010_pairs_fail.py").count("GL010") == 3


def test_gl011_flags_partial_and_missing_declarations():
    # nat_count has restype but no argtypes; nat_load has neither;
    # memcpy is declared only on the OTHER handle (libc) but called on
    # lib. One finding per (handle, symbol), not per call site.
    assert codes_for("gl011_ctypes_fail.py").count("GL011") == 3


def test_gl011_reports_which_attr_is_missing():
    findings = lint_files(
        [os.path.join(FIXTURES, "gl011_ctypes_fail.py")],
        fixture_config())
    msgs = {f.message for f in findings if f.code == "GL011"}
    assert any("`nat_count`" in m and "argtypes" in m
               and "restype" not in m.split("declared")[0]
               for m in msgs), msgs
    assert any("`nat_load`" in m and "argtypes or restype" in m
               for m in msgs), msgs


def test_gl011_declarations_are_per_handle():
    """A full declaration on libc must not silence the same-named
    symbol called through lib — the corruption is per-library."""
    findings = lint_files(
        [os.path.join(FIXTURES, "gl011_ctypes_fail.py")],
        fixture_config())
    msgs = {f.message for f in findings if f.code == "GL011"}
    assert any("`memcpy`" in m and "argtypes or restype" in m
               for m in msgs), msgs


def test_pass_fixtures_fully_clean():
    """Pass fixtures produce NO findings of any rule (not just 'not
    their own rule')."""
    for name in ("gl001_bare_acquire_pass.py", "gl001_module_state_pass.py",
                 "gl001_raw_lock_pass.py", "gl002_order_pass.py",
                 "gl003_hostsync_pass.py", "gl004_retrace_pass.py",
                 "gl005_dtype_pass.py", "gl006_jitsite_pass.py",
                 "gl007_ledger_pass.py", "gl008_growth_pass.py",
                 "gl009_blocking_pass.py", "gl010_pairs_pass.py",
                 "gl011_ctypes_pass.py", "gl015_checkthenact_pass.py",
                 "gl016_publication_pass.py"):
        assert codes_for(name) == [], name


def test_suppression_interplay_is_rule_keyed():
    """`disable=GL007` on an allocation line must NOT silence GL008 on
    the same line — suppressions are (rule, line)-keyed."""
    codes = codes_for("gl007_gl008_interplay.py")
    assert "GL007" not in codes, codes
    assert "GL008" in codes, codes


# -------------------------------------------------------- suppressions


def test_line_disable_suppresses(tmp_path):
    p = tmp_path / "snippet.py"
    p.write_text(
        "import threading\n"
        "_L = threading.Lock()  # graftlint: disable=GL001\n")
    cfg = fixture_config()
    cfg.factory_paths = (str(tmp_path).replace("\\", "/"),)
    assert lint_files([str(p)], cfg) == []


def test_standalone_comment_covers_next_code_line(tmp_path):
    p = tmp_path / "snippet.py"
    p.write_text(
        "import threading\n"
        "# graftlint: disable=GL001 — fixture justification spanning\n"
        "# a multi-line comment block\n"
        "_L = threading.Lock()\n")
    cfg = fixture_config()
    cfg.factory_paths = (str(tmp_path).replace("\\", "/"),)
    assert lint_files([str(p)], cfg) == []


def test_disable_file(tmp_path):
    p = tmp_path / "snippet.py"
    p.write_text(
        "# graftlint: disable-file=GL001\n"
        "import threading\n"
        "_A = threading.Lock()\n"
        "_B = threading.RLock()\n")
    cfg = fixture_config()
    cfg.factory_paths = (str(tmp_path).replace("\\", "/"),)
    assert lint_files([str(p)], cfg) == []


def test_select_and_ignore():
    cfg = fixture_config()
    cfg.select = {"GL005"}
    path = os.path.join(FIXTURES, "gl003_hostsync_fail.py")
    assert lint_files([path], cfg) == []
    cfg = fixture_config()
    cfg.ignore = {"GL003"}
    assert lint_files([path], cfg) == []


# ------------------------------------------------- repo must lint clean


def test_repo_tree_is_clean():
    """The acceptance gate: the shipped tree has zero findings across
    the FULL scanned set (pilosa_tpu, tests, benches, tools) with
    GL001-GL010 enabled — every true positive is fixed or carries a
    justified annotation, none is baselined."""
    findings = lint_paths(["pilosa_tpu", "tests", "benches", "tools"])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_shipped_baseline_is_empty():
    """The committed baseline is a ratchet that must stay at zero:
    known debt lands via --write-baseline + review, never silently."""
    from tools.graftlint import baseline
    assert baseline.load() == []


def test_fixture_dir_excluded_from_discovery():
    findings = lint_paths(["tests"])
    assert not any("graftlint_fixtures" in f.path for f in findings)


# --------------------------------------- CLI: baseline / sarif / diff


VIOLATION = "import threading\n_L = threading.Lock()\n"


def _main(argv):
    from tools.graftlint.__main__ import main
    return main(argv)


@pytest.fixture
def violating_tree(tmp_path):
    """A throwaway tree whose path matches the default Config scoping
    (factory_paths contains 'pilosa_tpu/')."""
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(VIOLATION)
    return tmp_path


def test_cli_baseline_roundtrip(violating_tree, capsys):
    bad = str(violating_tree / "pilosa_tpu" / "bad.py")
    bl = str(violating_tree / "baseline.json")
    assert _main([bad, "--baseline", bl]) == 1
    assert _main([bad, "--baseline", bl, "--write-baseline"]) == 0
    # Baselined findings do not fail the run, but are reported.
    assert _main([bad, "--baseline", bl]) == 0
    out = capsys.readouterr().out
    assert "1 baselined" in out
    # Debt paid down -> the leftover entry is called out as stale.
    (violating_tree / "pilosa_tpu" / "bad.py").write_text("x = 1\n")
    assert _main([bad, "--baseline", bl]) == 0
    assert "stale baseline" in capsys.readouterr().out


def test_cli_sarif_document(violating_tree, capsys):
    import json
    bad = str(violating_tree / "pilosa_tpu" / "bad.py")
    bl = str(violating_tree / "none.json")
    assert _main([bad, "--format", "sarif", "--baseline", bl]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"GL001", "GL007", "GL008", "GL009", "GL010"} <= rules
    res = run["results"]
    assert res and res[0]["ruleId"] == "GL001"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 2
    assert "baselineState" not in res[0]


def test_cli_sarif_output_file_keeps_text_on_stdout(violating_tree,
                                                    capsys):
    import json
    bad = str(violating_tree / "pilosa_tpu" / "bad.py")
    sarif_path = violating_tree / "graftlint.sarif"
    assert _main([bad, "--format", "sarif", "--output", str(sarif_path),
                  "--baseline", str(violating_tree / "none.json")]) == 1
    out = capsys.readouterr().out
    assert "GL001" in out  # the human text still reaches the gate log
    doc = json.loads(sarif_path.read_text())
    assert doc["runs"][0]["results"], doc


def test_cli_sarif_marks_baselined_results(violating_tree, capsys):
    import json
    bad = str(violating_tree / "pilosa_tpu" / "bad.py")
    bl = str(violating_tree / "baseline.json")
    assert _main([bad, "--baseline", bl, "--write-baseline"]) == 0
    capsys.readouterr()
    assert _main([bad, "--format", "sarif", "--baseline", bl]) == 0
    doc = json.loads(capsys.readouterr().out)
    res = doc["runs"][0]["results"]
    assert res and res[0]["baselineState"] == "unchanged"


def _git(repo, *args):
    import subprocess
    subprocess.run(["git", *args], cwd=repo, check=True,
                   capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t",
                        "GIT_COMMITTER_EMAIL": "t@t"})


def test_cli_changed_mode_filters_to_diffed_files(tmp_path, monkeypatch,
                                                  capsys):
    """--changed analyzes the whole tree but reports findings only in
    files touched since the merge-base with the base branch."""
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    (pkg / "legacy.py").write_text(VIOLATION)
    _git(tmp_path, "init", "-q", "-b", "main")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-q", "-m", "seed")
    _git(tmp_path, "checkout", "-q", "-b", "feature")
    (pkg / "fresh.py").write_text(VIOLATION)
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-q", "-m", "add fresh")
    monkeypatch.chdir(tmp_path)
    bl = str(tmp_path / "none.json")
    # Full scan sees both files ...
    assert _main(["pilosa_tpu", "--baseline", bl]) == 1
    full = capsys.readouterr().out
    assert "legacy.py" in full and "fresh.py" in full
    # ... diff mode reports only the branch's own file.
    assert _main(["pilosa_tpu", "--changed", "main",
                  "--baseline", bl]) == 1
    diff = capsys.readouterr().out
    assert "fresh.py" in diff and "legacy.py" not in diff
    # Fix the changed file -> diff mode is clean even though legacy
    # debt remains in the tree.
    (pkg / "fresh.py").write_text("x = 1\n")
    assert _main(["pilosa_tpu", "--changed", "main",
                  "--baseline", bl]) == 0
    capsys.readouterr()
    # Baselined debt in UNCHANGED files must not read as stale in diff
    # mode (its findings were filtered out, not fixed) ...
    real_bl = str(tmp_path / "baseline.json")
    assert _main(["pilosa_tpu", "--baseline", real_bl,
                  "--write-baseline"]) == 0
    assert _main(["pilosa_tpu", "--changed", "main",
                  "--baseline", real_bl]) == 0
    assert "stale" not in capsys.readouterr().out
    # ... and regenerating the baseline from a filtered set is refused
    # outright (it would silently drop every out-of-diff entry).
    assert _main(["pilosa_tpu", "--changed", "main",
                  "--baseline", real_bl, "--write-baseline"]) == 2
    assert "full-tree run" in capsys.readouterr().err


def test_cli_changed_mode_falls_back_without_git(tmp_path, monkeypatch,
                                                 capsys):
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(VIOLATION)
    monkeypatch.chdir(tmp_path)  # not a git repo
    assert _main(["pilosa_tpu", "--changed", "main",
                  "--baseline", str(tmp_path / "none.json")]) == 1
    err = capsys.readouterr().err
    assert "falling back to the full tree" in err


# --------------------------------------------- runtime order checker


@pytest.fixture
def clean_graph():
    from pilosa_tpu.utils.locks import reset_lock_order
    reset_lock_order()
    yield
    reset_lock_order()


def test_debugrlock_locked(clean_graph):
    """RLock.locked() is absent before py3.14; the wrapper tracks it."""
    from pilosa_tpu.utils.locks import DebugRLock
    r = DebugRLock("t.R")
    assert not r.locked()
    with r:
        assert r.locked()
        with r:
            assert r.locked()
    assert not r.locked()


def test_debuglock_records_edges(clean_graph):
    from pilosa_tpu.utils.locks import DebugLock, lock_order_edges
    a, b = DebugLock("t.A"), DebugLock("t.B")
    with a:
        with b:
            pass
    assert "t.B" in lock_order_edges().get("t.A", set())


def test_debuglock_raises_on_cycle(clean_graph):
    from pilosa_tpu.utils.locks import (
        DebugLock, LockOrderError, lock_order_violations,
    )
    a, b = DebugLock("t.A"), DebugLock("t.B")
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError):
        with b:
            with a:
                pass
    assert lock_order_violations()


def test_debuglock_consistent_order_is_silent(clean_graph):
    from pilosa_tpu.utils.locks import (
        DebugLock, DebugRLock, lock_order_violations,
    )
    a, b, c = DebugRLock("t.A"), DebugLock("t.B"), DebugLock("t.C")
    for _ in range(3):
        with a:
            with a:  # reentrant: no self edge
                with b:
                    with c:
                        pass
    assert lock_order_violations() == []


def test_debuglock_same_name_siblings_ok(clean_graph):
    """Holding one Fragment-class lock while taking a sibling's is not
    an order edge (instance ordering is out of scope by design)."""
    from pilosa_tpu.utils.locks import DebugLock, lock_order_violations
    f1, f2 = DebugLock("Fragment._lock"), DebugLock("Fragment._lock")
    with f1:
        with f2:
            pass
    with f2:
        with f1:
            pass
    assert lock_order_violations() == []


def test_debugcondition_wait_releases_held_stack(clean_graph):
    from pilosa_tpu.utils.locks import (
        DebugCondition, DebugLock, lock_order_violations,
    )
    cond = DebugCondition("t.cond")
    other = DebugLock("t.other")
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    # Give the waiter time to enter wait (releasing t.cond).
    import time
    time.sleep(0.1)
    # If wait() failed to pop t.cond from ITS thread's stack this would
    # not matter (stacks are per-thread) — but the waiter must be able
    # to reacquire and record edges consistently after wake.
    with other:
        with cond:
            cond.notify_all()
    t.join(timeout=5)
    assert hits == ["woke"]
    # Reverse order in the waiter thread after wake would now trip; the
    # plain wake path must be violation-free.
    assert lock_order_violations() == []


def test_notify_side_cycle_through_condition(clean_graph):
    """The waiter's wait() re-acquire is recorded from the NOTIFY side:
    ``with cond: with A: notify()`` acquires in cond -> A order (clean
    for the acquire-side checker) yet wakes waiters whose re-acquire of
    cond is ordered AFTER A — the A -> cond edge closes the cycle that
    only the notify path can see."""
    from pilosa_tpu.utils.locks import (
        DebugCondition, DebugLock, LockOrderError, lock_order_violations,
    )
    cond = DebugCondition("t.cond")
    a = DebugLock("t.A")
    with pytest.raises(LockOrderError, match="cycle through condition"):
        with cond:
            with a:  # establishes cond -> A; held at the notify
                cond.notify_all()
    assert lock_order_violations()


def test_notify_records_reacquire_edge(clean_graph):
    from pilosa_tpu.utils.locks import (
        DebugCondition, DebugLock, lock_order_edges,
    )
    cond = DebugCondition("t.cond")
    a = DebugLock("t.A")
    with a:
        with cond:
            cond.notify()
    assert "t.cond" in lock_order_edges().get("t.A", set())


def test_notify_lost_wakeup_retained_lock(clean_graph):
    """A lock held ACROSS a wait that the notify path also holds is the
    lost-wakeup deadlock shape — flagged at the notify even when the
    timed wait keeps the test itself live."""
    from pilosa_tpu.utils.locks import (
        DebugCondition, DebugLock, LockOrderError,
    )
    cond = DebugCondition("t.cond")
    outer = DebugLock("t.outer")

    def waiter():
        with outer:          # retained across the wait
            with cond:
                cond.wait(timeout=0.5)

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.1)
    with pytest.raises(LockOrderError, match="lost-wakeup"):
        with outer:          # notify path needs what the waiter keeps
            with cond:
                cond.notify_all()
    t.join(timeout=5)


def test_notify_without_extra_locks_is_silent(clean_graph):
    from pilosa_tpu.utils.locks import DebugCondition, lock_order_violations
    cond = DebugCondition("t.cond")
    with cond:
        cond.notify_all()
    assert lock_order_violations() == []


def test_coalescer_under_lock_check(clean_graph, monkeypatch):
    """Smoke: the coalescer's cond + stats + executor locks run clean
    under the checker with real concurrent submitters."""
    monkeypatch.setenv("PILOSA_TPU_LOCK_CHECK", "1")
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.locks import lock_order_violations
    from pilosa_tpu.utils.stats import MemStatsClient

    class StubExecutor:
        def execute_full(self, index, query, shards=None, profile=None):
            return {"results": [True]}

        def execute_batch_shaped(self, reqs, profiles=None):
            return [{"results": [True]} for _ in reqs]

    co = QueryCoalescer(StubExecutor(), window_s=0.002, max_batch=8,
                        stats=MemStatsClient())
    assert type(co._cond).__name__ == "DebugCondition"
    co.start()
    try:
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(
                co.submit("i", "Count(Row(f=1))")))
            for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(results) == 16
    finally:
        co.stop()
    assert lock_order_violations() == []


def test_make_lock_plain_without_env(monkeypatch):
    monkeypatch.delenv("PILOSA_TPU_LOCK_CHECK", raising=False)
    from pilosa_tpu.utils import locks
    assert type(locks.make_lock("x")) is type(threading.Lock())
    assert isinstance(locks.make_condition("x"), threading.Condition)
