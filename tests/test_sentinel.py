"""SLO & regression-sentinel plane (utils/sentinel.py + the
server/CLI wiring + tools/doctor.py): objective parsing, windowed-delta
latency quantiles (a step change shows up in the window, not diluted
by lifetime counts), the multi-window burn-rate fire/clear state
machine on an injected clock (no wall-clock sleeps anywhere), the
history ring bounds + ledger registration, the /debug/history +
/debug/slo + /cluster/slo surfaces, the client.5xx end-to-end alert
path across every surface, the drain ordering/once pins, the
zero-new-fences acceptance bar, and the doctor bundle verdicts."""

import json
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.utils.memledger import MemoryLedger
from pilosa_tpu.utils.sentinel import (
    BURN_WINDOWS, CLEAR_FACTOR, SENTINEL, SentinelRecorder,
    parse_objective, quantile_from_deltas,
)
from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text

SLO_BUCKETS = tuple(2.0 ** e for e in range(-14, 4))
EP_TAGS = ("endpoint:/index/{index}/query", "status:200")


@pytest.fixture(autouse=True)
def _reset_sentinel():
    """The recorder is process-wide (like roofline.ROOFLINE): every
    test starts clean and leaves defaults behind."""
    SENTINEL.reset()
    SENTINEL.configure(enabled=True, ring=720, decimate=10,
                       alert_ring=256, objectives={},
                       watermark_bytes=0)
    yield
    SENTINEL.reset()
    SENTINEL.configure(enabled=True, ring=720, decimate=10,
                       alert_ring=256, objectives={},
                       watermark_bytes=0)
    import time
    SENTINEL.clock = time.time


def _recorder(objectives=None, **kw):
    s = SentinelRecorder()
    s.configure(enabled=True, ring=kw.pop("ring", 720),
                decimate=kw.pop("decimate", 10),
                alert_ring=kw.pop("alert_ring", 64),
                objectives=objectives or {}, **kw)
    return s


def _observe(stats, seconds, n=1, status=200):
    red = stats.with_tags("endpoint:/index/{index}/query",
                          f"status:{status}")
    for _ in range(n):
        red.histogram("http_request_seconds", seconds,
                      buckets=SLO_BUCKETS)


def _histos(stats):
    return stats.snapshot()["histograms"]


# ------------------------------------------------------ objective parsing


def test_parse_objective():
    assert parse_objective("99.9% < 25ms") == \
        (pytest.approx(0.999), pytest.approx(0.025))
    assert parse_objective(" 95 % < 2 s ") == \
        (pytest.approx(0.95), 2.0)
    assert parse_objective("99% < 500us") == \
        (pytest.approx(0.99), pytest.approx(0.0005))
    for bad in ("99.9%", "< 25ms", "99.9 < 25ms", "99.9% < 25",
                "99.9% < 25m", "101% < 1s", "0% < 1s", "99% < 0ms"):
        with pytest.raises(ValueError):
            parse_objective(bad)


def test_quantile_from_deltas_interpolation():
    # Finite bounds only; the +Inf bucket is deltas' extra last entry.
    bounds = (0.001, 0.01, 0.1)
    # 10 obs in (0.001, 0.01]: p50 interpolates inside that bucket.
    q = quantile_from_deltas(bounds, (0, 10, 0, 0), 0.50)
    assert 0.001 < q <= 0.01
    # Observations in the +Inf bucket clamp to the last finite bound.
    assert quantile_from_deltas(bounds, (0, 0, 0, 5), 0.99) == 0.1
    assert quantile_from_deltas(bounds, (0, 0, 0, 0), 0.99) == 0.0


# -------------------------------------------------- windowed quantiles


def test_windowed_quantiles_see_step_change():
    """Satellite: latency quantiles derive from histogram DELTAS
    between consecutive samples, not lifetime counts — a latency step
    change shows in the next tick even after a long fast history."""
    sent = _recorder({"query": "99% < 25ms"})
    stats = MemStatsClient()
    t = 1000.0
    sent.sample({}, _histos(stats), now=t)
    # Long fast regime: 200 observations at ~5 ms over 20 ticks.
    for _ in range(20):
        _observe(stats, 0.005, n=10)
        t += 30.0
        sent.sample({}, _histos(stats), now=t)
    snap = sent.slo_snapshot()
    fast_p95 = snap["endpoints"][0]["rates"]["p95"]
    assert fast_p95 < 0.01
    # Step: ONE tick of 200 ms observations. A lifetime quantile over
    # 210 observations would still sit in the 5 ms buckets; the
    # windowed delta must land in the 200 ms regime.
    _observe(stats, 0.200, n=10)
    t += 30.0
    sent.sample({}, _histos(stats), now=t)
    snap = sent.slo_snapshot()
    rates = snap["endpoints"][0]["rates"]
    assert rates["p50"] > 0.1, rates
    assert rates["p95"] > 0.1, rates
    assert rates["qps"] == pytest.approx(10 / 30.0)
    # The derived rates are also history series (endpoint.query.*).
    hist = sent.history(series=["endpoint.query.p95"])
    pts = hist["series"]["endpoint.query.p95"]["points"]
    assert pts[-1][1] > 0.1 and pts[0][1] < 0.01


# ------------------------------------------------- burn-rate state machine


def test_burn_alert_fires_sticky_and_clears_with_hysteresis():
    """The multi-window multi-burn-rate state machine on an injected
    clock: a 50%-bad burst fires both window pairs, the alert stays
    sticky while burn hovers between clear and fire thresholds, and
    clears only when BOTH windows drop below threshold*CLEAR_FACTOR."""
    sent = _recorder({"query": "99.9% < 25ms"})
    stats = MemStatsClient()
    t = 1000.0
    sent.sample({}, _histos(stats), now=t)
    _observe(stats, 0.005, n=32)                  # healthy baseline
    t += 30.0
    sent.sample({}, _histos(stats), now=t)
    assert sent.active_alerts() == []
    _observe(stats, 0.005, n=32, status=500)      # the bad burst
    t += 30.0
    sent.sample({}, _histos(stats), now=t)
    keys = {a["key"] for a in sent.active_alerts()}
    assert keys == {"slo-burn:query:300s", "slo-burn:query:1800s"}
    snap = sent.slo_snapshot()
    ep = snap["endpoints"][0]
    assert len(ep["burn"]) == len(BURN_WINDOWS) == 2
    for b in ep["burn"]:
        assert b["active"]
        assert b["fastBurn"] > b["threshold"]
    assert ep["budgetConsumed"] > 1.0             # budget blown
    assert ep["budgetRemaining"] == 0.0
    # Recovery, but within the slow windows: cumulative counters mean
    # the old-window delta still contains the burst -> sticky, no
    # clear, no re-fire (fired count unchanged).
    fired = snap["alerts"]["fired"]
    _observe(stats, 0.005, n=32)
    t += 60.0
    sent.sample({}, _histos(stats), now=t)
    assert {a["key"] for a in sent.active_alerts()} == keys
    assert sent.slo_snapshot()["alerts"]["fired"] == fired == 2
    # Jump past the slowest window (6 h): every window's delta is now
    # bad-free -> burn 0 < threshold*CLEAR_FACTOR for both pairs.
    assert CLEAR_FACTOR == 0.5
    t += 22000.0
    _observe(stats, 0.005, n=32)
    sent.sample({}, _histos(stats), now=t)
    assert sent.active_alerts() == []
    snap = sent.slo_snapshot()
    assert snap["alerts"]["cleared"] == 2
    events = [(e["event"], e["key"]) for e in snap["alerts"]["ring"]]
    assert events.count(("fire", "slo-burn:query:300s")) == 1
    assert events.count(("clear", "slo-burn:query:300s")) == 1


def test_latency_violations_burn_budget_without_5xx():
    """The objective is availability AND latency: requests over the
    threshold bucket are bad even when every status is 200."""
    sent = _recorder({"query": "99% < 25ms"})
    stats = MemStatsClient()
    t = 0.0
    _observe(stats, 0.005, n=2)                   # baseline sample
    sent.sample({}, _histos(stats), now=t)
    _observe(stats, 0.200, n=10)                  # slow but 200
    t += 30.0
    sent.sample({}, _histos(stats), now=t)
    ep = sent.slo_snapshot()["endpoints"][0]
    assert ep["bad"] == 10
    assert ep["budgetConsumed"] > 1.0
    # thresholdBucket reports the bucket bound the 25 ms objective
    # actually snapped to (pow-2 buckets: 31.25 ms).
    assert ep["thresholdBucket"] == pytest.approx(0.03125)


def test_note_condition_edge_triggered():
    sent = _recorder()
    sent.note_condition("hbm.pressure", True, "over watermark",
                        kind="memory", now=1.0)
    sent.note_condition("hbm.pressure", True, "over watermark",
                        now=2.0)  # still true: no duplicate fire
    snap = sent.slo_snapshot()
    assert snap["alerts"]["fired"] == 1
    assert len(snap["alerts"]["ring"]) == 1
    sent.note_condition("hbm.pressure", False, now=3.0)
    sent.note_condition("hbm.pressure", False, now=4.0)
    snap = sent.slo_snapshot()
    assert snap["alerts"]["cleared"] == 1
    assert sent.active_alerts() == []


# ------------------------------------------------------ history ring


def test_history_ring_bounded_with_decimated_tier():
    sent = _recorder(ring=16, decimate=4)
    for i in range(100):
        sent.sample({"device_idle_ratio": i / 100.0}, None,
                    now=float(i))
    doc = sent.history()
    s = doc["series"]["device_idle_ratio"]
    assert len(s["points"]) == 16                # raw tier bounded
    assert s["points"][-1] == [99.0, 0.99]
    assert len(s["decimated"]) == 16             # 10:1 -> here 4:1
    assert s["decimate"] == 4
    # Decimated tier retains OLDER history than the raw tier spans.
    assert s["decimated"][0][0] < s["points"][0][0]
    # Timestamps strictly monotone in both tiers.
    for tier in (s["points"], s["decimated"]):
        ts = [p[0] for p in tier]
        assert ts == sorted(ts) and len(set(ts)) == len(ts)
    # series= filter and last= truncation.
    doc = sent.history(series=["nope"])
    assert doc["series"] == {}
    doc = sent.history(series=["device_idle_ratio"], last=3)
    assert len(doc["series"]["device_idle_ratio"]["points"]) == 3
    # Perfetto counter export: one ph:"C" event per returned point.
    evs = doc["traceEvents"]
    assert len(evs) == 3
    assert all(e["ph"] == "C" and e["name"] == "history:device_idle_ratio"
               for e in evs)
    assert evs[-1]["args"]["value"] == 0.99


def test_ring_nbytes_ledgered():
    """History ring bytes are ledger-provable: the `telemetry`
    category carries a sentinel_rings entry equal to ring_nbytes()."""
    sent = _recorder({"query": "99% < 25ms"})
    stats = MemStatsClient()
    _observe(stats, 0.005, n=8)
    for i in range(12):
        sent.sample({"device_idle_ratio": 0.5}, _histos(stats),
                    now=float(i))
    led = MemoryLedger()
    sent.register_memory(led)
    n = sent.ring_nbytes()
    assert n > 512
    assert led.totals()["telemetry"]["bytes"] == n
    entries = led.entries("telemetry")
    assert any(e.get("kind") == "sentinel" for e in entries)
    # Snapshot totals include it (the /debug/memory provability pin).
    snap = led.snapshot()
    assert snap["totalBytes"] == sum(
        c["bytes"] for c in snap["categories"].values())


def test_disabled_sentinel_is_inert():
    sent = _recorder()
    sent.configure(enabled=False)
    sent.sample({"device_idle_ratio": 0.5}, None, now=1.0)
    sent.note_condition("x", True, now=2.0)
    snap = sent.slo_snapshot()
    assert snap["samples"] == 0 and snap["alerts"]["fired"] == 0


# ------------------------------------------------------ /metrics + HELP


def test_publish_gauges_and_help_lines():
    """Satellite: publish() exports burn/budget/alert gauges, and
    prometheus_text emits exactly one # HELP immediately before
    exactly one # TYPE per family."""
    sent = _recorder({"query": "99.9% < 25ms"})
    stats = MemStatsClient()
    t = 0.0
    _observe(stats, 0.005, n=4)                   # baseline sample
    sent.sample({}, _histos(stats), now=t)
    _observe(stats, 0.005, n=32, status=500)
    t += 30.0
    sent.sample({}, _histos(stats), now=t)
    sent.publish(stats)
    prom = prometheus_text(stats)
    assert 'pilosa_slo_burn_rate{endpoint="query",window="300s"}' \
        in prom
    assert 'pilosa_slo_burn_rate{endpoint="query",window="21600s"}' \
        in prom
    assert 'pilosa_slo_error_budget_remaining{endpoint="query"} 0' \
        in prom
    assert "pilosa_sentinel_alerts_active 2" in prom
    assert "pilosa_sentinel_alerts_fired 2" in prom
    lines = prom.splitlines()
    helps = [l for l in lines if l.startswith("# HELP")]
    types = [l for l in lines if l.startswith("# TYPE")]
    assert len(helps) == len(types) > 0
    seen = set()
    for i, l in enumerate(lines):
        if not l.startswith("# TYPE "):
            continue
        fam = l.split()[2]
        assert fam not in seen          # one TYPE per family
        seen.add(fam)
        # HELP directly precedes its TYPE and names the same family.
        assert lines[i - 1].startswith(f"# HELP {fam} "), lines[i - 1]
    # Registered families get real help text, not the fallback.
    assert "# HELP pilosa_slo_burn_rate " in prom
    assert "pilosa-tpu metric pilosa_slo_burn_rate" not in prom


# ------------------------------------------------------ server wiring


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def test_debug_history_and_slo_routes(live_server):
    base, api, _h = live_server
    clock = [5000.0]
    SENTINEL.configure(objectives={"query": "99.9% < 25ms"},
                       clock=lambda: clock[0])
    for _ in range(3):
        api.sample_sentinel()
        clock[0] += 30.0
    doc = _get(base, "/debug/history")
    assert doc["samples"] == 3 and "node" in doc
    assert len(doc["series"]) >= 3          # idle/roofline/caches/hbm...
    for s in doc["series"].values():
        ts = [p[0] for p in s["points"]]
        assert ts == sorted(ts)
    names = set(doc["series"])
    assert {"device_idle_ratio", "hbm_live_bytes",
            "result_cache_hit_ratio"} <= names
    # series= + last= narrow the document.
    doc = _get(base, "/debug/history?series=device_idle_ratio&last=2")
    assert set(doc["series"]) == {"device_idle_ratio"}
    assert len(doc["series"]["device_idle_ratio"]["points"]) == 2
    # Unknown query params are rejected (the surface-wide contract).
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/debug/history?bogus=1")
    assert ei.value.code == 400
    doc = _get(base, "/debug/slo")
    assert doc["enabled"] and doc["samples"] == 3
    assert doc["objectives"]["query"]["thresholdS"] == 0.025
    assert doc["burnWindows"] == [dict(w) for w in BURN_WINDOWS]
    # Single-node /cluster/slo degrades to the local document.
    doc = _get(base, "/cluster/slo")
    assert doc["totalNodes"] == doc["respondedNodes"] == 1
    assert doc["totals"]["alertsActive"] == 0
    # /internal/health carries the compact slo stanza.
    doc = _get(base, "/internal/health")
    assert doc["slo"]["objectives"] == 1
    assert doc["slo"]["alertsActive"] == 0
    # /metrics carries uptime + build info (satellite).
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        met = r.read().decode()
    assert "pilosa_process_uptime_seconds" in met
    assert 'pilosa_build_info{' in met and 'version="' in met \
        and 'backend="' in met
    assert "pilosa_sentinel_series" in met


def test_sentinel_sampling_adds_no_device_fences(live_server,
                                                 monkeypatch):
    """Acceptance: the whole sentinel plane is host-side dict reads —
    sampling, history, slo and the metrics refresh never fence the
    device (GL003 by construction, pinned here)."""
    import pilosa_tpu.executor.executor as exmod
    base, api, _h = live_server
    SENTINEL.configure(objectives={"query": "99.9% < 25ms"},
                       clock=lambda: 1.0)
    fences = []
    monkeypatch.setattr(exmod, "_fence_device",
                        lambda out: fences.append(1) or 0.0)
    api.sample_sentinel()
    api.debug_history()
    api.debug_slo()
    api.cluster_slo()
    api.refresh_memory_gauges()
    assert fences == []


# ------------------------------------------------ cluster fire/clear e2e


def test_client_5xx_burst_fires_and_clears_across_surfaces(tmp_path):
    """The acceptance scenario end to end on a 2-node cluster with an
    injected clock: a client.5xx failpoint burst fires the burn-rate
    alert visibly in /debug/slo, /metrics, /internal/health and
    /cluster/slo; recovery past the slow window clears it with
    hysteresis. No wall-clock sleeps."""
    from pilosa_tpu.utils.failpoints import FAILPOINTS
    from tests.test_cluster import _seed_bits, req, run_cluster

    clock = [1000.0]
    # 100 s threshold sits past every finite pow-2 bucket, so the
    # objective degrades to availability-only (thresholdBucket +Inf):
    # the e2e pin is the 5xx path, and real wall-clock latency on a
    # loaded CI box must not be able to burn budget here (the latency
    # leg is pinned separately on synthetic histograms).
    SENTINEL.configure(objectives={"query": "99.9% < 100s"},
                       clock=lambda: clock[0])
    nodes = run_cluster(tmp_path, 2, replica_n=1)
    try:
        base = nodes[0].uri
        _seed_bits(base)
        api = nodes[0].api
        sent = [0]

        def settle():
            # _observe_slo runs in the handler's `finally`, AFTER the
            # response bytes hit the socket — the client can return
            # before the server thread records the observation. Wait
            # for every sent query to land in the histogram so a
            # straggler 5xx cannot leak past a sample into the
            # recovery window (which would keep the alert burning).
            def landed():
                return sum(
                    h["count"] for k, h in
                    api.stats.snapshot()["histograms"].items()
                    if k.startswith("http_request_seconds")
                    and "/index/{index}/query" in k)
            deadline = time.time() + 10.0
            while landed() < sent[0] and time.time() < deadline:
                time.sleep(0.005)
            assert landed() >= sent[0]

        for _ in range(8):   # warm jit/caches BEFORE the baseline
            sent[0] += 1
            req(base, "POST", "/index/ci/query", b"Count(Row(f=1))")

        def burst(n=32, expect_5xx=False):
            bad = 0
            for _ in range(n):
                sent[0] += 1
                try:
                    req(base, "POST", "/index/ci/query",
                        b"Count(Row(f=1))")
                except urllib.error.HTTPError as e:
                    assert e.code >= 500
                    bad += 1
            assert (bad > 0) == expect_5xx
            settle()
            clock[0] += 30.0
            api.sample_sentinel()

        settle()
        api.sample_sentinel()          # baseline sample
        clock[0] += 30.0
        burst()                        # healthy traffic
        doc = req(base, "GET", "/debug/slo")
        assert doc["alerts"]["active"] == []
        ep = next(e for e in doc["endpoints"] if "target" in e)
        assert ep["total"] >= 32 and ep["bad"] == 0
        assert ep["thresholdBucket"] == "+Inf"  # availability-only

        # Fail the partner node's client leg: fan-out queries now 500.
        port1 = nodes[1].uri.rsplit(":", 1)[1]
        FAILPOINTS.arm("client.5xx", f"partition(:{port1})")
        burst(expect_5xx=True)
        FAILPOINTS.disarm_all()

        doc = req(base, "GET", "/debug/slo")
        active = {a["key"] for a in doc["alerts"]["active"]}
        assert active == {"slo-burn:query:300s",
                          "slo-burn:query:1800s"}
        met = req(base, "GET", "/metrics", raw=True).decode()
        assert "pilosa_sentinel_alerts_active 2" in met
        assert 'pilosa_slo_burn_rate{endpoint="query",window="300s"}' \
            in met
        health = req(base, "GET", "/internal/health")
        assert health["slo"]["alertsActive"] == 2
        assert health["slo"]["worstBurn"] > 14.4
        cdoc = req(base, "GET", "/cluster/slo")
        assert cdoc["respondedNodes"] == 2
        assert cdoc["totals"]["alertsActive"] >= 2
        assert cdoc["totals"]["endpoints"]["query"]["bad"] > 0
        assert cdoc["totals"]["endpoints"]["query"][
            "budgetConsumed"] > 1.0
        chealth = req(base, "GET", "/cluster/health")
        assert chealth["totals"]["sloAlertsActive"] >= 2

        # Recovery: jump past the 6 h slow window; good traffic only.
        clock[0] += 22000.0
        burst()
        doc = req(base, "GET", "/debug/slo")
        assert doc["alerts"]["active"] == []
        assert doc["alerts"]["cleared"] == 2
        met = req(base, "GET", "/metrics", raw=True).decode()
        assert "pilosa_sentinel_alerts_active 0" in met
        # The fleet roll-up sums bad/total, so the burst stays visible
        # in the budget even after the alert clears.
        cdoc = req(base, "GET", "/cluster/slo")
        assert cdoc["totals"]["alertsActive"] == 0
    finally:
        FAILPOINTS.disarm_all()
        for nd in nodes:
            nd.stop()


# ------------------------------------------------------------- drain


def test_drain_telemetry_order_once_and_reentrant(tmp_holder):
    """Satellite: one drain dumps every ring exactly once, in plane
    order (watchdog -> profiler -> workload -> timeline -> roofline ->
    sentinel -> tracer); a second call is a no-op."""
    from pilosa_tpu.cli.main import drain_telemetry
    from pilosa_tpu.server.api import API
    from tests.test_memledger import _LogStub

    api = API(tmp_holder, stats=MemStatsClient())
    SENTINEL.configure(objectives={"query": "99% < 25ms"},
                       clock=lambda: 1.0)
    api.profiler.record_slow("i", "Count(Row(f=1))", 2.5)
    api.sample_sentinel()
    SENTINEL.note_condition("roofline.drift", True, "synthetic",
                            now=2.0)

    class _Tracer:
        stops = 0

        def stop(self):
            self.stops += 1

    api.tracer = _Tracer()
    log = _LogStub()
    drain_telemetry(api, watchdog=None, logger=log)
    sent_lines = [l for l in log.lines if l.startswith("sentinel:")]
    assert any("1 samples" in l for l in sent_lines)
    assert any("alert fire roofline.drift" in l for l in sent_lines)
    # Ordering: profiler's slow-query line precedes the sentinel dump.
    first_sent = next(i for i, l in enumerate(log.lines)
                      if l.startswith("sentinel:"))
    slow = next(i for i, l in enumerate(log.lines)
                if "Count(Row(f=1))" in l)
    assert slow < first_sent
    assert api.tracer.stops == 1
    # Re-entrant second drain: nothing dumps twice, tracer not
    # re-stopped.
    n = len(log.lines)
    drain_telemetry(api, watchdog=None, logger=log)
    assert len(log.lines) == n
    assert api.tracer.stops == 1


# ------------------------------------------------------------- doctor


def _load_doctor():
    import importlib.util
    import pathlib
    path = pathlib.Path(__file__).resolve().parents[1] / "tools" \
        / "doctor.py"
    spec = importlib.util.spec_from_file_location("_doctor", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_doctor_bundle_diff_and_baseline(live_server, tmp_path,
                                         capsys):
    """tools/doctor.py against a live server: the bundle captures
    every surface, self-diff is empty (exit 0), the baseline judge
    passes on a healthy unmodified tree, and an active alert flips the
    verdict to failing."""
    doctor = _load_doctor()
    base, api, _h = live_server
    SENTINEL.configure(objectives={"query": "99.9% < 25ms"},
                       clock=lambda: 7000.0)
    api.sample_sentinel()
    bundle = doctor.snapshot_bundle(base)
    assert [k for k, _ in doctor.SURFACES] == list(bundle["surfaces"])
    errs = {k: s["error"] for k, s in bundle["surfaces"].items()
            if "error" in s}
    assert errs == {}, errs
    p1 = tmp_path / "a.json"
    p1.write_text(json.dumps(bundle))
    assert doctor.main(["diff", str(p1), str(p1)]) == 0
    out = capsys.readouterr().out
    assert "0 difference(s)" in out
    # Structural diff pins: changed leaf, added key, volatile ignored.
    lines = doctor.diff_docs(
        doctor._normalize({"a": 1, "t": 5, "x": {"y": 2}}),
        doctor._normalize({"a": 2, "t": 9, "x": {"y": 2, "z": 3}}))
    assert any(l.startswith("~ a:") for l in lines)
    assert any(l.startswith("+ x.z") for l in lines)
    assert not any(" t" in l.split(":")[0] for l in lines)
    # Baseline judge on the healthy bundle: zero failing checks
    # (BASELINE.json's empty `published` skips, never passes).
    verdicts = doctor.judge_bundle(
        bundle, baseline={"published": {}})
    bad = [(c, s, d) for c, s, d in verdicts
           if s in ("FAIL", "REGRESSED")]
    assert bad == [], bad
    assert ("memory.sentinel-ledgered", "PASS") in \
        [(c, s) for c, s, _ in verdicts]
    assert any(c == "baseline.published" and s == "SKIP"
               for c, s, _ in verdicts)
    # Published numbers: regression detected beyond tolerance.
    bundle["metrics"] = {"qps": 50.0}
    verdicts = doctor.judge_bundle(
        bundle, baseline={"published": {"qps": 100.0}})
    assert any(c == "baseline.qps" and s == "REGRESSED"
               for c, s, _ in verdicts)
    # An active alert fails the bundle.
    SENTINEL.note_condition("hbm.pressure", True, "synthetic",
                            now=7100.0)
    bundle2 = doctor.snapshot_bundle(base)
    verdicts = doctor.judge_bundle(bundle2)
    assert any(c == "slo.no-active-alerts" and s == "FAIL"
               for c, s, _ in verdicts)


def test_doctor_records_unreachable_surface():
    doctor = _load_doctor()
    bundle = doctor.snapshot_bundle("http://localhost:1")  # refused
    assert all("error" in s for s in bundle["surfaces"].values())
    verdicts = doctor.judge_bundle(bundle)
    assert any(c == "surface:slo" and s == "FAIL"
               for c, s, _ in verdicts)


# ------------------------------------------------------------- config


def test_config_slo_and_sentinel_tables(tmp_path, monkeypatch):
    from pilosa_tpu.utils.config import Config, load_config
    cfg_path = tmp_path / "c.toml"
    cfg_path.write_text(
        '[slo]\n'
        'query = "99.9% < 25ms"\n'
        '"/batch/query" = "99% < 100ms"\n'
        '[sentinel]\n'
        'ring = 360\n'
        'decimate = 5\n')
    cfg = load_config(str(cfg_path))
    assert cfg.slo == {"query": "99.9% < 25ms",
                       "/batch/query": "99% < 100ms"}
    assert cfg.sentinel_ring == 360 and cfg.sentinel_decimate == 5
    assert cfg.sentinel_enabled
    # Env dict merge layers on top of the file.
    monkeypatch.setenv("PILOSA_TPU_SLO", "query=99% < 50ms")
    cfg = load_config(str(cfg_path))
    assert cfg.slo["query"] == "99% < 50ms"
    assert cfg.slo["/batch/query"] == "99% < 100ms"
    # validate() rejects malformed objectives and bad ring bounds.
    bad = Config()
    bad.slo = {"query": "fast please"}
    with pytest.raises(ValueError, match="objective"):
        bad.validate()
    bad = Config()
    bad.sentinel_ring = 1
    with pytest.raises(ValueError, match="sentinel ring"):
        bad.validate()
