"""Plan-IR verification plane (ops/megakernel.verify_plan +
executor/megakernel PILOSA_TPU_PLAN_VERIFY gate): the verifier must
accept every plan the shipped lowering emits, reject every mutation in
the coverage set BEFORE dispatch (no _call_program ever sees a
corrupted plan), prove the width-masking invariant via the abstract
interpreter, and feed the pilosa_executor_plan_verify_* counters."""

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor import megakernel as megamod
from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.ops.bitset import SHARD_WIDTH

from tools.planverify import (
    PLAN_MUTATIONS, clone_plan, mutate_plan, run_sweep,
)


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(31)
    rows = rng.integers(0, 8, 5000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 5000).astype(np.uint64)
    f.import_bits(rows, cols)
    g.import_bits(rows[::2], cols[::2])
    idx.create_field("v", FieldOptions(type="int", min=-500, max=10000))
    vcols = rng.integers(0, 2 * SHARD_WIDTH, 900).astype(np.uint64)
    idx.field("v").import_values(
        vcols, rng.integers(-500, 10000, 900).astype(np.int64))
    # "s": a sparse-RESIDENT field (hybrid layout) so live plans carry
    # OP_EXPAND and the expand mutation kinds apply.
    s = idx.create_field("s")
    srows = np.repeat(np.arange(300, dtype=np.uint64), 2)
    scols = rng.integers(0, 4096, 600).astype(np.uint64)
    s.import_bits(srows, scols)
    assert s.view("standard").set_layout("sparse")
    idx.add_existence(np.concatenate([cols, scols]))
    executor = Executor(h)
    executor.result_cache.enabled = False
    prev = megamod.MEGAKERNEL_ENABLED
    prev_mode = megamod.PLAN_VERIFY_MODE
    megamod.MEGAKERNEL_ENABLED = True
    megamod.PLAN_VERIFY_MODE = "on"
    yield executor
    megamod.MEGAKERNEL_ENABLED = prev
    megamod.PLAN_VERIFY_MODE = prev_mode
    h.close()


MIXED = ([("i", f"Count(Row(f={r}))", None) for r in (1, 2)]
         + [("i", "Row(g=3)", None)]
         + [("i", "Count(Intersect(Row(f=4), Row(g=4)))", None)]
         + [("i", "Count(Row(v > 300))", None)])


def capture_plans(monkeypatch):
    captured = []
    orig = megamod._build

    def wrapped(cohort):
        plan, w_mega, lanes = orig(cohort)
        captured.append((plan, cohort[0].entries[0].n_shards, w_mega))
        return plan, w_mega, lanes

    monkeypatch.setattr(megamod, "_build", wrapped)
    return captured


# ------------------------------------------------------------ live gate


def test_on_mode_verifies_every_launch(ex):
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in MIXED]
    assert ex.execute_batch_shaped(MIXED) == direct
    assert ex.mega_launches == 1
    assert ex.plan_verify_passes == 1
    assert ex.plan_verify_rejects == 0
    # `on` re-verifies even a jit-cache-hit repeat.
    assert ex.execute_batch_shaped(MIXED) == direct
    assert ex.plan_verify_passes == 2


def test_auto_mode_verifies_first_launch_per_jit_key(ex, monkeypatch):
    monkeypatch.setattr(megamod, "PLAN_VERIFY_MODE", "auto")
    ex.execute_batch_shaped(MIXED)
    assert (ex.mega_launches, ex.plan_verify_passes) == (1, 1)
    # Same composition -> same capacities -> jit hit -> no re-verify.
    ex.execute_batch_shaped(MIXED)
    assert (ex.mega_launches, ex.plan_verify_passes) == (2, 1)
    # A composition landing in a fresh capacity bucket compiles anew
    # and is verified once.
    bigger = MIXED + [("i", f"Count(Union(Row(f={r}), Row(g={r})))",
                       None) for r in range(5)]
    ex.execute_batch_shaped(bigger)
    assert ex.mega_launches == 3
    assert ex.plan_verify_passes == 2


def test_off_mode_skips_verification(ex, monkeypatch):
    monkeypatch.setattr(megamod, "PLAN_VERIFY_MODE", "off")
    ex.execute_batch_shaped(MIXED)
    assert ex.mega_launches == 1
    assert ex.plan_verify_passes == 0
    assert ex.plan_verify_rejects == 0


def test_reject_raises_before_dispatch(ex, monkeypatch):
    """A corrupted plan must surface as per-request errors WITHOUT the
    compiled program ever being invoked — wrong bits can never serve."""
    orig_build = megamod._build

    def corrupt_build(cohort):
        plan, w_mega, lanes = orig_build(cohort)
        assert plan.n_instrs > 0
        plan.instrs[0, 0] = 9  # opcode off the table
        return plan, w_mega, lanes

    monkeypatch.setattr(megamod, "_build", corrupt_build)
    calls = []
    orig_call = Executor._call_program

    def counting(self, fn, *args):
        calls.append(fn)
        return orig_call(self, fn, *args)

    monkeypatch.setattr(Executor, "_call_program", counting)
    out = ex.execute_batch_shaped(MIXED)
    assert all(isinstance(r, mk.PlanVerifyError) for r in out), out
    assert calls == [], "rejected plan must never dispatch"
    assert ex.plan_verify_rejects == 1
    assert ex.plan_verify_passes == 0
    assert ex.mega_launches == 0
    # The executor keeps serving after a reject.
    monkeypatch.undo()
    assert ex.execute("i", "Count(Row(f=1))")[0] >= 0


def test_counters_export_on_metrics(ex):
    from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text
    ex.stats = MemStatsClient()
    ex.execute_batch_shaped(MIXED)
    text = prometheus_text(ex.stats)
    assert "pilosa_executor_plan_verify_passes_total 1" in text


def test_health_document_carries_verify_counters(ex, tmp_path):
    from pilosa_tpu.server.api import API
    from pilosa_tpu.utils.stats import MemStatsClient
    api = API(ex.holder, stats=MemStatsClient())
    api.executor = ex
    ex.execute_batch_shaped(MIXED)
    doc = api.node_health()
    assert doc["executor"]["planVerifyPasses"] == 1
    assert doc["executor"]["planVerifyRejects"] == 0


# ------------------------------------------------- mutation coverage set


def test_every_mutation_kind_rejected_on_live_plans(ex, monkeypatch):
    """The acceptance criterion: capture plans the LIVE lowering
    builds, corrupt each across the full mutation-kind coverage set,
    and require every applied mutation to be rejected pre-launch —
    with every kind proven live (applied at least once)."""
    captured = capture_plans(monkeypatch)
    ex.execute_batch_shaped(MIXED)
    big = MIXED + [("i", "Count(Row(-100 < v < 500))", None),
                   ("i", "Row(v <= 9000)", None),
                   # Sparse-resident operands: the OP_EXPAND path, so
                   # the expand_* / xslot_row mutation kinds apply.
                   ("i", "Count(Row(s=1))", None),
                   ("i", "Count(Intersect(Row(s=2), Row(f=2)))", None),
                   # Threshold: OP_THRESH thermometer rows, so the
                   # thresh_off_by_one mutation kind applies.
                   ("i", "Count(Threshold(Row(f=1), Row(f=3), "
                         "Row(g=5), k=2))", None)]
    ex.execute_batch_shaped(big)
    assert captured
    applied = set()
    for pi, (plan, n_shards, w_mega) in enumerate(captured):
        mk.verify_plan(plan, n_shards, w_mega)  # accepts the original
        for ki, kind in enumerate(PLAN_MUTATIONS):
            rng = np.random.default_rng([5, pi, ki])
            mutated = mutate_plan(rng, plan, kind, w_mega=w_mega)
            if mutated is None:
                continue
            applied.add(kind)
            with pytest.raises(mk.PlanVerifyError):
                mk.verify_plan(mutated, n_shards, w_mega)
    assert applied == set(PLAN_MUTATIONS), \
        f"dead mutation kinds: {set(PLAN_MUTATIONS) - applied}"


def test_planverify_sweep_is_clean():
    """The jax-free synthetic sweep (tools/planverify): the shipped
    lowering and the checker agree across the opcode/BSI table."""
    assert run_sweep(seed=3) == []


# --------------------------------------------- abstract interpreter unit


def _tiny_plan():
    bank = np.zeros((16, 2, 8), np.uint32)
    low = mk.Lowering()
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2)),
                  [bank], [1, 2], [], 8, "count")
    low.add_entry((("slot", 0, 0),), [bank], [3], [], 4, "row")
    return low.finish()


def test_masking_invariant_caught_by_lattice():
    """A width corruption that stays inside [1, w_mega] is invisible
    to the bounds check — only the zero-extension lattice catches the
    register's span overrunning its lane's plan width."""
    plan = _tiny_plan()
    mk.verify_plan(plan, 2, 8)
    bad = clone_plan(plan)
    # The row entry's slot carries width 4; claim 8: abstract span 8
    # now exceeds the lane's plan width 4.
    k = [i for i in range(bad.n_slots) if int(bad.widths[i]) == 4][0]
    bad.widths[k] = 8
    with pytest.raises(mk.PlanVerifyError, match="masking invariant"):
        mk.verify_plan(bad, 2, 8)


def test_def_before_use_violation_caught():
    plan = _tiny_plan()
    bad = clone_plan(plan)
    # Point the AND's a-operand at an unwritten scratch register.
    bad.instrs[0, 2] = bad.n_regs - 1
    with pytest.raises(mk.PlanVerifyError, match="before any"):
        mk.verify_plan(bad, 2, 8)


def test_slot_registers_are_write_protected():
    plan = _tiny_plan()
    bad = clone_plan(plan)
    bad.instrs[0, 1] = 0
    with pytest.raises(mk.PlanVerifyError, match="read-only"):
        mk.verify_plan(bad, 2, 8)


def test_pad_tail_must_be_provable_noops():
    # A 4-way fold lowers to 3 instructions -> pow2 pad to 4: exactly
    # one pad-tail instruction to corrupt.
    bank = np.zeros((16, 2, 8), np.uint32)
    low = mk.Lowering()
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("slot", 0, 2),
                   ("slot", 0, 3), ("fold", "or", 4)),
                  [bank], [1, 2, 3, 4], [], 8, "count")
    plan = low.finish()
    mk.verify_plan(plan, 2, 8)
    assert plan.instrs.shape[0] > plan.n_instrs, "needs a pad tail"
    bad = clone_plan(plan)
    bad.instrs[plan.n_instrs, 0] = mk.OP_AND
    with pytest.raises(mk.PlanVerifyError, match="pad"):
        mk.verify_plan(bad, 2, 8)
    # A pad ZERO aimed at a register a real output lane reads is just
    # as corrupting as a wrong opcode.
    bad2 = clone_plan(plan)
    bad2.instrs[plan.n_instrs, 1] = int(plan.out_count[0])
    with pytest.raises(mk.PlanVerifyError, match="pad"):
        mk.verify_plan(bad2, 2, 8)


def test_zero_extension_commutes_through_fold_chain():
    """OR widens to the max span, AND narrows to the min: a chain
    mixing widths must prove exactly the lane's width, no more."""
    bank = np.zeros((16, 2, 8), np.uint32)
    low = mk.Lowering()
    # (w4 OR w4) at entry width 4 -> span 4 == lane width 4.
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "or", 2)),
                  [bank], [1, 2], [], 4, "count")
    # (w8 AND w8) -> 8 == lane width 8.
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2)),
                  [bank], [1, 2], [], 8, "count")
    plan = low.finish()
    mk.verify_plan(plan, 2, 8)


def test_gather_only_plan_verifies():
    """n_instrs == 0: the whole instruction buffer is pad tail and the
    output lane reads a slot register directly."""
    bank = np.zeros((4, 2, 4), np.uint32)
    low = mk.Lowering()
    low.add_entry((("slot", 0, 0),), [bank], [2], [], 4, "row")
    plan = low.finish()
    assert plan.n_instrs == 0
    mk.verify_plan(plan, 2, 4)
