"""Two-process jax.distributed CPU dryrun (SURVEY §7 step 6; VERDICT r2
missing #5): the cross-host code path — one global mesh over two
processes' devices, shard-axis reductions lowered to cross-process
collectives — must compile and reduce correctly."""

import os
import subprocess
import sys

import pytest


@pytest.mark.timeout(360)
def test_two_process_jax_distributed_dryrun():
    env = dict(os.environ)
    # The parent re-spawns children with its own platform/device flags;
    # scrub this test process's conftest-driven settings.
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "pilosa_tpu.parallel.multihost"],
        cwd=repo, env=env, capture_output=True, timeout=330)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out
    assert "multihost dryrun: OK" in out, out
    assert out.count("OK counts=") == 2, out  # both processes verified
