"""Two-process jax.distributed CPU dryrun (SURVEY §7 step 6; VERDICT r2
missing #5): the cross-host code path — one global mesh over two
processes' devices, shard-axis reductions lowered to cross-process
collectives — must compile and reduce correctly."""

import os
import subprocess
import sys

import pytest

from pilosa_tpu.parallel.multihost import cpu_multiprocess_supported


def test_timeout_mark_is_enforced():
    """The vendored SIGALRM timeout (conftest.alarm_timeout) actually
    interrupts a blocking wait — a hung distributed child must fail the
    suite, not hang it (VERDICT r3 weak #4). The helper is taken from
    the conftest module pytest ALREADY loaded (its import name varies
    with rootdir/package layout, and a fresh `import tests.conftest`
    would execute it a second time)."""
    import time

    alarm_timeout = next(
        m.alarm_timeout for name, m in sorted(sys.modules.items())
        if name.endswith("conftest") and hasattr(m, "alarm_timeout"))

    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="exceeded 1s"):
        with alarm_timeout(1, what="sleeper"):
            time.sleep(30)
    assert time.monotonic() - t0 < 5


@pytest.mark.timeout(360)
@pytest.mark.skipif(
    not cpu_multiprocess_supported(),
    reason="XLA:CPU lacks a cross-process collectives plugin (no gloo "
           "hooks in jaxlib / no jax_cpu_collectives_implementation "
           "knob) — multiprocess CPU computations cannot run here")
def test_two_process_jax_distributed_dryrun():
    env = dict(os.environ)
    # The parent re-spawns children with its own platform/device flags;
    # scrub this test process's conftest-driven settings.
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "pilosa_tpu.parallel.multihost"],
        cwd=repo, env=env, capture_output=True, timeout=330)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out
    assert "multihost dryrun: OK" in out, out
    assert out.count("OK counts=") == 2, out  # both processes verified
