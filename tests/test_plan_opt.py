"""Cost-based plan optimizer (ops/plan_opt.py): per-pass unit tests
over plans built through the real Lowering, plus executor-level
threshold truth tables and the PILOSA_TPU_PLAN_OPT kill-switch
bit-identity contract across the unfused / fused / megakernel legs.

The pass-level tests pin EXACT counts (entries, cse hits, reorders,
narrowed lanes) so a regression shows up as a number, not a timing;
every optimized plan is pushed back through ``verify_plan`` because
"the optimizer only emits verifiable plans" is the contract the
PV001 sweep (tools/planverify.py) enforces fleet-wide."""

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor import executor as exmod
from pilosa_tpu.executor import megakernel as megamod
from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.ops import plan_opt
from pilosa_tpu.ops.bitset import SHARD_WIDTH

N_SHARDS = 2
BANK_ROWS = 32


def bank(w):
    """Shape-carrying operand bank (contents never read host-side)."""
    return np.zeros((BANK_ROWS, N_SHARDS, w), np.uint32)


def run_opt(low, w_mega=8):
    plan = low.finish()
    new, stats = plan_opt.optimize_plan(plan, N_SHARDS, w_mega)
    mk.verify_plan(new, N_SHARDS, w_mega)
    return plan, new, stats


# ------------------------------------------------------------------ CSE


def test_cse_identical_entries_collapse_to_one_row():
    """Four identical AND entries: one fold row survives, every count
    lane aliases the same register, and the freed scratch shrinks the
    register file (slab bytes drop with it)."""
    low = mk.Lowering()
    b = bank(8)
    ir = (("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2))
    for _ in range(4):
        low.add_entry(ir, [b], [3, 5], [], 8, "count")
    plan, new, st = run_opt(low)
    assert st.entries_before == 4
    assert st.entries_after == 1
    assert new.n_instrs == 1
    assert st.cse_hits == 3
    assert st.entries_eliminated == 3
    assert len({int(r) for r in new.out_count[:4]}) == 1
    assert st.regs_after <= st.regs_before
    assert st.bytes_saved > 0


def test_cse_matches_commuted_operands():
    """AND(a, b) and AND(b, a) are the same value — the fingerprint
    sorts commutative operands, so the commuted twin is a hit."""
    low = mk.Lowering()
    b = bank(8)
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2)),
                  [b], [3, 5], [], 8, "count")
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2)),
                  [b], [5, 3], [], 8, "count")
    _, new, st = run_opt(low)
    assert new.n_instrs == 1
    assert st.cse_hits == 1
    assert int(new.out_count[0]) == int(new.out_count[1])


def test_cse_never_commutes_andnot():
    """Difference is pinned: diff(a, b) and diff(b, a) are different
    values and must keep separate rows."""
    low = mk.Lowering()
    b = bank(8)
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "diff", 2)),
                  [b], [3, 5], [], 8, "count")
    low.add_entry((("slot", 0, 0), ("slot", 0, 1), ("fold", "diff", 2)),
                  [b], [5, 3], [], 8, "count")
    _, new, st = run_opt(low)
    assert new.n_instrs == 2
    assert st.cse_hits == 0
    assert int(new.out_count[0]) != int(new.out_count[1])


def test_cse_shared_subtree_across_requests():
    """The cross-request shape the batch planner produces: N entries
    sharing one operand. Each keeps its distinct fold, but the shared
    gather slot is one register and the folds stay one row each."""
    low = mk.Lowering()
    b = bank(8)
    ir = (("slot", 0, 0), ("slot", 0, 1), ("fold", "and", 2))
    for c in (5, 6, 7, 9):
        low.add_entry(ir, [b], [3, c], [], 8, "count")
    _, new, st = run_opt(low)
    assert new.n_instrs == 4
    assert st.cse_hits == 0
    assert len({int(r) for r in new.out_count[:4]}) == 4


# -------------------------------------------------------- fold reorder


def test_reorder_commutative_density_ascending():
    """An OR chain sorts its operands cheapest-first (ties keep
    program order); the chain head rewrites in place."""
    rows = [[mk.OP_OR, 3, 0, 1], [mk.OP_OR, 3, 3, 2]]
    st = plan_opt.OptStats()
    plan_opt._reorder_folds(rows, {0: 0.9, 1: 0.5, 2: 0.1}, st)
    assert st.folds_reordered == 1
    assert rows == [[mk.OP_OR, 3, 2, 1], [mk.OP_OR, 3, 3, 0]]


def test_reorder_andnot_head_pinned_densest_negative_first():
    """ANDNOT keeps its left operand (the value being subtracted
    from); the negatives sort densest-first so the accumulator
    shrinks early."""
    rows = [[mk.OP_ANDNOT, 4, 0, 1],
            [mk.OP_ANDNOT, 4, 4, 2],
            [mk.OP_ANDNOT, 4, 4, 3]]
    st = plan_opt.OptStats()
    plan_opt._reorder_folds(
        rows, {0: 0.2, 1: 0.1, 2: 0.9, 3: 0.5}, st)
    assert st.folds_reordered == 1
    assert rows == [[mk.OP_ANDNOT, 4, 0, 2],
                    [mk.OP_ANDNOT, 4, 4, 3],
                    [mk.OP_ANDNOT, 4, 4, 1]]


def test_reorder_is_stable_without_density_signal():
    """No ledger signal -> every operand weighs the same -> program
    order is already sorted and the pass must not count a reorder
    (the CSE fingerprints depend on the order being canonical)."""
    rows = [[mk.OP_OR, 3, 0, 1], [mk.OP_OR, 3, 3, 2]]
    before = [list(r) for r in rows]
    st = plan_opt.OptStats()
    plan_opt._reorder_folds(rows, {}, st)
    assert st.folds_reordered == 0
    assert rows == before


def test_reorder_end_to_end_preserves_verification():
    """Through the full pipeline: three banks with sampled densities,
    one OR fold across them — the reorder is observed in stats and
    the rewritten plan still verifies."""
    low = mk.Lowering()
    b_dense, b_mid, b_sparse = bank(8), bank(8), bank(8)
    plan_opt.note_bank_density(b_dense, 0.9)
    plan_opt.note_bank_density(b_mid, 0.5)
    plan_opt.note_bank_density(b_sparse, 0.05)
    low.add_entry((("slot", 0, 0), ("slot", 1, 1), ("slot", 2, 2),
                   ("fold", "or", 3)),
                  [b_dense, b_mid, b_sparse], [1, 2, 3], [], 8, "count")
    _, new, st = run_opt(low)
    assert st.folds_reordered == 1
    assert new.n_instrs == 2


# ------------------------------------------------------ width narrowing


def test_narrowing_follows_zero_extension_lattice():
    """The absent-row shape: an AND with a zero leaf is provably empty
    past limb 1 (verify_plan's span transfer takes the min), so its
    w=8 count lane narrows to 1. The OR with the same zero leaf keeps
    the operand's full span and must NOT narrow."""
    low = mk.Lowering()
    b = bank(8)
    low.add_entry((("zero",), ("slot", 0, 0), ("fold", "and", 2)),
                  [b], [2], [], 8, "count")
    low.add_entry((("zero",), ("slot", 0, 0), ("fold", "or", 2)),
                  [b], [2], [], 8, "count")
    _, new, st = run_opt(low)
    assert st.narrowed_lanes == 1
    assert new.lane_count_widths[0] == 1
    assert new.lane_count_widths[1] == 8


def test_gather_only_row_plan_survives():
    """A plan with NO instructions (pure gather row lane) goes through
    the pipeline: the lane's slot register must get an input value
    number even though no instruction ever read it."""
    low = mk.Lowering()
    b = bank(4)
    low.add_entry((("slot", 0, 0),), [b], [5], [], 4, "row")
    _, new, st = run_opt(low, w_mega=4)
    assert new.n_instrs == 0
    assert st.entries_eliminated == 0
    assert tuple(new.lane_row_widths) == (4,)


# --------------------------------------------------------- density feed


def test_density_feed_roundtrip_and_cap():
    # The registry keys on id(); arrays freed by earlier tests can leave
    # stale entries whose id a fresh bank() may reuse. Harmless in prod
    # (ordering-only), but this test asserts exact defaults — isolate it.
    with plan_opt._density_lock:
        plan_opt._density.clear()
    a, b = bank(4), bank(4)
    plan_opt.note_bank_density(a, 0.25)
    assert plan_opt.bank_density(a) == 0.25
    plan_opt.note_bank_density(b, None)  # best-effort: None is a no-op
    assert plan_opt.bank_density(b) == plan_opt.DEFAULT_DENSITY
    # The id()->density map is bounded: old entries evict FIFO.
    keep = [np.zeros(1, np.uint32)
            for _ in range(plan_opt._DENSITY_CAP)]
    for arr in keep:
        plan_opt.note_bank_density(arr, 0.75)
    assert plan_opt.bank_density(a) == plan_opt.DEFAULT_DENSITY
    assert plan_opt.bank_density(keep[-1]) == 0.75


# ------------------------------------------- executor: threshold + kill


N_ROWS = 16


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(41)
    rows = rng.integers(0, N_ROWS, 6000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 6000).astype(np.uint64)
    f.import_bits(rows, cols)
    g.import_bits(rows[::2], cols[::2])
    idx.create_field("v", FieldOptions(type="int", min=-500, max=10000))
    vcols = rng.integers(0, 2 * SHARD_WIDTH, 900).astype(np.uint64)
    idx.field("v").import_values(
        vcols, rng.integers(-500, 10000, 900).astype(np.int64))
    idx.add_existence(cols)
    executor = Executor(h)
    executor.result_cache.enabled = False
    prev = megamod.MEGAKERNEL_ENABLED
    megamod.MEGAKERNEL_ENABLED = True
    yield executor
    megamod.MEGAKERNEL_ENABLED = prev
    h.close()


A, B, C = "Row(f=1)", "Row(f=2)", "Row(g=3)"

# Threshold(k) over {A, B, C} == the union of all k-subsets'
# intersections — the classic expansion the thermometer lowering
# replaces. Each pair below must be bit-identical.
THRESH_EQUIV = [
    (f"Threshold({A}, {B}, {C}, k=1)", f"Union({A}, {B}, {C})"),
    (f"Threshold({A}, {B}, {C}, k=2)",
     f"Union(Intersect({A}, {B}), Intersect({A}, {C}), "
     f"Intersect({B}, {C}))"),
    (f"Threshold({A}, {B}, {C}, k=3)", f"Intersect({A}, {B}, {C})"),
    (f"Threshold({A}, {B}, {C}, k=4)", f"Difference({A}, {A})"),
]


def test_threshold_truth_table_row_and_count(ex):
    for thresh, equiv in THRESH_EQUIV:
        assert ex.execute_full("i", thresh) \
            == ex.execute_full("i", equiv), thresh
        assert ex.execute_full("i", f"Count({thresh})") \
            == ex.execute_full("i", f"Count({equiv})"), thresh


def test_threshold_in_megakernel_batch_matches_direct(ex):
    reqs = [("i", f"Count({t})", None) for t, _ in THRESH_EQUIV] \
        + [("i", THRESH_EQUIV[1][0], None)]
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in reqs]
    assert ex.execute_batch_shaped(reqs) == direct
    assert ex.mega_launches == 1


def test_threshold_argument_validation(ex):
    for bad in ("Threshold(Row(f=1), Row(f=2))",      # k missing
                "Threshold(Row(f=1), k=0)",           # k < 1
                "Threshold(Row(f=1), k=1.5)",         # non-integer k
                "Threshold(k=2)"):                    # no rows
        with pytest.raises(Exception):
            ex.execute_full("i", bad)


# Shared-subtree burst: the cross-request CSE shape (every query
# reuses Intersect(A, B)) plus a threshold rider.
SHARED = ([("i", f"Count(Intersect({A}, {B}))", None)] * 2
          + [("i", f"Count(Intersect(Intersect({A}, {B}), Row(g={r})))",
              None) for r in (4, 5, 6)]
          + [("i", f"Count(Threshold({A}, {B}, Row(g=4), k=2))", None)]
          + [("i", f"Intersect({B}, {A})", None)])


def test_optimizer_bit_identity_and_counters(ex):
    """Opt ON, megakernel ON: one launch, CSE observed, results
    bit-identical to the direct path."""
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in SHARED]
    assert ex.execute_batch_shaped(SHARED) == direct
    assert ex.mega_launches == 1
    assert ex.opt_plans == 1
    assert ex.opt_cse_hits > 0
    assert ex.opt_entries_eliminated > 0
    assert ex.opt_bytes_saved > 0


def test_kill_switch_bit_identity_across_paths(ex, monkeypatch):
    """PILOSA_TPU_PLAN_OPT=0 (module switch) and the megakernel /
    fusion fallbacks all agree bit-for-bit with the optimized path."""
    optimized = ex.execute_batch_shaped(SHARED)
    plans_after_opt = ex.opt_plans

    monkeypatch.setattr(megamod, "PLAN_OPT_ENABLED", False)
    assert ex.execute_batch_shaped(SHARED) == optimized
    assert ex.opt_plans == plans_after_opt, \
        "kill switch must keep the optimizer fully out of the path"

    monkeypatch.setattr(megamod, "MEGAKERNEL_ENABLED", False)
    assert ex.execute_batch_shaped(SHARED) == optimized  # fused leg

    monkeypatch.setattr(exmod, "FUSION_ENABLED", False)
    assert ex.execute_batch_shaped(SHARED) == optimized  # unfused leg


def test_profile_carries_before_after_entry_counts(ex):
    """The profile tree's megakernel node reports planEntriesBefore/
    After so a perf regression in the optimizer is visible per
    request, not just in process counters."""
    from pilosa_tpu.utils.profile import QueryProfile
    profs = [QueryProfile(i, q) for i, q, _ in SHARED]
    ex.execute_batch(SHARED, profiles=profs)
    found = []
    for p in profs:
        for op in p.ops:
            for node in op.children:
                if "planEntriesBefore" in node.attrs:
                    found.append(node.attrs)
    assert found, "no profile node carried optimizer attribution"
    for attrs in found:
        assert attrs["planEntriesAfter"] < attrs["planEntriesBefore"]
