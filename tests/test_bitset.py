"""Golden tests: device bitset kernels vs a plain-numpy model."""

import numpy as np
import jax.numpy as jnp

from pilosa_tpu.ops import bitset as bs


def np_pack(positions):
    return bs.pack_positions(positions)


def rand_positions(rng, n, width=bs.SHARD_WIDTH):
    return np.unique(rng.integers(0, width, size=n, dtype=np.uint64))


def test_pack_unpack_roundtrip(rng):
    pos = rand_positions(rng, 5000)
    words = bs.pack_positions(pos)
    assert words.dtype == np.uint32
    got = bs.unpack_positions(words)
    np.testing.assert_array_equal(got, pos)


def test_u64_u32_view_roundtrip(rng):
    u64 = rng.integers(0, 2**63, size=1024, dtype=np.uint64)
    words = bs.u64_to_words(u64)
    assert words.dtype == np.uint32 and len(words) == 2048
    back = bs.words_to_u64(words)
    np.testing.assert_array_equal(back, u64)


def test_bit_position_consistency():
    # bit p lives at u32 word p>>5, bit p&31, and that layout must agree
    # with the little-endian u64 view used by host storage.
    for p in [0, 1, 31, 32, 63, 64, 65, 2**16, 2**20 - 1]:
        words = bs.pack_positions([p])
        assert words[p >> 5] == np.uint32(1 << (p & 31))
        u64 = bs.words_to_u64(words)
        assert u64[p >> 6] == np.uint64(1 << (p & 63))


def test_set_algebra_matches_numpy(rng):
    a_pos = rand_positions(rng, 20000)
    b_pos = rand_positions(rng, 20000)
    a, b = np_pack(a_pos), np_pack(b_pos)
    ja, jb = jnp.asarray(a), jnp.asarray(b)

    cases = {
        "and": (bs.b_and(ja, jb), np.intersect1d(a_pos, b_pos)),
        "or": (bs.b_or(ja, jb), np.union1d(a_pos, b_pos)),
        "xor": (bs.b_xor(ja, jb), np.setxor1d(a_pos, b_pos)),
        "andnot": (bs.b_andnot(ja, jb), np.setdiff1d(a_pos, b_pos)),
    }
    for name, (got_words, want_pos) in cases.items():
        got = bs.unpack_positions(np.asarray(got_words))
        np.testing.assert_array_equal(got, want_pos, err_msg=name)


def test_not_with_existence(rng):
    a_pos = rand_positions(rng, 1000)
    exist_pos = rand_positions(rng, 5000)
    ja, je = jnp.asarray(np_pack(a_pos)), jnp.asarray(np_pack(exist_pos))
    got = bs.unpack_positions(np.asarray(bs.b_not(ja, je)))
    np.testing.assert_array_equal(got, np.setdiff1d(exist_pos, a_pos))


def test_counts(rng):
    a_pos = rand_positions(rng, 30000)
    b_pos = rand_positions(rng, 30000)
    ja, jb = jnp.asarray(np_pack(a_pos)), jnp.asarray(np_pack(b_pos))
    assert int(bs.popcount(ja)) == len(a_pos)
    assert int(bs.count_and(ja, jb)) == len(np.intersect1d(a_pos, b_pos))
    assert int(bs.count_or(ja, jb)) == len(np.union1d(a_pos, b_pos))
    assert int(bs.count_xor(ja, jb)) == len(np.setxor1d(a_pos, b_pos))
    assert int(bs.count_andnot(ja, jb)) == len(np.setdiff1d(a_pos, b_pos))


def test_popcount_batched(rng):
    rows = np.stack([np_pack(rand_positions(rng, n)) for n in (10, 100, 1000)])
    counts = bs.popcount(jnp.asarray(rows), axis=-1)
    assert counts.shape == (3,)
    for i, row in enumerate(rows):
        assert int(counts[i]) == len(bs.unpack_positions(row))


def test_union_intersect_many(rng):
    stacks = [rand_positions(rng, 5000) for _ in range(4)]
    stack = jnp.asarray(np.stack([np_pack(p) for p in stacks]))
    got_u = bs.unpack_positions(np.asarray(bs.union_many(stack)))
    want_u = stacks[0]
    for p in stacks[1:]:
        want_u = np.union1d(want_u, p)
    np.testing.assert_array_equal(got_u, want_u)

    got_i = bs.unpack_positions(np.asarray(bs.intersect_many(stack)))
    want_i = stacks[0]
    for p in stacks[1:]:
        want_i = np.intersect1d(want_i, p)
    np.testing.assert_array_equal(got_i, want_i)


def test_shift(rng):
    for n in (1, 31, 32, 33, 64, 1000):
        pos = rand_positions(rng, 2000)
        ja = jnp.asarray(np_pack(pos))
        got = bs.unpack_positions(np.asarray(bs.shift_bits(ja, n)))
        want = pos + np.uint64(n)
        want = want[want < bs.SHARD_WIDTH]  # dropped at shard top
        np.testing.assert_array_equal(got, want, err_msg=f"shift {n}")


def test_range_mask(rng):
    for start, end in [(0, 1), (5, 37), (0, bs.SHARD_WIDTH), (100, 100), (64, 128),
                       (bs.SHARD_WIDTH - 3, bs.SHARD_WIDTH)]:
        mask = bs.range_mask_np(start, end)
        got = bs.unpack_positions(mask)
        np.testing.assert_array_equal(got, np.arange(start, end, dtype=np.uint64),
                                      err_msg=f"[{start},{end})")
