"""Docs fidelity: the getting-started walkthrough and query-language
examples must actually work against a live server, verbatim — users copy
these (the analog of the reference keeping docs/getting-started.md and
executor_test.go in behavioral sync)."""

import json
import urllib.request

import pytest



@pytest.fixture
def base(live_server):
    yield live_server[0]


def post(base, path, body):
    data = body if isinstance(body, bytes) else body.encode()
    r = urllib.request.Request(base + path, data=data, method="POST")
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read() or b"{}")


def test_getting_started_walkthrough(base):
    # Create the schema (docs/getting-started.md "Create the schema")
    post(base, "/index/repository", "{}")
    post(base, "/index/repository/field/stargazer",
         '{"options": {"type": "set"}}')
    # Write data
    assert post(base, "/index/repository/query",
                "Set(1, stargazer=14)")["results"] == [True]
    post(base, "/index/repository/query",
         "Set(1, stargazer=19) Set(2, stargazer=14) Set(3, stargazer=14)")
    # Query
    r = post(base, "/index/repository/query", "Row(stargazer=14)")
    assert r["results"][0]["columns"] == [1, 2, 3]
    r = post(base, "/index/repository/query",
             "Intersect(Row(stargazer=14), Row(stargazer=19))")
    assert r["results"][0]["columns"] == [1]
    r = post(base, "/index/repository/query",
             "Count(Intersect(Row(stargazer=14), Row(stargazer=19)))")
    assert r["results"] == [1]
    r = post(base, "/index/repository/query", "TopN(stargazer, n=5)")
    assert r["results"][0][0] == {"id": 14, "count": 3}
    # multi-call batching shape from the docs
    r = post(base, "/index/repository/query",
             "Count(Row(stargazer=14)) Count(Row(stargazer=19))")
    assert r["results"] == [3, 1]


def test_readme_quickstart(base):
    """README.md quick-start block, verbatim semantics."""
    post(base, "/index/repo", "{}")
    post(base, "/index/repo/field/stars", "{}")
    assert post(base, "/index/repo/query",
                "Set(1, stars=14)")["results"] == [True]
    r = post(base, "/index/repo/query", "TopN(stars, n=5)")
    assert r["results"][0] == [{"id": 14, "count": 1}]


def test_query_language_reference_table(base):
    """Every call form from docs/query-language.md's tables executes and
    returns the documented shape."""
    post(base, "/index/ql", '{"options": {"trackExistence": true}}')
    post(base, "/index/ql/field/f", "{}")
    post(base, "/index/ql/field/g", "{}")
    post(base, "/index/ql/field/iv",
         '{"options": {"type": "int", "min": -100, "max": 1000}}')
    post(base, "/index/ql/field/t",
         '{"options": {"type": "time", "timeQuantum": "YMD"}}')

    # write calls
    assert post(base, "/index/ql/query",
                "Set(1, f=10) Set(2, f=10) Set(2, g=4)")["results"] == \
        [True, True, True]
    assert post(base, "/index/ql/query",
                "Set(1, t=3, 2018-01-15T00:00)")["results"] == [True]
    assert post(base, "/index/ql/query", "Set(1, iv=-3)")["results"] == \
        [True]
    post(base, "/index/ql/query", "Set(2, iv=500)")
    post(base, "/index/ql/query", 'SetRowAttrs(f, 10, color="red")')
    post(base, "/index/ql/query", 'SetColumnAttrs(7, city="spokane")')

    # read calls
    r = post(base, "/index/ql/query", "Row(f=10)")
    assert r["results"][0]["columns"] == [1, 2]
    r = post(base, "/index/ql/query",
             "Row(t=3, from='2018-01-01T00:00', to='2018-02-01T00:00')")
    assert r["results"][0]["columns"] == [1]
    r = post(base, "/index/ql/query", "Range(iv > 100)")
    assert r["results"][0]["columns"] == [2]
    r = post(base, "/index/ql/query", "Range(iv >< [-10, 0])")
    assert r["results"][0]["columns"] == [1]
    r = post(base, "/index/ql/query",
             "Intersect(Row(f=10), Row(g=4)) Union(Row(f=10), Row(g=4)) "
             "Difference(Row(f=10), Row(g=4)) Xor(Row(f=10), Row(g=4))")
    assert [x["columns"] for x in r["results"]] == \
        [[2], [1, 2], [1], [1]]
    r = post(base, "/index/ql/query", "Not(Row(g=4))")
    # existence {1,2} minus {2}; attrs-only columns don't join existence
    assert r["results"][0]["columns"] == [1]
    r = post(base, "/index/ql/query", "Shift(Row(g=4), n=1)")
    assert r["results"][0]["columns"] == [3]
    r = post(base, "/index/ql/query", "Count(Row(f=10))")
    assert r["results"] == [2]
    r = post(base, "/index/ql/query", "TopN(f, n=5)")
    assert r["results"][0] == [{"id": 10, "count": 2}]
    r = post(base, "/index/ql/query", "Rows(f)")
    assert r["results"][0]["rows"] == [10]
    r = post(base, "/index/ql/query", "GroupBy(Rows(f), Rows(g))")
    assert r["results"][0][0]["count"] == 1
    r = post(base, "/index/ql/query", 'Sum(field="iv") Min(field="iv") '
                                      'Max(field="iv")')
    assert r["results"][0] == {"value": 497, "count": 2}
    assert r["results"][1] == {"value": -3, "count": 1}
    assert r["results"][2] == {"value": 500, "count": 1}
    r = post(base, "/index/ql/query",
             "Options(Row(f=10), excludeColumns=true)")
    assert r["results"][0]["columns"] == []

    # remaining write calls
    post(base, "/index/ql/query", "Store(Row(f=10), g=20)")
    r = post(base, "/index/ql/query", "Row(g=20)")
    assert r["results"][0]["columns"] == [1, 2]
    post(base, "/index/ql/query", "Clear(1, f=10)")
    r = post(base, "/index/ql/query", "Row(f=10)")
    assert r["results"][0]["columns"] == [2]
    post(base, "/index/ql/query", "ClearRow(f=10)")
    r = post(base, "/index/ql/query", "Count(Row(f=10))")
    assert r["results"] == [0]
