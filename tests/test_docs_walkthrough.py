"""Docs fidelity: the getting-started walkthrough and query-language
examples must actually work against a live server, verbatim — users copy
these (the analog of the reference keeping docs/getting-started.md and
executor_test.go in behavioral sync)."""

import json
import urllib.request

import pytest



@pytest.fixture
def base(live_server):
    yield live_server[0]


def post(base, path, body):
    data = body if isinstance(body, bytes) else body.encode()
    r = urllib.request.Request(base + path, data=data, method="POST")
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read() or b"{}")


def test_getting_started_walkthrough(base):
    # Create the schema (docs/getting-started.md "Create the schema")
    post(base, "/index/repository", "{}")
    post(base, "/index/repository/field/stargazer",
         '{"options": {"type": "set"}}')
    # Write data
    assert post(base, "/index/repository/query",
                "Set(1, stargazer=14)")["results"] == [True]
    post(base, "/index/repository/query",
         "Set(1, stargazer=19) Set(2, stargazer=14) Set(3, stargazer=14)")
    # Query
    r = post(base, "/index/repository/query", "Row(stargazer=14)")
    assert r["results"][0]["columns"] == [1, 2, 3]
    r = post(base, "/index/repository/query",
             "Intersect(Row(stargazer=14), Row(stargazer=19))")
    assert r["results"][0]["columns"] == [1]
    r = post(base, "/index/repository/query",
             "Count(Intersect(Row(stargazer=14), Row(stargazer=19)))")
    assert r["results"] == [1]
    r = post(base, "/index/repository/query", "TopN(stargazer, n=5)")
    assert r["results"][0][0] == {"id": 14, "count": 3}
    # multi-call batching shape from the docs
    r = post(base, "/index/repository/query",
             "Count(Row(stargazer=14)) Count(Row(stargazer=19))")
    assert r["results"] == [3, 1]


def test_readme_quickstart(base):
    """README.md quick-start block, verbatim semantics."""
    post(base, "/index/repo", "{}")
    post(base, "/index/repo/field/stars", "{}")
    assert post(base, "/index/repo/query",
                "Set(1, stars=14)")["results"] == [True]
    r = post(base, "/index/repo/query", "TopN(stars, n=5)")
    assert r["results"][0] == [{"id": 14, "count": 1}]
