"""Fused bulk-import path: OP_ADD_ROARING records, the byte-based
snapshot fold policy, and the torn-tail tolerance bound.

Reference anchors: bulkImportStandard/importPositions
(/root/reference/fragment.go:1494-1604), MaxOpN snapshot trigger
(fragment.go:79,1769), op log format (roaring.go:3628-3691). The
OP_ADD_ROARING record (type 4) and the byte-based fold are documented
divergences — see storage/roaring.py and core/fragment.py docstrings.
"""

import os
import struct

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.storage import roaring as roaring_mod
from pilosa_tpu.storage.roaring import (
    Bitmap,
    encode_op_roaring,
)


def _bits(frag):
    return {(r, int(c)) for r in frag.row_ids()
            for c in frag.row_columns(r).tolist()}


def _mk(tmp_path, name="f"):
    f = Fragment(str(tmp_path / name), "i", "f", "standard", 0)
    f.open()
    return f


def test_import_batch_native_and_fallback_agree(tmp_path, monkeypatch):
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 50, 20_000, dtype=np.uint64)
    cols = rng.integers(0, 1 << 20, 20_000, dtype=np.uint64)

    f1 = _mk(tmp_path, "native")
    f1.bulk_import(rows, cols)

    monkeypatch.setattr(roaring_mod.native, "available", lambda: False)
    f2 = _mk(tmp_path, "fallback")
    f2.bulk_import(rows, cols)

    assert sorted(f1.storage.containers) == sorted(f2.storage.containers)
    for k in f1.storage.containers:
        assert (f1.storage.container_count(k)
                == f2.storage.container_count(k))
    assert f1.storage.op_n == f2.storage.op_n
    f1.close()
    f2.close()


def test_op_add_roaring_cross_reader(tmp_path, monkeypatch):
    """A file written with the native fused path replays identically
    through the pure-Python reader, and vice versa."""
    rng = np.random.default_rng(8)
    rows = rng.integers(0, 20, 5_000, dtype=np.uint64)
    cols = rng.integers(0, 1 << 20, 5_000, dtype=np.uint64)

    f = _mk(tmp_path)
    f.bulk_import(rows, cols)
    want = _bits(f)
    f.close()
    data = open(f.path, "rb").read()

    # Python-only read of the natively-written file.
    monkeypatch.setattr(roaring_mod.native, "available", lambda: False)
    pb = Bitmap.from_bytes(data)
    got = {(p // (1 << 20), p % (1 << 20)) for p in pb.slice().tolist()}
    assert got == want
    assert pb.op_n == f.storage.op_n

    # Python-only WRITE, then native read.
    f2 = _mk(tmp_path, "pyw")
    f2.bulk_import(rows, cols)
    assert _bits(f2) == want
    f2.close()
    monkeypatch.undo()
    if native.available():
        f3 = Fragment(f2.path, "i", "f", "standard", 0)
        f3.open()
        assert _bits(f3) == want
        f3.close()


def test_batch_does_not_snapshot_small_oplog(tmp_path):
    """Batches below the byte threshold append a record and do NOT
    rewrite the file (the reference would snapshot on every >MaxOpN-bit
    import, fragment.go:1769 — the amortized divergence under test)."""
    f = _mk(tmp_path)
    size0 = os.path.getsize(f.path)
    rows = np.zeros(20_000, np.uint64)
    cols = np.arange(20_000, dtype=np.uint64)
    f.bulk_import(rows, cols)
    f._file.flush()
    assert f.storage.op_n == 20_000
    assert f.storage.op_n_small == 0
    # File grew by ~the record, not a rewrite; snapshot section unchanged.
    assert f.storage.snapshot_bytes == size0
    assert os.path.getsize(f.path) - size0 == f.storage.oplog_bytes
    f.close()


def test_oplog_bytes_fold_triggers_snapshot(tmp_path, monkeypatch):
    monkeypatch.setattr(fragment_mod, "OPLOG_FOLD_MIN_BYTES", 1024)
    f = _mk(tmp_path)
    rows = np.zeros(5_000, np.uint64)
    cols = np.arange(5_000, dtype=np.uint64)
    f.bulk_import(rows, cols)  # record >> 1 KiB => fold
    assert f.storage.oplog_bytes == 0  # folded
    assert f.storage.op_n == 0
    assert f._last_snapshot_bytes == os.path.getsize(f.path)
    f.close()
    f2 = Fragment(f.path, "i", "f", "standard", 0)
    f2.open()
    assert f2.row_count(0) == 5_000
    f2.close()


def test_single_ops_still_fold_by_count(tmp_path):
    f = _mk(tmp_path)
    f.max_op_n = 10
    for i in range(12):
        f.set_bit(0, i)
    assert f.storage.op_n_small < 10  # folded at least once
    assert f.row_count(0) == 12
    f.close()


def test_op_add_roaring_torn_tail_recovered(tmp_path):
    f = _mk(tmp_path)
    rows = np.zeros(1_000, np.uint64)
    cols = np.arange(1_000, dtype=np.uint64)
    f.bulk_import(rows, cols)
    f.close()
    data = open(f.path, "rb").read()
    # Append a second record torn mid-payload.
    payload = Bitmap(np.arange(100, dtype=np.uint64)).write_bytes()
    rec = encode_op_roaring(payload)
    torn = rec[:len(rec) // 2]
    with open(f.path, "ab") as fh:
        fh.write(torn)
    f2 = Fragment(f.path, "i", "f", "standard", 0)
    f2.open()
    assert f2.row_count(0) == 1_000  # intact ops preserved
    assert f2.tail_dropped_bytes == len(torn)
    assert os.path.exists(f.path + ".torn")
    assert os.path.getsize(f.path) == len(data)  # truncated to clean
    f2.close()


def test_op_add_roaring_crc_mismatch_fails(tmp_path):
    f = _mk(tmp_path)
    f.bulk_import(np.zeros(500, np.uint64),
                  np.arange(500, dtype=np.uint64))
    f.close()
    data = bytearray(open(f.path, "rb").read())
    data[-3] ^= 0xFF  # corrupt inside the final record's payload
    err = (native.NativeParseError if native.available() else ValueError)
    with pytest.raises((err, ValueError)):
        Bitmap.from_bytes(bytes(data))


def test_torn_tail_bound_fails_hard(tmp_path, monkeypatch):
    """A dangling tail larger than any plausible record is mid-file
    corruption: refuse to open instead of silently sidecarring it
    (ADVICE r2 low #1)."""
    monkeypatch.setattr(fragment_mod, "MAX_TORN_TAIL_BYTES", 16)
    f = _mk(tmp_path)
    f.bulk_import(np.zeros(200, np.uint64),
                  np.arange(200, dtype=np.uint64))
    f.close()
    # A truncated record whose dangling bytes exceed the bound.
    payload = Bitmap(np.arange(500, dtype=np.uint64)).write_bytes()
    rec = encode_op_roaring(payload)
    with open(f.path, "ab") as fh:
        fh.write(rec[:-10])
    f2 = Fragment(f.path, "i", "f", "standard", 0)
    with pytest.raises(ValueError, match="torn"):
        f2.open()
    assert not os.path.exists(f.path + ".torn")  # nothing destroyed


def test_import_batch_merges_into_existing(tmp_path):
    f = _mk(tmp_path)
    f.bulk_import(np.zeros(10, np.uint64), np.arange(10, dtype=np.uint64))
    f.bulk_import(np.zeros(10, np.uint64),
                  np.arange(5, 15, dtype=np.uint64))
    assert f.row_count(0) == 15
    # Duplicate pairs within one batch are idempotent.
    f.bulk_import(np.zeros(4, np.uint64),
                  np.array([100, 100, 101, 101], np.uint64))
    assert f.row_count(0) == 17
    f.close()


def test_incremental_block_checksums_match_full(tmp_path):
    """The dirty-block checksum cache must equal a cold full pass after
    every mutation kind: set, clear, bulk import, bulk clear, set_row
    (VERDICT r2 weak #5 — reference re-hashes everything per sync,
    fragment.go:1259-1355)."""
    rng = np.random.default_rng(9)
    f = _mk(tmp_path)
    f.bulk_import(rng.integers(0, 300, 5_000, dtype=np.uint64),
                  rng.integers(0, 1 << 20, 5_000, dtype=np.uint64))
    first = f.checksum_blocks()  # cold full pass, warms the cache
    assert [b for b, _ in first] == sorted({b for b, _ in first})

    def assert_matches_cold():
        got = f.checksum_blocks()
        f.flush_cache()
        f._file.flush()
        cold = Fragment(f.path, "i", "f", "standard", 0)
        cold.open()
        want = cold.checksum_blocks()
        cold.close()
        assert got == want

    f.set_bit(5, 123)
    assert f._dirty_blocks == {0}
    assert_matches_cold()
    f.clear_bit(5, 123)
    assert_matches_cold()
    f.bulk_import(np.full(10, 250, np.uint64),
                  np.arange(10, dtype=np.uint64))
    assert_matches_cold()
    f.bulk_import(np.full(5, 250, np.uint64),
                  np.arange(5, dtype=np.uint64), clear=True)
    assert_matches_cold()
    f.set_row(42, np.zeros(1 << 14, dtype=np.uint64))
    assert_matches_cold()
    # Idle pass: nothing dirty, digests served from cache.
    assert f._dirty_blocks == set()
    assert f.checksum_blocks() == f.checksum_blocks()
    f.close()


def test_replace_with_bytes_dirties_removed_blocks(tmp_path):
    f = _mk(tmp_path)
    f.bulk_import(np.full(100, 250, np.uint64),
                  np.arange(100, dtype=np.uint64))
    f.checksum_blocks()
    # Replacement drops row 250 entirely and adds row 10.
    other = _mk(tmp_path, "other")
    other.bulk_import(np.full(3, 10, np.uint64),
                      np.arange(3, dtype=np.uint64))
    data = other.write_bytes()
    other.close()
    f.replace_with_bytes(data)
    got = dict(f.checksum_blocks())
    assert 2 not in got  # block of row 250 gone
    assert 0 in got
    f.close()


def test_ranked_cache_saturation_stops_write_path_cost(tmp_path):
    """Once cardinality exceeds the ranked-cache bound the cache latches
    saturated: write paths stop recounting rows for it (VERDICT r2 weak
    #7 — write-path overhead only where reads can benefit), the warm
    TopN read path refuses it, and the sidecar persists empty."""
    from pilosa_tpu.core import cache as cache_mod

    f = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0,
                 cache_size=10)
    f.open()
    rows = np.arange(50, dtype=np.uint64).repeat(4)
    cols = np.tile(np.arange(4, dtype=np.uint64), 50)
    f.bulk_import(rows, cols)  # 50 rows >> bound of 10
    assert f.cache.saturated
    # Further writes skip the recount entirely.
    calls = {"n": 0}
    orig = Fragment.row_count

    def counting(self, row_id):
        calls["n"] += 1
        return orig(self, row_id)

    Fragment.row_count = counting
    try:
        f.bulk_import(np.arange(50, dtype=np.uint64),
                      np.full(50, 9, np.uint64))
    finally:
        Fragment.row_count = orig
    assert calls["n"] == 0
    # Persisted empty: a reload must come up cold, not plausibly-stale.
    f.flush_cache()
    reloaded = cache_mod.RankedCache(10)
    assert cache_mod.load_cache(reloaded, f.cache_path(),
                                stamp=f._storage_stamp())
    assert len(reloaded) == 0
    # invalidate resets the latch.
    f.cache.invalidate()
    assert not f.cache.saturated
    f.close()


def test_saturated_cache_never_serves_topn(tmp_path):
    """Mass clears can shrink row count back under the cache size; the
    saturated flag must still block the warm-read path because the
    remaining counts are stale."""
    import jax

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    with jax.default_device(jax.devices("cpu")[0]):
        h = Holder(str(tmp_path / "h"))
        h.open()
        idx = h.create_index("sat")
        f = idx.create_field("f")
        frag = f.create_view_if_not_exists("standard") \
                .create_fragment_if_not_exists(0)
        frag.cache = __import__(
            "pilosa_tpu.core.cache", fromlist=["RankedCache"]
        ).RankedCache(4)
        rows = np.arange(20, dtype=np.uint64).repeat(3)
        cols = np.tile(np.arange(3, dtype=np.uint64), 20)
        f.import_bits(rows, cols)
        assert frag.cache.saturated
        # Clear most rows so len(counts) >= len(rows) could hold.
        f.import_bits(rows[rows >= 2], cols[rows >= 2], clear=True)
        ex = Executor(h)
        (res,) = ex.execute("sat", "TopN(f, n=5)")
        assert ex.topn_cache_hits == 0  # exact sweep, not stale cache
        assert res.pairs == [(0, 3), (1, 3)]
        h.close()


def test_topn_selfcheck_catches_stale_cache(tmp_path):
    """Injected staleness: corrupt a warm ranked cache directly (the
    stand-in for a write path that forgot to refresh counts). The
    sampled self-check (first warm hit is always sampled) must serve
    the EXACT result, bump the mismatch counter, and repair the cache
    so later warm hits are correct again (VERDICT r3 weak #5)."""
    import jax

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    with jax.default_device(jax.devices("cpu")[0]):
        h = Holder(str(tmp_path / "h"))
        h.open()
        idx = h.create_index("chk")
        f = idx.create_field("f")
        rows = np.repeat(np.arange(4, dtype=np.uint64), [5, 4, 3, 2])
        cols = np.concatenate([np.arange(n, dtype=np.uint64)
                               for n in (5, 4, 3, 2)])
        f.import_bits(rows, cols)
        frag = f.view().fragment(0)
        ex = Executor(h)

        # Inject staleness: row 3's cached count lies (says 9, real 2).
        frag.cache.counts[3] = 9
        (res,) = ex.execute("chk", "TopN(f, n=4)")
        assert ex.topn_cache_hits == 1 and ex.topn_selfchecks == 1
        assert ex.topn_selfcheck_mismatches == 1
        # The exact sweep's answer was served, not the lie.
        assert res.pairs == [(0, 5), (1, 4), (2, 3), (3, 2)]
        # The cache was repaired from storage.
        assert frag.cache.counts[3] == 2

        # Next warm hit (not sampled) now serves correct counts.
        (res2,) = ex.execute("chk", "TopN(f, n=4)")
        assert ex.topn_cache_hits == 2 and ex.topn_selfchecks == 1
        assert res2.pairs == res.pairs

        # A clean sampled hit records no mismatch.
        ex2 = Executor(h)
        (res3,) = ex2.execute("chk", "TopN(f, n=4)")
        assert ex2.topn_selfchecks == 1
        assert ex2.topn_selfcheck_mismatches == 0
        assert res3.pairs == res.pairs

        # EVERY=1 means EVERY warm hit is checked (the % EVERY == 1
        # literal would silently disable it at its most aggressive
        # setting — code-review r4).
        from pilosa_tpu.executor import executor as ex_mod
        old = ex_mod.TOPN_SELFCHECK_EVERY
        ex_mod.TOPN_SELFCHECK_EVERY = 1
        try:
            ex3 = Executor(h)
            ex3.execute("chk", "TopN(f, n=4)")
            ex3.execute("chk", "TopN(f, n=4)")
            assert ex3.topn_selfchecks == 2
        finally:
            ex_mod.TOPN_SELFCHECK_EVERY = old
        h.close()


def test_import_values_overwrite_and_dups(tmp_path):
    """BSI import: re-imported columns clear their old zero planes
    (fresh columns skip every remove pass), and duplicate columns in a
    batch resolve last-wins like the reference's sequential column
    loop (fragment.go:679)."""
    f = _mk(tmp_path)
    depth = 8
    cols = np.arange(10, dtype=np.uint64)
    vals = np.arange(10, dtype=np.uint64) + 100  # 100..109
    f.import_values(cols, vals, depth)
    for c in range(10):
        v, ok = f.value(c, depth)
        assert ok and v == 100 + c
    # Overwrite a subset with SMALLER values (old high bits must clear).
    f.import_values(np.array([2, 3], np.uint64),
                    np.array([1, 0], np.uint64), depth)
    assert f.value(2, depth) == (1, True)
    assert f.value(3, depth) == (0, True)
    assert f.value(4, depth) == (104, True)
    # Duplicates: last occurrence wins.
    f.import_values(np.array([5, 5, 5], np.uint64),
                    np.array([7, 9, 42], np.uint64), depth)
    assert f.value(5, depth) == (42, True)
    # clear drops the value entirely.
    f.import_values(np.array([5], np.uint64), np.array([0], np.uint64),
                    depth, clear=True)
    assert f.value(5, depth) == (0, False)
    f.close()
    # Reopen: everything durable through the fused records.
    f2 = Fragment(f.path, "i", "f", "standard", 0)
    f2.open()
    assert f2.value(2, depth) == (1, True)
    assert f2.value(4, depth) == (104, True)
    assert f2.value(5, depth) == (0, False)
    f2.close()


def test_compact_snapshot_load_parity(tmp_path, monkeypatch):
    """Deterministic compact-path check: a snapshot-only file holding
    ARRAY, BITMAP and RUN containers must parse identically through the
    native compact fast path and the pure-Python reader (bits, counts,
    accounting), and a one-op tail must route to the dense path with
    the same result."""
    if not native.available():
        pytest.skip("native codec not built")
    b = Bitmap()
    b.direct_add_n(np.array([5, 9, 100], np.uint64))           # array
    b.direct_add_n(np.arange(1 << 16, (1 << 16) + 60000,
                             dtype=np.uint64))                  # run
    b.direct_add_n(np.unique(np.random.default_rng(3).integers(
        2 << 16, 3 << 16, 30000, dtype=np.uint64)))             # bitmap
    data = b.write_bytes()
    # The writer actually chose all three encodings.
    types = {struct.unpack_from("<H", data, 8 + 12 * i + 8)[0]
             for i in range(3)}
    assert types == {1, 2, 3}

    def load_both(blob):
        got_n = Bitmap.from_bytes(blob)
        monkeypatch.setattr(roaring_mod.native, "available",
                            lambda: False)
        got_p = Bitmap.from_bytes(blob)
        monkeypatch.undo()
        return got_n, got_p

    gn, gp = load_both(data)
    assert np.array_equal(gn.slice(), gp.slice())
    assert np.array_equal(gn.slice(), b.slice())
    for k in gn.containers:
        assert gn.container_count(k) == gp.container_count(k)
    assert gn.snapshot_bytes == gp.snapshot_bytes == len(data)
    assert gn.op_n == 0 and gn.oplog_bytes == 0
    # With an op tail the dense path takes over; results still agree.
    from pilosa_tpu.storage.roaring import encode_op, OP_ADD
    tailed = data + encode_op(OP_ADD, value=7)
    gn2, gp2 = load_both(tailed)
    assert np.array_equal(gn2.slice(), gp2.slice())
    assert gn2.contains(7) and gn2.op_n == 1
    assert gn2.snapshot_bytes == len(data)


def test_truncation_fuzz_native_python_agree(tmp_path, monkeypatch):
    """Crash-recovery differential fuzz: for random truncation points of
    a file holding mixed op records (singles, legacy batches, type-4
    roaring payloads), the native and pure-Python readers must agree
    bit-for-bit on the recovered prefix state and its accounting."""
    if not native.available():
        pytest.skip("native codec not built")
    rng = np.random.default_rng(21)
    f = _mk(tmp_path)
    f.bulk_import(rng.integers(0, 30, 3_000, dtype=np.uint64),
                  rng.integers(0, 1 << 20, 3_000, dtype=np.uint64))
    for i in range(40):
        f.set_bit(int(rng.integers(0, 30)), int(rng.integers(0, 1 << 20)))
    f.storage.add_batch(
        rng.integers(0, 30 << 20, 500, dtype=np.uint64))  # legacy type 2
    f.bulk_import(rng.integers(0, 30, 2_000, dtype=np.uint64),
                  rng.integers(0, 1 << 20, 2_000, dtype=np.uint64))
    f.close()
    data = open(f.path, "rb").read()
    snap = Bitmap.from_bytes(data).snapshot_bytes
    points = sorted(set(
        int(p) for p in rng.integers(snap, len(data), 12)) | {len(data)})
    for cut in points:
        sliced = data[:cut]
        got_native = Bitmap.from_bytes(sliced, tolerate_torn_tail=True)
        monkeypatch.setattr(roaring_mod.native, "available",
                            lambda: False)
        got_py = Bitmap.from_bytes(sliced, tolerate_torn_tail=True)
        monkeypatch.undo()
        assert np.array_equal(got_native.slice(), got_py.slice()), cut
        assert got_native.op_n == got_py.op_n, cut
        assert got_native.op_n_small == got_py.op_n_small, cut
        assert got_native.oplog_bytes == got_py.oplog_bytes, cut
        assert got_native.tail_dropped == got_py.tail_dropped, cut
        # Recovered state is exactly the valid-record prefix: applied
        # bytes + dangling bytes must tile the op region.
        assert (snap + got_native.oplog_bytes + got_native.tail_dropped
                == cut), cut


def test_import_batch_wide_row_range_falls_back(tmp_path):
    """A batch spanning a huge sparse row range is unsuited to dense
    scatter; the grouped path must still import it correctly."""
    f = _mk(tmp_path)
    rows = np.array([0, 1 << 30, (1 << 30) + 5], dtype=np.uint64)
    cols = np.array([3, 4, 5], dtype=np.uint64)
    f.bulk_import(rows, cols)
    assert f.bit(0, 3)
    assert f.bit((1 << 30) + 5, 5)
    f.close()
    f2 = Fragment(f.path, "i", "f", "standard", 0)
    f2.open()
    assert f2.bit(1 << 30, 4)
    f2.close()
