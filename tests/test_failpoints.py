"""Fault-injection plane tests (pilosa_tpu/utils/failpoints.py): spec
parsing, mode semantics, count exhaustion, the registry contract, the
test-only HTTP surface gate, and the real client seams — including the
pin that a fully DISARMED registry changes nothing."""

import json
import urllib.request

import pytest

# Imported for their side effect: seam modules register their failpoint
# sites at import (client.*, heartbeat.probe, resize.pull) — a bare
# single-node API would otherwise never load them.
import pilosa_tpu.parallel.client  # noqa: F401
import pilosa_tpu.parallel.heartbeat  # noqa: F401
import pilosa_tpu.parallel.syncer  # noqa: F401
from pilosa_tpu.utils.failpoints import (
    FAILPOINTS, FailpointDrop, FailpointError, FailpointRegistry,
    parse_spec,
)


# ----------------------------------------------------------- spec parse


def test_parse_spec_forms():
    s = parse_spec("error")
    assert (s.mode, s.arg, s.remaining) == ("error", "", -1)
    s = parse_spec("errorx3")
    assert (s.mode, s.remaining) == ("error", 3)
    s = parse_spec("delay(0.25)")
    assert (s.mode, s.arg) == ("delay", "0.25")
    s = parse_spec("partition(:10102)x2")
    assert (s.mode, s.arg, s.remaining) == ("partition", ":10102", 2)
    s = parse_spec("drop")
    assert s.mode == "drop"


@pytest.mark.parametrize("bad", [
    "explode", "", "error(x", "delay", "delay(abc)", "partition",
    "partition()", "errorx", "error x2",
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


# ------------------------------------------------------------- registry


def test_register_duplicate_raises():
    reg = FailpointRegistry()
    reg.register("a.site")
    with pytest.raises(ValueError, match="registered twice"):
        reg.register("a.site")


def test_arm_unknown_site_raises():
    reg = FailpointRegistry()
    with pytest.raises(KeyError, match="unknown failpoint"):
        reg.arm("nope", "error")
    with pytest.raises(KeyError):
        reg.disarm("nope")


def test_disarmed_fire_is_noop():
    reg = FailpointRegistry()
    site = reg.register("quiet")
    site.fire(uri="anything")  # no raise, no state
    assert site.hits == 0
    assert reg.snapshot() == {
        "sites": {"quiet": {"armed": None, "hits": 0}},
        "armed": 0, "fired": 0}


def test_error_drop_delay_partition_modes():
    import time
    reg = FailpointRegistry()
    err = reg.register("e")
    drp = reg.register("d")
    dly = reg.register("s")
    par = reg.register("p")
    reg.configure({"e": "error", "d": "drop", "s": "delay(0.01)",
                   "p": "partition(:9999)"}, env="")
    with pytest.raises(FailpointError):
        err.fire()
    with pytest.raises(FailpointDrop):
        drp.fire()
    t0 = time.perf_counter()
    dly.fire()  # sleeps, continues
    assert time.perf_counter() - t0 >= 0.01
    par.fire(uri="http://h:1234/x")  # no match: silent
    with pytest.raises(FailpointError):
        par.fire(uri="http://h:9999/x")
    # FailpointError is ConnectionError-shaped so client seams treat it
    # exactly like a real transport failure.
    assert issubclass(FailpointError, ConnectionError)
    snap = reg.snapshot()
    assert snap["fired"] == 4  # the unmatched partition fire is free
    assert snap["sites"]["p"]["hits"] == 1


def test_count_exhaustion_self_disarms():
    reg = FailpointRegistry()
    site = reg.register("limited")
    reg.arm("limited", "errorx2")
    for _ in range(2):
        with pytest.raises(FailpointError):
            site.fire()
    site.fire()  # exhausted: disarmed
    assert site.spec is None
    assert reg.snapshot()["sites"]["limited"] == {"armed": None,
                                                  "hits": 2}


def test_configure_env_string_and_unknown_name():
    reg = FailpointRegistry()
    a = reg.register("a")
    reg.register("b")
    reg.configure({"a": "delay(0)"}, env="b=errorx1; a=error")
    # env wins over the mapping for the same site
    assert a.spec is not None and a.spec.mode == "error"
    with pytest.raises(KeyError):
        reg.configure({"typo.site": "error"}, env="")


# ------------------------------------------------------- http surface


def _api(tmp_path):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server.api import API
    holder = Holder(str(tmp_path / "fp"))
    holder.open()
    return API(holder), holder


def test_http_surface_gated(tmp_path):
    from pilosa_tpu.server.api import ApiError
    api, holder = _api(tmp_path)
    was = FAILPOINTS.http_enabled
    try:
        FAILPOINTS.http_enabled = False
        with pytest.raises(ApiError) as ei:
            api.failpoints_snapshot()
        assert ei.value.status == 403
        with pytest.raises(ApiError):
            api.failpoints_update({"arm": {"api.query": "error"}})
        FAILPOINTS.http_enabled = True
        snap = api.failpoints_snapshot()
        assert "client.connect" in snap["sites"]
        out = api.failpoints_update(
            {"arm": {"api.status": "delay(0)"}})
        assert out["sites"]["api.status"]["armed"] == "delay(0)"
        out = api.failpoints_update({"disarm_all": True})
        assert out["armed"] == 0
        with pytest.raises(ApiError) as ei:
            api.failpoints_update({"arm": {"nope": "error"}})
        assert ei.value.status == 400
    finally:
        FAILPOINTS.disarm_all()
        FAILPOINTS.http_enabled = was
        holder.close()


def test_http_route_serves_and_gates(tmp_path):
    from pilosa_tpu.server import serve
    api, holder = _api(tmp_path)
    server = serve(api, "localhost", 0, background=True)
    port = server.server_address[1]
    was = FAILPOINTS.http_enabled
    try:
        FAILPOINTS.http_enabled = True
        body = json.dumps(
            {"arm": {"heartbeat.probe": "dropx1"}}).encode()
        r = urllib.request.Request(
            f"http://localhost:{port}/internal/failpoints",
            data=body, method="POST")
        with urllib.request.urlopen(r, timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["sites"]["heartbeat.probe"]["armed"] == "dropx1"
        with urllib.request.urlopen(
                f"http://localhost:{port}/internal/failpoints",
                timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["armed"] == 1
        FAILPOINTS.disarm_all()
        FAILPOINTS.http_enabled = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://localhost:{port}/internal/failpoints",
                timeout=10)
        assert ei.value.code == 403
    finally:
        FAILPOINTS.disarm_all()
        FAILPOINTS.http_enabled = was
        server.shutdown()
        server.server_close()
        holder.close()


# ------------------------------------------------------- client seams


def test_client_seams_inject_expected_shapes(tmp_path):
    """The four InternalClient._req sites produce exactly the failure
    classes the catalog documents: 5xx -> ClientError(status=500),
    connect -> transport ClientError, torn body -> a NON-ClientError
    parse failure (the silent-undercount class). Disarmed afterwards,
    the same calls answer normally — the zero-overhead pin."""
    from pilosa_tpu.parallel.client import ClientError, InternalClient
    from pilosa_tpu.server import serve
    api, holder = _api(tmp_path)
    server = serve(api, "localhost", 0, background=True)
    uri = f"http://localhost:{server.server_address[1]}"
    client = InternalClient(timeout=10)
    try:
        baseline = client.status(uri)

        FAILPOINTS.arm("client.5xx", "errorx1")
        with pytest.raises(ClientError) as ei:
            client.status(uri)
        assert ei.value.status == 500

        FAILPOINTS.arm("client.connect", "errorx1")
        with pytest.raises(ClientError) as ei:
            client.status(uri)
        assert ei.value.status is None  # transport, not HTTP

        FAILPOINTS.arm("client.torn_body", "errorx1")
        with pytest.raises(Exception) as ei:
            client.schema(uri)
        assert not isinstance(ei.value, ClientError), ei.value

        # drop mode on the torn site: the whole body is lost — the
        # codec layer refuses it (non-ClientError), same class as torn
        FAILPOINTS.arm("client.torn_body", "dropx1")
        with pytest.raises(Exception) as ei:
            client.status(uri)
        assert not isinstance(ei.value, ClientError), ei.value

        # partition scoped by URI substring: other targets unaffected
        FAILPOINTS.arm("client.connect", "partition(:1)x1")
        assert client.status(uri) == baseline  # no match, no fire

        FAILPOINTS.disarm_all()
        assert client.status(uri) == baseline  # disarmed = identical
        snap = FAILPOINTS.snapshot()
        assert snap["armed"] == 0 and snap["fired"] >= 4
    finally:
        FAILPOINTS.disarm_all()
        server.shutdown()
        server.server_close()
        holder.close()


def test_heartbeat_probe_site_drop_and_error(tmp_path):
    """heartbeat.probe drop = probe lost (no verdict); error = failed
    probe driving mark_down after suspect_after rounds."""
    from pilosa_tpu.parallel.cluster import Cluster, Node, STATE_NORMAL
    from pilosa_tpu.parallel.heartbeat import Heartbeater
    c = Cluster(Node("n0", "http://127.0.0.1:1"), replica_n=1)
    c.add_node(Node("n1", "http://127.0.0.1:9"))  # unreachable anyway
    c.set_state(STATE_NORMAL)
    hb = Heartbeater(c, interval=0, suspect_after=2, timeout=0.2)
    try:
        FAILPOINTS.arm("heartbeat.probe", "drop")
        hb.probe_once()
        hb.probe_once()
        assert not c.down_ids  # lost probes carry no verdict
        FAILPOINTS.arm("heartbeat.probe", "error")
        hb.probe_once()
        assert not c.down_ids  # one failure: suspect, not down
        hb.probe_once()
        assert "n1" in c.down_ids  # second consecutive: down
        ev = [e["type"] for e in c.recent_events()]
        assert "node-down" in ev
    finally:
        FAILPOINTS.disarm_all()
