"""Diagnostics (utils/diagnostics.py) and slow-query logging tests."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from pilosa_tpu.utils.diagnostics import DiagnosticsCollector, RuntimeMonitor
from pilosa_tpu.utils.stats import MemStatsClient


def test_disabled_by_default():
    d = DiagnosticsCollector()
    assert not d.enabled()
    assert d.flush() is False  # no URL → never POSTs


def test_payload_shape(tmp_path):
    from pilosa_tpu.core.holder import Holder
    holder = Holder(str(tmp_path))
    holder.open()
    idx = holder.create_index("d1")
    idx.create_field("f1")
    idx.create_field("f2")
    d = DiagnosticsCollector(holder=holder)
    d.set("ClusterID", "abc")
    p = d.payload()
    assert p["NumIndexes"] == 1 and p["NumFields"] >= 2
    assert p["Version"] and p["OS"] and p["ClusterID"] == "abc"
    holder.close()


def test_flush_posts_json():
    received = []

    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        d = DiagnosticsCollector(
            url=f"http://127.0.0.1:{srv.server_port}/diagnostics")
        assert d.flush() is True
        assert received and received[0]["Version"]
    finally:
        srv.shutdown()


def test_flush_survives_unreachable_endpoint():
    d = DiagnosticsCollector(url="http://127.0.0.1:1/nope")
    assert d.flush() is False  # no raise


@pytest.mark.parametrize("latest,expect_update", [
    ("v9.9.9", True),
    ("0.0.1", False),
    ("garbage", False),
])
def test_check_version(latest, expect_update):
    d = DiagnosticsCollector()
    msg = d.check_version(latest)
    assert (msg is not None) == expect_update
    assert d.server_version == latest


def test_runtime_monitor_samples_gauges():
    stats = MemStatsClient()
    mon = RuntimeMonitor(stats, interval=1000)
    mon.sample()
    snap = stats.snapshot()
    assert snap["gauges"]["threads"] >= 1
    assert snap["gauges"].get("heapInuse", 0) > 0  # /proc available on linux


def test_slow_query_logged(tmp_path):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server.api import API

    logged = []

    class FakeLogger:
        def printf(self, fmt, *args):
            logged.append(fmt % args)

        def debugf(self, fmt, *args):
            pass

    holder = Holder(str(tmp_path))
    holder.open()
    holder.create_index("q").create_field("f")
    api = API(holder)
    api.logger = FakeLogger()
    api.long_query_time = 0.0000001  # everything is slow
    api.query("q", "Set(1, f=2)")
    assert any("SLOW QUERY" in line for line in logged)
    logged.clear()
    api.long_query_time = 0.0  # disabled
    api.query("q", "Count(Row(f=2))")
    assert not any("SLOW QUERY" in line for line in logged)
    holder.close()


def test_statsd_client_wire_format():
    """DataDog-flavored statsd datagrams over UDP (reference
    statsd/statsd.go:41: prefix 'pilosa.', |c/|g/|ms types, #tags)."""
    import socket

    from pilosa_tpu.utils.stats import StatsdStatsClient

    srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv.bind(("localhost", 0))
    srv.settimeout(5)
    port = srv.getsockname()[1]

    c = StatsdStatsClient(f"localhost:{port}")
    tagged = c.with_tags("index:i", "field:f")
    tagged.count("query", 3)
    c.gauge("goroutines", 12.5)
    c.timing("exec", 0.25)  # seconds -> 250 ms
    c.flush()
    tagged.flush()

    data = b""
    while b"exec" not in data or b"query" not in data:
        data += srv.recv(65536) + b"\n"
    lines = data.decode().split("\n")
    assert any(l == "pilosa.query:3|c|#field:f,index:i" for l in lines), lines
    assert any(l == "pilosa.goroutines:12.5|g" for l in lines), lines
    assert any(l == "pilosa.exec:250|ms" for l in lines), lines
    srv.close()


def test_statsd_send_failure_never_raises():
    from pilosa_tpu.utils.stats import StatsdStatsClient

    c = StatsdStatsClient("localhost:1")  # nothing listening; UDP is
    for _ in range(64):                   # fire-and-forget either way
        c.count("x")
    c.flush()
