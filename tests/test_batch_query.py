"""Multi-query batching: Executor.execute_batch, API.query_batch, and
the /batch/query HTTP route. The cross-request extension of the
reference's multi-call pipelining (executor.go:84): N queries, one
dispatch phase, one overlapped device->host drain."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops.bitset import SHARD_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    f.import_bits(np.array([1, 1, 1, 2, 2], np.uint64),
                  np.array([1, 2, 3, 2, 3], np.uint64))
    idx2 = h.create_index("j")
    g = idx2.create_field("g")
    g.import_bits(np.array([5, 5], np.uint64),
                  np.array([9, SHARD_WIDTH + 4], np.uint64))
    yield h
    h.close()


def unwrap(res):
    assert not isinstance(res, Exception), res
    return res[0]


def test_batch_matches_serial(holder):
    ex = Executor(holder)
    reqs = [("i", "Count(Row(f=1))", None),
            ("j", "Count(Row(g=5))", None),
            ("i", "TopN(f, n=2)", None),
            ("i", "Row(f=2)", None)]
    serial = [ex.execute(i, q, shards=s) for i, q, s in reqs]
    batched = ex.execute_batch(reqs)
    for s, b in zip(serial, batched):
        got = unwrap(b)
        if hasattr(s[0], "pairs"):
            assert got[0].pairs == s[0].pairs
        elif hasattr(s[0], "columns"):
            assert got[0].columns().tolist() == s[0].columns().tolist()
        else:
            assert got == s


def test_batch_error_isolation(holder):
    ex = Executor(holder)
    out = ex.execute_batch([
        ("i", "Count(Row(f=1))", None),
        ("nosuch", "Count(Row(f=1))", None),
        ("i", "Bogus((", None),
        ("i", "Count(Row(f=2))", None)])
    assert unwrap(out[0]) == [3]
    assert isinstance(out[1], Exception)
    assert isinstance(out[2], Exception)
    assert unwrap(out[3]) == [2]


def test_batch_write_then_read_ordering(holder):
    """A write in request k is visible to request k+1 and NOT to
    request k-1's already-dispatched read (sequential semantics across
    the batch, like calls within one query)."""
    ex = Executor(holder)
    out = ex.execute_batch([
        ("i", "Count(Row(f=1))", None),          # pre-write count: 3
        ("i", "Set(77, f=1)", None),
        ("i", "Count(Row(f=1))", None)])         # post-write: 4
    assert unwrap(out[0]) == [3]
    assert unwrap(out[1]) == [True]
    assert unwrap(out[2]) == [4]


def test_batch_write_isolation_under_chunked_topn(holder, monkeypatch):
    """TopN's chunked path defers bank uploads to finalize; a write in
    a LATER BATCH REQUEST must not leak into it (the same guard that
    protects later calls within one query — _tls.later_writes)."""
    from pilosa_tpu.executor import executor as executor_mod
    monkeypatch.setattr(executor_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(executor_mod, "TOPN_CHUNK_ROWS", 1)
    ex = Executor(holder)
    out = ex.execute_batch([
        ("i", "TopN(f, n=4)", None),
        ("i", "Set(100, f=1) Set(101, f=1) Set(102, f=1)", None)])
    pairs = unwrap(out[0])[0].pairs
    assert pairs == [(1, 3), (2, 2)]  # pre-write counts
    (count,) = ex.execute("i", "Count(Row(f=1))")
    assert count == 6  # writes landed after


def test_batch_write_scan_sees_bare_call_writes(holder, monkeypatch):
    """The write pre-scan must recognize a write passed as a bare Call
    (not a string/Query) so earlier chunked reads still snapshot."""
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.pql.ast import Call
    monkeypatch.setattr(executor_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(executor_mod, "TOPN_CHUNK_ROWS", 1)
    ex = Executor(holder)
    out = ex.execute_batch([
        ("i", "TopN(f, n=4)", None),
        ("i", Call("Set", {"_col": 200, "f": 1}), None)])
    assert unwrap(out[0])[0].pairs == [(1, 3), (2, 2)]
    assert unwrap(out[1]) == [True]


def test_query_batch_api(tmp_path):
    from pilosa_tpu.server import API
    h = Holder(str(tmp_path))
    h.open()
    api = API(h)
    api.create_index("b1")
    api.create_field("b1", "f")
    api.query("b1", "Set(1, f=2) Set(3, f=2)")
    out = api.query_batch([
        {"index": "b1", "query": "Count(Row(f=2))"},
        {"index": "b1", "query": "Row(f=2)"},
        {"index": "zzz", "query": "Count(Row(f=2))"},
        {"index": "b1"},  # malformed: degrades per-item, not the batch
    ])
    assert out[0] == {"results": [2]}
    assert out[1]["results"][0]["columns"] == [1, 3]
    assert "error" in out[2]
    assert "error" in out[3]
    h.close()


def test_http_batch_route(live_server):
    base, api, _h = live_server
    api.create_index("hb")
    api.create_field("hb", "f")

    def post(path, body):
        r = urllib.request.Request(
            base + path, data=json.dumps(body).encode(), method="POST")
        with urllib.request.urlopen(r) as resp:
            return resp.status, json.loads(resp.read() or b"{}")

    api.query("hb", "Set(4, f=9)")
    st, res = post("/batch/query", {"queries": [
        {"index": "hb", "query": "Count(Row(f=9))"},
        {"index": "hb", "query": "Row(f=9)"},
        {"index": "hb", "query": "Nope(("},
    ]})
    assert st == 200
    assert res["responses"][0] == {"results": [1]}
    assert res["responses"][1]["results"][0]["columns"] == [4]
    assert "error" in res["responses"][2]
    # malformed body
    r = urllib.request.Request(base + "/batch/query",
                               data=b'{"queries": "nope"}', method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r)
    assert ei.value.code == 400
    # over the documented cap (1024): rejected whole, nothing executes
    big = {"queries": [{"index": "hb", "query": "Count(Row(f=9))"}] * 1025}
    r = urllib.request.Request(base + "/batch/query",
                               data=json.dumps(big).encode(),
                               method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r)
    assert ei.value.code == 400
    # exactly at the cap passes validation
    st, res = post("/batch/query", {"queries": [
        {"index": "hb", "query": "Count(Row(f=9))"}] * 1024})
    assert st == 200 and len(res["responses"]) == 1024
    assert all(r == {"results": [1]} for r in res["responses"])
