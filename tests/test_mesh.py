"""Mesh placement + mesh plan typing (parallel/mesh.py +
ops/megakernel mesh rules): ShardPlacement's pad/device_of math,
MeshContext's sharding specs and jit-cache key across replica shapes,
and the verify_plan mesh rules — shard-axis agreement, the
replica-axis no-op proof and per-lane collective typing — each
rejection branch pinned against a LIVE plan captured from the
lowering, so the rules are proven on the IR the executor actually
ships."""

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor import megakernel as megamod
from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.parallel import MeshContext
from pilosa_tpu.parallel.mesh import ShardPlacement


# ----------------------------------------------------------- placement


def test_pad_rounds_up_to_device_multiple():
    p = ShardPlacement(4)
    assert p.pad([0, 1, 2, 3]) == [0, 1, 2, 3]
    padded = p.pad([0, 1, 2, 3, 4, 5])
    assert len(padded) == 8
    assert padded[:6] == [0, 1, 2, 3, 4, 5]


def test_pad_ids_are_provably_absent():
    p = ShardPlacement(4)
    # Pad ids must sit above BOTH the requested shards and the floor
    # (every existing shard of the index) — otherwise padding aliases
    # a real shard the caller excluded and its bits leak into the
    # reduction.
    padded = p.pad([0, 2], floor=9)
    assert padded[:2] == [0, 2]
    assert all(s >= 9 for s in padded[2:])
    assert len(set(padded)) == len(padded)
    # Without a floor the pads clear the requested max.
    padded = p.pad([7, 3])
    assert all(s >= 8 for s in padded[2:])


def test_pad_empty_shard_list():
    assert ShardPlacement(2).pad([]) == [0, 1]


def test_device_of_block_assignment():
    p = ShardPlacement(4)
    shards = [10, 11, 12, 13, 14, 15, 16, 17]
    for pos, s in enumerate(shards):
        assert p.device_of(shards, s) == pos % 4


# -------------------------------------------------------- mesh context


@pytest.fixture
def mesh4():
    import jax
    assert len(jax.devices()) >= 4
    return MeshContext(jax.devices()[:4])


def test_mesh_axes_and_shardings(mesh4):
    from jax.sharding import PartitionSpec as P
    assert mesh4.n_shard_devices == 4
    assert mesh4.replicas == 1
    assert mesh4.mesh.axis_names == (MeshContext.SHARD_AXIS,)
    assert mesh4.bank_sharding().spec == P(None, "shards", None)
    assert mesh4.row_sharding().spec == P("shards", None)
    assert mesh4.replicated().spec == P()


def test_replica_axis_leads_and_banks_stay_replicated_over_it():
    import jax
    from jax.sharding import PartitionSpec as P
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    m = MeshContext(jax.devices()[:8], replicas=2)
    assert m.mesh.axis_names == (MeshContext.REPLICA_AXIS,
                                 MeshContext.SHARD_AXIS)
    assert m.replicas == 2
    assert m.n_shard_devices == 4
    # The bank spec names ONLY the shard axis: PartitionSpec None on
    # the replica axis is what replicates banks across replicas — the
    # structural half of the replica-axis no-op proof.
    assert m.bank_sharding().spec == P(None, "shards", None)
    assert MeshContext.REPLICA_AXIS not in (
        m.bank_sharding().spec + m.row_sharding().spec)


def test_replicas_must_divide_devices():
    import jax
    with pytest.raises(ValueError, match="not divisible"):
        MeshContext(jax.devices()[:4], replicas=3)


def test_cache_key_stable_and_shape_sensitive(mesh4):
    import jax
    devs = jax.devices()
    assert mesh4.cache_key() == MeshContext(devs[:4]).cache_key()
    assert mesh4.cache_key() != MeshContext(devs[:2]).cache_key()
    if len(devs) >= 8:
        # Same 8 devices, different replica shape -> different
        # partitioned program -> different key.
        assert (MeshContext(devs[:8]).cache_key()
                != MeshContext(devs[:8], replicas=2).cache_key())


def test_put_bank_splits_shard_axis(mesh4):
    bank = np.zeros((3, 4, 8), dtype=np.uint32)
    dev = mesh4.put_bank(bank)
    assert dev.sharding == mesh4.bank_sharding()
    # Each device holds one shard column, rows/words unsplit.
    shard_shape = dev.sharding.shard_shape(dev.shape)
    assert shard_shape == (3, 1, 8)


# ------------------------------------------- mesh plan rules (live IR)


@pytest.fixture
def live_plan(tmp_path, monkeypatch):
    """One (plan, n_shards, w_mega) captured from the shipped lowering
    on a mixed batch — count lanes and row lanes both present."""
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 8, 3000).astype(np.uint64)
    cols = rng.integers(0, 4 * SHARD_WIDTH, 3000).astype(np.uint64)
    f.import_bits(rows, cols)
    idx.add_existence(cols)
    executor = Executor(h)
    executor.result_cache.enabled = False
    prev = megamod.MEGAKERNEL_ENABLED
    megamod.MEGAKERNEL_ENABLED = True

    captured = []
    orig = megamod._build

    def wrapped(cohort):
        plan, w_mega, lanes = orig(cohort)
        captured.append((plan, cohort[0].entries[0].n_shards, w_mega))
        return plan, w_mega, lanes

    monkeypatch.setattr(megamod, "_build", wrapped)
    executor.execute_batch_shaped(
        [("i", "Count(Row(f=1))", None), ("i", "Row(f=2)", None),
         ("i", "Count(Row(f=3))", None)])
    megamod.MEGAKERNEL_ENABLED = prev
    h.close()
    assert captured, "batch did not reach the megakernel lowering"
    return captured[0]


def _spec(plan, n_devices=2, **kw):
    epi = kw.pop("epilogue", mk.mesh_epilogue(plan))
    return mk.MeshSpec("shards", "replica", n_devices,
                       kw.pop("replicas", 1), epi)


def test_canonical_mesh_plan_verifies(live_plan):
    plan, n_shards, w_mega = live_plan
    spec = _spec(plan, n_devices=2)
    mk.verify_plan(plan, n_shards, w_mega, mesh=spec)
    # The epilogue types every REAL lane, pad lanes excluded.
    assert len(spec.epilogue.count_ops) == len(plan.lane_count_widths)
    assert len(spec.epilogue.row_ops) == len(plan.lane_row_widths)


def test_mesh_rejects_uneven_shard_split(live_plan):
    plan, n_shards, w_mega = live_plan
    with pytest.raises(mk.PlanVerifyError, match="split evenly"):
        mk.verify_plan(plan, n_shards, w_mega,
                       mesh=_spec(plan, n_devices=3))


def test_mesh_rejects_missing_epilogue(live_plan):
    plan, n_shards, w_mega = live_plan
    with pytest.raises(mk.PlanVerifyError, match="no collective"):
        mk.verify_plan(plan, n_shards, w_mega,
                       mesh=_spec(plan, epilogue=None))


def test_mesh_rejects_replica_axis_reduction(live_plan):
    # The replica-axis no-op proof: an epilogue that reduces over the
    # replica axis would count replicated banks replicas-x.
    plan, n_shards, w_mega = live_plan
    epi = mk.mesh_epilogue(plan)
    bad = mk.Epilogue(("shards", "replica"), epi.count_ops, epi.row_ops)
    with pytest.raises(mk.PlanVerifyError, match="axes"):
        mk.verify_plan(plan, n_shards, w_mega,
                       mesh=_spec(plan, epilogue=bad))


def test_mesh_rejects_axis_name_collision(live_plan):
    plan, n_shards, w_mega = live_plan
    spec = mk.MeshSpec("shards", "shards", 2, 1, mk.mesh_epilogue(plan))
    with pytest.raises(mk.PlanVerifyError, match="distinct"):
        mk.verify_plan(plan, n_shards, w_mega, mesh=spec)


def test_mesh_rejects_mistyped_lanes(live_plan):
    plan, n_shards, w_mega = live_plan
    epi = mk.mesh_epilogue(plan)
    if len(epi.count_ops):
        bad = mk.Epilogue(epi.axes,
                          [mk.EPI_NONE] * len(epi.count_ops),
                          epi.row_ops)
        with pytest.raises(mk.PlanVerifyError, match="psum"):
            mk.verify_plan(plan, n_shards, w_mega,
                           mesh=_spec(plan, epilogue=bad))
    if len(epi.row_ops):
        bad = mk.Epilogue(epi.axes, epi.count_ops,
                          [mk.EPI_PSUM] * len(epi.row_ops))
        with pytest.raises(mk.PlanVerifyError, match="all_gather"):
            mk.verify_plan(plan, n_shards, w_mega,
                           mesh=_spec(plan, epilogue=bad))


def test_mesh_rejects_lane_count_mismatch(live_plan):
    plan, n_shards, w_mega = live_plan
    epi = mk.mesh_epilogue(plan)
    bad = mk.Epilogue(epi.axes,
                      list(epi.count_ops) + [mk.EPI_PSUM], epi.row_ops)
    with pytest.raises(mk.PlanVerifyError, match="lanes"):
        mk.verify_plan(plan, n_shards, w_mega,
                       mesh=_spec(plan, epilogue=bad))


def test_plan_cost_mesh_attribution(live_plan):
    plan, n_shards, w_mega = live_plan
    base = mk.plan_cost(plan, n_shards, w_mega)
    spec = _spec(plan, n_devices=2)
    cost = mk.plan_cost(plan, n_shards, w_mega, mesh=spec)
    assert cost["meshDevices"] == 2
    # Per-device traffic: the same total HBM bytes split across chips
    # (ceil division — the roofline models the slowest device).
    assert cost["deviceBytes"] == -(-base["totalBytes"] // 2)
    nc = len(plan.lane_count_widths)
    nr = len(plan.lane_row_widths)
    assert cost["psumBytes"] == 2 * (2 - 1) * nc * 4
    assert cost["collectiveBytes"] == (cost["psumBytes"]
                                       + cost["allGatherBytes"])
    if nr:
        assert cost["allGatherBytes"] > 0
    # One device -> no wire traffic.
    assert mk.plan_cost(plan, n_shards, w_mega, mesh=_spec(
        plan, n_devices=1))["collectiveBytes"] == 0
