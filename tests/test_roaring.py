"""Host roaring bitmap + Pilosa file format codec tests."""

import struct

import numpy as np
import pytest

from pilosa_tpu.storage import roaring as rr


def rand_positions(rng, n, hi=2**30):
    return np.unique(rng.integers(0, hi, size=n, dtype=np.uint64))


def test_point_ops():
    b = rr.Bitmap()
    assert not b.contains(5)
    assert b.add(5)
    assert not b.add(5)
    assert b.contains(5)
    assert b.count() == 1
    assert b.add(2**40)
    assert b.count() == 2
    assert b.max() == 2**40
    assert b.min() == 5
    assert b.remove(5)
    assert not b.remove(5)
    assert b.count() == 1


def test_bulk_add_remove(rng):
    pos = rand_positions(rng, 10000)
    b = rr.Bitmap()
    assert b.direct_add_n(pos) == len(pos)
    assert b.count() == len(pos)
    np.testing.assert_array_equal(b.slice(), pos)
    half = pos[: len(pos) // 2]
    assert b.direct_remove_n(half) == len(half)
    np.testing.assert_array_equal(b.slice(), pos[len(pos) // 2 :])


def test_count_range(rng):
    pos = rand_positions(rng, 5000, hi=2**22)
    b = rr.Bitmap(pos)
    for start, end in [(0, 2**22), (1000, 2**17), (2**16, 2**16 + 1), (5, 5)]:
        want = int(np.count_nonzero((pos >= start) & (pos < end)))
        assert b.count_range(start, end) == want, (start, end)


def test_set_algebra(rng):
    a_pos = rand_positions(rng, 5000, hi=2**20)
    b_pos = rand_positions(rng, 5000, hi=2**20)
    a, b = rr.Bitmap(a_pos), rr.Bitmap(b_pos)
    np.testing.assert_array_equal(a.intersect(b).slice(), np.intersect1d(a_pos, b_pos))
    np.testing.assert_array_equal(a.union(b).slice(), np.union1d(a_pos, b_pos))
    np.testing.assert_array_equal(a.difference(b).slice(), np.setdiff1d(a_pos, b_pos))
    np.testing.assert_array_equal(a.xor(b).slice(), np.setxor1d(a_pos, b_pos))
    assert a.intersection_count(b) == len(np.intersect1d(a_pos, b_pos))


def test_union_in_place(rng):
    parts = [rand_positions(rng, 3000, hi=2**21) for _ in range(3)]
    b = rr.Bitmap(parts[0])
    b.union_in_place(rr.Bitmap(parts[1]), rr.Bitmap(parts[2]))
    want = np.union1d(np.union1d(parts[0], parts[1]), parts[2])
    np.testing.assert_array_equal(b.slice(), want)


def test_offset_range_and_dense(rng):
    # A fragment row read: bits of shard s, row r live at
    # [r*2^20 + 0, r*2^20 + 2^20) and get rebased to [s*2^20, ...).
    pos = rand_positions(rng, 4000, hi=2**20)
    row, shard = 7, 3
    b = rr.Bitmap(pos + np.uint64(row << 20))
    out = b.offset_range(shard << 20, row << 20, (row + 1) << 20)
    np.testing.assert_array_equal(out.slice(), pos + np.uint64(shard << 20))

    dense = b.dense_range(row << 20, (row + 1) << 20)
    assert dense.shape == (2**20 // 64,)
    bits = np.unpackbits(dense.view(np.uint8), bitorder="little")
    np.testing.assert_array_equal(np.nonzero(bits)[0].astype(np.uint64), pos)


def test_set_dense_range(rng):
    pos = rand_positions(rng, 1000, hi=2**20)
    dense = np.zeros(2**20 // 64, dtype=np.uint64)
    w = (pos >> np.uint64(6)).astype(np.int64)
    np.bitwise_or.at(dense, w, np.left_shift(np.uint64(1), pos & np.uint64(63)))
    b = rr.Bitmap()
    b.set_dense_range(5 << 20, dense)
    np.testing.assert_array_equal(b.slice(), pos + np.uint64(5 << 20))
    # overwrite with zeros clears
    b.set_dense_range(5 << 20, np.zeros_like(dense))
    assert b.count() == 0


def test_serialize_roundtrip_encodings(rng):
    b = rr.Bitmap()
    # array container (sparse)
    b.direct_add_n(rand_positions(rng, 100, hi=2**16))
    # bitmap container (dense, random)
    b.direct_add_n(rand_positions(rng, 30000, hi=2**16) + np.uint64(2**16))
    # run container (contiguous)
    b.direct_add_n(np.arange(2 * 2**16 + 100, 2 * 2**16 + 60000, dtype=np.uint64))
    # high key
    b.direct_add_n(np.array([2**45 + 1, 2**45 + 2], dtype=np.uint64))
    data = b.write_bytes()
    got = rr.Bitmap.from_bytes(data)
    np.testing.assert_array_equal(got.slice(), b.slice())


def test_serialize_header_layout(rng):
    b = rr.Bitmap(np.array([1, 2, 3], dtype=np.uint64))
    data = b.write_bytes()
    magic, version, n = struct.unpack_from("<HHI", data, 0)
    assert magic == 12348 and version == 0 and n == 1
    key, typ, card_m1 = struct.unpack_from("<QHH", data, 8)
    assert key == 0 and typ == rr.CONTAINER_ARRAY and card_m1 == 2
    (offset,) = struct.unpack_from("<I", data, 20)
    assert offset == 24
    vals = np.frombuffer(data, dtype="<u2", count=3, offset=24)
    np.testing.assert_array_equal(vals, [1, 2, 3])


def test_run_container_chosen_for_contiguous():
    b = rr.Bitmap(np.arange(0, 60000, dtype=np.uint64))
    data = b.write_bytes()
    _, typ, _ = struct.unpack_from("<QHH", data, 8)
    assert typ == rr.CONTAINER_RUN


def test_ops_log_roundtrip(rng):
    import io

    b = rr.Bitmap(np.array([10, 20], dtype=np.uint64))
    snapshot = b.write_bytes()
    log = io.BytesIO()
    b.op_writer = log
    b.add(30)
    b.remove(10)
    b.add_batch(np.array([100, 200, 300], dtype=np.uint64))
    b.remove_batch(np.array([20, 200], dtype=np.uint64))
    assert b.op_n == 7
    got = rr.Bitmap.from_bytes(snapshot + log.getvalue())
    np.testing.assert_array_equal(got.slice(), b.slice())
    assert got.op_n == 7


def test_ops_log_checksum_rejects_corruption():
    op = rr.encode_op(rr.OP_ADD, value=42)
    bad = bytearray(op)
    bad[1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        rr.decode_op(memoryview(bytes(bad)))


def test_fnv1a32_vectors():
    # Known FNV-1a 32-bit test vectors.
    assert rr.fnv1a32(b"") == 0x811C9DC5
    assert rr.fnv1a32(b"a") == 0xE40C292C
    assert rr.fnv1a32(b"foobar") == 0xBF9CF968


def test_shift_flip(rng):
    pos = rand_positions(rng, 200, hi=2**18)
    b = rr.Bitmap(pos)
    np.testing.assert_array_equal(b.shift(1).slice(), pos + np.uint64(1))
    f = b.flip(0, 2**10)
    span = np.arange(0, 2**10 + 1, dtype=np.uint64)
    want = np.union1d(np.setdiff1d(span, pos), pos[pos > 2**10])
    np.testing.assert_array_equal(f.slice(), want)


def test_array_encoding_roundtrip_and_ops():
    """Dual in-memory encodings (SURVEY component #3; reference array
    containers roaring.go:55-63 + Optimize :1745): sparse containers
    re-encode as sorted u16 arrays; every read path handles both; any
    mutation transparently materializes dense."""
    import numpy as np

    from pilosa_tpu.storage.roaring import ARRAY_MAX_SIZE, Bitmap

    pos = [1, 7, 65536 + 3, 65536 + 9, 5 << 16]
    b = Bitmap(pos)
    assert b.optimize() == 3
    assert all(c.dtype == np.uint16 for c in b.containers.values())
    # reads on array-encoded containers
    assert b.count() == 5 and b.contains(7) and not b.contains(8)
    assert b.slice().tolist() == sorted(pos)
    assert b.max() == 5 << 16 and b.min() == 1
    assert b.count_range(0, 65536) == 2
    assert b.count_range(2, 65536 + 4) == 2
    dense = b.dense_range(0, 2 << 16)
    assert int(np.bitwise_count(dense).sum()) == 4
    # algebra across mixed encodings
    other = Bitmap([7, 65536 + 9, 99])
    assert b.intersection_count(other) == 2
    assert other.intersection_count(b) == 2
    other.optimize()
    assert b.intersect(other).slice().tolist() == [7, 65536 + 9]
    assert b.union(other).count() == 6
    # mutation materializes and stays correct
    assert b.add(8)
    assert b.containers[0].dtype == np.uint64
    assert b.contains(8) and b.count() == 6
    assert b.remove(65536 + 3) and b.count_range(65536, 2 << 16) == 1
    # serialization round-trips from mixed encodings
    data = b.write_bytes()
    b2 = Bitmap.from_bytes(data)
    assert b2.slice().tolist() == b.slice().tolist()
    # Both parsers keep array-eligible payloads array-encoded on load
    # (the native path via the encoding-split export) — no dense blowup
    # on open.
    assert any(c.dtype == np.uint16 for c in b2.containers.values())
    b2.optimize()
    assert any(c.dtype == np.uint16 for c in b2.containers.values())
    # large containers stay dense through optimize
    big = Bitmap(range(ARRAY_MAX_SIZE + 1))
    assert big.optimize() == 0
    assert big.containers[0].dtype == np.uint64


def test_array_encoding_union_in_place_and_clear():
    import numpy as np

    from pilosa_tpu.storage.roaring import Bitmap

    a = Bitmap([1, 2, 3])
    a.optimize()
    b = Bitmap([3, 4])
    b.optimize()
    a.union_in_place(b)
    assert a.slice().tolist() == [1, 2, 3, 4]
    c = Bitmap()
    c.union_in_place(b)  # copy branch keeps the array encoding
    assert c.slice().tolist() == [3, 4]
    assert c.containers[0].dtype == np.uint16
    c.add(4)  # no-op add still must not corrupt
    assert c.slice().tolist() == [3, 4]


def test_fragment_rows_dense_from_array_containers(tmp_path):
    import numpy as np

    from pilosa_tpu.core.fragment import Fragment

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0)
    frag.open()
    frag.bulk_import(np.array([0, 0, 1], np.uint64),
                     np.array([3, 4000, 70000], np.uint64))
    want0 = frag.row_dense(0, u32_words=128).copy()
    want1w = frag.rows_dense([1], 4096).copy()
    assert frag.optimize_storage() >= 2
    got = frag.rows_dense([0, 1], 128)
    np.testing.assert_array_equal(got[0], want0)
    assert not got[1].any()  # row 1's bit is past the 4096-bit window
    np.testing.assert_array_equal(frag.rows_dense([1], 4096), want1w)
    # reopen keeps arrays array-encoded from the snapshot
    frag._snapshot()
    frag.close()
    frag2 = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0)
    frag2.open()
    assert any(c.dtype == np.uint16
               for c in frag2.storage.containers.values())
    np.testing.assert_array_equal(frag2.rows_dense([0, 1], 128)[0], want0)
    assert frag2.bit(1, 70000)
    frag2.close()


# ---------------------------------------- container-transition properties


def _container_lattice_cases(rng):
    """In-container position sets straddling every encoding boundary:
    the array<->dense threshold (ARRAY_MAX_SIZE = 4096), the run
    thresholds in _serialize_container_seq, and the u16 edges."""
    yield np.array([0], dtype=np.uint64)                     # singleton
    yield np.array([0, 65535], dtype=np.uint64)              # u16 edges
    yield np.arange(0, 65536, dtype=np.uint64)               # full
    yield np.sort(rng.choice(65536, size=4096, replace=False)
                  ).astype(np.uint64)                        # == threshold
    yield np.sort(rng.choice(65536, size=4097, replace=False)
                  ).astype(np.uint64)                        # threshold + 1
    yield np.arange(100, 5000, dtype=np.uint64)              # one long run
    yield np.concatenate([np.arange(i, i + 9, dtype=np.uint64)
                          for i in range(0, 60000, 100)])    # many runs
    yield np.sort(rng.choice(65536, size=30000, replace=False)
                  ).astype(np.uint64)                        # dense random


def test_array_dense_run_round_trips(rng):
    """array->dense->array and run->dense->run are identities, and both
    meet in the same dense words, at every boundary density."""
    for pos in _container_lattice_cases(rng):
        arr = pos.astype(np.uint16)
        dense = rr._array_to_dense(arr)
        np.testing.assert_array_equal(rr._dense_to_array(dense), arr)
        runs = rr._dense_to_runs(dense)
        # Runs are sorted, disjoint, non-adjacent (else they would have
        # been one run), and expand back to the identical words.
        assert (runs[:, 0] <= runs[:, 1]).all()
        if len(runs) > 1:
            assert (runs[1:, 0].astype(np.uint32)
                    > runs[:-1, 1].astype(np.uint32) + 1).all()
        np.testing.assert_array_equal(rr._runs_to_dense(runs), dense)
        # Cardinality is conserved across all three encodings.
        n = int(np.bitwise_count(dense).sum())
        assert n == len(arr)
        assert n == int((runs[:, 1].astype(np.uint64)
                         - runs[:, 0].astype(np.uint64) + 1).sum())


def test_optimize_flips_encodings_at_boundary_densities(rng):
    """optimize() re-encodes exactly the containers at or below
    ARRAY_MAX_SIZE, keeps denser ones dense, and the flip changes no
    observable state (slice/count/serialization)."""
    at = np.sort(rng.choice(65536, size=rr.ARRAY_MAX_SIZE,
                            replace=False)).astype(np.uint64)
    above = np.sort(rng.choice(65536, size=rr.ARRAY_MAX_SIZE + 1,
                               replace=False)).astype(np.uint64)
    pos = np.concatenate([at, (1 << 16) + above])
    b = rr.Bitmap(pos)
    before = b.slice()
    assert b.containers[0].dtype == np.uint64  # mutation path is dense
    assert b.optimize() == 1                   # only container 0 flips
    assert b.containers[0].dtype == np.uint16
    assert b.containers[1].dtype == np.uint64
    np.testing.assert_array_equal(b.slice(), before)
    assert b.optimize() == 0                   # idempotent
    # A mutation re-materializes dense; optimize() flips it back (the
    # removal keeps the count at the threshold, so it stays eligible).
    removed = int(at[0])
    assert b.remove(removed)
    assert b.containers[0].dtype == np.uint64
    assert b.optimize() == 1
    assert not b.contains(removed)
    # Serialized form is encoding-independent: the optimized bitmap and
    # a freshly-built one emit identical bytes.
    b.add(removed)
    b.optimize()
    assert b.write_bytes() == rr.Bitmap(pos).write_bytes()


def test_serializer_picks_each_container_type_and_reader_inverts(rng):
    """The writer's run/array/bitmap choice at boundary densities, and
    read_bytes inverting every choice bit-exactly."""
    cases = {
        rr.CONTAINER_RUN: np.arange(0, 60000, dtype=np.uint64),
        rr.CONTAINER_ARRAY: np.sort(
            rng.choice(65536, size=1000, replace=False)
        ).astype(np.uint64),
        rr.CONTAINER_BITMAP: np.sort(
            rng.choice(65536, size=30000, replace=False)
        ).astype(np.uint64),
    }
    for want_typ, pos in cases.items():
        data = rr.Bitmap(pos).write_bytes()
        (_, n) = struct.unpack_from("<II", data, 0)
        assert n == 1
        _, typ, card_minus_1 = struct.unpack_from("<QHH", data, 8)
        assert typ == want_typ, (want_typ, typ)
        assert card_minus_1 + 1 == len(pos)
        got = rr.Bitmap.from_bytes(data)
        np.testing.assert_array_equal(got.slice(), pos)
