"""CLI tests (reference cmd/*_test.go / ctl tests)."""

import os

import pytest

from pilosa_tpu.cli.main import main
from pilosa_tpu.utils.config import Config, load_config


def test_generate_config_roundtrip(tmp_path, capsys):
    assert main(["generate-config"]) == 0
    toml_text = capsys.readouterr().out
    p = tmp_path / "cfg.toml"
    p.write_text(toml_text)
    cfg = load_config(str(p))
    assert cfg == Config()


def test_config_precedence(tmp_path, monkeypatch):
    p = tmp_path / "cfg.toml"
    p.write_text('bind = "localhost:7777"\nverbose = true\n')
    cfg = load_config(str(p))
    assert cfg.port == 7777 and cfg.verbose
    monkeypatch.setenv("PILOSA_TPU_BIND", "localhost:8888")
    cfg = load_config(str(p))
    assert cfg.port == 8888  # env beats file
    cfg = load_config(str(p), {"bind": "localhost:9999"})
    assert cfg.port == 9999  # flags beat env
    with pytest.raises(ValueError, match="unknown config key"):
        bad = tmp_path / "bad.toml"
        bad.write_text('no_such_key = 1\n')
        load_config(str(bad))


def test_import_export_check_inspect(tmp_path, capsys):
    csv_file = tmp_path / "data.csv"
    csv_file.write_text("1,10\n1,20\n2,10\n")
    data_dir = str(tmp_path / "data")
    assert main(["import", "-d", data_dir, "-i", "idx", "-f", "f",
                 str(csv_file)]) == 0
    out_file = tmp_path / "out.csv"
    assert main(["export", "-d", data_dir, "-i", "idx", "-f", "f",
                 "-o", str(out_file)]) == 0
    got = sorted(out_file.read_text().strip().split("\n"))
    assert got == ["1,10", "1,20", "2,10"]

    frag = os.path.join(data_dir, "idx", "f", "views", "standard",
                        "fragments", "0")
    assert main(["check", frag]) == 0
    assert "ok" in capsys.readouterr().out
    assert main(["inspect", frag, "--verbose"]) == 0
    out = capsys.readouterr().out
    assert "2 rows" in out

    # corrupt file detected
    bad = tmp_path / "bad.bin"
    bad.write_bytes(b"\x00\x01\x02")
    assert main(["check", str(bad)]) == 1


def test_fold_rewrites_to_pure_snapshot(tmp_path, capsys):
    """`fold` rewrites a fragment with OP_ADD_ROARING extension records
    as a pure reference-format snapshot (ADVICE r3: the downgrade path
    for the one-way data-file compatibility, docs/parity.md)."""
    from pilosa_tpu.storage.roaring import Bitmap

    csv_file = tmp_path / "data.csv"
    csv_file.write_text("1,10\n1,20\n2,10\n7,999999\n")
    data_dir = str(tmp_path / "data")
    assert main(["import", "-d", data_dir, "-i", "idx", "-f", "f",
                 str(csv_file)]) == 0
    frag = os.path.join(data_dir, "idx", "f", "views", "standard",
                        "fragments", "0")
    with open(frag, "rb") as f:
        before = Bitmap.from_bytes(f.read())
    # The bulk import path appends the extension record the reference
    # cannot read — the precondition that makes fold necessary.
    assert before.op_n > 0
    want = before.count()

    assert main(["fold", frag]) == 0
    assert "folded" in capsys.readouterr().out
    with open(frag, "rb") as f:
        raw = f.read()
    after = Bitmap.from_bytes(raw)
    assert after.op_n == 0 and after.count() == want
    # No op records remain at all: the snapshot section spans the file.
    assert after.snapshot_bytes == len(raw) and after.oplog_bytes == 0
    # Idempotent, and the folded holder still answers queries.
    assert main(["fold", frag]) == 0
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.core.holder import Holder
    h = Holder(data_dir)
    h.open()
    (res,) = Executor(h).execute("idx", "Row(f=7)")
    assert res.columns() == [999999]
    h.close()


def test_fold_force_sidecars_torn_tail(tmp_path, capsys):
    """fold refuses a torn-tail file without --force; with --force it
    preserves the dropped bytes in a .torn sidecar (the same
    never-destroy-bytes rule as Fragment.open) before rewriting."""
    from pilosa_tpu.storage.roaring import Bitmap, encode_op, OP_ADD

    b = Bitmap()
    b.add(5)
    b.add(70000)
    torn = encode_op(OP_ADD, 123)[:-2]  # record truncated mid-checksum
    frag = tmp_path / "frag"
    frag.write_bytes(b.write_bytes() + torn)

    assert main(["fold", str(frag)]) == 1
    assert "--force" in capsys.readouterr().err
    assert main(["fold", str(frag), "--force"]) == 0
    err = capsys.readouterr().err
    assert "sidecarred" in err
    assert (tmp_path / "frag.torn").read_bytes() == torn
    after = Bitmap.from_bytes(frag.read_bytes())
    assert after.op_n == 0 and after.count() == 2


def test_import_int_field(tmp_path, capsys):
    csv_file = tmp_path / "vals.csv"
    csv_file.write_text("1,100\n2,-5\n3,40\n")
    data_dir = str(tmp_path / "data")
    assert main(["import", "-d", data_dir, "-i", "idx", "-f", "n",
                 "--field-type", "int", str(csv_file)]) == 0
    from pilosa_tpu.core.holder import Holder
    h = Holder(data_dir)
    h.open()
    assert h.index("idx").field("n").value(2) == (-5, True)
    h.close()


def test_import_remote_host(tmp_path, live_server, capsys):
    """`import --host` posts CSV batches through a running server's
    import API, creating the schema if missing (reference ctl/import.go
    remote mode; VERDICT r3 missing #5)."""
    base, api, holder = live_server
    csv_file = tmp_path / "r.csv"
    csv_file.write_text("1,5\n1,6\n2,5\n")
    assert main(["import", "--host", base, "-i", "ri", "-f", "f",
                 str(csv_file)]) == 0
    assert "via" in capsys.readouterr().out
    (res,) = api.executor.execute("ri", "Count(Row(f=1))")
    assert res == 2
    # Int-field variant creates the field with a fitting range.
    vals = tmp_path / "v.csv"
    vals.write_text("1,100\n2,-7\n")
    assert main(["import", "--host", base, "-i", "ri", "-f", "n",
                 "--field-type", "int", str(vals)]) == 0
    assert holder.index("ri").field("n").value(2) == (-7, True)
    # Re-import into the existing schema is fine (ensure tolerates 409).
    assert main(["import", "--host", base, "-i", "ri", "-f", "f",
                 str(csv_file)]) == 0
    # Neither --host nor --data-dir is an error, not a crash.
    assert main(["import", "-i", "x", "-f", "f", str(csv_file)]) == 2


def test_backup_restore_roundtrip(tmp_path, capsys):
    """backup tars the data dir; restore unpacks it; the restored holder
    answers the same query (offline analog of the reference's tar-stream
    backup, fragment.go:1885-2230)."""
    src = str(tmp_path / "src")
    csvf = tmp_path / "in.csv"
    csvf.write_text("1,5\n1,9\n2,5\n")
    assert main(["import", "-d", src, "-i", "idx", "-f", "f",
                 str(csvf)]) == 0
    tar = str(tmp_path / "bk.tgz")
    assert main(["backup", "-d", src, "-o", tar]) == 0
    dst = str(tmp_path / "dst")
    assert main(["restore", "-d", dst, "-i", tar]) == 0
    out1 = str(tmp_path / "a.csv")
    out2 = str(tmp_path / "b.csv")
    assert main(["export", "-d", src, "-i", "idx", "-f", "f",
                 "-o", out1]) == 0
    assert main(["export", "-d", dst, "-i", "idx", "-f", "f",
                 "-o", out2]) == 0
    assert open(out1).read() == open(out2).read() != ""
    # refuse restore into non-empty without --force
    assert main(["restore", "-d", dst, "-i", tar]) == 1
    assert main(["restore", "-d", dst, "-i", tar, "--force"]) == 0


def test_restore_force_replaces_and_rejects_bad_members(tmp_path):
    """--force replaces (post-backup files don't survive); symlink
    members are rejected before extraction."""
    import tarfile
    src = str(tmp_path / "s")
    csvf = tmp_path / "in.csv"
    csvf.write_text("1,5\n")
    assert main(["import", "-d", src, "-i", "idx", "-f", "f",
                 str(csvf)]) == 0
    tar = str(tmp_path / "bk.tgz")
    assert main(["backup", "-d", src, "-o", tar]) == 0
    dst = tmp_path / "d"
    assert main(["restore", "-d", str(dst), "-i", tar]) == 0
    stray = dst / "idx" / "stray.bin"
    stray.write_text("post-backup junk")
    assert main(["restore", "-d", str(dst), "-i", tar, "--force"]) == 0
    assert not stray.exists()  # replaced, not merged
    # symlink member refused up front
    evil = str(tmp_path / "evil.tgz")
    with tarfile.open(evil, "w:gz") as t:
        info = tarfile.TarInfo("link")
        info.type = tarfile.SYMTYPE
        info.linkname = "/etc/passwd"
        t.addfile(info)
    empty = str(tmp_path / "e")
    assert main(["restore", "-d", empty, "-i", evil]) == 1


def test_backup_output_inside_data_dir(tmp_path):
    src = tmp_path / "s"
    csvf = tmp_path / "in.csv"
    csvf.write_text("1,5\n")
    assert main(["import", "-d", str(src), "-i", "idx", "-f", "f",
                 str(csvf)]) == 0
    tar = str(src / "bk.tgz")
    assert main(["backup", "-d", str(src), "-o", tar]) == 0
    import tarfile
    with tarfile.open(tar) as t:
        assert "bk.tgz" not in t.getnames()
