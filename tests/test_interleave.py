"""Concurrency verification plane: the deterministic interleaving
explorer (pilosa_tpu/utils/sched.py + tools/interleave.py).

Pins the plane's own contract: schedule enumeration is deterministic
(the --digest pin), the wait-for graph catches a minimal AB/BA
deadlock, the three re-introduced historical races are found within
the default schedule budget, every good scenario sweeps clean, and the
pinned corpus replays to its recorded verdicts.
"""

import json
import os
import threading

import pytest

from pilosa_tpu.utils import sched

import tools.interleave as il

CORPUS = os.path.join(os.path.dirname(__file__), "interleave_corpus")


# ------------------------------------------------- scheduler basics


def test_factories_return_sched_wrappers_under_scheduler():
    from pilosa_tpu.utils.locks import (make_condition, make_lock,
                                        make_rlock)
    with sched.Scheduler(sched.schedule_decider([])):
        assert isinstance(make_lock("x"), sched.SchedLock)
        assert isinstance(make_rlock("x"), sched.SchedRLock)
        assert isinstance(make_condition("x"), sched.SchedCondition)
    # Back to uninstrumented primitives once the scheduler deactivates.
    assert not isinstance(make_lock("x"), sched.SchedLock)


def _explore_increment_finals(use_lock, budget):
    finals = set()

    def run_with(decide):
        with sched.Scheduler(decide) as s:
            from pilosa_tpu.utils.locks import make_lock
            lock = make_lock("L")
            state = {"n": 0}

            def inc():
                if use_lock:
                    with lock:
                        v = state["n"]
                        sched.checkpoint()
                        state["n"] = v + 1
                else:
                    v = state["n"]
                    sched.checkpoint()
                    state["n"] = v + 1

            s.spawn("t1", inc)
            s.spawn("t2", inc)
            out = s.run()
        assert not out.failed
        finals.add(state["n"])
        return out

    sched.explore_dfs(run_with, budget)
    return finals


def test_unlocked_increment_races_locked_does_not():
    # Exhaustive over the schedule space: the lost update IS reachable
    # without the lock, and unreachable in EVERY interleaving with it.
    assert _explore_increment_finals(False, 100) == {1, 2}
    assert _explore_increment_finals(True, 200) == {2}


def test_explore_enumerates_deterministically():
    def run_with(decide):
        with sched.Scheduler(decide) as s:
            from pilosa_tpu.utils.locks import make_lock
            lock = make_lock("L")

            def worker():
                with lock:
                    sched.checkpoint()

            s.spawn("a", worker)
            s.spawn("b", worker)
            return s.run()

    one = [schedule for schedule, _ in sched.explore_dfs(run_with, 50)]
    two = [schedule for schedule, _ in sched.explore_dfs(run_with, 50)]
    assert one == two
    assert len(one) == len({tuple(s) for s in one})  # no duplicates


def test_deadlock_abba_minimal():
    """The wait-for graph names both parties of an AB/BA deadlock."""

    def run_with(decide):
        with sched.Scheduler(decide) as s:
            from pilosa_tpu.utils.locks import make_lock
            a, b = make_lock("A"), make_lock("B")

            def t1():
                with a:
                    with b:
                        pass

            def t2():
                with b:
                    with a:
                        pass

            s.spawn("t1", t1)
            s.spawn("t2", t2)
            return s.run()

    deadlocks = [o.deadlock for _, o in sched.explore_dfs(run_with, 500)
                 if o.deadlock is not None]
    assert deadlocks, "AB/BA deadlock not found"
    assert "t1" in deadlocks[0] and "t2" in deadlocks[0]
    assert "'A'" in deadlocks[0] and "'B'" in deadlocks[0]


def test_timed_wait_fires_only_at_quiescence():
    def run_with(decide):
        log = []
        with sched.Scheduler(decide) as s:
            from pilosa_tpu.utils.locks import make_condition
            cond = make_condition("C")

            def waiter():
                with cond:
                    log.append(cond.wait(timeout=0.01))

            s.spawn("w", waiter)
            out = s.run()
        return out, log

    out, log = run_with(sched.schedule_decider([]))
    assert not out.failed
    assert log == [False]  # timed out, did not deadlock


def test_untimed_wait_without_notifier_is_deadlock():
    def run_with(decide):
        with sched.Scheduler(decide) as s:
            from pilosa_tpu.utils.locks import make_condition
            cond = make_condition("C")

            def waiter():
                with cond:
                    cond.wait()

            s.spawn("w", waiter)
            return s.run()

    out = run_with(sched.schedule_decider([]))
    assert out.deadlock is not None
    assert "no notifier" in out.deadlock


# ------------------------------------------------ the scenario corpus


GOOD = [s for s in il.SCENARIOS if not s.known_bad]
KNOWN_BAD = [s for s in il.SCENARIOS if s.known_bad]
HISTORICAL = ["bad_resize_two_step_route", "bad_bank_cache_unlocked_evict",
              "bad_cache_stamp_then_read"]


def test_corpus_has_the_three_historical_races():
    names = {s.name for s in KNOWN_BAD}
    assert set(HISTORICAL) <= names


@pytest.mark.parametrize("scn", GOOD, ids=lambda s: s.name)
def test_good_scenarios_sweep_clean(scn):
    runs, failures = il.sweep(scn, scn.budget)
    assert not failures, failures[:3]
    assert runs > 10  # the sweep actually explored


@pytest.mark.parametrize("scn", KNOWN_BAD, ids=lambda s: s.name)
def test_known_bad_found_within_default_budget(scn):
    """Each seeded re-introduction of a historical race must be found
    deterministically within the DEFAULT budget — the explorer's own
    regression gate."""
    runs, failures = il.sweep(scn, il.DEFAULT_BUDGET)
    assert failures, (f"{scn.name}: not caught within "
                      f"{il.DEFAULT_BUDGET} schedules")


def test_known_bad_failure_is_replayable():
    """A found schedule is a complete reproducer: replaying it yields
    the same verdict kind, twice."""
    scn = il.scenario_by_name("bad_bank_cache_unlocked_evict")
    _, failures = il.sweep(scn, il.DEFAULT_BUDGET)
    pinned = failures[0]
    r1 = il.judge(scn, il.run_once(
        scn, sched.schedule_decider(pinned.schedule)))
    r2 = il.judge(scn, il.run_once(
        scn, sched.schedule_decider(pinned.schedule)))
    assert r1.kind == r2.kind == pinned.kind


def test_seed_index_reproducer_contract():
    """(seed, index) regenerates the exact schedule — the
    roaring_fuzz/plan_fuzz contract."""
    import numpy as np
    scn = il.scenario_by_name("bank_cache_miss_race")
    a = il.run_once(scn, sched.rng_decider(np.random.default_rng([7, 3])))
    b = il.run_once(scn, sched.rng_decider(np.random.default_rng([7, 3])))
    assert a.schedule == b.schedule


def test_digest_pin(capsys):
    """Schedule-enumeration determinism: the full-sweep digest is
    identical across back-to-back runs in one process."""
    assert il.main(["--digest", "--no-save"]) == 0
    d1 = capsys.readouterr().out.strip().splitlines()[-1]
    assert il.main(["--digest", "--no-save"]) == 0
    d2 = capsys.readouterr().out.strip().splitlines()[-1]
    assert d1 == d2
    assert len(d1) == 64  # sha256 hex


def test_corpus_replay_green(capsys):
    assert os.path.isdir(CORPUS), "pinned corpus missing"
    entries = [f for f in os.listdir(CORPUS) if f.endswith(".json")]
    assert len(entries) >= 4
    assert il.main(["--replay"]) == 0


def test_corpus_entries_are_wellformed():
    for fname in sorted(os.listdir(CORPUS)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(CORPUS, fname)) as fh:
            entry = json.load(fh)
        assert {"scenario", "schedule", "expect"} <= set(entry)
        il.scenario_by_name(entry["scenario"])  # must still exist
        assert all(isinstance(c, int) for c in entry["schedule"])


def test_sarif_output_shape(tmp_path):
    out = tmp_path / "interleave.sarif"
    rc = il.main(["--scenario", "bank_cache_miss_race",
                  "--output", str(out), "--no-save"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "interleave"
    assert run["results"] == []  # green run: no findings


def test_gate_flags_a_missing_known_bad(monkeypatch):
    """If a 'known-bad' scenario stops failing (the race got fixed but
    the fixture wasn't retired), the gate must fail loudly."""

    class Fixed(il.Scenario):
        name = "bad_fixture_actually_fixed"
        known_bad = True

        def build(self):
            return None

        def workers(self, state):
            return [("t", lambda: None)]

    ok, msg, _ = il.gate_scenario(Fixed(), 20)
    assert not ok and "NOT caught" in msg
