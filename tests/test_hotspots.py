"""Workload analytics plane (utils/hotspots.py): EWMA decay math,
LRU bounding with provable totals, zero-fence recording, the
cache-opportunity report's synthetic repeat structure, cross-request
repeat accounting through the coalescer, and the HTTP surfaces."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.server.api import API
from pilosa_tpu.utils.hotspots import (
    ROW_CAP_PER_CALL, WORKLOAD, WorkloadRecorder, _Window,
)
from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text


@pytest.fixture(autouse=True)
def _reset_workload():
    """The recorder is process-wide (like memledger's LEDGER): every
    test starts from a clean slate and leaves defaults behind."""
    WORKLOAD.reset()
    WORKLOAD.configure(enabled=True, half_life_s=600.0, window_s=300.0,
                       top_k=10, max_fragments=4096, max_rows=4096,
                       max_signatures=1024)
    WORKLOAD.stats = None
    yield
    WORKLOAD.reset()
    WORKLOAD.stats = None


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _seed(holder, fields=("f", "g")):
    idx = holder.create_index("ws")
    cols = np.array([1, 2, SHARD_WIDTH + 3], np.uint64)
    for name in fields:
        idx.create_field(name).import_bits(
            np.full(3, 1, np.uint64), cols)
    idx.add_existence(cols)
    return idx


# ------------------------------------------------------------- decay math


def test_ewma_half_life_under_injected_clock():
    """The decayed rate halves per half-life of inactivity while the
    cumulative count never decays."""
    clock = FakeClock()
    rec = WorkloadRecorder(half_life_s=10.0, clock=clock)
    for _ in range(100):
        rec.record_read("i", "f", "standard", [0])
    snap = rec.snapshot()
    ent = snap["fragments"][0]
    assert ent["reads"] == 100
    assert ent["readRate"] == pytest.approx(100.0, rel=1e-6)
    clock.advance(10.0)  # one half-life
    ent = rec.snapshot()["fragments"][0]
    assert ent["reads"] == 100  # cumulative: no decay
    assert ent["readRate"] == pytest.approx(50.0, rel=1e-6)
    clock.advance(20.0)  # two more half-lives
    ent = rec.snapshot()["fragments"][0]
    assert ent["readRate"] == pytest.approx(12.5, rel=1e-6)
    # New activity adds on top of the decayed value, not the raw one.
    rec.record_read("i", "f", "standard", [0])
    ent = rec.snapshot()["fragments"][0]
    assert ent["readRate"] == pytest.approx(13.5, rel=1e-6)
    assert ent["reads"] == 101


def test_window_prunes_by_age_and_caps_events():
    clock = FakeClock()
    w = _Window(window_s=30.0, max_events=4)
    assert w.add("a", clock()) is False
    assert w.add("a", clock()) is True  # live repeat
    clock.advance(31.0)
    assert w.add("a", clock()) is False  # pruned by age: fresh again
    # Event cap: only the newest max_events stay live.
    for k in ("b", "c", "d", "e"):
        w.add(k, clock())
    snap = w.snapshot(clock())
    assert snap["seen"] == 4  # "a" fell off the cap
    assert snap["seenTotal"] == 7
    assert snap["repeatsTotal"] == 1


# ---------------------------------------------------------- LRU + totals


def test_fragment_lru_bound_and_provable_totals():
    """Fragment keys are LRU-bounded; evicted entries fold their
    counts into `evicted`, so totals.X == tracked.X + evicted.X holds
    at every moment."""
    rec = WorkloadRecorder(max_fragments=8, clock=FakeClock())
    for s in range(32):
        rec.record_read("i", "f", "standard", [s])
        rec.record_write("i", "f", "standard", s, generation=s)
    snap = rec.snapshot(top_k=100)
    assert len(snap["fragments"]) == 8  # bounded
    assert snap["totals"]["fragmentReads"] == 32
    assert snap["totals"]["fragmentWrites"] == 32
    assert snap["totals"]["fragmentReads"] == \
        snap["tracked"]["fragmentReads"] + \
        snap["evicted"]["fragmentReads"]
    assert snap["totals"]["fragmentWrites"] == \
        snap["tracked"]["fragmentWrites"] + \
        snap["evicted"]["fragmentWrites"]
    assert snap["evicted"]["fragmentReads"] == 24
    # LRU, not FIFO: touching an old key keeps it resident.
    rec2 = WorkloadRecorder(max_fragments=4, clock=FakeClock())
    for s in range(4):
        rec2.record_read("i", "f", "standard", [s])
    rec2.record_read("i", "f", "standard", [0])  # touch shard 0
    rec2.record_read("i", "f", "standard", [99])  # evicts shard 1
    shards = {f["shard"] for f in rec2.snapshot(top_k=100)["fragments"]}
    assert 0 in shards and 1 not in shards


def test_row_and_signature_lru_bounds():
    rec = WorkloadRecorder(max_rows=4, max_signatures=4,
                           clock=FakeClock())
    rec.record_read("i", "f", "standard", [0], rows=range(16))
    snap = rec.snapshot(top_k=100)
    assert len(snap["rows"]) == 4
    assert snap["totals"]["rowTouches"] == 16
    assert snap["totals"]["rowTouches"] == \
        snap["tracked"]["rowTouches"] + snap["evicted"]["rowTouches"]
    for i in range(9):
        rec.record_query(("sig", i), ("g",), index="i", mode="count",
                         n_shards=1)
    snap = rec.snapshot(top_k=100)
    assert len(snap["signatures"]) == 4
    assert snap["totals"]["queries"] == 9
    assert snap["totals"]["queries"] == \
        snap["tracked"]["queries"] + snap["evicted"]["queries"]


def test_row_cap_per_call_records_scan_aggregate():
    """A sweep naming more rows than ROW_CAP_PER_CALL records the cap
    as identities and the remainder as rowsScanned — full-bank TopN
    scans must not flood the row map."""
    rec = WorkloadRecorder(clock=FakeClock())
    rec.record_read("i", "f", "standard", [0],
                    rows=range(ROW_CAP_PER_CALL + 100))
    snap = rec.snapshot(top_k=1000)
    assert len(snap["rows"]) == ROW_CAP_PER_CALL
    assert snap["totals"]["rowsScanned"] == 100


def test_kill_switch_skips_all_recording():
    rec = WorkloadRecorder(clock=FakeClock())
    rec.enabled = False
    rec.record_read("i", "f", "standard", [0], rows=[1])
    rec.record_write("i", "f", "standard", 0)
    rec.record_query("fp", "g", index="i", mode="count", n_shards=1)
    assert rec.record_request("k") is False
    snap = rec.snapshot()
    assert snap["totals"]["fragmentReads"] == 0
    assert snap["totals"]["fragmentWrites"] == 0
    assert snap["totals"]["queries"] == 0
    assert snap["queriesWindow"]["seen"] == 0


# ------------------------------------------------- executor wiring (reads)


def test_zero_fences_on_recording_path(tmp_holder, monkeypatch):
    """Acceptance: workload recording adds NO block_until_ready fences
    — the unprofiled hot path stays fully async with the recorder on
    (the GL003-by-construction claim, pinned like PR 3's test)."""
    import pilosa_tpu.executor.executor as ex

    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    # Repeats must EXECUTE here (the recorder plane is under test);
    # the result cache would serve them without staging.
    api.executor.result_cache.enabled = False
    fences = []
    monkeypatch.setattr(ex, "_fence_device",
                        lambda out: fences.append(1) or 0.0)
    for i in range(8):
        api.query("ws", f"Count(Row(f={i % 2}))")
    assert fences == []
    # ...and it actually recorded while staying fence-free.
    assert WORKLOAD.summary()["fragmentReads"] > 0
    assert WORKLOAD.summary()["queries"] == 8


def test_reads_writes_and_generation_recorded(tmp_holder):
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    WORKLOAD.reset()  # drop the import-time writes; isolate the query
    api.query("ws", "Count(Row(f=1))")
    snap = api.debug_hotspots()
    frags = {(f["field"], f["shard"]): f for f in snap["fragments"]}
    assert frags[("f", 0)]["reads"] == 1
    assert frags[("f", 1)]["reads"] == 1
    # Row 1 of field f was the named row.
    assert snap["rows"][0]["row"] == 1
    assert snap["rows"][0]["field"] == "f"
    # A write records churn + the generation caches key on.
    api.query("ws", "Set(5, f=1)")
    snap = api.debug_hotspots()
    f0 = next(f for f in snap["fragments"]
              if f["field"] == "f" and f["shard"] == 0
              and f["writes"] > 0)
    frag = tmp_holder.index("ws").field("f").view().fragment(0)
    assert f0["generation"] == frag.version
    # The next read of f finds the cached bank stale: churn cost a
    # device-bank patch, recorded as an invalidation.
    api.query("ws", "Count(Row(f=1))")
    snap = api.debug_hotspots()
    f0 = next(f for f in snap["fragments"]
              if f["field"] == "f" and f["shard"] == 0)
    assert f0["bankInvalidations"] >= 1


def test_topn_and_groupby_record_reads(tmp_holder):
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    WORKLOAD.reset()
    api.query("ws", "TopN(f, n=2)")
    snap = api.debug_hotspots()
    assert any(f["field"] == "f" and f["reads"] > 0
               for f in snap["fragments"])
    assert any(r["field"] == "f" and r["row"] == 1
               for r in snap["rows"])
    WORKLOAD.reset()
    api.query("ws", "GroupBy(Rows(f), Rows(g))")
    snap = api.debug_hotspots()
    touched = {f["field"] for f in snap["fragments"] if f["reads"] > 0}
    assert {"f", "g"} <= touched


# --------------------------------------------- cache-opportunity report


def test_synthetic_repeat_structure_and_saved_seconds(tmp_holder):
    """Acceptance: 64 requests of 4 distinct signatures -> repeat
    ratio == 15/16 and the 4 signatures ranked with profiler-derived
    saved-seconds attached."""
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    # The estimator under test prices repeats that EXECUTE; cache off
    # so all 64 stage (with it on, hits skip staging by design and
    # the estimator ranks only the remaining miss traffic).
    api.executor.result_cache.enabled = False
    WORKLOAD.reset()
    for i in range(64):
        api.query("ws", f"Count(Row(f={i % 4}))")
    snap = api.debug_hotspots()
    win = snap["queriesWindow"]
    assert win["seen"] == 64
    assert win["repeats"] == 60
    assert win["ratio"] == pytest.approx(15 / 16)
    sigs = snap["signatures"]
    assert len(sigs) == 4
    for s in sigs:
        assert s["hits"] == 16
        assert s["genHits"] == 16  # no writes: generation never moved
        assert s["avgEvalS"] is not None and s["avgEvalS"] > 0
        # 15 cacheable repeats x the observed per-eval seconds.
        assert s["estSavedS"] == pytest.approx(15 * s["avgEvalS"])
    opp = snap["opportunity"]["signatures"]
    assert len(opp) == 4
    assert opp == sorted(opp, key=lambda s: -s["estSavedS"])
    total = snap["opportunity"]["totalEstSavedS"]
    assert total == pytest.approx(sum(s["estSavedS"] for s in opp))
    # totalEstSavedS covers EVERY cacheable signature — the cache
    # sizing number must not change with the requested list bound.
    narrow = api.debug_hotspots(top_k=1)["opportunity"]
    assert len(narrow["signatures"]) == 1
    assert narrow["totalEstSavedS"] == pytest.approx(total)
    # Fingerprints are stable digests (16 hex chars), identical
    # across snapshots — NOT process-salted hash() values.
    fps = sorted(s["fingerprint"] for s in sigs)
    assert all(len(f) == 16 and int(f, 16) >= 0 for f in fps)
    fps2 = sorted(s["fingerprint"]
                  for s in api.debug_hotspots()["signatures"])
    assert fps == fps2


def test_generation_bump_resets_cacheable_run(tmp_holder):
    """A write between repeats moves the operand generation: the
    signature's cacheable run restarts (a result cache would have
    been invalidated exactly there)."""
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.executor.result_cache.enabled = False  # repeats must stage
    WORKLOAD.reset()
    for _ in range(4):
        api.query("ws", "Count(Row(f=1))")
    api.query("ws", "Set(7, f=1)")
    api.query("ws", "Count(Row(f=1))")
    snap = api.debug_hotspots()
    sig = next(s for s in snap["signatures"] if s["mode"] == "count"
               and s["hits"] >= 5)
    assert sig["hits"] == 5
    assert sig["genHits"] == 1  # run reset by the generation bump


def test_bank_quadrants_join_ledger_and_access(tmp_holder):
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    WORKLOAD.reset()
    api.query("ws", "Count(Row(f=1))")
    banks = api.debug_hotspots()["opportunity"]["banks"]
    assert banks, "resident banks must appear in the quadrant report"
    by_field = {b["field"]: b for b in banks if b["index"] == "ws"}
    assert by_field["f"]["quadrant"].endswith("-hot")
    assert by_field["f"]["readRate"] > 0
    for b in banks:
        assert 0.0 <= b["density"] <= 1.0
        assert b["quadrant"] in ("dense-hot", "dense-cold",
                                 "sparse-hot", "sparse-cold")
        assert b["demotionScore"] >= 0.0
    # Demotion ranking: sparse-cold outranks dense-hot.
    scores = [b["demotionScore"] for b in banks]
    assert scores == sorted(scores, reverse=True)


# -------------------------------------------------- coalescer + surfaces


def test_cross_request_repeats_through_coalescer(live_server):
    """Identical queries arriving in DIFFERENT flushes are invisible
    to in-batch dedup; the recorder's rolling window still counts
    them as cross-request repeats."""
    base, api, h = live_server
    _seed(h)
    WORKLOAD.reset()

    def post(q):
        return urllib.request.urlopen(
            base + "/index/ws/query", data=q.encode()).read()

    # Sequential requests: each lands in its own flush (no batchmates),
    # so any repeat counted is cross-request by construction.
    for _ in range(6):
        post("Count(Row(f=1))")
    win = WORKLOAD.requests_window.snapshot(WORKLOAD.clock())
    assert win["seen"] == 6
    assert win["repeats"] == 5
    # Concurrent burst of two identities keeps accounting consistent.
    threads = [threading.Thread(
        target=post, args=(f"Count(Row(f={i % 2}))",))
        for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    win = WORKLOAD.requests_window.snapshot(WORKLOAD.clock())
    assert win["seen"] == 14
    # f=1 was already live (4 burst arrivals all repeat); f=0 is a
    # fresh identity (first arrival unique, 3 repeats): 5 + 4 + 3.
    assert win["repeats"] == 12


def test_debug_hotspots_http_surface_and_metrics(live_server):
    base, api, h = live_server
    _seed(h)
    WORKLOAD.reset()
    WORKLOAD.stats = api.stats
    for i in range(8):
        urllib.request.urlopen(base + "/index/ws/query",
                               data=f"Count(Row(f={i % 2}))".encode()
                               ).read()
    doc = json.loads(urllib.request.urlopen(
        base + "/debug/hotspots").read())
    assert doc["enabled"] is True
    assert doc["totals"]["fragmentReads"] > 0
    assert doc["totals"]["fragmentReads"] == \
        doc["tracked"]["fragmentReads"] + \
        doc["evicted"]["fragmentReads"]
    assert doc["fragments"] and doc["signatures"]
    # ?topk bounds the lists.
    doc1 = json.loads(urllib.request.urlopen(
        base + "/debug/hotspots?topk=1").read())
    assert len(doc1["fragments"]) == 1
    # Counter families + the repeat-ratio gauge on /metrics.
    met = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# TYPE pilosa_fragment_reads_total counter" in met
    assert "pilosa_fragment_reads_total" in met
    assert "# TYPE pilosa_query_repeat_ratio gauge" in met
    # Write churn counter appears once a write lands.
    urllib.request.urlopen(base + "/index/ws/query",
                           data=b"Set(9, f=1)").read()
    met = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "pilosa_fragment_writes_total" in met
    # Single-node /cluster/hotspots serves the same totals.
    ch = json.loads(urllib.request.urlopen(
        base + "/cluster/hotspots").read())
    assert ch["totalNodes"] == ch["respondedNodes"] == 1
    assert ch["totals"]["fragmentReads"] == \
        json.loads(urllib.request.urlopen(
            base + "/debug/hotspots").read())["totals"]["fragmentReads"]


def test_slow_ring_hot_fragments_annotation(tmp_holder):
    """Slow-query ring records carry hotFragments: the recorder's
    current standings for exactly the fragments that query touched."""
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    api.executor.result_cache.enabled = False  # repeats must stage
    api.long_query_time = 1e-9  # everything is "slow"
    WORKLOAD.reset()
    for _ in range(3):
        api.query("ws", "Count(Row(f=1))")
    recs = api.profiler.slow_queries()
    assert recs and "hotFragments" in recs[0]
    hot = recs[0]["hotFragments"]
    assert hot[0]["index"] == "ws" and hot[0]["field"] == "f"
    assert hot[0]["reads"] >= 1
    assert all(h["field"] == "f" for h in hot)  # only touched frags


def test_health_stanza_and_publish(tmp_holder):
    _seed(tmp_holder)
    api = API(tmp_holder, stats=MemStatsClient())
    WORKLOAD.reset()
    api.query("ws", "Count(Row(f=1))")
    doc = api.node_health()
    wl = doc["workload"]
    assert wl["enabled"] is True
    assert wl["fragmentReads"] == 2  # two shards
    assert wl["queries"] == 1
    assert wl["trackedSignatures"] == 1
    # Fleet totals pick the workload counters up.
    ch = api.cluster_health()
    assert ch["totals"]["fragmentReads"] == 2
    # publish() exports the scrape-time gauges.
    api.refresh_memory_gauges()
    out = prometheus_text(api.stats)
    assert "pilosa_query_repeat_ratio" in out
    assert "pilosa_workload_tracked_fragments" in out
