"""Kernel cost & roofline attribution plane (ops/megakernel.plan_cost
+ utils/roofline.py + the executor/metrics wiring): exact hand-computed
byte arithmetic over the full opcode table, the zero-new-fences
acceptance bar on the unsampled path, the /metrics family and label
invariants, the predicted-vs-measured drift detector, and the recorder
bounds (LRU cohorts, memory-ledger registration)."""

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor import megakernel as megamod
from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.utils.memledger import MemoryLedger
from pilosa_tpu.utils.roofline import (
    DRIFT_MARGIN, ROOFLINE, RooflineRecorder,
)
from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text


@pytest.fixture(autouse=True)
def _reset_roofline():
    """The recorder is process-wide (like timeline.TIMELINE): every
    test starts clean and leaves defaults behind."""
    ROOFLINE.reset()
    ROOFLINE.configure(enabled=True, gbps=0.0, ewma_alpha=0.25,
                       max_cohorts=256)
    ROOFLINE.note_sample_every(0)
    yield
    ROOFLINE.reset()
    ROOFLINE.configure(enabled=True, gbps=0.0, ewma_alpha=0.25,
                       max_cohorts=256)
    ROOFLINE.note_sample_every(0)


# --------------------------------------------------- plan_cost arithmetic


def _plan(*, n_slots, widths, instrs, n_instrs, n_regs, out_count,
          out_row, lane_count_widths=(), lane_row_widths=(),
          slots=None, xbanks=(), xslots=(), n_xslots=0):
    """Hand-built Plan: plan_cost reads only host-side fields, so dense
    banks can be empty stand-ins."""
    if slots is None:
        slots = tuple(np.array([i], np.int32) for i in range(n_slots))
    w = np.zeros(n_regs, np.int32)
    w[:len(widths)] = widths
    return mk.Plan(
        banks=tuple(None for _ in range(n_slots)), slots=slots,
        widths=w, instrs=np.asarray(instrs, np.int32),
        out_count=np.asarray(out_count, np.int32),
        out_row=np.asarray(out_row, np.int32),
        n_slots=n_slots, n_regs=n_regs, n_instrs=n_instrs,
        lane_count_widths=lane_count_widths,
        lane_row_widths=lane_row_widths,
        xbanks=xbanks, xslots=xslots, n_xslots=n_xslots)


def test_plan_cost_full_opcode_table_exact():
    """Every opcode priced by its verifier read set: ZERO writes only
    (1 row), COPY reads one (2), AND/OR/XOR/ANDNOT read two (3),
    THRESH is the accumulate opcode — dst is a READ operand too (4)."""
    S, W = 2, 8
    row = S * W * 4                                   # 64
    instrs = [
        (mk.OP_AND, 2, 0, 1), (mk.OP_OR, 3, 0, 1),
        (mk.OP_XOR, 4, 0, 1), (mk.OP_ANDNOT, 5, 2, 3),
        (mk.OP_ZERO, 6, 0, 0), (mk.OP_COPY, 2, 4, 0),
        (mk.OP_THRESH, 6, 2, 3),
        (mk.OP_ZERO, 7, 7, 7),                        # pad tail
    ]
    plan = _plan(n_slots=2, widths=[3, 8], instrs=instrs, n_instrs=7,
                 n_regs=8, out_count=[6, 7], out_row=[4],
                 lane_count_widths=(5,), lane_row_widths=(8,))
    cost = mk.plan_cost(plan, S, W)
    # Gather: per dense slot, live masked words read + one row written.
    assert cost["gatherBytes"] == (S * 3 * 4 + row) + (S * 8 * 4 + row)
    # Compute: 4 three-operand ops + ZERO(1) + COPY(2) + THRESH(4),
    # plus 1 real count lane (popcount row + S*4 out) and 1 real row
    # lane (2 rows).
    assert cost["computeBytes"] == (4 * 3 * row + 1 * row + 2 * row
                                    + 4 * row
                                    + (row + S * 4) + 2 * row)
    assert cost["expandBytes"] == 0
    # Pad: 1 slab register above the high-water mark (the spare), 1 pad
    # instruction, 1 pad count lane; row lanes have no padding.
    assert cost["padBytes"] == row + row + (row + S * 4)
    assert cost["totalBytes"] == (cost["gatherBytes"]
                                  + cost["computeBytes"]
                                  + cost["expandBytes"]
                                  + cost["padBytes"])
    assert cost["opcodeHist"] == {"and": 1, "or": 1, "xor": 1,
                                  "andnot": 1, "zero": 1, "copy": 1,
                                  "thresh": 1}   # REAL instrs only
    assert cost["nInstrs"] == 7
    # Ledger restatement: slab/live-slab/plan bytes as registered.
    assert cost["slabBytes"] == mk.slab_nbytes(8, S, W)
    assert cost["liveSlabBytes"] == mk.slab_nbytes(2, S, W)
    assert cost["planBytes"] == plan.plan_nbytes


def test_plan_cost_expand_scatter_exact():
    """OP_EXPAND traffic: per expand register the sparse bank's full
    (pos, starts) buffers + one scatter-written row; per instruction
    one row read + one written."""
    S, W = 2, 8
    row = S * W * 4
    pos = np.zeros(10, np.int32)                      # 40 bytes
    starts = np.zeros(5, np.int32)                    # 20 bytes
    instrs = [
        (mk.OP_EXPAND, 4, 1, 0), (mk.OP_EXPAND, 5, 2, 0),
        (mk.OP_AND, 6, 4, 5),
        (mk.OP_ZERO, 7, 7, 7),                        # pad tail
    ]
    plan = _plan(n_slots=1, widths=[4], instrs=instrs, n_instrs=3,
                 n_regs=8, out_count=[], out_row=[6],
                 lane_row_widths=(4,),
                 xbanks=((pos, starts),),
                 xslots=(np.array([0, 1], np.int32),), n_xslots=2)
    cost = mk.plan_cost(plan, S, W)
    assert cost["gatherBytes"] == S * 4 * 4 + row
    # 2 expand instrs * 2 rows + 2 expand regs * (pos + starts + row).
    assert cost["expandBytes"] == 2 * 2 * row \
        + 2 * (pos.nbytes + starts.nbytes + row)
    assert cost["computeBytes"] == 3 * row + 2 * row  # AND + row lane
    assert cost["padBytes"] == row + row              # spare + pad instr
    assert cost["liveSlabBytes"] == mk.slab_nbytes(3, S, W)  # slot+2x


def test_plan_cost_zero_reads_opaque_xbank_buffers():
    """Device-opaque (pos, starts) stubs without .nbytes price as 0
    instead of raising — attribution never kills a launch."""
    S, W = 1, 4

    class _Opaque:  # no nbytes, no shape
        pass

    plan = _plan(n_slots=0, widths=[], slots=(),
                 instrs=[(mk.OP_EXPAND, 1, 0, 0)], n_instrs=1,
                 n_regs=4, out_count=[], out_row=[1],
                 lane_row_widths=(4,),
                 xbanks=((_Opaque(), _Opaque()),),
                 xslots=(np.array([0], np.int32),), n_xslots=1)
    cost = mk.plan_cost(plan, S, W)
    row = S * W * 4
    assert cost["expandBytes"] == 2 * row + 1 * row   # buffers priced 0
    assert cost["totalBytes"] > 0


# ------------------------------------------------------ live mega wiring


N_ROWS = 8
MIXED = ([("i", f"Count(Row(f={r}))", None) for r in (1, 2, 3)]
         + [("i", f"Row(g={r})", None) for r in (4, 5)]
         + [("i", "Count(Intersect(Row(f=6), Row(g=7)))", None)])


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(23)
    rows = rng.integers(0, N_ROWS, 4000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 4000).astype(np.uint64)
    f.import_bits(rows, cols)
    g.import_bits(rows[::2], cols[::2])
    idx.add_existence(cols)
    executor = Executor(h)
    executor.result_cache.enabled = False
    prev = megamod.MEGAKERNEL_ENABLED
    megamod.MEGAKERNEL_ENABLED = True
    yield executor
    megamod.MEGAKERNEL_ENABLED = prev
    h.close()


def test_zero_new_fences_on_unsampled_path(ex, monkeypatch):
    """Acceptance: the cost/roofline plane adds NO block_until_ready
    fences — bytes are recorded for every launch, bandwidth only when
    a profiler-sampled fence already fires."""
    import pilosa_tpu.executor.executor as exmod

    fences = []
    monkeypatch.setattr(exmod, "_fence_device",
                        lambda out: fences.append(1) or 0.0)
    ex.execute_batch_shaped(MIXED)
    assert fences == []
    assert ex.mega_launches == 1
    snap = ROOFLINE.snapshot()
    assert snap["launches"] == 1          # cost recorded fence-free
    assert snap["fencedLaunches"] == 0    # ...but no bandwidth sample
    assert snap["bytesByKind"]["gather"] > 0
    assert ex.launch_bytes_gather > 0 and ex.launch_bytes_compute > 0


def test_launch_cost_metrics_families(ex):
    """/metrics invariants: the byte splits export as one counter
    family split by kind=, opcodes as one family split by op= — never
    a family per kind/op (bounded label sets, test_stats.py rules)."""
    from pilosa_tpu.utils.profile import QueryProfile

    ex.stats = MemStatsClient()
    profs = [QueryProfile(i, q, sample_device=True)
             for i, q, _s in MIXED]
    ex.execute_batch_shaped(MIXED, profiles=profs)
    ROOFLINE.publish(ex.stats)
    prom = prometheus_text(ex.stats)
    for kind in ("gather", "compute", "pad"):
        assert f'pilosa_executor_launch_bytes_total{{kind="{kind}"}}' \
            in prom, prom
    assert 'pilosa_executor_opcode_total{op="' in prom
    assert prom.count("# TYPE pilosa_executor_launch_bytes_total") == 1
    assert prom.count("# TYPE pilosa_executor_opcode_total") == 1
    assert "pilosa_roofline_gbps" in prom
    assert "pilosa_roofline_fraction" in prom
    assert "pilosa_roofline_achieved_gbps" in prom
    # The executor's totals agree with the recorder's.
    snap = ROOFLINE.snapshot()
    assert snap["bytesByKind"]["gather"] == ex.launch_bytes_gather
    assert snap["opcodeTotals"] == ex.opcode_counts
    assert snap["fencedLaunches"] == 1
    assert snap["achievedGbps"] > 0


def test_cost_rides_profile_tree_and_slow_ring(ex):
    """Satellite: eval nodes of a megakernel launch carry launchBytes +
    opcodeHist, so the slow-query ring shows what a launch MOVED."""
    from pilosa_tpu.utils.profile import QueryProfile

    profs = [QueryProfile(i, q) for i, q, _s in MIXED]
    ex.execute_batch(MIXED, profiles=profs)
    assert ex.mega_launches == 1
    for p in profs:
        evals = [n for op in p.ops for n in op.children
                 if n.name.startswith("eval:")]
        assert evals, p.ops
        node = evals[0]
        assert node.attrs["launchBytes"] > 0
        assert isinstance(node.attrs["opcodeHist"], dict)
        assert sum(node.attrs["opcodeHist"].values()) > 0


# ------------------------------------------------------- drift detector


def _cost(total):
    return {"gatherBytes": total, "computeBytes": 0, "expandBytes": 0,
            "padBytes": 0, "totalBytes": total,
            "opcodeHist": {"and": 1}, "nInstrs": 1}


def test_drift_detector_flags_inverted_cohorts():
    """Predicted says A cheaper than B (margin 1.25 on both axes);
    measured fences say the opposite -> both cohorts flagged, the
    counter increments once per transition, re-agreement clears the
    gauge but not the counter."""
    rec = RooflineRecorder(ewma_alpha=1.0)
    rec.configure(enabled=True, gbps=100.0, ewma_alpha=1.0)
    rec.note_launch("A", _cost(100_000), predicted_bytes=100_000)
    rec.note_device("A", 100_000, 0.001)
    assert rec.snapshot()["driftFlags"] == 0   # nothing to compare yet
    # B predicted 2x A's bytes but measured 2.5x FASTER: inversion.
    assert 200_000 > 100_000 * DRIFT_MARGIN
    rec.note_launch("B", _cost(200_000), predicted_bytes=200_000)
    rec.note_device("B", 200_000, 0.0004)
    snap = rec.snapshot()
    assert snap["driftFlags"] == 2             # both sides flagged
    assert all(c["drift"] for c in snap["cohorts"])
    # Residuals rank drift-flagged cohorts first.
    assert snap["residuals"][0]["drift"]
    # Stats counter sees the transitions exactly once.
    stats = MemStatsClient()
    rec.publish(stats)
    rec.publish(stats)  # no new transitions -> no double count
    prom = prometheus_text(stats)
    assert "pilosa_roofline_drift_total 2" in prom, prom
    assert "pilosa_roofline_drift_flagged 2" in prom
    # Measured ordering swings back (alpha=1.0: EWMA = latest): B now
    # slower than A, agreeing with the prediction -> flags clear.
    rec.note_device("B", 200_000, 0.005)
    rec.note_device("A", 100_000, 0.001)
    snap = rec.snapshot()
    assert not any(c["drift"] for c in snap["cohorts"])
    assert snap["driftFlags"] == 2             # history, not state
    rec.publish(stats)
    assert "pilosa_roofline_drift_flagged 0" in prometheus_text(stats)


def test_cohort_lru_bound_and_ledger_registration():
    rec = RooflineRecorder(max_cohorts=2)
    for key in ("A", "B", "C"):
        rec.note_launch(key, _cost(1000))
    snap = rec.snapshot()
    assert len(snap["cohorts"]) == 2
    assert {c["cohort"] for c in snap["cohorts"]} == {"B", "C"}
    led = MemoryLedger()
    rec.register_memory(led)
    tel = led.totals()["telemetry"]
    assert tel["bytes"] == rec.state_nbytes() > 0


def test_device_seconds_estimate_scales_by_sample_rate():
    """Satellite 1: the sampled device-seconds sum is 1-in-N biased;
    the snapshot carries the rate and the scaled unbiased estimate,
    while achieved GB/s comes from per-fence pairs (unbiased as-is)."""
    rec = RooflineRecorder()
    rec.configure(enabled=True, gbps=10.0)
    rec.note_sample_every(4)
    rec.note_launch("A", _cost(10_000_000))
    rec.note_device("A", 10_000_000, 0.001)
    snap = rec.snapshot()
    assert snap["deviceSampleEvery"] == 4
    assert snap["deviceSecondsSampled"] == pytest.approx(0.001)
    assert snap["deviceSecondsEstimate"] == pytest.approx(0.004)
    assert snap["achievedGbps"] == pytest.approx(10.0)  # 10MB in 1ms
    assert snap["rooflineFraction"] == pytest.approx(1.0)


def test_unattributed_fences_counted():
    """Fused/unfused fences carry no plan IR: the surface states its
    own coverage instead of silently claiming all device time."""
    rec = RooflineRecorder()
    rec.note_unattributed_fence(0.002)
    rec.note_unattributed_fence(0.0)   # ignored: unusable
    snap = rec.snapshot()
    assert snap["unattributedFences"] == 1
    assert snap["unattributedDeviceSeconds"] == pytest.approx(0.002)


def test_disabled_recorder_records_nothing():
    rec = RooflineRecorder()
    rec.configure(enabled=False)
    rec.note_launch("A", _cost(1000), predicted_bytes=1000)
    assert rec.note_device("A", 1000, 0.001) is None
    rec.note_unattributed_fence(0.001)
    snap = rec.snapshot()
    assert snap["launches"] == 0 and snap["fencedLaunches"] == 0
    assert snap["unattributedFences"] == 0


def test_roofline_gbps_source_precedence():
    rec = RooflineRecorder()
    assert rec.roofline_gbps() == (0.0, "unresolved", True)
    assert rec.needs_resolve()
    rec.set_resolved(819.0, "cpu", True)
    assert rec.roofline_gbps() == (819.0, "cpu", True)
    assert not rec.needs_resolve()
    rec.configure(gbps=1640.0)         # config wins over resolution
    assert rec.roofline_gbps() == (1640.0, "config", False)
    assert not rec.needs_resolve()


# --------------------------------------------------- optimizer calibration


def test_optimizer_records_predicted_bytes(ex, monkeypatch):
    """Calibration feed: every optimized plan carries the density-
    predicted byte cost the drift detector compares against."""
    from pilosa_tpu.ops import plan_opt

    captured = []
    orig = plan_opt.optimize_plan

    def spy(plan, n_shards, w_mega):
        out_plan, stats = orig(plan, n_shards, w_mega)
        captured.append((out_plan, stats))
        return out_plan, stats

    monkeypatch.setattr(plan_opt, "optimize_plan", spy)
    monkeypatch.setattr(megamod, "PLAN_OPT_ENABLED", True)
    ex.execute_batch_shaped(MIXED)
    assert captured
    out_plan, stats = captured[0]
    assert stats.predicted_bytes > 0
    assert stats.as_dict()["predictedBytes"] == stats.predicted_bytes
    # The attached stats ride the plan into _launch's note_launch.
    assert out_plan.opt_stats is stats
    cohorts = ROOFLINE.snapshot()["cohorts"]
    assert cohorts and cohorts[0]["predictedBytesEwma"] == \
        pytest.approx(stats.predicted_bytes)


def test_predict_cost_bytes_density_weighting():
    """The host-side predictor prices reads by operand density: a
    dense-read AND costs more than the same AND over sparse operands,
    and every instruction pays its full row write."""
    from pilosa_tpu.ops.plan_opt import (
        SPARSE_DENSITY, predict_cost_bytes,
    )

    S, W = 2, 8
    row = S * W * 4
    rows = [(mk.OP_AND, 2, 0, 1)]
    dense = predict_cost_bytes(rows, {0: 1.0, 1: 1.0}, S, W)
    sparse = predict_cost_bytes(
        rows, {0: SPARSE_DENSITY, 1: SPARSE_DENSITY}, S, W)
    assert dense == int((1.0 + 1.0 + 1.0) * row)
    assert sparse == int((2 * SPARSE_DENSITY + 1.0) * row)
    assert sparse < dense


# ------------------------------------------------------------- shutdown


def test_dump_writes_printf_lines():
    rec = RooflineRecorder()
    rec.configure(enabled=True, gbps=100.0)
    rec.note_launch("A", _cost(1000), predicted_bytes=1000)
    rec.note_device("A", 1000, 0.001)

    lines = []

    class _Log:
        def printf(self, fmt, *args):
            lines.append(fmt % args if args else fmt)

    assert rec.dump(_Log()) >= 2
    assert all(ln.startswith("roofline:") for ln in lines)
    assert any("residual" in ln for ln in lines)
    # Nothing recorded -> nothing written (quiet shutdowns stay quiet).
    assert RooflineRecorder().dump(_Log()) == 0


def test_config_roofline_keys(tmp_path):
    from pilosa_tpu.utils.config import load_config
    p = tmp_path / "c.toml"
    p.write_text("[roofline]\nenabled = false\ngbps = 1640.0\n"
                 "ewma_alpha = 0.5\nmax_cohorts = 32\n")
    cfg = load_config(str(p))
    assert cfg.roofline_enabled is False
    assert cfg.roofline_gbps == 1640.0
    assert cfg.roofline_ewma_alpha == 0.5
    assert cfg.roofline_max_cohorts == 32
    with pytest.raises(ValueError):
        load_config(None, {"roofline_gbps": -1.0})
    with pytest.raises(ValueError):
        load_config(None, {"roofline_ewma_alpha": 0.0})
    with pytest.raises(ValueError):
        load_config(None, {"roofline_max_cohorts": 0})
