"""PQL parser tests — shapes mirror the reference's parser behavioral spec."""

import pytest

from pilosa_tpu.pql import parse_string, ParseError
from pilosa_tpu.pql.ast import BETWEEN, Condition


def one(src):
    q = parse_string(src)
    assert len(q.calls) == 1
    return q.calls[0]


def test_row():
    c = one("Row(f=10)")
    assert c.name == "Row" and c.args == {"f": 10}


def test_row_key():
    c = one('Row(f="ten")')
    assert c.args == {"f": "ten"}


def test_nested_bitmap_ops():
    c = one("Intersect(Row(a=1), Union(Row(b=2), Row(c=3)))")
    assert c.name == "Intersect"
    assert [ch.name for ch in c.children] == ["Row", "Union"]
    assert c.children[1].children[0].args == {"b": 2}


def test_set_and_clear():
    c = one("Set(100, f=1)")
    assert c.name == "Set" and c.args == {"_col": 100, "f": 1}
    c = one("Set('colkey', f=1)")
    assert c.args["_col"] == "colkey"
    c = one("Set(100, f=1, 2018-03-04T05:06)")
    assert c.args["_timestamp"] == "2018-03-04T05:06"
    c = one("Clear(7, f=3)")
    assert c.name == "Clear" and c.args == {"_col": 7, "f": 3}


def test_clear_row_and_store():
    c = one("ClearRow(f=5)")
    assert c.name == "ClearRow" and c.args == {"f": 5}
    c = one("Store(Row(f=9), g=2)")
    assert c.name == "Store"
    assert c.children[0].name == "Row" and c.args == {"g": 2}


def test_topn():
    c = one("TopN(f, n=25)")
    assert c.name == "TopN" and c.args == {"_field": "f", "n": 25}
    c = one("TopN(f)")
    assert c.args == {"_field": "f"}
    c = one("TopN(f, Row(other=7), n=10)")
    assert c.children[0].name == "Row" and c.args["n"] == 10


def test_rows():
    c = one("Rows(f, previous=42, limit=10, column=3)")
    assert c.args == {"_field": "f", "previous": 42, "limit": 10, "column": 3}


def test_groupby():
    c = one("GroupBy(Rows(a), Rows(b), limit=10, filter=Row(c=1))")
    assert c.name == "GroupBy"
    assert [ch.name for ch in c.children] == ["Rows", "Rows"]
    assert c.args["limit"] == 10
    assert c.args["filter"].name == "Row"


def test_conditions():
    for src, op, val in [
        ("Row(n > 5)", ">", 5),
        ("Row(n >= 5)", ">=", 5),
        ("Row(n < -3)", "<", -3),
        ("Row(n <= 0)", "<=", 0),
        ("Row(n == 9)", "==", 9),
        ("Row(n != 9)", "!=", 9),
    ]:
        c = one(src)
        cond = c.args["n"]
        assert isinstance(cond, Condition) and (cond.op, cond.value) == (op, val)


def test_between_forms():
    c = one("Row(n >< [4, 8])")
    assert c.args["n"].op == BETWEEN and c.args["n"].value == [4, 8]
    # conditional form, '<' bumps bounds inward (reference endConditional)
    c = one("Row(4 < n < 9)")
    assert c.args["n"].op == BETWEEN and c.args["n"].value == [5, 8]
    c = one("Row(4 <= n <= 9)")
    assert c.args["n"].value == [4, 9]


def test_set_row_attrs():
    c = one('SetRowAttrs(f, 10, color="blue", happy=true, age=18, x=null)')
    assert c.args == {"_field": "f", "_row": 10, "color": "blue",
                      "happy": True, "age": 18, "x": None}


def test_set_column_attrs():
    c = one('SetColumnAttrs(9, name="bob", active=false)')
    assert c.args == {"_col": 9, "name": "bob", "active": False}


def test_value_types():
    c = one('Opts(a=1, b=-2, c=1.5, d=-0.5, e=[1,2,3], f="q\\"x", g=tok-en_1)')
    assert c.args["a"] == 1 and c.args["b"] == -2
    assert c.args["c"] == 1.5 and c.args["d"] == -0.5
    assert c.args["e"] == [1, 2, 3]
    assert c.args["f"] == 'q"x'
    assert c.args["g"] == "tok-en_1"


def test_multiple_calls():
    q = parse_string(" Set(1, f=2)\n Row(f=2) ")
    assert [c.name for c in q.calls] == ["Set", "Row"]
    assert q.write_calls()[0].name == "Set"


def test_time_range_row():
    c = one("Row(f=1, from='2018-01-01T00:00', to='2019-01-01T00:00')")
    assert c.args["from"] == "2018-01-01T00:00"


def test_parse_errors():
    for bad in ["Row(", "Row)", "Set(1 f=2)", "Row(f=)", "Row(=3)", "Foo", "5"]:
        with pytest.raises(ParseError):
            parse_string(bad)


def test_call_as_value():
    c = one("Count(Distinct(Row(f=1), field=other))")
    assert c.children[0].name == "Distinct"
    assert c.children[0].children[0].name == "Row"


def test_parse_cache_clones_are_isolated():
    """parse_string_cached clones must not share any mutable structure
    with the cached tree: the executor's key translation writes
    resolved ids into args in place — including nested filter Calls,
    `previous` lists, and Condition list values (code-review r4: a
    shallow clone leaked the first execution's ids into every later
    one)."""
    from pilosa_tpu.pql import parse_string_cached

    src = ('GroupBy(Rows(f), filter=Row(color="red"), previous=["a"]) '
           'Row(v == 3)')
    a = parse_string_cached(src)
    b = parse_string_cached(src)
    ga, gb = a.calls[0], b.calls[0]
    # Mutate everything translation mutates, through clone a only.
    ga.args["filter"].args["color"] = 7
    ga.args["previous"][0] = 42
    ga.children[0].args["_field"] = "XX"
    ra = a.calls[1]
    cond = next(v for v in ra.args.values()
                if hasattr(v, "op"))
    cond.value = 99
    # Clone b (and any future clone) still sees the pristine parse.
    assert gb.args["filter"].args["color"] == "red"
    assert gb.args["previous"] == ["a"]
    c = parse_string_cached(src)
    assert c.calls[0].args["filter"].args["color"] == "red"
    assert c.calls[0].args["previous"] == ["a"]
    condc = next(v for v in c.calls[1].args.values() if hasattr(v, "op"))
    assert condc.value == 3
