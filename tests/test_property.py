"""Property tests: random PQL trees evaluated by the executor must match
a naive numpy-set reference model (the analog of the reference's
programmatic query generators, internal/test/querygenerator.go)."""

import os

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops.bitset import SHARD_WIDTH

SEED_OFFSET = int(os.environ.get("PILOSA_TEST_SEED", 0))

N_FIELDS = 3
ROWS_PER_FIELD = 4
N_SHARDS = 2
DENSITY = 60  # bits per row


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("prop")
    h = Holder(str(tmp))
    h.open()
    idx = h.create_index("p")
    rng = np.random.default_rng(99 + SEED_OFFSET)
    model = {}  # (field, row) -> set of columns
    universe = set()
    for fi in range(N_FIELDS):
        fname = f"f{fi}"
        f = idx.create_field(fname)
        for row in range(ROWS_PER_FIELD):
            cols = rng.integers(0, N_SHARDS * SHARD_WIDTH, DENSITY,
                                dtype=np.uint64)
            cols = np.unique(cols)
            f.import_bits(np.full(len(cols), row, np.uint64), cols)
            model[(fname, row)] = set(cols.tolist())
            universe |= model[(fname, row)]
    idx.add_existence(np.array(sorted(universe), np.uint64))
    yield Executor(h), model, universe
    h.close()


def gen_tree(rng, depth):
    """Random call tree; returns (pql, eval_fn(model, universe) -> set)."""
    if depth == 0 or rng.random() < 0.35:
        fi = rng.integers(0, N_FIELDS)
        row = rng.integers(0, ROWS_PER_FIELD)
        return (f"Row(f{fi}={row})",
                lambda m, u, fi=fi, row=row: m[(f"f{fi}", int(row))])
    op = rng.choice(["Intersect", "Union", "Difference", "Xor", "Not"])
    if op == "Not":
        pql, fn = gen_tree(rng, depth - 1)
        return f"Not({pql})", lambda m, u, fn=fn: u - fn(m, u)
    k = int(rng.integers(2, 4))
    subs = [gen_tree(rng, depth - 1) for _ in range(k)]
    pql = f"{op}({', '.join(s[0] for s in subs)})"

    def ev(m, u, subs=subs, op=op):
        sets = [s[1](m, u) for s in subs]
        if op == "Intersect":
            out = sets[0]
            for s in sets[1:]:
                out = out & s
        elif op == "Union":
            out = set().union(*sets)
        elif op == "Difference":
            out = sets[0]
            for s in sets[1:]:
                out = out - s
        else:  # Xor
            out = sets[0]
            for s in sets[1:]:
                out = out ^ s
        return out

    return pql, ev


def test_random_trees_match_set_model(world):
    ex, model, universe = world
    rng = np.random.default_rng(123 + SEED_OFFSET)
    for i in range(40):
        pql, ev = gen_tree(rng, depth=3)
        want = ev(model, universe)
        (got,) = ex.execute("p", pql)
        got_cols = set(got.columns().tolist())
        assert got_cols == want, f"iter {i}: {pql}"
        # Count() over the same tree agrees
        (cnt,) = ex.execute("p", f"Count({pql})")
        assert cnt == len(want), f"iter {i}: Count({pql})"


def test_random_trees_batched_query(world):
    """All trees in ONE multi-call query string — exercises the
    dispatch-then-fetch pipeline shape at property scale."""
    ex, model, universe = world
    rng = np.random.default_rng(7 + SEED_OFFSET)
    trees = [gen_tree(rng, depth=2) for _ in range(12)]
    results = ex.execute("p", " ".join(f"Count({p})" for p, _ in trees))
    for (pql, ev), got in zip(trees, results):
        assert got == len(ev(model, universe)), pql


def test_shard_scoped_queries_match(world):
    """Options(shards=[...]) restricts evaluation to given shards."""
    ex, model, universe = world
    pql = "Row(f0=1)"
    full = model[("f0", 1)]
    (got,) = ex.execute("p", f"Options({pql}, shards=[0])")
    want = {c for c in full if c // SHARD_WIDTH == 0}
    assert set(got.columns().tolist()) == want


def test_random_ops_with_interleaved_optimize(tmp_path):
    """Random add/remove batches interleaved with optimize() (encoding
    flips) must always match a python-set model — the dual-encoding
    equivalence property (reference container conversions,
    roaring.go:1927-2100)."""
    from pilosa_tpu.storage.roaring import ARRAY_MAX_SIZE, Bitmap

    rng = np.random.default_rng(3 + SEED_OFFSET)
    b = Bitmap()
    model = set()
    universe = 5 << 16
    for step in range(60):
        kind = rng.random()
        batch = rng.integers(0, universe,
                             rng.integers(1, 2000), dtype=np.uint64)
        if kind < 0.45:
            b.direct_add_n(batch)
            model |= set(batch.tolist())
        elif kind < 0.8:
            b.direct_remove_n(batch)
            model -= set(batch.tolist())
        elif kind < 0.9:
            # dense run to push some containers past ARRAY_MAX_SIZE
            start = int(rng.integers(0, universe - ARRAY_MAX_SIZE * 2))
            run = np.arange(start, start + ARRAY_MAX_SIZE * 2,
                            dtype=np.uint64)
            b.direct_add_n(run)
            model |= set(run.tolist())
        else:
            b.optimize()
        if step % 7 == 0:
            b.optimize()
            assert b.count() == len(model)
            got = set(b.slice().tolist())
            assert got == model, (len(got), len(model))
            # spot-check point reads across encodings
            for p in rng.integers(0, universe, 20, dtype=np.uint64):
                assert b.contains(int(p)) == (int(p) in model)
    # serialization equivalence at the end state
    assert set(Bitmap.from_bytes(b.write_bytes()).slice().tolist()) == model
