"""HBM memory ledger + watchdog invariants (utils/memledger.py).

The invariants that make /debug/memory trustworthy: registered bytes
return to baseline after bank evict/replace/close, jit-cache eviction
decrements the gauge, the /debug/memory totals equal the sum of the
per-category totals, and the watchdog samples without ever touching
the device.
"""

import gc
import json
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.ops.bitset import SHARD_WIDTH
from pilosa_tpu.utils.memledger import (
    LEDGER, MemoryLedger, MemoryWatchdog,
)


class _LogStub:
    def __init__(self):
        self.lines = []

    def printf(self, fmt, *args):
        self.lines.append(fmt % args if args else fmt)

    debugf = printf


def _cat(ledger, name):
    return ledger.totals().get(name,
                               {"bytes": 0, "paddedBytes": 0, "count": 0})


# ------------------------------------------------------------- pure ledger


def test_register_replace_unregister_totals():
    led = MemoryLedger()
    led.register("bank", "k1", 100, padded_bytes=20, index="i")
    led.register("bank", "k2", 50)
    assert _cat(led, "bank") == {"bytes": 150, "paddedBytes": 20,
                                 "count": 2}
    # Same-key registration REPLACES (the bank-rebuild path): totals
    # must not double-count.
    led.register("bank", "k1", 200, padded_bytes=10)
    assert _cat(led, "bank") == {"bytes": 250, "paddedBytes": 10,
                                 "count": 2}
    led.unregister("bank", "k1")
    led.unregister("bank", "k1")  # idempotent (evict races close)
    led.unregister("bank", "k2")
    assert _cat(led, "bank") == {"bytes": 0, "paddedBytes": 0,
                                 "count": 0}
    # Categories persist at zero so exported gauges drop to 0 instead
    # of disappearing between scrapes.
    assert "bank" in led.totals()


def test_snapshot_total_equals_category_sum_and_top():
    led = MemoryLedger()
    led.register("bank", "a", 300, padded_bytes=50, field="big")
    led.register("pbank", "b", 100, shard=0)
    led.register("jit_cache", "c", 0)
    snap = led.snapshot(top_k=5)
    assert snap["totalBytes"] == sum(
        c["bytes"] for c in snap["categories"].values()) == 400
    assert snap["paddingBytes"] == 50
    # top is byte-ordered and excludes zero-byte entries (jit slots).
    assert [e["bytes"] for e in snap["top"]] == [300, 100]
    assert snap["top"][0]["field"] == "big"


def test_owner_scoped_entries_purge_on_gc():
    led = MemoryLedger()

    class Owner:
        pass

    o = Owner()
    led.register("bank", "k", 64, owner=o)
    led.track(o, "pending", 32)
    assert led.total_bytes() == 96
    del o
    gc.collect()
    assert led.total_bytes() == 0


def test_bare_key_unregister_cleans_owner_set():
    """Eviction paths unregister by bare scoped key (no owner in
    hand); the owner's key-set must shrink anyway or a long-lived
    view's bookkeeping grows without bound."""
    led = MemoryLedger()

    class Owner:
        pass

    o = Owner()
    led.register("bank", "k", 64, owner=o)
    assert led._owned[id(o)]
    led.unregister("bank", (id(o), "k"))  # how BankBudget evicts
    assert not led._owned[id(o)]
    assert led.total_bytes() == 0


def test_host_categories_excluded_from_device_bytes():
    led = MemoryLedger()
    led.register("bank", "d", 100)
    led.register("host_block", "h", 1000)
    assert led.total_bytes() == 1100
    assert led.total_bytes(device_only=True) == 100


# ----------------------------------------------------- bank lifecycle wiring


def test_bank_bytes_return_to_baseline_after_close(tmp_holder):
    gc.collect()  # settle prior tests' dropped owners first
    before = _cat(LEDGER, "bank")["bytes"]
    idx = tmp_holder.create_index("ml")
    f = idx.create_field("f")
    f.import_bits(np.array([1, 1, 2], np.uint64),
                  np.array([1, 2, SHARD_WIDTH + 3], np.uint64))
    from pilosa_tpu.executor import Executor
    ex = Executor(tmp_holder)
    assert ex.execute("ml", "Count(Row(f=1))") == [2]
    assert _cat(LEDGER, "bank")["bytes"] > before
    tmp_holder.delete_index("ml")
    assert _cat(LEDGER, "bank")["bytes"] == before


def test_bank_replace_reregisters_not_double_counts(tmp_holder):
    idx = tmp_holder.create_index("mr")
    f = idx.create_field("f")
    f.import_bits(np.array([1, 2], np.uint64),
                  np.array([5, 6], np.uint64))
    from pilosa_tpu.executor import Executor
    ex = Executor(tmp_holder)
    ex.execute("mr", "Count(Row(f=1))")
    c1 = _cat(LEDGER, "bank")
    # A write bumps the fragment version; the next query rebuilds or
    # patches the cached bank under the SAME ledger key.
    ex.execute("mr", "Set(7, f=1)")
    ex.execute("mr", "Count(Row(f=1))")
    c2 = _cat(LEDGER, "bank")
    assert c2["count"] == c1["count"]
    assert c2["bytes"] == c1["bytes"]  # same capacity -> same footprint
    tmp_holder.delete_index("mr")


def test_bank_eviction_unregisters(tmp_holder):
    """When a bank budget evicts a cached bank, its ledger entry goes
    with it — the ledger mirrors residency, not history. Exercised on
    a dedicated BankBudget (same eviction code path as the process
    BANK_BUDGET) so the test cannot storm-evict other tests' banks."""
    from pilosa_tpu.core.view import BankBudget
    from pilosa_tpu.executor import Executor
    idx = tmp_holder.create_index("me")
    idx.create_field("f").import_bits(
        np.array([1], np.uint64), np.array([1], np.uint64))
    ex = Executor(tmp_holder)
    ex.execute("me", "Count(Row(f=1))")
    view = idx.field("f").view()
    key = next(iter(view._bank_cache))
    gc.collect()  # settle other tests' dropped owners first
    b1 = _cat(LEDGER, "bank")
    small = BankBudget(1)
    small.admit(view, key)       # over budget alone: stays (LRU floor)
    small.admit(view, "other", nbytes=8)  # second entry evicts `key`
    assert small.evictions == 1
    assert key not in view._bank_cache
    b2 = _cat(LEDGER, "bank")
    assert b2["count"] == b1["count"] - 1
    assert b2["bytes"] < b1["bytes"]
    small.forget(view, "other")
    tmp_holder.delete_index("me")


def test_jit_cache_eviction_decrements_gauge(tmp_holder):
    from pilosa_tpu.executor import Executor
    gc.collect()  # settle prior tests' dropped executors first
    before = _cat(LEDGER, "jit_cache")["count"]
    ex = Executor(tmp_holder)
    ex.JIT_CACHE_MAX = 2
    for i in range(5):
        ex._jit_put(f"sig{i}", lambda: None)
    assert ex.jit_cache_size() == 2
    # Evicted programs left the ledger with the cache.
    assert _cat(LEDGER, "jit_cache")["count"] == before + 2
    del ex
    gc.collect()
    assert _cat(LEDGER, "jit_cache")["count"] == before


def test_fusion_pad_lanes_ledgered_and_released(tmp_holder):
    """A non-pow2 fused batch registers its pad lanes as padding bytes
    for the group's lifetime, and releases them when results shape."""
    from pilosa_tpu.executor import Executor
    idx = tmp_holder.create_index("mf")
    f = idx.create_field("f")
    rng = np.random.default_rng(3)
    f.import_bits(rng.integers(0, 8, 500).astype(np.uint64),
                  rng.integers(0, SHARD_WIDTH, 500).astype(np.uint64))
    ex = Executor(tmp_holder)
    out = ex.execute_batch(
        [("mf", f"Count(Row(f={r}))", None) for r in range(3)])
    assert len(out) == 3 and ex.fused_queries == 3
    gc.collect()
    fp = _cat(LEDGER, "fusion_pad")
    assert fp["count"] == 0 and fp["bytes"] == 0  # group released
    assert "fusion_pad" in LEDGER.totals()        # but it was ledgered
    tmp_holder.delete_index("mf")


# ------------------------------------------------------------ HTTP surfaces


def test_debug_memory_totals_equal_category_sum(live_server):
    base, api, h = live_server
    idx = h.create_index("dm")
    idx.create_field("f").import_bits(
        np.array([1, 1], np.uint64),
        np.array([1, SHARD_WIDTH + 2], np.uint64))
    body = json.dumps({"query": "Count(Row(f=1))"}).encode()
    urllib.request.urlopen(base + "/index/dm/query", data=body).read()
    doc = json.loads(urllib.request.urlopen(
        base + "/debug/memory").read())
    assert doc["totalBytes"] > 0
    assert doc["totalBytes"] == sum(
        c["bytes"] for c in doc["categories"].values())
    assert doc["paddingBytes"] == sum(
        c["paddedBytes"] for c in doc["categories"].values())
    assert doc["top"] and doc["top"][0]["bytes"] > 0
    # top is byte-ordered and tagged (the ledger is process-global, so
    # banks from other live holders may legitimately outrank ours).
    tops = [e["bytes"] for e in doc["top"]]
    assert tops == sorted(tops, reverse=True)
    assert all("category" in e for e in doc["top"])
    # /metrics carries the matching gauges.
    met = urllib.request.urlopen(base + "/metrics").read().decode()
    assert 'pilosa_memory_bytes{category="bank"}' in met
    assert "pilosa_memory_padding_bytes" in met


def test_single_node_cluster_health(live_server):
    base, api, h = live_server
    doc = json.loads(urllib.request.urlopen(
        base + "/cluster/health").read())
    assert doc["totalNodes"] == doc["healthyNodes"] == 1
    node = doc["nodes"][0]
    assert node["healthy"] is True
    assert node["coalescer"]["attached"] is True
    assert "jitCacheSize" in node["executor"]
    assert doc["totals"]["memoryBytes"] >= 0


# ------------------------------------------------------------------ watchdog


def test_watchdog_never_touches_the_device():
    """The always-on sampler must be fence-free by construction: no jax
    import, no block_until_ready anywhere in the module (graftlint
    GL003 enforces the same in CI)."""
    import inspect
    import pilosa_tpu.utils.memledger as m
    src = inspect.getsource(m)
    assert "import jax" not in src
    assert "block_until_ready" not in src


def test_watchdog_ring_and_extra_gauges():
    from pilosa_tpu.utils.stats import MemStatsClient, prometheus_text
    led = MemoryLedger()
    led.register("bank", "k", 4096, padded_bytes=1024)
    stats = MemStatsClient()
    wd = MemoryWatchdog(led, stats=stats, ring=3,
                        extra_gauges=lambda: {"queueDepth": 7})
    for _ in range(5):
        wd.sample_once()
    snaps = wd.snapshots()
    assert len(snaps) == 3  # bounded flight recorder
    assert snaps[-1]["totalBytes"] == 4096
    assert snaps[-1]["paddingBytes"] == 1024
    assert snaps[-1]["queueDepth"] == 7
    assert wd.samples_taken == 5
    out = prometheus_text(stats)
    assert 'pilosa_memory_bytes{category="bank"} 4096' in out
    assert 'pilosa_memory_padding_bytes{category="bank"} 1024' in out


def test_watchdog_watermark_warns_once_with_top_banks():
    led = MemoryLedger()
    led.register("bank", "hog", 1 << 20, index="i", field="big")
    log = _LogStub()
    wd = MemoryWatchdog(led, logger=log, watermark_bytes=1 << 10)
    wd.sample_once()
    wd.sample_once()  # still over: must not re-log every sample
    warns = [l for l in log.lines if "HBM pressure" in l]
    assert len(warns) == 1
    assert "big" in warns[0]  # names the top occupant
    # Dropping below 90% of the watermark re-arms the warning.
    led.unregister("bank", "hog")
    wd.sample_once()
    led.register("bank", "hog2", 1 << 20)
    wd.sample_once()
    assert len([l for l in log.lines if "HBM pressure" in l]) == 2


def test_watchdog_thread_lifecycle():
    led = MemoryLedger()
    wd = MemoryWatchdog(led, sample_every_s=0.05)
    wd.start()
    deadline = time.time() + 5
    while wd.samples_taken == 0 and time.time() < deadline:
        time.sleep(0.02)
    assert wd.samples_taken >= 1
    assert wd.running
    wd.stop()
    assert not wd.running
    # Restartable: start() after stop() must sample again, not spawn
    # a thread that sees the stale stop event and exits immediately.
    n = wd.samples_taken
    wd.start()
    deadline = time.time() + 5
    while wd.samples_taken == n and time.time() < deadline:
        time.sleep(0.02)
    assert wd.samples_taken > n
    wd.stop()


def test_watchdog_dump_writes_ring_to_log():
    led = MemoryLedger()
    led.register("bank", "k", 123)
    log = _LogStub()
    wd = MemoryWatchdog(led, logger=log, ring=4)
    wd.sample_once()
    wd.sample_once()
    n = wd.dump(log, last=10)
    assert n == 2
    assert any("dumping last 2" in l for l in log.lines)
    assert any("'totalBytes': 123" in l for l in log.lines)


# -------------------------------------------------------------- SIGTERM drain


def test_drain_telemetry_simulated(tmp_holder):
    """The SIGTERM drain path: watchdog stops and dumps its ring, the
    profiler dumps its slow-query ring, and the tracer's stop() (the
    final exporter flush) runs — no buffered telemetry is dropped."""
    from pilosa_tpu.cli.main import drain_telemetry
    from pilosa_tpu.server.api import API
    from pilosa_tpu.utils.stats import MemStatsClient

    class _TracerStub:
        stopped = False

        def stop(self):
            self.stopped = True

    api = API(tmp_holder, stats=MemStatsClient())
    api.tracer = _TracerStub()
    api.profiler.record_slow("i", "Count(Row(f=1))", 2.5)
    log = _LogStub()
    wd = MemoryWatchdog(MemoryLedger(), logger=log,
                        sample_every_s=0.05)
    wd.start()
    wd.sample_once()
    drain_telemetry(api, watchdog=wd, logger=log)
    assert not wd.running
    assert any("memory watchdog: dumping" in l for l in log.lines)
    assert any("slow-query record" in l for l in log.lines)
    assert any("Count(Row(f=1))" in l for l in log.lines)
    assert api.tracer.stopped


def test_drain_telemetry_without_watchdog(tmp_holder):
    """Embedded servers may run ledger-only: drain degrades cleanly."""
    from pilosa_tpu.cli.main import drain_telemetry
    from pilosa_tpu.server.api import API
    from pilosa_tpu.utils.stats import MemStatsClient
    api = API(tmp_holder, stats=MemStatsClient())
    drain_telemetry(api, watchdog=None, logger=_LogStub())
