"""Chaos acceptance test (ISSUE 15): a node killed via failpoints
mid-resize under 64-thread live traffic yields zero wrong answers
(bit-exact vs a single-node oracle), zero request errors through the
surviving coordinators, the kill/recovery events visible in
/cluster/health and GET /cluster/timeline, torn scatter-leg bodies
recovered by failover, and the placement generation advanced on every
member.

The scenario itself lives in tools/chaos.py (also runnable standalone
and as the check.sh chaos smoke lane); this wraps it at the acceptance
scale. Slow tier: real OS processes, real HTTP, real clocks."""

import pytest

from tools import chaos


@pytest.mark.slow
@pytest.mark.timeout(540)
def test_chaos_kill_mid_resize_under_live_traffic():
    summary = chaos.run(threads=64, base=24, verbose=True)
    # chaos.run raises AssertionError on any violated invariant; the
    # summary re-asserts the headline numbers for the test report.
    assert summary["errors"] == 0
    assert summary["mismatches"] == 0
    assert summary["ok"] > 500  # 64 threads actually produced traffic
    assert summary["tornBodies"] >= 4
    assert {"node-down", "node-up", "resize-begin",
            "resize-complete"} <= set(summary["events"])
    assert all(g >= 1 for g in summary["placementGens"])
