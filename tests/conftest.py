"""Test config: force an 8-device virtual CPU platform BEFORE jax imports,
so sharding/mesh tests run anywhere (the driver separately dry-runs the
multi-chip path; real-TPU benching happens in bench.py, not tests)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Plan-IR verification gate default for the whole suite: every
# megakernel launch is checked (production default is `auto` =
# first-launch-per-jit-cache-key; docs/development.md "Plan-IR
# verification plane").
os.environ.setdefault("PILOSA_TPU_PLAN_VERIFY", "on")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize hook force-sets jax_platforms="axon,cpu" through
# jax.config (overriding the env var), so tests must override it back.
jax.config.update("jax_platforms", "cpu")

import contextlib  # noqa: E402
import math  # noqa: E402
import signal  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@contextlib.contextmanager
def alarm_timeout(seconds: int, what: str = "test"):
    """SIGALRM-based hard timeout (main thread only). Vendored because
    pytest-timeout is not in the image (VERDICT r3 weak #4) and the
    multihost test's subprocess.run(timeout=...) is not airtight: when
    the killed parent's jax.distributed grandchildren inherit the
    captured pipes, communicate() blocks on the pipe read forever. The
    handler raises, so PEP 475 does not retry the interrupted read."""

    def on_alarm(signum, frame):
        raise TimeoutError(f"{what} exceeded {seconds}s timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    # Ceil with a floor of 1: alarm(0) CANCELS the alarm, so a
    # sub-second timeout must round up, never down to "disabled".
    signal.alarm(max(1, math.ceil(seconds)))
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "timeout(seconds): fail (not hang) a test that overruns; "
        "SIGALRM-based, vendored in conftest.py")
    config.addinivalue_line(
        "markers",
        "slow: multi-minute harness tests (process-level cluster "
        "faults); deselect with -m 'not slow'")


def pytest_sessionfinish(session, exitstatus):
    """Under PILOSA_TPU_LOCK_CHECK=1 every lock is a Debug* wrapper that
    raises at a cycle-closing acquire — but application code may swallow
    that raise (the coalescer's dispatcher-died handler, for one), so
    the session additionally fails loudly if ANY violation was recorded.
    tools/check.sh runs the concurrency suites in this mode."""
    if os.environ.get("PILOSA_TPU_LOCK_CHECK") != "1":
        return
    from pilosa_tpu.utils.locks import lock_order_violations

    violations = lock_order_violations()
    if violations:
        session.exitstatus = 3
        tr = session.config.pluginmanager.get_plugin("terminalreporter")
        for v in violations:
            (tr.write_line if tr else print)(
                f"LOCK-ORDER VIOLATION: {v}")


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("timeout")
    if marker and hasattr(signal, "SIGALRM"):
        seconds = marker.args[0] if marker.args \
            else marker.kwargs.get("seconds")
        if not isinstance(seconds, (int, float)) or seconds <= 0:
            raise pytest.UsageError(
                f"{item.nodeid}: @pytest.mark.timeout needs one "
                f"positive number, got args={marker.args} "
                f"kwargs={marker.kwargs}")
        with alarm_timeout(seconds, what=item.nodeid):
            return (yield)
    return (yield)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def tmp_holder(tmp_path):
    from pilosa_tpu.core.holder import Holder

    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def live_server(tmp_path):
    """One live HTTP server on a random port: (base_url, api, holder).
    Shared by the HTTP-surface, docs-walkthrough, and endpoint tests so
    startup/teardown stays in one place."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server import API, serve
    from pilosa_tpu.utils.stats import MemStatsClient

    from pilosa_tpu.server.coalescer import QueryCoalescer

    h = Holder(str(tmp_path / "srv"))
    h.open()
    api = API(h, stats=MemStatsClient())
    # The coalescer must be semantically invisible, so the shared
    # fixture runs WITH it attached: every HTTP-surface test doubles as
    # an equivalence check of the coalesced path (test_coalescer.py
    # additionally diffs coalesced vs direct byte-for-byte).
    api.coalescer = QueryCoalescer(api.executor, window_s=0.0005,
                                   stats=api.stats, tracer=api.tracer)
    api.coalescer.start()
    srv = serve(api, "localhost", 0, background=True)
    yield f"http://localhost:{srv.server_address[1]}", api, h
    srv.shutdown()
    srv.server_close()
    api.coalescer.stop()
    h.close()
