"""Test config: force an 8-device virtual CPU platform BEFORE jax imports,
so sharding/mesh tests run anywhere (the driver separately dry-runs the
multi-chip path; real-TPU benching happens in bench.py, not tests)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize hook force-sets jax_platforms="axon,cpu" through
# jax.config (overriding the env var), so tests must override it back.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def tmp_holder(tmp_path):
    from pilosa_tpu.core.holder import Holder

    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def live_server(tmp_path):
    """One live HTTP server on a random port: (base_url, api, holder).
    Shared by the HTTP-surface, docs-walkthrough, and endpoint tests so
    startup/teardown stays in one place."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server import API, serve
    from pilosa_tpu.utils.stats import MemStatsClient

    h = Holder(str(tmp_path / "srv"))
    h.open()
    api = API(h, stats=MemStatsClient())
    srv = serve(api, "localhost", 0, background=True)
    yield f"http://localhost:{srv.server_address[1]}", api, h
    srv.shutdown()
    srv.server_close()
    h.close()
