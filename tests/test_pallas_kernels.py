"""Pallas kernel correctness vs the jnp reference (interpret mode on CPU).

Mirrors the reference's container-kernel matrices
(/root/reference/roaring/roaring_internal_test.go) at the bank-sweep level:
same counts out of the Pallas path as out of the fused-jnp path for dense,
sparse, empty, and full operands.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pilosa_tpu.ops import pallas_kernels as pk  # noqa: E402
from pilosa_tpu.ops.bitset import WORDS_PER_SHARD, popcount  # noqa: E402


def _bank(rng, r, s, density):
    if density == 0:
        return np.zeros((r, s, WORDS_PER_SHARD), np.uint32)
    if density == 1:
        return np.full((r, s, WORDS_PER_SHARD), 0xFFFFFFFF, np.uint32)
    b = rng.integers(0, 2**32, (r, s, WORDS_PER_SHARD), dtype=np.uint32)
    if density < 0.5:
        b &= rng.integers(0, 2**32, b.shape, dtype=np.uint32)
    return b


@pytest.mark.parametrize("density", [0, 0.25, 0.5, 1])
def test_bank_row_counts_matches_jnp(density):
    rng = np.random.default_rng(3)
    bank = _bank(rng, 4, 2, density)
    got = np.asarray(pk.bank_row_counts(jnp.asarray(bank), interpret=True))
    want = np.asarray(popcount(jnp.asarray(bank), axis=(-2, -1)))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("density", [0, 0.5, 1])
def test_bank_row_counts_masked_matches_jnp(density):
    rng = np.random.default_rng(4)
    bank = _bank(rng, 3, 2, 0.5)
    filt = _bank(rng, 1, 2, density)[0]
    gi, gr = pk.bank_row_counts_masked(jnp.asarray(bank), jnp.asarray(filt),
                                       interpret=True)
    wi = np.asarray(popcount(jnp.asarray(bank & filt), axis=(-2, -1)))
    wr = np.asarray(popcount(jnp.asarray(bank), axis=(-2, -1)))
    np.testing.assert_array_equal(np.asarray(gi), wi)
    np.testing.assert_array_equal(np.asarray(gr), wr)


def test_bsi_plane_counts_matches_jnp():
    rng = np.random.default_rng(5)
    planes = _bank(rng, 5, 2, 0.5)
    mask = _bank(rng, 1, 2, 0.5)[0]
    got = np.asarray(pk.bsi_plane_counts(jnp.asarray(planes),
                                         jnp.asarray(mask), interpret=True))
    want = np.asarray(popcount(jnp.asarray(planes & mask), axis=(-2, -1)))
    np.testing.assert_array_equal(got, want)


def test_swar_popcount_exhaustive_words():
    words = np.array([0, 1, 0xFFFFFFFF, 0x80000000, 0xAAAAAAAA, 0x55555555,
                      0x12345678, 0xDEADBEEF], np.uint32)
    tile = np.zeros((8, 128), np.uint32)
    tile[: len(words), 0] = words
    got = np.asarray(pk._popcount32(jnp.asarray(tile)))[: len(words), 0]
    want = np.array([bin(int(w)).count("1") for w in words], np.uint32)
    np.testing.assert_array_equal(got, want)


def test_executor_pallas_path_topn(tmp_path, monkeypatch):
    """End-to-end: TopN through the executor with the Pallas sweep forced
    on (interpret lowering is exercised separately; here we only verify the
    dispatch plumbing keeps results identical)."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    holder = Holder(str(tmp_path))
    holder.open()
    idx = holder.create_index("i")
    f = idx.create_field("f")
    cols = np.arange(0, 5000, 7, dtype=np.uint64)
    f.import_bits(np.arange(len(cols), dtype=np.uint64) % 5, cols)
    (want,) = Executor(holder).execute("i", "TopN(f, n=3)")

    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    if pk.available():
        (got,) = Executor(holder).execute("i", "TopN(f, n=3)")
        assert got.pairs == want.pairs
    holder.close()


def test_pbank_membership_counts_matches_numpy():
    """Fused membership+rowsum (probe-stage, VERDICT r5 #2): grouped
    u16-pair layout vs a numpy reference, pads excluded."""
    rng = np.random.default_rng(5)
    R, L, qk = 2048, 48, 48
    pos = np.sort(rng.integers(0, 4096, (R, L), dtype=np.uint16), axis=1)
    # Pad some rows (0xFFFF matches nothing).
    lens = rng.integers(10, L + 1, R)
    mask = np.arange(L)[None, :] >= lens[:, None]
    pos[mask] = 0xFFFF
    q = np.unique(rng.integers(0, 4096, qk * 2, dtype=np.uint16))[:qk]
    qtop_pad = np.full((8, 128), -1, np.int32)
    qtop_pad.reshape(-1)[:len(q)] = q.astype(np.int32)
    grouped = (pos.view(np.uint32)
               .reshape(R // 16, 16 * (L // 2)))
    got = np.asarray(pk.pbank_membership_counts(
        jnp.asarray(grouped), jnp.asarray(qtop_pad), qk=len(q),
        interpret=True))
    qset = set(int(x) for x in q)
    want = np.array([sum(1 for p in row if int(p) in qset and p != 0xFFFF)
                     for row in pos], np.int32)
    np.testing.assert_array_equal(got, want)


def test_pbank_search_membership_matches_compare(tmp_path, monkeypatch):
    """The searchsorted membership form answers identically to the
    compare form through the full executor tanimoto path."""
    import os
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    def build(d):
        h = Holder(d)
        h.open()
        idx = h.create_index("m")
        f = idx.create_field("fp", FieldOptions(max_columns=512))
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        rng = np.random.default_rng(9)
        cpr = SHARD_WIDTH // 65536
        for i in range(3000):
            frag.storage.containers[i * cpr] = np.unique(
                rng.integers(0, 512, 24, dtype=np.uint16))
            frag._touch_row(i)
        return h

    monkeypatch.setattr(executor_mod, "TOPN_MAX_BANK_BYTES", 1)
    q = ("TopN(fp, Row(fp=7), n=20, tanimotoThreshold=30)")
    # Pin the baseline to "compare": the module default is "auto",
    # which resolves to "search" on the CPU test mesh — without the
    # pin this test would compare search against itself.
    monkeypatch.setattr(executor_mod, "PBANK_MEMBERSHIP", "compare")
    h1 = build(str(tmp_path / "a"))
    (want,) = Executor(h1).execute("m", q)
    h1.close()
    monkeypatch.setattr(executor_mod, "PBANK_MEMBERSHIP", "search")
    h2 = build(str(tmp_path / "b"))
    (got,) = Executor(h2).execute("m", q)
    h2.close()
    assert got.pairs == want.pairs and want.pairs


def test_pbank_membership_auto_resolves_per_backend(tmp_path,
                                                    monkeypatch):
    """'auto' (the default) must resolve to 'search' on the XLA CPU
    backend (measured 1.33x warm / 7.7x faster cold at 1M molecules,
    docs/round5-notes.md §3) and be cached under the RESOLVED name, so
    an explicit-'search' run shares the same compiled kernel."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    assert jax.devices()[0].platform == "cpu"  # test mesh is CPU-forced
    monkeypatch.setattr(executor_mod, "PBANK_MEMBERSHIP", "auto")
    monkeypatch.setattr(executor_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(executor_mod.Executor, "_PBANK_KERNELS", {})
    h = Holder(str(tmp_path / "auto"))
    h.open()
    idx = h.create_index("m")
    f = idx.create_field("fp", FieldOptions(max_columns=512))
    view = f.create_view_if_not_exists("standard")
    frag = view.create_fragment_if_not_exists(0)
    rng = np.random.default_rng(11)
    cpr = SHARD_WIDTH // 65536
    for i in range(512):
        frag.storage.containers[i * cpr] = np.unique(
            rng.integers(0, 512, 24, dtype=np.uint16))
        frag._touch_row(i)
    (res,) = Executor(h).execute(
        "m", "TopN(fp, Row(fp=3), n=5, tanimotoThreshold=20)")
    h.close()
    assert res.pairs
    forms = {key[3] for key in executor_mod.Executor._PBANK_KERNELS}
    assert "search" in forms
    assert "auto" not in forms


# ------------------------------------------------------- megakernel loop


def _mega_reference(slab, instrs):
    """Host reference for the plan-buffer interpreter."""
    from pilosa_tpu.ops import megakernel as mk
    ref = slab.copy()
    for op, d, a, b in instrs:
        va, vb = ref[a], ref[b]
        ref[d] = {mk.OP_AND: va & vb, mk.OP_OR: va | vb,
                  mk.OP_XOR: va ^ vb, mk.OP_ANDNOT: va & ~vb,
                  mk.OP_ZERO: np.zeros_like(va), mk.OP_COPY: va}[op]
    return ref


def test_mega_interpret_matches_reference_with_raw_chains():
    """The Pallas plan-buffer loop must honor read-after-write chains
    BETWEEN plan entries (entry k reading the register entry k-1
    wrote) — the property a grid-per-entry formulation breaks."""
    from pilosa_tpu.ops import megakernel as mk
    rng = np.random.default_rng(5)
    slab = rng.integers(0, 2**32, (16, 2, 8), dtype=np.uint32)
    instrs = np.array([
        [mk.OP_AND, 12, 0, 1],
        [mk.OP_OR, 12, 12, 2],      # reads its own prior write
        [mk.OP_ANDNOT, 13, 3, 12],  # reads entry 1's write
        [mk.OP_XOR, 13, 13, 4],
        [mk.OP_COPY, 14, 13, 0],
        [mk.OP_ZERO, 15, 15, 15],
        [mk.OP_OR, 14, 14, 15],
    ], np.int32)
    out = np.asarray(pk.mega_interpret(jnp.asarray(slab),
                                       jnp.asarray(instrs),
                                       interpret=True))
    assert np.array_equal(out, _mega_reference(slab, instrs))


def test_mega_interpret_random_programs():
    from pilosa_tpu.ops import megakernel as mk
    rng = np.random.default_rng(17)
    slab = rng.integers(0, 2**32, (8, 1, 4), dtype=np.uint32)
    for _ in range(5):
        p = int(rng.integers(1, 12))
        instrs = np.stack([
            rng.integers(0, 6, p), rng.integers(0, 8, p),
            rng.integers(0, 8, p), rng.integers(0, 8, p),
        ], axis=1).astype(np.int32)
        out = np.asarray(pk.mega_interpret(jnp.asarray(slab),
                                           jnp.asarray(instrs),
                                           interpret=True))
        assert np.array_equal(out, _mega_reference(slab, instrs))
