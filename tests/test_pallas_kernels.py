"""Pallas kernel correctness vs the jnp reference (interpret mode on CPU).

Mirrors the reference's container-kernel matrices
(/root/reference/roaring/roaring_internal_test.go) at the bank-sweep level:
same counts out of the Pallas path as out of the fused-jnp path for dense,
sparse, empty, and full operands.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pilosa_tpu.ops import pallas_kernels as pk  # noqa: E402
from pilosa_tpu.ops.bitset import WORDS_PER_SHARD, popcount  # noqa: E402


def _bank(rng, r, s, density):
    if density == 0:
        return np.zeros((r, s, WORDS_PER_SHARD), np.uint32)
    if density == 1:
        return np.full((r, s, WORDS_PER_SHARD), 0xFFFFFFFF, np.uint32)
    b = rng.integers(0, 2**32, (r, s, WORDS_PER_SHARD), dtype=np.uint32)
    if density < 0.5:
        b &= rng.integers(0, 2**32, b.shape, dtype=np.uint32)
    return b


@pytest.mark.parametrize("density", [0, 0.25, 0.5, 1])
def test_bank_row_counts_matches_jnp(density):
    rng = np.random.default_rng(3)
    bank = _bank(rng, 4, 2, density)
    got = np.asarray(pk.bank_row_counts(jnp.asarray(bank), interpret=True))
    want = np.asarray(popcount(jnp.asarray(bank), axis=(-2, -1)))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("density", [0, 0.5, 1])
def test_bank_row_counts_masked_matches_jnp(density):
    rng = np.random.default_rng(4)
    bank = _bank(rng, 3, 2, 0.5)
    filt = _bank(rng, 1, 2, density)[0]
    gi, gr = pk.bank_row_counts_masked(jnp.asarray(bank), jnp.asarray(filt),
                                       interpret=True)
    wi = np.asarray(popcount(jnp.asarray(bank & filt), axis=(-2, -1)))
    wr = np.asarray(popcount(jnp.asarray(bank), axis=(-2, -1)))
    np.testing.assert_array_equal(np.asarray(gi), wi)
    np.testing.assert_array_equal(np.asarray(gr), wr)


def test_bsi_plane_counts_matches_jnp():
    rng = np.random.default_rng(5)
    planes = _bank(rng, 5, 2, 0.5)
    mask = _bank(rng, 1, 2, 0.5)[0]
    got = np.asarray(pk.bsi_plane_counts(jnp.asarray(planes),
                                         jnp.asarray(mask), interpret=True))
    want = np.asarray(popcount(jnp.asarray(planes & mask), axis=(-2, -1)))
    np.testing.assert_array_equal(got, want)


def test_swar_popcount_exhaustive_words():
    words = np.array([0, 1, 0xFFFFFFFF, 0x80000000, 0xAAAAAAAA, 0x55555555,
                      0x12345678, 0xDEADBEEF], np.uint32)
    tile = np.zeros((8, 128), np.uint32)
    tile[: len(words), 0] = words
    got = np.asarray(pk._popcount32(jnp.asarray(tile)))[: len(words), 0]
    want = np.array([bin(int(w)).count("1") for w in words], np.uint32)
    np.testing.assert_array_equal(got, want)


def test_executor_pallas_path_topn(tmp_path, monkeypatch):
    """End-to-end: TopN through the executor with the Pallas sweep forced
    on (interpret lowering is exercised separately; here we only verify the
    dispatch plumbing keeps results identical)."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    holder = Holder(str(tmp_path))
    holder.open()
    idx = holder.create_index("i")
    f = idx.create_field("f")
    cols = np.arange(0, 5000, 7, dtype=np.uint64)
    f.import_bits(np.arange(len(cols), dtype=np.uint64) % 5, cols)
    (want,) = Executor(holder).execute("i", "TopN(f, n=3)")

    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    if pk.available():
        (got,) = Executor(holder).execute("i", "TopN(f, n=3)")
        assert got.pairs == want.pairs
    holder.close()
