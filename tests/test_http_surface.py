"""HTTP surface sweep — the analog of the reference's
TestHandler_Endpoints (server/handler_test.go:40): hit every route on a
live server and check status + response shape."""

import json
import urllib.error
import urllib.request

import pytest



@pytest.fixture
def srv(live_server):
    base, _api, h = live_server
    yield base, h


def req(base, method, path, body=None, expect=200):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(base + path, data=data, method=method)
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            assert resp.status == expect, (path, resp.status)
            payload = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return json.loads(payload) if "json" in ctype else payload
    except urllib.error.HTTPError as e:
        assert e.code == expect, (path, e.code, e.read()[:200])
        return json.loads(e.read() or b"{}")


def test_all_endpoints(srv):
    base, h = srv
    # home/info/version
    assert req(base, "GET", "/")["pilosa-tpu"] is True
    assert "version" in req(base, "GET", "/version")
    req(base, "GET", "/info")
    req(base, "GET", "/status")
    req(base, "GET", "/debug/vars")

    # schema CRUD
    req(base, "POST", "/index/e1", {"options": {}})
    req(base, "POST", "/index/e1/field/f1", {"options": {}})
    assert any(i["name"] == "e1" for i in req(base, "GET", "/index"))
    assert req(base, "GET", "/index/e1")["name"] == "e1"
    assert req(base, "GET", "/index/e1/field")["fields"][0]["name"] == "f1"
    req(base, "GET", "/index/nope", expect=404)
    req(base, "POST", "/index/e1", {"options": {}}, expect=409)

    # query + import
    r = req(base, "POST", "/index/e1/query", b"Set(3, f1=2)")
    assert r["results"] == [True]
    req(base, "POST", "/index/e1/field/f1/import",
        {"rowIDs": [2, 2], "columnIDs": [5, 9]})
    r = req(base, "POST", "/index/e1/query", b"Count(Row(f1=2))")
    assert r["results"] == [3]
    req(base, "POST", "/index/e1/query", b"NotACall(1)", expect=400)

    # import-roaring
    from pilosa_tpu.storage.roaring import Bitmap
    bits = Bitmap([1 << 20 | 7])  # row 1, col 7 in fragment-position space
    req(base, "POST", "/index/e1/field/f1/import-roaring/0",
        bits.write_bytes())

    # export
    out = req(base, "GET", "/export?index=e1&field=f1")
    assert b"2,5" in out

    # internal sync primitives
    blocks = req(base, "GET",
                 "/internal/fragment/blocks?index=e1&field=f1&shard=0")
    assert blocks["blocks"]
    bd = req(base, "GET", "/internal/fragment/block/data?index=e1"
                          "&field=f1&shard=0&block=0")
    assert bd["rows"] and bd["columns"]
    raw = req(base, "GET",
              "/internal/fragment/data?index=e1&field=f1&shard=0")
    assert Bitmap.from_bytes(raw).count() > 0
    req(base, "GET", "/internal/shards/max")
    req(base, "GET", "/internal/nodes")
    req(base, "GET", "/internal/local-shards")
    assert req(base, "GET",
               "/internal/attr/blocks?index=e1") == {"blocks": []}

    # fragment owners (single-node pseudo-entry)
    owners = req(base, "GET", "/internal/fragment/nodes?index=e1&shard=0")
    assert owners and owners[0]["isCoordinator"]

    # caches + deletes
    req(base, "POST", "/recalculate-caches")
    req(base, "DELETE", "/index/e1/field/f1")
    req(base, "DELETE", "/index/e1")
    req(base, "GET", "/index/e1", expect=404)


def test_keyed_translate_endpoints(srv):
    base, h = srv
    req(base, "POST", "/index/k1", {"options": {"keys": True}})
    req(base, "POST", "/index/k1/field/kf",
        {"options": {"keys": True}})
    req(base, "POST", "/index/k1/query", b'Set("c1", kf="r1")')
    r = req(base, "POST", "/internal/translate/keys",
            {"index": "k1", "keys": ["c1"]})
    assert r["ids"] == [1]
    r = req(base, "POST", "/internal/translate/ids",
            {"index": "k1", "ids": [1]})
    assert r["keys"] == ["c1"]
    data = req(base, "GET", "/internal/translate/data?index=k1")
    assert b"c1" in data
