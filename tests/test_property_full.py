"""Extended property tests: randomized PQL over the FULL call surface —
set rows, BSI conditions, time ranges, aggregates, TopN — checked against
a naive host model (the analog of the reference's programmatic query
generators, internal/test/querygenerator.go, widened past bitmap algebra)."""

import os

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops.bitset import SHARD_WIDTH

# Seed offset: a CI/burn-in loop can sweep PILOSA_TEST_SEED to fuzz
# fresh schedules; default 0 keeps runs deterministic.
SEED_OFFSET = int(os.environ.get("PILOSA_TEST_SEED", 0))

N_SHARDS = 2
SET_ROWS = 4
DENSITY = 50
INT_MIN, INT_MAX = -120, 900
DAYS = [f"200{y}-{m:02d}-{d:02d}"
        for y in (1, 2) for m in (1, 6) for d in (1, 15)]


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("propfull")
    h = Holder(str(tmp))
    h.open()
    idx = h.create_index("q")
    rng = np.random.default_rng(41 + SEED_OFFSET)
    universe_n = N_SHARDS * SHARD_WIDTH

    sets = {}  # (field, row) -> set(cols)
    for fi in range(2):
        f = idx.create_field(f"s{fi}")
        for row in range(SET_ROWS):
            cols = np.unique(rng.integers(0, universe_n, DENSITY,
                                          dtype=np.uint64))
            f.import_bits(np.full(len(cols), row, np.uint64), cols)
            sets[(f"s{fi}", row)] = set(cols.tolist())

    # int field over a random column subset
    ints = {}  # col -> value
    iv = idx.create_field("v", FieldOptions(type="int", min=INT_MIN,
                                            max=INT_MAX))
    vcols = np.unique(rng.integers(0, universe_n, 300, dtype=np.uint64))
    vvals = rng.integers(INT_MIN, INT_MAX + 1, len(vcols), dtype=np.int64)
    iv.import_values(vcols, vvals)
    ints = dict(zip(vcols.tolist(), vvals.tolist()))

    # time field: one row, bits stamped on day boundaries
    times = {}  # col -> day string
    tf = idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
    tcols = np.unique(rng.integers(0, universe_n, 200, dtype=np.uint64))
    ex = Executor(h)
    from datetime import datetime
    tdays = rng.integers(0, len(DAYS), len(tcols))
    rows_l, cols_l, stamps = [], [], []
    for c, di in zip(tcols.tolist(), tdays.tolist()):
        times[c] = DAYS[di]
        rows_l.append(0)
        cols_l.append(c)
        stamps.append(datetime.strptime(DAYS[di], "%Y-%m-%d"))
    tf.import_bits(np.array(rows_l, np.uint64), np.array(cols_l, np.uint64),
                   timestamps=stamps)

    universe = set()
    for s in sets.values():
        universe |= s
    universe |= set(ints)
    universe |= set(times)
    idx.add_existence(np.array(sorted(universe), np.uint64))
    yield ex, sets, ints, times, universe
    h.close()


def gen_leaf(rng, sets, ints, times, universe):
    kind = rng.random()
    if kind < 0.45:
        fi, row = int(rng.integers(0, 2)), int(rng.integers(0, SET_ROWS))
        return (f"Row(s{fi}={row})",
                lambda: set(sets[(f"s{fi}", row)]))
    if kind < 0.8:
        op = rng.choice(["<", "<=", ">", ">=", "==", "!="])
        val = int(rng.integers(INT_MIN - 20, INT_MAX + 20))
        pql = f"Row(v {op} {val})"
        import operator as _op
        fn = {"<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge,
              "==": _op.eq, "!=": _op.ne}[op]
        return pql, lambda: {c for c, v in ints.items() if fn(v, val)}
    if kind < 0.9:
        lo = int(rng.integers(INT_MIN, INT_MAX - 10))
        hi = lo + int(rng.integers(1, 200))
        return (f"Row(v >< [{lo}, {hi}])",
                lambda: {c for c, v in ints.items() if lo <= v <= hi})
    # time-range leaf, day-aligned bounds
    i0 = int(rng.integers(0, len(DAYS) - 1))
    i1 = int(rng.integers(i0 + 1, len(DAYS)))
    frm, to = DAYS[i0], DAYS[i1]
    return (f"Row(t=0, from='{frm}T00:00', to='{to}T00:00')",
            lambda: {c for c, d in times.items() if frm <= d < to})


def gen_tree(rng, depth, sets, ints, times, universe):
    if depth == 0 or rng.random() < 0.35:
        return gen_leaf(rng, sets, ints, times, universe)
    op = rng.choice(["Intersect", "Union", "Difference", "Xor", "Not"])
    if op == "Not":
        pql, fn = gen_tree(rng, depth - 1, sets, ints, times, universe)
        return f"Not({pql})", lambda: universe - fn()
    k = int(rng.integers(2, 4))
    subs = [gen_tree(rng, depth - 1, sets, ints, times, universe)
            for _ in range(k)]
    pql = f"{op}({', '.join(s[0] for s in subs)})"

    def ev():
        vals = [s[1]() for s in subs]
        out = vals[0]
        for s in vals[1:]:
            out = {"Intersect": out.__and__, "Union": out.__or__,
                   "Difference": out.__sub__, "Xor": out.__xor__}[op](s)
        return out

    return pql, ev


def test_full_surface_trees(world):
    ex, sets, ints, times, universe = world
    rng = np.random.default_rng(17 + SEED_OFFSET)
    for i in range(50):
        pql, ev = gen_tree(rng, 3, sets, ints, times, universe)
        want = ev()
        (got,) = ex.execute("q", pql)
        assert set(got.columns().tolist()) == want, f"iter {i}: {pql}"
        (cnt,) = ex.execute("q", f"Count({pql})")
        assert cnt == len(want), f"iter {i}: Count({pql})"


def test_aggregates_with_random_filters(world):
    ex, sets, ints, times, universe = world
    rng = np.random.default_rng(29 + SEED_OFFSET)
    for i in range(25):
        pql, ev = gen_tree(rng, 2, sets, ints, times, universe)
        domain = {c: v for c, v in ints.items() if c in ev()}
        (s,) = ex.execute("q", f'Sum({pql}, field="v")')
        assert s.value == sum(domain.values()), f"iter {i}: Sum({pql})"
        assert s.count == len(domain), f"iter {i}: Sum({pql}) count"
        if domain:
            (mn,) = ex.execute("q", f'Min({pql}, field="v")')
            vmin = min(domain.values())
            assert mn.value == vmin, f"iter {i}: Min({pql})"
            assert mn.count == sum(1 for v in domain.values() if v == vmin)
            (mx,) = ex.execute("q", f'Max({pql}, field="v")')
            vmax = max(domain.values())
            assert mx.value == vmax, f"iter {i}: Max({pql})"
            assert mx.count == sum(1 for v in domain.values() if v == vmax)


def test_topn_with_random_filters(world):
    ex, sets, ints, times, universe = world
    rng = np.random.default_rng(31 + SEED_OFFSET)
    for i in range(15):
        pql, ev = gen_tree(rng, 2, sets, ints, times, universe)
        filt = ev()
        (res,) = ex.execute("q", f"TopN(s0, {pql}, n=4)")
        want = sorted(
            ((r, len(sets[("s0", r)] & filt)) for r in range(SET_ROWS)),
            key=lambda p: (-p[1], p[0]))
        want = [(r, n) for r, n in want if n][:4]
        got = sorted(res.pairs, key=lambda p: (-p[1], p[0]))
        # counts must match exactly; ties may order differently
        assert {r: n for r, n in got} == {r: n for r, n in want}, \
            f"iter {i}: TopN filter {pql}"


def test_groupby_with_random_filter(world):
    ex, sets, ints, times, universe = world
    rng = np.random.default_rng(37 + SEED_OFFSET)
    for i in range(10):
        pql, ev = gen_tree(rng, 1, sets, ints, times, universe)
        filt = ev()
        (res,) = ex.execute("q", f"GroupBy(Rows(s0), Rows(s1), "
                                 f"filter={pql})")
        got = {tuple(fr.row_id for fr in gc.group): gc.count for gc in res}
        want = {}
        for r0 in range(SET_ROWS):
            for r1 in range(SET_ROWS):
                n = len(sets[("s0", r0)] & sets[("s1", r1)] & filt)
                if n:
                    want[(r0, r1)] = n
        assert got == want, f"iter {i}: filter {pql}"


def test_sparse_coverage_trees(tmp_path):
    """Randomized bitmap trees over fields with RANDOM shard coverage
    (r4 shard-coverage restriction): fields covering disjoint/partial
    shard subsets of a wide index, random Union/Intersect/Difference/
    Xor/Not trees, Count and Row answers vs a host set model. Exercises
    the restriction walk against the planner for every tree shape."""
    rng = np.random.default_rng(97 + SEED_OFFSET)
    h = Holder(str(tmp_path / "w"))
    h.open()
    idx = h.create_index("sc")
    n_shards = 5
    fields = {}
    model = {}  # field -> set(cols)  (row 1 everywhere)
    for fi in range(4):
        f = idx.create_field(f"f{fi}")
        cover = rng.choice(n_shards, size=rng.integers(1, n_shards + 1),
                           replace=False)
        cols = []
        for s in cover:
            base = int(s) * SHARD_WIDTH
            cols.extend(base + c for c in
                        rng.integers(0, 3000, 40).tolist())
        cols = sorted(set(cols))
        f.import_bits(np.ones(len(cols), np.uint64),
                      np.array(cols, np.uint64))
        fields[f"f{fi}"] = f
        model[f"f{fi}"] = set(cols)
    idx.add_existence(np.array(sorted(set().union(*model.values())),
                               np.uint64))
    everything = set().union(*model.values())
    ex = Executor(h)

    def gen(depth):
        if depth == 0 or rng.random() < 0.4:
            name = f"f{rng.integers(0, 4)}"
            return f"Row({name}=1)", model[name]
        op = rng.choice(["Union", "Intersect", "Difference", "Xor",
                         "Not"])
        if op == "Not":
            q, s = gen(depth - 1)
            return f"Not({q})", everything - s
        k = int(rng.integers(2, 4))
        subs = [gen(depth - 1) for _ in range(k)]
        qs = ", ".join(q for q, _ in subs)
        sets = [s for _, s in subs]
        if op == "Union":
            want = set().union(*sets)
        elif op == "Intersect":
            want = set.intersection(*sets)
        elif op == "Difference":
            want = sets[0].difference(*sets[1:])
        else:
            want = set(sets[0])  # copy: ^= would mutate model[...]
            for s in sets[1:]:
                want ^= s
        return f"{op}({qs})", want

    for trial in range(40):
        q, want = gen(int(rng.integers(1, 4)))
        (cnt,) = ex.execute("sc", f"Count({q})")
        assert cnt == len(want), (q, cnt, len(want))
        (row,) = ex.execute("sc", q)
        assert set(row.columns().tolist()) == want, q
    h.close()
