"""Reference-client protobuf compatibility (internal/public.proto over
/index/{i}/query and /import — /root/reference/http/handler.go:916-1060).

The expected wire bytes come from the real `google.protobuf` runtime
with message types built PROGRAMMATICALLY from the public.proto schema
(field numbers/types are protocol constants) — an independent
implementation to differentially test the hand-rolled codec in
server/proto_compat.py.
"""

import urllib.request

import numpy as np
import pytest

from pilosa_tpu.server import proto_compat


def test_translate_keys_protobuf_leg(live_server):
    """Reference clients translate keys over protobuf
    (http/handler.go:1617): TranslateKeysRequest in,
    TranslateKeysResponse (packed IDs) out."""
    from pilosa_tpu.server.proto_compat import (
        decode_translate_keys_request,
        encode_translate_keys_response,
        _fields,
    )

    base, api, _h = live_server
    api.create_index("tk", {"keys": True})
    api.create_field("tk", "f", {})
    body = (b"\x0a\x02tk"            # Index=1 "tk"
            b"\x1a\x05alpha"         # Keys=3 "alpha"
            b"\x1a\x04beta")         # Keys=3 "beta"
    assert decode_translate_keys_request(body) == {
        "index": "tk", "field": "", "keys": ["alpha", "beta"]}
    r = urllib.request.Request(
        base + "/internal/translate/keys", data=body, method="POST",
        headers={"Content-Type": "application/x-protobuf"})
    with urllib.request.urlopen(r) as resp:
        payload = resp.read()
        assert resp.headers["Content-Type"] == "application/protobuf"
    # Parse the packed-IDs response with the hand codec's field walker.
    from pilosa_tpu.server.proto_compat import _repeated_uint64
    ids = _repeated_uint64(_fields(payload), 3)
    assert len(ids) == 2 and len(set(ids)) == 2
    # Same keys again -> same ids (get-or-allocate).
    with urllib.request.urlopen(urllib.request.Request(
            base + "/internal/translate/keys", data=body, method="POST",
            headers={"Content-Type": "application/x-protobuf"})) as resp:
        assert _repeated_uint64(_fields(resp.read()), 3) == ids
    assert encode_translate_keys_response(ids) == payload


def _build_messages():
    """Dynamic protobuf message classes matching internal/public.proto."""
    from google.protobuf import descriptor_pb2, descriptor_pool
    from google.protobuf import message_factory

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "public_compat_test.proto"
    fdp.package = "internal"
    fdp.syntax = "proto3"
    T = descriptor_pb2.FieldDescriptorProto

    def msg(name, *fields):
        m = fdp.message_type.add()
        m.name = name
        for fname, num, ftype, label, type_name in fields:
            f = m.field.add()
            f.name = fname
            f.number = num
            f.type = ftype
            f.label = label
            if type_name:
                f.type_name = type_name

    R, O = T.LABEL_REPEATED, T.LABEL_OPTIONAL
    U64, I64, STR, BOOL, U32, MSG, DBL, BYT = (
        T.TYPE_UINT64, T.TYPE_INT64, T.TYPE_STRING, T.TYPE_BOOL,
        T.TYPE_UINT32, T.TYPE_MESSAGE, T.TYPE_DOUBLE, T.TYPE_BYTES)
    msg("Attr", ("Key", 1, STR, O, None), ("Type", 2, U64, O, None),
        ("StringValue", 3, STR, O, None), ("IntValue", 4, I64, O, None),
        ("BoolValue", 5, BOOL, O, None), ("FloatValue", 6, DBL, O, None))
    msg("Row", ("Columns", 1, U64, R, None),
        ("Attrs", 2, MSG, R, ".internal.Attr"),
        ("Keys", 3, STR, R, None))
    msg("RowIdentifiers", ("Rows", 1, U64, R, None),
        ("Keys", 2, STR, R, None))
    msg("Pair", ("ID", 1, U64, O, None), ("Count", 2, U64, O, None),
        ("Key", 3, STR, O, None))
    msg("FieldRow", ("Field", 1, STR, O, None), ("RowID", 2, U64, O, None),
        ("RowKey", 3, STR, O, None))
    msg("GroupCount", ("Group", 1, MSG, R, ".internal.FieldRow"),
        ("Count", 2, U64, O, None))
    msg("ValCount", ("Val", 1, I64, O, None), ("Count", 2, I64, O, None))
    msg("ColumnAttrSet", ("ID", 1, U64, O, None),
        ("Attrs", 2, MSG, R, ".internal.Attr"), ("Key", 3, STR, O, None))
    msg("QueryRequest", ("Query", 1, STR, O, None),
        ("Shards", 2, U64, R, None), ("ColumnAttrs", 3, BOOL, O, None),
        ("Remote", 5, BOOL, O, None), ("ExcludeRowAttrs", 6, BOOL, O, None),
        ("ExcludeColumns", 7, BOOL, O, None))
    msg("QueryResult", ("Row", 1, MSG, O, ".internal.Row"),
        ("N", 2, U64, O, None), ("Pairs", 3, MSG, R, ".internal.Pair"),
        ("Changed", 4, BOOL, O, None),
        ("ValCount", 5, MSG, O, ".internal.ValCount"),
        ("Type", 6, U32, O, None), ("RowIDs", 7, U64, R, None),
        ("GroupCounts", 8, MSG, R, ".internal.GroupCount"),
        ("RowIdentifiers", 9, MSG, O, ".internal.RowIdentifiers"))
    msg("QueryResponse", ("Err", 1, STR, O, None),
        ("Results", 2, MSG, R, ".internal.QueryResult"),
        ("ColumnAttrSets", 3, MSG, R, ".internal.ColumnAttrSet"))
    msg("ImportRequest", ("Index", 1, STR, O, None),
        ("Field", 2, STR, O, None), ("Shard", 3, U64, O, None),
        ("RowIDs", 4, U64, R, None), ("ColumnIDs", 5, U64, R, None),
        ("Timestamps", 6, I64, R, None), ("RowKeys", 7, STR, R, None),
        ("ColumnKeys", 8, STR, R, None))
    msg("ImportValueRequest", ("Index", 1, STR, O, None),
        ("Field", 2, STR, O, None), ("Shard", 3, U64, O, None),
        ("ColumnIDs", 5, U64, R, None), ("Values", 6, I64, R, None),
        ("ColumnKeys", 7, STR, R, None))
    msg("ImportRoaringRequestView", ("Name", 1, STR, O, None),
        ("Data", 2, BYT, O, None))
    msg("ImportRoaringRequest", ("Clear", 1, BOOL, O, None),
        ("views", 2, MSG, R, ".internal.ImportRoaringRequestView"))

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    get = getattr(message_factory, "GetMessageClass", None)
    if get is not None:
        return {n: get(pool.FindMessageTypeByName(f"internal.{n}"))
                for n in ("QueryRequest", "QueryResponse", "ImportRequest",
                          "ImportValueRequest", "ImportRoaringRequest")}
    factory = message_factory.MessageFactory(pool)  # pragma: no cover
    return {n: factory.GetPrototype(
        pool.FindMessageTypeByName(f"internal.{n}"))
        for n in ("QueryRequest", "QueryResponse", "ImportRequest",
                  "ImportValueRequest", "ImportRoaringRequest")}


M = _build_messages()


def test_decode_query_request_matches_protobuf_lib():
    m = M["QueryRequest"]()
    m.Query = "Count(Row(f=1))"
    m.Shards.extend([0, 3, 9])
    m.Remote = True
    m.ExcludeColumns = True
    got = proto_compat.decode_query_request(m.SerializeToString())
    assert got["query"] == "Count(Row(f=1))"
    assert got["shards"] == [0, 3, 9]
    assert got["remote"] is True
    assert got["excludeColumns"] is True
    assert got["excludeRowAttrs"] is False


def test_decode_import_requests_match_protobuf_lib():
    m = M["ImportRequest"]()
    m.Index, m.Field, m.Shard = "i", "f", 2
    m.RowIDs.extend([1, 2])
    m.ColumnIDs.extend([10, 20])
    m.Timestamps.extend([1546300800_000_000_000, 0])
    got = proto_compat.decode_import_request(m.SerializeToString())
    assert got["rowIDs"] == [1, 2] and got["columnIDs"] == [10, 20]
    assert got["timestamps"][0] == 1546300800_000_000_000
    v = M["ImportValueRequest"]()
    v.Index, v.Field = "i", "n"
    v.ColumnIDs.extend([5, 6])
    v.Values.extend([-12, 400])
    got = proto_compat.decode_import_value_request(v.SerializeToString())
    assert got["values"] == [-12, 400]  # negative int64 varint
    r = M["ImportRoaringRequest"]()
    r.Clear = True
    view = r.views.add()
    view.Name, view.Data = "standard", b"\x3c\x30abc"
    got = proto_compat.decode_import_roaring_request(r.SerializeToString())
    assert got["clear"] is True
    assert got["views"] == [("standard", b"\x3c\x30abc")]


def test_encode_query_response_parses_with_protobuf_lib():
    body = proto_compat.encode_query_response([
        {"columns": [1, 5, 9], "attrs": {"color": "red", "n": 3,
                                         "ok": True, "w": 1.5}},
        2,
        True,
        [{"id": 4, "count": 7}, {"key": "k", "count": 1}],
        {"value": -3, "count": 2},
        {"rows": [1, 2, 3]},
        [{"group": [{"field": "a", "rowID": 1},
                    {"field": "b", "rowKey": "x"}], "count": 9}],
        None,
    ], column_attr_sets=[{"id": 5, "attrs": {"city": "nyc"}}])
    resp = M["QueryResponse"]()
    resp.ParseFromString(body)
    rs = resp.Results
    assert rs[0].Type == 1 and list(rs[0].Row.Columns) == [1, 5, 9]
    attrs = {a.Key: a for a in rs[0].Row.Attrs}
    assert attrs["color"].Type == 1 and attrs["color"].StringValue == "red"
    assert attrs["n"].Type == 2 and attrs["n"].IntValue == 3
    assert attrs["ok"].Type == 3 and attrs["ok"].BoolValue is True
    assert attrs["w"].Type == 4 and attrs["w"].FloatValue == 1.5
    assert rs[1].Type == 4 and rs[1].N == 2
    assert rs[2].Type == 5 and rs[2].Changed is True
    assert rs[3].Type == 2
    assert [(p.ID, p.Count, p.Key) for p in rs[3].Pairs] == \
        [(4, 7, ""), (0, 1, "k")]
    assert rs[4].Type == 3 and rs[4].ValCount.Val == -3
    assert rs[4].ValCount.Count == 2
    assert rs[5].Type == 8 and list(rs[5].RowIdentifiers.Rows) == [1, 2, 3]
    gc = rs[6]
    assert gc.Type == 7 and gc.GroupCounts[0].Count == 9
    assert gc.GroupCounts[0].Group[0].Field == "a"
    assert gc.GroupCounts[0].Group[0].RowID == 1
    assert gc.GroupCounts[0].Group[1].RowKey == "x"
    assert rs[7].Type == 0
    assert resp.ColumnAttrSets[0].ID == 5
    assert resp.ColumnAttrSets[0].Attrs[0].StringValue == "nyc"


def _mk_query(pql):
    m = M["QueryRequest"]()
    m.Query = pql
    return m


def _preq(base, path, msg, accept=True):
    r = urllib.request.Request(
        base + path, data=msg.SerializeToString(), method="POST",
        headers={"Content-Type": "application/x-protobuf",
                 **({"Accept": "application/x-protobuf"} if accept else {})})
    with urllib.request.urlopen(r) as resp:
        return resp.status, resp.read(), resp.headers.get("Content-Type")


def test_reference_client_protocol_end_to_end(live_server):
    """A protobuf-speaking reference client imports and queries through
    the live HTTP server."""
    base, api, _h = live_server
    api.create_index("pb", {})
    api.create_field("pb", "f", {})
    api.create_field("pb", "n", {"type": "int", "min": 0, "max": 1000})

    imp = M["ImportRequest"]()
    imp.Index, imp.Field = "pb", "f"
    imp.RowIDs.extend([1, 1, 2])
    imp.ColumnIDs.extend([10, 20, 10])
    st, _, _ = _preq(base, "/index/pb/field/f/import", imp)
    assert st == 200

    vimp = M["ImportValueRequest"]()
    vimp.Index, vimp.Field = "pb", "n"
    vimp.ColumnIDs.extend([10, 20])
    vimp.Values.extend([7, 9])
    st, _, _ = _preq(base, "/index/pb/field/n/import", vimp)
    assert st == 200

    qreq = M["QueryRequest"]()
    qreq.Query = ("Row(f=1) Count(Row(f=1)) TopN(f, n=2) "
                  'Sum(field="n") Rows(f)')
    st, body, ctype = _preq(base, "/index/pb/query", qreq)
    assert st == 200 and ctype == "application/protobuf"
    resp = M["QueryResponse"]()
    resp.ParseFromString(body)
    rs = resp.Results
    assert list(rs[0].Row.Columns) == [10, 20]
    assert rs[1].N == 2
    assert [(p.ID, p.Count) for p in rs[2].Pairs] == [(1, 2), (2, 1)]
    assert rs[3].ValCount.Val == 16 and rs[3].ValCount.Count == 2
    assert list(rs[4].RowIdentifiers.Rows) == [1, 2]

    # Keep-alive regression: two protobuf queries on ONE pooled
    # connection (go-pilosa pools) — an accidental second response after
    # the first would desync the next exchange.
    import http.client
    from urllib.parse import urlsplit
    host = urlsplit(base)
    conn = http.client.HTTPConnection(host.hostname, host.port)
    try:
        for _ in range(2):
            q2 = M["QueryRequest"]()
            q2.Query = "Count(Row(f=1))"
            conn.request("POST", "/index/pb/query",
                         body=q2.SerializeToString(),
                         headers={"Content-Type":
                                  "application/x-protobuf"})
            r2 = conn.getresponse()
            payload = r2.read()
            assert r2.status == 200
            out = M["QueryResponse"]()
            out.ParseFromString(payload)
            assert out.Results[0].N == 2
    finally:
        conn.close()

    # Protobuf roaring import (ImportRoaringRequest with a view payload).
    from pilosa_tpu.storage.roaring import Bitmap
    bm = Bitmap(np.array([3 * 2**20 + 5], dtype=np.uint64))
    rr = M["ImportRoaringRequest"]()
    view = rr.views.add()
    view.Name, view.Data = "standard", bm.write_bytes()
    st, _, _ = _preq(base, "/index/pb/field/f/import-roaring/0", rr)
    assert st == 200
    st, body, _ = _preq(base, "/index/pb/query",
                        _mk_query("Row(f=3)"))
    resp2 = M["QueryResponse"]()
    resp2.ParseFromString(body)
    assert list(resp2.Results[0].Row.Columns) == [5]

    # Invalid UTF-8 in the Query field answers 400, not 500.
    bad = b"\x0a\x02\xff\xfe"  # field 1 (Query), 2 bytes of non-utf8
    r = urllib.request.Request(
        base + "/index/pb/query", data=bad, method="POST",
        headers={"Content-Type": "application/x-protobuf"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r)
    assert ei.value.code == 400

    # Errors come back as QueryResponse.Err with HTTP 400.
    qbad = M["QueryRequest"]()
    qbad.Query = "Nope(f=1)"
    r = urllib.request.Request(
        base + "/index/pb/query", data=qbad.SerializeToString(),
        method="POST", headers={"Content-Type": "application/x-protobuf"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r)
    err = M["QueryResponse"]()
    err.ParseFromString(ei.value.read())
    assert ei.value.code == 400 and err.Err


def test_truncated_fields_raise_proto_error():
    """Every wire type's truncation raises ProtoError instead of
    silently dropping trailing fields (ADVICE r3: the fixed64/fixed32
    paths lacked the bounds check the varint/length-delimited paths
    had)."""
    import pytest

    from pilosa_tpu.server.proto_compat import ProtoError, _fields

    # field 1, each wire type, with a short body.
    for tag, body in [
        (b"\x09", b"\x01\x02\x03"),        # I64 with 3 of 8 bytes
        (b"\x0d", b"\x01\x02"),            # I32 with 2 of 4 bytes
        (b"\x08", b"\x80"),                # varint cut mid-continuation
        (b"\x0a", b"\x05ab"),              # LEN claiming 5, giving 2
    ]:
        with pytest.raises(ProtoError):
            _fields(tag + body)
    # Intact messages of each type still parse.
    assert _fields(b"\x09" + bytes(8))[0][1] == 1
    assert _fields(b"\x0d" + bytes(4))[0][1] == 5
