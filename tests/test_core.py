"""Data model tests: fragment durability, field types, time views, holder walk."""

from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core import timeq
from pilosa_tpu.ops.bitset import SHARD_WIDTH


def test_fragment_set_clear_persist(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    assert f.set_bit(1, 100)
    assert not f.set_bit(1, 100)
    assert f.set_bit(1, SHARD_WIDTH + 5)  # second shard
    assert f.set_bit(2, 100)
    assert f.clear_bit(2, 100)
    assert f.available_shards() == [0, 1]
    h.close()

    h2 = Holder(str(tmp_path))
    h2.open()
    f2 = h2.index("i").field("f")
    frag = f2.view().fragment(0)
    assert frag.bit(1, 100)
    assert not frag.bit(2, 100)
    assert f2.view().fragment(1).bit(1, SHARD_WIDTH + 5)
    assert f2.available_shards() == [0, 1]
    h2.close()


def test_fragment_snapshot_rolls_oplog(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    frag = f.create_view_if_not_exists("standard").create_fragment_if_not_exists(0)
    frag.max_op_n = 10
    for c in range(25):
        frag.set_bit(0, c)
    assert frag.storage.op_n < 10  # snapshotted at least once
    h.close()
    h2 = Holder(str(tmp_path))
    h2.open()
    frag2 = h2.index("i").field("f").view().fragment(0)
    assert frag2.row_count(0) == 25
    h2.close()


def test_row_reads_and_bank(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    cols = np.array([1, 5, 99, SHARD_WIDTH - 1], dtype=np.uint64)
    f.import_bits(np.full(len(cols), 3, dtype=np.uint64), cols)
    frag = f.view().fragment(0)
    np.testing.assert_array_equal(frag.row_columns(3), cols)
    assert frag.row_ids() == (3,)
    bank, slots = frag.bank()
    assert bank.shape[0] == 1 and 3 in slots
    # write -> dirty -> bank refresh
    frag.set_bit(3, 42)
    bank2, slots2 = frag.bank()
    from pilosa_tpu.ops import bitset as bs
    got = bs.unpack_positions(np.asarray(bank2[slots2[3]]))
    np.testing.assert_array_equal(got, np.sort(np.append(cols, 42)))
    h.close()


def test_mutex_field(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("m", FieldOptions(type="mutex"))
    f.set_bit(1, 10)
    f.set_bit(2, 10)  # clears row 1
    frag = f.view().fragment(0)
    assert not frag.bit(1, 10)
    assert frag.bit(2, 10)
    # bulk mutex import
    f.import_bits(np.array([5, 6], np.uint64), np.array([10, 20], np.uint64))
    assert frag.mutex_vector(10) == 5
    assert frag.mutex_vector(20) == 6
    h.close()


def test_bool_field(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("b", FieldOptions(type="bool"))
    f.set_bit(1, 7)   # true
    f.set_bit(0, 7)   # flips to false
    frag = f.view().fragment(0)
    assert frag.bit(0, 7) and not frag.bit(1, 7)
    h.close()


def test_int_field_values(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field(
        "n", FieldOptions(type="int", min=-10, max=1000))
    assert f.set_value(3, -10)
    assert f.set_value(4, 1000)
    assert f.set_value(5, 0)
    assert f.value(3) == (-10, True)
    assert f.value(4) == (1000, True)
    assert f.value(5) == (0, True)
    assert f.value(6) == (0, False)
    with pytest.raises(ValueError):
        f.set_value(7, 1001)
    # bulk
    cols = np.arange(100, 200, dtype=np.uint64)
    vals = np.arange(-10, 90, dtype=np.int64)
    f.import_values(cols, vals)
    assert f.value(150) == (40, True)
    h.close()
    h2 = Holder(str(tmp_path))
    h2.open()
    f2 = h2.index("i").field("n")
    assert f2.value(150) == (40, True)
    assert f2.value(3) == (-10, True)
    h2.close()


def test_time_field_views(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field(
        "t", FieldOptions(type="time", time_quantum="YMDH"))
    ts = datetime(2018, 3, 4, 5)
    f.set_bit(1, 9, timestamp=ts)
    names = set(f.views.keys())
    assert {"standard", "standard_2018", "standard_201803",
            "standard_20180304", "standard_2018030405"} <= names
    for vn in names:
        assert f.view(vn).fragment(0).bit(1, 9)
    h.close()


def test_views_by_time_range_minimal_cover():
    views = timeq.views_by_time_range(
        "standard", datetime(2018, 1, 31, 22), datetime(2018, 3, 2, 2), "YMDH")
    assert views == [
        "standard_2018013122", "standard_2018013123",
        "standard_201802",
        "standard_20180301",
        "standard_2018030200", "standard_2018030201",
    ]
    # whole year aligns to one view
    assert timeq.views_by_time_range(
        "standard", datetime(2018, 1, 1), datetime(2019, 1, 1), "YMDH") == \
        ["standard_2018"]


def test_existence_field_tracks_columns(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i", track_existence=True)
    idx.create_field("f")
    idx.add_existence(np.array([1, 2, 3], dtype=np.uint64))
    ef = idx.existence_field()
    frag = ef.view().fragment(0)
    np.testing.assert_array_equal(frag.row_columns(0), [1, 2, 3])
    h.close()


def test_block_checksums_and_merge(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    f.import_bits(np.array([0, 1, 250], np.uint64), np.array([5, 6, 7], np.uint64))
    frag = f.view().fragment(0)
    blocks = dict(frag.checksum_blocks())
    assert set(blocks) == {0, 2}
    # identical data on a second holder hashes identically
    h2 = Holder(str(tmp_path / "other"))
    h2.open()
    g = h2.create_index("i").create_field("f")
    g.import_bits(np.array([0, 1, 250], np.uint64), np.array([5, 6, 7], np.uint64))
    frag2 = g.view().fragment(0)
    assert dict(frag2.checksum_blocks()) == blocks
    # diverge and merge
    frag2.set_bit(1, 8)
    rows, cols = frag2.block_data(0)
    (_, _), (theirs_rows, theirs_cols) = frag.merge_block(0, rows, cols)
    assert frag.bit(1, 8)
    assert len(theirs_rows) == 0
    h.close()
    h2.close()


def test_holder_schema_and_delete(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("myindex")
    idx.create_field("f1")
    idx.create_field("n1", FieldOptions(type="int", min=0, max=100))
    schema = h.schema()
    assert schema[0]["name"] == "myindex"
    assert [f["name"] for f in schema[0]["fields"]] == ["f1", "n1"]
    with pytest.raises(ValueError):
        h.create_index("myindex")
    with pytest.raises(ValueError):
        h.create_index("BadName")
    idx.delete_field("f1")
    assert idx.field("f1") is None
    h.delete_index("myindex")
    assert h.index("myindex") is None
    h.close()


def test_import_roaring(tmp_path):
    from pilosa_tpu.storage import Bitmap

    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("i").create_field("f")
    # row 2, columns 10,11 encoded as a roaring fragment payload
    bm = Bitmap(np.array([2 * SHARD_WIDTH + 10, 2 * SHARD_WIDTH + 11],
                         dtype=np.uint64))
    frag = f.create_view_if_not_exists("standard").create_fragment_if_not_exists(0)
    frag.import_roaring(bm.write_bytes())
    assert frag.bit(2, 10) and frag.bit(2, 11)
    np.testing.assert_array_equal(frag.row_columns(2), [10, 11])
    h.close()


def test_topn_cache_persists_and_reloads(tmp_path):
    """.cache sidecar flush + reload (reference flushCache fragment.go:1858,
    openCache :252)."""
    import os
    from pilosa_tpu.core.fragment import Fragment

    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    for row, n in [(1, 3), (2, 5), (9, 1)]:
        for c in range(n):
            f.set_bit(row, c)
    f.close()  # flushes cache
    assert os.path.exists(f.cache_path())
    g = Fragment(path, "i", "f", "standard", 0)
    g.open()
    assert g.cache.get(2) == 5
    assert g.cache.get(1) == 3
    top = g.cache.top()
    assert top[0] == (2, 5)
    g.close()


def test_time_field_bulk_import_with_timestamps(tmp_path):
    """Timestamped bulk import fans bits into quantum views (reference
    field.Import routing per RowTime, field.go:1054, time.go:91)."""
    from datetime import datetime
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor

    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("t")
    f = idx.create_field("e", FieldOptions(type="time",
                                           time_quantum="YMD"))
    ts = [datetime(2018, 1, 2), datetime(2018, 1, 5), datetime(2018, 2, 1)]
    f.import_bits(np.array([1, 1, 1], np.uint64),
                  np.array([10, 11, 12], np.uint64),
                  timestamps=ts)
    names = set(f.views.keys())
    assert "standard_2018" in names and "standard_201801" in names \
        and "standard_20180102" in names
    ex = Executor(h)
    (res,) = ex.execute(
        "t", "Row(e=1, from='2018-01-01T00:00', to='2018-02-01T00:00')")
    assert res.columns().tolist() == [10, 11]
    (res,) = ex.execute(
        "t", "Row(e=1, from='2018-01-03T00:00', to='2018-03-01T00:00')")
    assert res.columns().tolist() == [11, 12]
    h.close()


def test_bulk_import_clear_flag(tmp_path):
    """Import with clear=True removes the given bits (reference
    fragment.bulkImport clear path / Import clear arg)."""
    from pilosa_tpu.core.holder import Holder

    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("c").create_field("f")
    f.import_bits(np.array([1, 1, 1], np.uint64),
                  np.array([5, 6, 7], np.uint64))
    f.import_bits(np.array([1, 1], np.uint64),
                  np.array([6, 7], np.uint64), clear=True)
    frag = f.view().fragment(0)
    assert frag.bit(1, 5) and not frag.bit(1, 6) and not frag.bit(1, 7)
    h.close()


def test_mutex_bulk_import_last_wins(tmp_path):
    """Mutex bulk import keeps one row per column — later value wins
    (reference bulkImportMutex, fragment.go:1605)."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions

    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("m").create_field("mx", FieldOptions(type="mutex"))
    f.import_bits(np.array([1, 2, 3], np.uint64),
                  np.array([7, 7, 7], np.uint64))
    frag = f.view().fragment(0)
    assert not frag.bit(1, 7) and not frag.bit(2, 7) and frag.bit(3, 7)
    # and a fresh write still clears the previous value
    f.set_bit(1, 7)
    assert frag.bit(1, 7) and not frag.bit(3, 7)
    h.close()


def test_cache_sidecar_rejected_after_unclean_shutdown(tmp_path):
    """A .cache sidecar saved before later ops reached disk must load as
    COLD on reopen — a complete-looking stale cache would let TopN's
    warm-cache shortcut serve wrong counts. The sidecar is stamped with
    the storage bytes it was computed from (size + tail checksum)."""
    from pilosa_tpu.core.fragment import Fragment

    path = str(tmp_path / "frag")
    f1 = Fragment(path, "i", "f", "standard", 0)
    f1.open()
    f1.bulk_import(np.array([1, 1, 1], np.uint64),
                   np.array([1, 2, 3], np.uint64))
    f1.close()  # clean: sidecar saved, stamp matches

    # Clean reopen loads the cache.
    f2 = Fragment(path, "i", "f", "standard", 0)
    f2.open()
    assert len(f2.cache) == 1 and f2.cache.get(1) == 3
    # More writes reach the op log on disk...
    f2.bulk_import(np.array([2, 2, 2, 2], np.uint64),
                   np.array([1, 2, 3, 4], np.uint64))
    f2._file.flush()
    # ...but the process dies without close(): no sidecar update.
    f2._file.close()
    f2.storage.op_writer = None

    f3 = Fragment(path, "i", "f", "standard", 0)
    f3.open()
    assert f3.row_count(2) == 4  # ops replayed: storage is current
    # Stale sidecar rejected — cache cold, so the TopN shortcut is
    # ineligible and the exact sweep answers.
    assert len(f3.cache) == 0
    f3.close()


def test_mutex_bulk_import_vectorized_conflicts(tmp_path):
    """Wide mutex import against pre-existing assignments: the dense
    conflict pass must clear exactly the columns whose row changes and
    keep columns re-asserting their current row (reference
    bulkImportMutex, fragment.go:1605). Cross-checked against a dict
    model."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions

    rng = np.random.default_rng(7)
    h = Holder(str(tmp_path))
    h.open()
    f = h.create_index("m").create_field("mx", FieldOptions(type="mutex"))

    model = {}
    for _ in range(3):
        cols = rng.integers(0, 5000, 800, dtype=np.uint64)
        rows = rng.integers(0, 20, 800, dtype=np.uint64)
        f.import_bits(rows, cols)
        for r, c in zip(rows.tolist(), cols.tolist()):
            model[c] = r

    frag = f.view().fragment(0)
    got = {}
    for r in frag.row_ids():
        for c in frag.row_columns(r).tolist():
            assert c not in got, f"column {c} set in rows {got[c]} and {r}"
            got[c] = r
    assert got == model
    h.close()


def test_translate_replica_cursor_survives_out_of_order_adoption():
    """Incremental translate replication resumes from an explicit cursor
    into the primary's log, not the replica's own log size — replicas
    adopt out-of-order entries via primary-fallback lookups, so their
    logs are not prefixes of the primary's (reference replicate loop,
    translate.go:400)."""
    from pilosa_tpu.core.translate import TranslateStore

    primary, replica = TranslateStore(), TranslateStore()
    a = primary.translate_key("alpha")
    b = primary.translate_key("beta")
    replica.apply_entries([("beta", b)])  # out-of-order adoption
    replica.apply_log(primary.read_log_from(replica.replica_offset),
                      resume=True)
    assert replica.translate_id(a) == "alpha"  # not skipped by the offset
    assert replica.replica_offset == len(primary.log_bytes())
    # resumed pass is a no-op
    assert replica.apply_log(
        primary.read_log_from(replica.replica_offset), resume=True) == 0
    # new allocations stream incrementally
    c = primary.translate_key("gamma")
    applied = replica.apply_log(
        primary.read_log_from(replica.replica_offset), resume=True)
    assert applied == 1 and replica.translate_id(c) == "gamma"


def test_max_columns_trimmed_banks(tmp_path):
    """Declared column bound: banks trim to a 128-word granule instead of
    the 8 KiB container floor (TPU-first extension, no reference
    counterpart; motivates the 4096-bit fingerprint workload,
    docs/examples.md chem use case)."""
    import pytest as _pytest

    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder

    h = Holder(str(tmp_path))
    h.open()
    try:
        idx = h.create_index("mc")
        f = idx.create_field("fp", FieldOptions(max_columns=4096))
        rows = np.repeat(np.arange(50, dtype=np.uint64), 8)
        cols = np.tile(np.arange(8, dtype=np.uint64) * 512 + 3, 50)
        f.import_bits(rows, cols)
        view = f.view()
        assert view.trimmed_words() == 128  # 4096 bits exactly
        bank = view.device_bank((0,), trim=True)
        assert bank.array.shape[-1] == 128
        # Row data survives the narrow round trip.
        got = np.asarray(bank.array[bank.slot(7)][0])
        import numpy as _np
        want = f.view().fragment(0).row_dense(7, u32_words=128)
        _np.testing.assert_array_equal(got, want)
        # Writes past the bound fail loudly.
        with _pytest.raises(ValueError, match="max_columns"):
            f.set_bit(1, 4096)
        with _pytest.raises(ValueError, match="max_columns"):
            f.import_bits(np.array([1], np.uint64),
                          np.array([5000], np.uint64))
        # In another shard the per-shard offset is what's bounded.
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        assert f.set_bit(1, SHARD_WIDTH + 100)
        # Reopen: the bound persists via .meta.
        h.close()
        h2 = Holder(str(tmp_path))
        h2.open()
        f2 = h2.index("mc").field("fp")
        assert f2.options.max_columns == 4096
        assert f2.view().trimmed_words() == 128
        h2.close()
    finally:
        try:
            h.close()
        except Exception:
            pass


def test_sub_container_row_dense_and_set_row(tmp_path):
    """row_dense/rows_dense/set_row at sub-container widths."""
    from pilosa_tpu.core.fragment import Fragment

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0)
    frag.open()
    frag.bulk_import(np.array([2, 2, 3], np.uint64),
                     np.array([0, 4095, 70000], np.uint64))
    d = frag.row_dense(2, u32_words=128)
    assert d.shape == (128,) and d[0] & 1 and (d[127] >> 31) & 1
    bulk = frag.rows_dense([2, 3], 128)
    np.testing.assert_array_equal(bulk[0], d)
    assert bulk[1].any() == False  # row 3's bit is past 4096
    bulk_wide = frag.rows_dense([3], 4096)
    assert bulk_wide[0][70000 // 32] >> (70000 % 32) & 1
    # set_row with a 128-word operand clears the whole rest of the row.
    words = np.zeros(128, np.uint32)
    words[1] = 0b100
    frag.set_row(3, words)
    assert frag.bit(3, 34) and not frag.bit(3, 70000)
    frag.close()


def test_time_field_requires_quantum_and_bsi_bound(tmp_path):
    """Regressions from review: time fields must still demand a quantum,
    and max_columns binds BSI writes too."""
    import pytest as _pytest

    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder

    with _pytest.raises(ValueError, match="quantum"):
        FieldOptions(type="time", time_quantum="").validate()
    h = Holder(str(tmp_path))
    h.open()
    try:
        idx = h.create_index("tb")
        f = idx.create_field("v", FieldOptions(type="int", min=0, max=100,
                                               max_columns=4096))
        f.set_value(10, 5)
        with _pytest.raises(ValueError, match="max_columns"):
            f.set_value(5000, 7)
        with _pytest.raises(ValueError, match="max_columns"):
            f.import_values(np.array([4096], np.uint64),
                            np.array([1], np.int64))
    finally:
        h.close()


def test_noop_remove_keeps_array_encoding(tmp_path):
    import numpy as np

    from pilosa_tpu.storage.roaring import Bitmap

    b = Bitmap([1, 5, 9])
    b.optimize()
    assert not b.remove(6)  # no-op: must not materialize dense
    assert b.containers[0].dtype == np.uint16
    assert b.remove(5) and b.containers[0].dtype == np.uint64


def test_bulk_import_snapshot_failure_keeps_durability(tmp_path, monkeypatch):
    """Batch imports append their op record BEFORE the amortized fold
    check, so even a snapshot that fails mid-rewrite (disk full during
    the byte-triggered fold) leaves the batch durable in the log."""
    import numpy as np
    from pilosa_tpu.core import fragment as fragment_mod
    from pilosa_tpu.core.fragment import Fragment

    # Any batch record trips the byte-based fold immediately.
    monkeypatch.setattr(fragment_mod, "OPLOG_FOLD_MIN_BYTES", 1)
    p = str(tmp_path / "f")
    f = Fragment(p, "i", "f", "standard", 0)
    f.open()
    # Fail INSIDE the real _snapshot, after it has already closed the
    # op-log append handle — the hard case: _snapshot's finally must
    # restore the handle so later appends still work.
    import os as _os
    calls = {"n": 0}
    orig_replace = _os.replace

    def failing_replace(src, dst):
        if dst.endswith("f") and "snapshotting" in src:
            calls["n"] += 1
            raise OSError("disk full (simulated)")
        return orig_replace(src, dst)

    rows = np.zeros(50, np.uint64)
    cols = np.arange(50, dtype=np.uint64)
    _os.replace = failing_replace
    try:
        f.bulk_import(rows, cols)
    except OSError:
        pass
    finally:
        _os.replace = orig_replace
    assert calls["n"] == 1  # the fold fired and failed
    f.close()
    f2 = Fragment(p, "i", "f", "standard", 0)
    f2.open()
    assert f2.row_count(0) == 50  # batch survived via its own op record
    f2.close()
