"""PQL conformance corpus, ported from the reference parser tests.

Table-driven differential suite pinning grammar conformance against the
reference's PEG corpus (/root/reference/pql/pqlpeg_test.go:1-674) and
the older parser suite (/root/reference/pql/parser_test.go:26-195).
Every case asserts the same outcome the reference asserts for the same
input: parses-with-N-calls, exact AST deep equality, or a parse error.
Intentional divergences are documented inline next to their case.
"""

import pytest

from pilosa_tpu.pql import parse_string
from pilosa_tpu.pql.ast import (
    BETWEEN, Call, Condition, EQ, GT, GTE, LT, LTE, NEQ,
)


def C(name, args=None, children=None):
    return Call(name, args or {}, children or [])


# ---------------------------------------------------------------------------
# pqlpeg_test.go TestPEGWorking (:57-283): input parses to N calls.

WORKING = [
    ("Empty", "", 0),
    ("Set", "Set(2, f=10)", 1),
    ("SetWithColKeySingleQuote", "Set('foo', f=10)", 1),
    ("SetWithColKeyDoubleQuote", 'Set("foo", f=10)', 1),
    ("SetTime", "Set(2, f=1, 1999-12-31T00:00)", 1),
    ("DoubleSet", "Set(1, a=4)Set(2, a=4)", 2),
    ("DoubleSetSpc", "Set(1, a=4) Set(2, a=4)", 2),
    ("DoubleSetNewline", "Set(1, a=4) \n Set(2, a=4)", 2),
    ("SetWithArbCall", "Set(1, a=4)Blerg(z=ha)", 2),
    ("SetArbSet", "Set(1, a=4)Blerg(z=ha)Set(2, z=99)", 3),
    ("ArbSetArb", "Arb(q=1, a=4)Set(1, z=9)Arb(z=99)", 3),
    ("SetStringArg", "Set(1, a=zoom)", 1),
    ("SetManyArgs", "Set(1, a=4, b=5)", 1),
    ("SetManyMixedArgs", "Set(1, a=4, bsd=haha)", 1),
    ("SetTimestamp", "Set(1, a=4, 2017-04-03T19:34)", 1),
    ("Union()", "Union()", 1),
    ("UnionOneRow", "Union(Row(a=1))", 1),
    ("UnionTwoRows", "Union(Row(a=1), Row(z=44))", 1),
    ("UnionNested", "Union(Intersect(Row(), Union(Row(), Row())), Row())",
     1),
    ("TopN no args", "TopN(boondoggle)", 1),
    ("TopN with args", "TopN(boon, doggle=9)", 1),
    ("double quoted args", """B(a="zm''e")""", 1),
    ("single quoted args", '''B(a='zm""e')''', 1),
    ("SetRowAttrs", "SetRowAttrs(blah, 9, a=47)", 1),
    ("SetRowAttrs2args", "SetRowAttrs(blah, 9, a=47, b=bval)", 1),
    ("SetRowAttrsWithRowKeySingleQuote",
     "SetRowAttrs(blah, 'rowKey', a=47)", 1),
    ("SetRowAttrsWithRowKeyDoubleQuote",
     'SetRowAttrs(blah, "rowKey", a=47)', 1),
    ("SetColumnAttrs", "SetColumnAttrs(9, a=47)", 1),
    ("SetColumnAttrs2args", "SetColumnAttrs(9, a=47, b=bval)", 1),
    ("SetColumnAttrsWithColKeySingleQuote",
     "SetColumnAttrs('colKey', a=47)", 1),
    ("SetColumnAttrsWithColKeyDoubleQuote",
     'SetColumnAttrs("colKey", a=47)', 1),
    ("Clear", "Clear(1, a=53)", 1),
    ("Clear2args", "Clear(1, a=53, b=33)", 1),
    ("TopN", "TopN(myfield, n=44)", 1),
    ("TopNBitmap", "TopN(myfield, Row(a=47), n=10)", 1),
    ("RangeLT", "Row(a < 4)", 1),
    ("RangeGT", "Row(a > 4)", 1),
    ("RangeLTE", "Row(a <= 4)", 1),
    ("RangeGTE", "Row(a >= 4)", 1),
    ("RangeEQ", "Row(a == 4)", 1),
    ("RangeNEQ", "Row(a != null)", 1),
    ("RangeLTLT", "Row(4 < a < 9)", 1),
    ("RangeLTLTE", "Row(4 < a <= 9)", 1),
    ("RangeLTELT", "Row(4 <= a < 9)", 1),
    ("RangeLTELTE", "Row(4 <= a <= 9)", 1),
    ("RangeTime",
     "Row(a=4, from=2010-07-04T00:00, to=2010-08-04T00:00)", 1),
    ("RangeTimeQuotes",
     "Row(a=4, from='2010-07-04T00:00', to=\"2010-08-04T00:00\")", 1),
    ("RangeTimeFromQuotes", "Row(a=4, from='2010-07-04T00:00')", 1),
    ("RangeTimeToQuotes", 'Row(a=4, to="2010-08-04T00:00")', 1),
    ("Dashed Frame", "Set(1, my-frame=9)", 1),
    ("newlines", "Set(\n1,\nmy-frame\n=9)", 1),
]


@pytest.mark.parametrize("name,src,ncalls", WORKING,
                         ids=[w[0] for w in WORKING])
def test_peg_working(name, src, ncalls):
    q = parse_string(src)
    assert len(q.calls) == ncalls, q.calls


# ---------------------------------------------------------------------------
# pqlpeg_test.go TestPEGErrors (:285-327): input must NOT parse.

ERRORS = [
    ("SetNoParens", "Set"),
    ("SetBadTimestamp", "Set(1, a=4, 2017-94-03T19:34)"),
    ("SetTimestampNoArg", "Set(1, 2017-04-03T19:34)"),
    ("SetStartingComma", "Set(, 1, a=4)"),
    ("StartinCommaArb", "Zeeb(, a=4)"),
    ("SetRowAttrs0args", "SetRowAttrs(blah, 9)"),
    ("Clear0args", "Clear(9)"),
    ("RangeTimeGT", "Row(a>4, 2010-07-04T00:00, 2010-08-04T00:00)"),
    ("RangeTimeOneStamp", "Row(a=4, 2010-07-04T00:00)"),
    # pqlpeg_test.go:19-24 — interior unescaped double quote.
    ("InteriorUnescapedQuote",
     'SetRowAttrs(attr="http://zoo9.com=\\\\\'hello\' "and \\"hello\\"")'),
]


@pytest.mark.parametrize("name,src", ERRORS, ids=[e[0] for e in ERRORS])
def test_peg_errors(name, src):
    with pytest.raises(ValueError):
        parse_string(src)


# ---------------------------------------------------------------------------
# pqlpeg_test.go TestPQLDeepEquality (:329-674): exact AST.

DEEP = [
    ("Set", "Set(1, a=7, 2010-07-08T14:44)",
     C("Set", {"a": 7, "_col": 1, "_timestamp": "2010-07-08T14:44"})),
    ("SetRowAttrs", "SetRowAttrs(myfield, 9, z=4)",
     C("SetRowAttrs", {"z": 4, "_field": "myfield", "_row": 9})),
    ("SetRowAttrsWithRowKeySingleQuote",
     "SetRowAttrs(myfield, 'rowKey', z=4)",
     C("SetRowAttrs", {"z": 4, "_field": "myfield", "_row": "rowKey"})),
    ("SetRowAttrsWithRowKeyDoubleQuote",
     'SetRowAttrs(myfield, "rowKey", z=4)',
     C("SetRowAttrs", {"z": 4, "_field": "myfield", "_row": "rowKey"})),
    ("SetColumnAttrs", "SetColumnAttrs(9, z=4)",
     C("SetColumnAttrs", {"z": 4, "_col": 9})),
    ("SetColumnAttrsWithColKeySingleQuote",
     "SetColumnAttrs('colKey', z=4)",
     C("SetColumnAttrs", {"z": 4, "_col": "colKey"})),
    ("SetColumnAttrsWithColKeyDoubleQuote",
     'SetColumnAttrs("colKey", z=4)',
     C("SetColumnAttrs", {"z": 4, "_col": "colKey"})),
    ("Clear", "Clear(1, a=7)", C("Clear", {"a": 7, "_col": 1})),
    ("TopN", "TopN(myfield, Row(), a=7)",
     C("TopN", {"a": 7, "_field": "myfield"}, [C("Row")])),
    ("RangeEQ", "Row(a==7)", C("Row", {"a": Condition(EQ, 7)})),
    ("RangeLT", "Row(a<7)", C("Row", {"a": Condition(LT, 7)})),
    ("RangeLTE", "Row(a<=7)", C("Row", {"a": Condition(LTE, 7)})),
    ("RangeGTE", "Row(a>=7)", C("Row", {"a": Condition(GTE, 7)})),
    ("RangeGT", "Row(a>7)", C("Row", {"a": Condition(GT, 7)})),
    ("RangeNEQ", "Row(a!=null)", C("Row", {"a": Condition(NEQ, None)})),
    # Open bounds normalize to inclusive BETWEEN, ast.go:514-529.
    ("RangeLTELT", "Row(4 <= a < 9)",
     C("Row", {"a": Condition(BETWEEN, [4, 8])})),
    ("RangeLTLT", "Row(4 < a < 9)",
     C("Row", {"a": Condition(BETWEEN, [5, 8])})),
    ("RangeLTELTE", "Row(4 <= a <= 9)",
     C("Row", {"a": Condition(BETWEEN, [4, 9])})),
    ("RangeLTLTE", "Row(4 < a <= 9)",
     C("Row", {"a": Condition(BETWEEN, [5, 9])})),
    ("Sum", "Sum(field=f)", C("Sum", {"field": "f"})),
    ("Weird dash", "Sum(field-=f)", C("Sum", {"field-": "f"})),
    ("SumChild", "Sum(Row(), field=f)",
     C("Sum", {"field": "f"}, [C("Row")])),
    ("MinChild", "Min(Row(), field=f)",
     C("Min", {"field": "f"}, [C("Row")])),
    ("MaxChild", "Max(Row(), field=f)",
     C("Max", {"field": "f"}, [C("Row")])),
    ("OptionsWrapper", "Options(Row(f1=123), excludeRowAttrs=true)",
     C("Options", {"excludeRowAttrs": True},
       [C("Row", {"f1": 123})])),
    ("GroupBy", "GroupBy(Rows(), filter=Row(a=1))",
     C("GroupBy", {"filter": C("Row", {"a": 1})}, [C("Rows")])),
    ("GroupByFilterRangeLTLT", "GroupBy(Rows(), filter=Row(4 < a < 9))",
     C("GroupBy", {"filter": C("Row", {"a": Condition(BETWEEN, [5, 8])})},
       [C("Rows")])),
]


@pytest.mark.parametrize("name,src,want", DEEP, ids=[d[0] for d in DEEP])
def test_deep_equality(name, src, want):
    q = parse_string(src)
    assert len(q.calls) == 1
    assert q.calls[0] == want


# ---------------------------------------------------------------------------
# parser_test.go TestParser_Parse (:26-195).

PARSER = [
    ("Empty", "Bitmap()", C("Bitmap")),
    ("ChildrenOnly", "Union(  Bitmap()  , Count()  )",
     C("Union", None, [C("Bitmap"), C("Count")])),
    ("ChildWithArgument", "Count( Bitmap( id=100))",
     C("Count", None, [C("Bitmap", {"id": 100})])),
    ("ArgumentsOnly",
     'MyCall( key= value, foo=\'bar\', age = 12 , bool0=true, '
     'bool1=false, x=null, escape="\\" \\\\escape\\n\\\\\\\\"  )',
     C("MyCall", {"key": "value", "foo": "bar", "age": 12,
                  "bool0": True, "bool1": False, "x": None,
                  "escape": '" \\escape\n\\\\'})),
    ("WithFloatArgs", "MyCall( key=12.25, foo= 13.167, bar=2., baz=0.9)",
     C("MyCall", {"key": 12.25, "foo": 13.167, "bar": 2.0, "baz": 0.9})),
    ("WithNegativeArgs", "MyCall( key=-12.25, foo= -13)",
     C("MyCall", {"key": -12.25, "foo": -13})),
    ("ChildrenAndArguments", "TopN(f, Bitmap(id=100, field=other), n=3)",
     C("TopN", {"n": 3, "_field": "f"},
       [C("Bitmap", {"id": 100, "field": "other"})])),
    ("ListArgument", "TopN(f, ids=[0,10,30])",
     C("TopN", {"_field": "f", "ids": [0, 10, 30]})),
    ("WithCondition",
     "MyCall(key=foo, x == 12.25, y >= 100, z >< [4,8], m != null)",
     C("MyCall", {"key": "foo",
                  "x": Condition(EQ, 12.25),
                  "y": Condition(GTE, 100),
                  "z": Condition(BETWEEN, [4, 8]),
                  "m": Condition(NEQ, None)})),
]


@pytest.mark.parametrize("name,src,want", PARSER,
                         ids=[p[0] for p in PARSER])
def test_parser_parse(name, src, want):
    q = parse_string(src)
    assert len(q.calls) == 1
    assert q.calls[0] == want


def test_float_args_are_floats():
    """int64 vs float64 distinction survives (parser_test.go:100-135):
    2. stays float even though it is integral."""
    q = parse_string("MyCall(bar=2.)")
    v = q.calls[0].args["bar"]
    assert isinstance(v, float) and not isinstance(v, bool)
    q = parse_string("MyCall(bar=2)")
    v = q.calls[0].args["bar"]
    assert isinstance(v, int) and not isinstance(v, bool)


# ---------------------------------------------------------------------------
# pqlpeg_test.go TestPEG (:9-48) — the gnarly smoke cases.

def test_peg_smoke_multicall():
    src = ('SetBit(Union(Zitmap(row==4), Intersect(Qitmap(blah>4), '
           'Ritmap(field="http://zoo9.com=\\\\\'hello\' and \\"hello\\"")),'
           ' Hitmap(row=ag-bee)), a="4z", b=5) '
           'Count(Union(Witmap(row=5.73, frame=.10), Row(zztop><[2, 9]))) '
           'TopN(blah, fields=["hello", "goodbye", "zero"])')
    q = parse_string(src)
    assert len(q.calls) == 3
    setbit, count, topn = q.calls
    assert setbit.name == "SetBit"
    assert setbit.args["a"] == "4z" and setbit.args["b"] == 5
    union = setbit.children[0]
    assert union.name == "Union"
    assert union.children[0] == C("Zitmap", {"row": Condition(EQ, 4)})
    ritmap = union.children[1].children[1]
    assert ritmap.args["field"] == 'http://zoo9.com=\\\'hello\' and "hello"'
    assert union.children[2] == C("Hitmap", {"row": "ag-bee"})
    witmap = count.children[0].children[0]
    assert witmap.args == {"row": 5.73, "frame": 0.10}
    zz = count.children[0].children[1]
    assert zz.args["zztop"] == Condition(BETWEEN, [2, 9])
    assert topn.args["_field"] == "blah"
    assert topn.args["fields"] == ["hello", "goodbye", "zero"]


def test_peg_topn_rewrite_ast():
    """pqlpeg_test.go:26-32 asserts a String() round-trip; the rebuild
    asserts the same parse as AST equality instead (Call.to_pql's
    serialization is its own round-trip surface, covered in
    test_pql.py) — intentional divergence, same conformance pinned."""
    q = parse_string("TopN(blah, Bitmap(id==other), field=f, n=0)")
    assert q.calls[0] == C(
        "TopN", {"_field": "blah", "field": "f", "n": 0},
        [C("Bitmap", {"id": Condition(EQ, "other")})])


def test_peg_falsen0_is_string():
    q = parse_string("C(a=falsen0)")
    assert q.calls[0].args["a"] == "falsen0"


def test_peg_bitmap_cond_and_arg():
    q = parse_string("Bitmap(row=4, did==other)")
    assert q.calls[0] == C("Bitmap", {"row": 4,
                                      "did": Condition(EQ, "other")})


def test_old_pql_setbit():
    """pqlpeg_test.go:50-55 — legacy SetBit form still parses."""
    q = parse_string("SetBit(f=11, col=1)")
    assert len(q.calls) == 1 and q.calls[0].name == "SetBit"


# ---------------------------------------------------------------------------
# Double-quote escape edges (Go strconv.Unquote bounds, pql.peg:50).

def test_dq_numeric_escapes():
    q = parse_string('C(a="\\x41\\u00e9\\U0001F600\\101")')
    assert q.calls[0].args["a"] == "Aé\U0001F600A"


@pytest.mark.parametrize("bad", [
    'C(a="\\ud800")',      # lone surrogate — Go rejects
    'C(a="\\777")',        # octal > 255 — Go rejects
    'C(a="\\0_1")',        # '_' is not an octal digit
    'C(a="\\x4")',         # truncated hex
    'C(a="\\q")',          # unknown escape
], ids=["surrogate", "octal-overflow", "underscore", "short-hex",
        "unknown"])
def test_dq_invalid_escapes(bad):
    with pytest.raises(ValueError):
        parse_string(bad)


def test_fallback_reports_furthest_error():
    """When both the special form and the generic fallback fail, the
    error that got furthest into the input wins — the invalid escape,
    not the generic attempt's confusion at the positional col."""
    with pytest.raises(ValueError, match="escape"):
        parse_string('Set(1, f="\\q")')


# ---------------------------------------------------------------------------
# ast_test.go (:25-69) — serialization + condition values. The exact
# String() format intentionally differs (docs/parity.md); the pinned
# property is the ROUND TRIP: to_pql output re-parses to the same AST.

def test_call_to_pql_round_trips():
    for src in ("Bitmap()",
                "Range(field0 >= 10, other=f)",
                "Row(4 < a <= 9)",
                "TopN(f, Row(x=1), n=3, fields=[\"a\", \"b\"])",
                "GroupBy(Rows(f), filter=Row(a=1))"):
        q = parse_string(src)
        again = parse_string(q.calls[0].to_pql())
        assert again.calls[0] == q.calls[0], src


def test_condition_int_slice():
    assert Condition(BETWEEN, [4, 8]).int_slice() == [4, 8]
    assert Condition(BETWEEN, [1, 2, 3]).int_slice() == [1, 2, 3]
    with pytest.raises(ValueError):
        Condition(BETWEEN, 7).int_slice()
