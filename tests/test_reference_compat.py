"""File-format compatibility against the reference's own produced bytes.

The reference ships a real import-produced fragment storage file at
testdata/sample_view/0 (used by its fragment benchmarks,
/root/reference/fragment_internal_test.go:41-42) — a pilosa-roaring file
(cookie 12348, /root/reference/roaring/roaring.go:31-38) parsed by
unmarshalPilosaRoaring (/root/reference/roaring/roaring.go:1037). These
tests prove our Python and native codecs read those exact bytes, agree
with each other, and round-trip them — not merely our own output.
"""

import os
import shutil
import struct

import numpy as np
import pytest

from pilosa_tpu.storage.roaring import Bitmap

REF_FRAGMENT = "/root/reference/testdata/sample_view/0"

needs_ref = pytest.mark.skipif(
    not os.path.exists(REF_FRAGMENT),
    reason="reference testdata not mounted")


@pytest.fixture(scope="module")
def ref_bytes():
    with open(REF_FRAGMENT, "rb") as f:
        return f.read()


@needs_ref
def test_python_codec_parses_reference_fragment(ref_bytes):
    b = Bitmap.from_bytes(ref_bytes)
    # Container count comes straight from the file header (keyN at
    # offset 4, roaring.go:1050), so parsing must surface exactly that
    # many containers.
    (key_n,) = struct.unpack_from("<I", ref_bytes, 4)
    assert len(b.containers) == key_n == 14207
    assert b.count() == 35001
    # The fragment holds 1000 rows x ~35 bits in a 2^20-wide shard, so
    # the max position sits in row 999.
    assert b.max() // (1 << 20) == 999
    # Positions are strictly sorted unique uint64s.
    pos = b.slice()
    assert len(pos) == b.count()
    assert np.all(np.diff(pos.astype(np.int64)) > 0)


@needs_ref
def test_python_codec_roundtrips_reference_bytes(ref_bytes):
    b = Bitmap.from_bytes(ref_bytes)
    again = Bitmap.from_bytes(b.write_bytes())
    assert np.array_equal(b.slice(), again.slice())


@needs_ref
def test_native_codec_agrees_with_python(ref_bytes):
    from pilosa_tpu import native

    if not native.available():
        pytest.skip("native codec not built")
    out = native.roaring_load(ref_bytes)
    assert out is not None
    keys, words, op_n, _ = out
    assert len(keys) == 14207 and op_n == 0
    # Expand (key, dense-words) to absolute positions and compare with
    # the Python parse bit-for-bit.
    words = np.asarray(words, dtype=np.uint64).reshape(len(keys), -1)
    got = []
    for key, dense in zip(keys, words):
        bits = np.unpackbits(dense.view(np.uint8), bitorder="little")
        got.append(np.nonzero(bits)[0].astype(np.uint64)
                   + np.uint64(key << 16))
    got = np.concatenate(got)
    assert np.array_equal(np.sort(got), Bitmap.from_bytes(ref_bytes).slice())
    # And the native serializer's output parses back identically in
    # Python (cross-codec round trip).
    blob = native.roaring_serialize(
        np.asarray(keys, dtype=np.uint64),
        words.reshape(-1))
    if blob is not None:
        assert np.array_equal(Bitmap.from_bytes(bytes(blob)).slice(),
                              Bitmap.from_bytes(ref_bytes).slice())


@needs_ref
def test_fragment_opens_reference_file(tmp_path):
    """A Fragment pointed at the reference's storage file opens, reports
    rows, and checksums blocks (the reference's own benchmark asserts
    len(Blocks()) > 0 on this file, fragment_internal_test.go:1331)."""
    from pilosa_tpu.core.fragment import Fragment

    path = tmp_path / "i" / "f" / "standard" / "0"
    path.parent.mkdir(parents=True)
    shutil.copy(REF_FRAGMENT, path)
    frag = Fragment(str(path), "i", "f", "standard", 0)
    frag.open()
    rows = frag.row_ids()
    assert len(rows) == 1000 and rows[0] == 0 and rows[-1] == 999
    assert sum(frag.row_count(r) for r in rows) == 35001
    blocks = frag.checksum_blocks()
    assert len(blocks) == 10  # 1000 rows / 100-row blocks
    # Reads work: every row has at least one column.
    assert all(len(frag.row_columns(r)) for r in rows[:5])
    frag.close()
