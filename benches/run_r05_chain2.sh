#!/bin/bash
# Round-5 capture chain, phase 2. Context: the 08:31-08:47 UTC window
# landed the live bench.py record (the round's #1 item); the tunnel
# then dropped mid-probe. This chain uses the outage productively:
#   1. 100M tanimoto NOW — its long host-side build is tunnel-
#      independent; the leg then holds at the query boundary (3 h) so
#      WHENEVER the next window opens, the highest-value remaining
#      capture is already sitting at the device call.
#   2. Quick legs (membership probe, 10M, startrace, bsi, membership
#      e2e) in a probe-gated retry loop until the janitor deadline:
#      a cheap device probe gates each pass so legs only build+run
#      when the tunnel actually answers; short holds keep a flapping
#      tunnel from pinning one leg for hours.
#   3. Postcheck: graft entry + 8-device dryrun + full pytest.
# Promotion judges each leg by its own artifact; markers only on
# promotion; re-runnable (markers skip landed legs).
cd /root/repo
log() { echo "$(date -u +%H:%M:%S) chain2: $*" >&2; }
DEADLINE="${1:-11:38}"       # quick-leg loop stops after this (UTC HH:MM)
PASS2_CUTOFF="${2:-10:30}"   # no 100M pass 2 after this

# Epoch-second deadlines with the shared midnight-wrap rule (ADVICE
# r5; see benches/deadline_epoch.sh for the 6 h disambiguation).
. benches/deadline_epoch.sh
DEADLINE_EPOCH=$(deadline_epoch "$DEADLINE")
PASS2_CUTOFF_EPOCH=$(deadline_epoch "$PASS2_CUTOFF")

promote_tanimoto() {  # $1=tmp $2=final $3=marker $4=want_n
  python - "$1" "$2" "$3" "$4" <<'EOF'
import json, os, sys
tmp, final, marker, want_n = sys.argv[1:5]
rec = None
try:
    for ln in reversed(open(tmp).read().strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
ok = (rec is not None and not rec.get("partial")
      and rec.get("molecules") == int(want_n) and "p50_query_s" in rec)
if ok:
    with open(final, "w") as fh:
        fh.write(json.dumps(rec) + "\n")
    open(marker, "w").close()
    os.unlink(tmp)
    print("promoted:", rec.get("p50_query_s"))
sys.exit(0 if ok else 1)
EOF
}

promote_value() {  # $1=tmp $2=final $3=marker
  python - "$1" "$2" "$3" <<'EOF'
import json, os, sys
tmp, final, marker = sys.argv[1:4]
rec = None
try:
    for ln in reversed(open(tmp).read().strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
ok = rec is not None and not rec.get("partial") and "value" in rec
if ok:
    os.replace(tmp, final)
    open(marker, "w").close()
sys.exit(0 if ok else 1)
EOF
}

# ---- 1. 100M tanimoto: build now, hold at the query boundary ----------
for pass in 1 2; do
  [ -e benches/.tanimoto_chunked_100m_r05_done ] && break
  log "100M tanimoto pass $pass (build rides the outage)"
  timeout 18000 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=10800 PILOSA_TANIMOTO_N=100000000 \
      PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py \
      > benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp \
      2> benches/tanimoto_chunked_100m_r05_tpu.err
  log "100M rc=$?"
  promote_tanimoto benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp \
      benches/tanimoto_chunked_100m_r05_tpu.jsonl \
      benches/.tanimoto_chunked_100m_r05_done 100000000 >&2 && break
  rm -f benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp
  [ "$(date -u +%s)" -ge "$PASS2_CUTOFF_EPOCH" ] && break  # no room for pass 2
done

# ---- 2. probe-gated quick-leg loop -----------------------------------
tunnel_up() {
  timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, _ = probe_device_once(90)
sys.exit(0 if ok else 1)" 2>/dev/null
}

all_done() {
  [ -e benches/.membership_probe_r05_done ] && \
  [ -e benches/.tanimoto_chunked_10m_r05_done ] && \
  [ -e benches/.startrace_r05_done ] && \
  [ -e benches/.bsi_r05_done ] && \
  [ -e benches/.membership_e2e_r05_done ]
}

while :; do
  all_done && { log "all quick legs landed"; break; }
  [ "$(date -u +%s)" -ge "$DEADLINE_EPOCH" ] && \
    { log "deadline, stopping quick loop"; break; }
  if ! tunnel_up; then
    sleep 90
    continue
  fi
  log "tunnel answered; running missing quick legs"

  if [ ! -e benches/.membership_probe_r05_done ]; then
    log "membership probe"
    timeout 1800 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
        PILOSA_BENCH_HOLD_MAX_S=300 \
        python benches/pbank_membership_probe.py \
        > benches/membership_probe_r05_tpu.jsonl.tmp \
        2> benches/membership_probe_r05_tpu.err
    rc=$?
    log "probe rc=$rc"
    # rc gate matches run_r05_live_chain.sh: a timed-out/killed probe
    # that already emitted the line must not be promoted (ADVICE r5).
    if [ "$rc" -eq 0 ] && grep -q pbank_membership_best \
        benches/membership_probe_r05_tpu.jsonl.tmp 2>/dev/null; then
      mv benches/membership_probe_r05_tpu.jsonl.tmp \
         benches/membership_probe_r05_tpu.jsonl
      touch benches/.membership_probe_r05_done
    else
      rm -f benches/membership_probe_r05_tpu.jsonl.tmp
    fi
  fi

  if [ ! -e benches/.tanimoto_chunked_10m_r05_done ]; then
    log "10M tanimoto"
    timeout 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
        PILOSA_BENCH_HOLD_MAX_S=600 PILOSA_TANIMOTO_N=10000000 \
        PILOSA_TANIMOTO_ITERS=5 python benches/tanimoto_chunked.py \
        > benches/tanimoto_chunked_10m_r05_tpu.jsonl.tmp \
        2> benches/tanimoto_chunked_10m_r05_tpu.err
    log "10M rc=$?"
    promote_tanimoto benches/tanimoto_chunked_10m_r05_tpu.jsonl.tmp \
        benches/tanimoto_chunked_10m_r05_tpu.jsonl \
        benches/.tanimoto_chunked_10m_r05_done 10000000 >&2
    rm -f benches/tanimoto_chunked_10m_r05_tpu.jsonl.tmp
  fi

  for leg in startrace bsi; do
    if [ ! -e "benches/.${leg}_r05_done" ]; then
      log "$leg batch leg"
      timeout 2400 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
          PILOSA_BENCH_HOLD_MAX_S=600 python "benches/${leg}.py" \
          > "benches/${leg}_r05_tpu.jsonl.tmp" \
          2> "benches/${leg}_r05_tpu.err"
      log "$leg rc=$?"
      promote_value "benches/${leg}_r05_tpu.jsonl.tmp" \
          "benches/${leg}_r05_tpu.jsonl" "benches/.${leg}_r05_done" >&2 \
        || rm -f "benches/${leg}_r05_tpu.jsonl.tmp"
    fi
  done

  if [ -f benches/membership_probe_r05_tpu.jsonl ] && \
     [ ! -e benches/.membership_e2e_r05_done ]; then
    VARIANT=$(python - <<'EOF'
import json
best = None
for ln in open("benches/membership_probe_r05_tpu.jsonl"):
    try:
        rec = json.loads(ln)
    except ValueError:
        continue
    if rec.get("metric") == "pbank_membership_best":
        best = rec
if best and best.get("best") == "search" and \
        best.get("speedup_vs_compare", 0) > 1.10:
    print("search")
EOF
)
    if [ -n "$VARIANT" ]; then
      log "membership e2e leg with $VARIANT"
      timeout 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
          PILOSA_BENCH_HOLD_MAX_S=600 PILOSA_TANIMOTO_N=10000000 \
          PILOSA_TANIMOTO_ITERS=5 "PILOSA_TPU_PBANK_MEMBERSHIP=$VARIANT" \
          python benches/tanimoto_chunked.py \
          > "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" \
          2> "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.err"
      log "membership e2e rc=$?"
      promote_tanimoto \
          "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" \
          "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl" \
          benches/.membership_e2e_r05_done 10000000 >&2
      rm -f "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp"
    else
      log "probe verdict: compare stands; no e2e leg"
      touch benches/.membership_e2e_r05_done
    fi
  fi
done

# ---- 3. postcheck -----------------------------------------------------
log "postcheck: graft entry + dryrun + pytest"
timeout 900 env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g
import jax
fn, args = g.entry()
jax.jit(fn)(*args)
print('entry ok')
g.dryrun_multichip(8)
print('dryrun_multichip ok')
" > benches/postcheck_r05.log 2>&1
echo "graft rc=$?" >> benches/postcheck_r05.log
timeout 2400 python -m pytest tests/ -x -q >> benches/postcheck_r05.log 2>&1
echo "pytest rc=$?" >> benches/postcheck_r05.log
log "chain2 done"
