"""NYC-taxi-shaped multi-field workload — BASELINE.md config 5 (scaled).

The reference's flagship example (docs/examples.md:15-209): one index of
rides with low-cardinality set fields (cab_type, passenger_count), BSI
int fields (dist_miles, total_amount_dollars), and a time field
(pickup). Queries mix Count/Intersect, BSI range + Sum, TopN, GroupBy,
and a time-range Row — the cross-section a taxi dashboard issues.

Scaled: PILOSA_TAXI_N rides (default 10M, = 10 shards of 2^20 columns;
the 1B x 1024-shard BASELINE config is this times 100 — every query
here is a per-shard map + associative reduce, so shards scale linearly
onto chips; HBM per shard is what the budget manager bounds).

For each query family: p50 latency through the production executor vs
an exact numpy recomputation on the same arrays, printed as one JSON
line each, plus a closing summary line.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_RIDES = int(os.environ.get("PILOSA_TAXI_N", 10_000_000))
N_TIMED = min(N_RIDES, 200_000)  # rides that also get pickup timestamps
ITERS = int(os.environ.get("PILOSA_TAXI_ITERS", 3))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def emit(metric, tpu_t, cpu_t, **extra):
    print(json.dumps({"metric": metric, "value": tpu_t, "unit": "seconds",
                      "vs_baseline": cpu_t / tpu_t if tpu_t else 0.0,
                      **extra}), flush=True)


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()

    from pilosa_tpu.utils.benchenv import \
        install_partial_record_handler
    install_partial_record_handler(
        "taxi_workload_total", "rides")
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    rng = np.random.default_rng(5)
    cols = np.arange(N_RIDES, dtype=np.uint64)
    cab = rng.integers(0, 3, N_RIDES).astype(np.uint64)       # yellow/green/fhv
    pax = rng.integers(1, 7, N_RIDES).astype(np.uint64)
    dist = rng.integers(0, 300, N_RIDES).astype(np.int64)     # tenths of miles
    amount = (dist * 25 // 10 + rng.integers(3, 20, N_RIDES)).astype(np.int64)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        idx = holder.create_index("taxi")
        t0 = time.perf_counter()
        idx.create_field("cab_type").import_bits(cab, cols)
        log(f"taxi: cab_type loaded {time.perf_counter()-t0:.1f}s")
        idx.create_field("passenger_count").import_bits(pax, cols)
        log(f"taxi: passenger_count loaded {time.perf_counter()-t0:.1f}s")
        idx.create_field("dist", FieldOptions(type="int", min=0, max=300)) \
            .import_values(cols, dist)
        log(f"taxi: dist loaded {time.perf_counter()-t0:.1f}s")
        idx.create_field("amount", FieldOptions(type="int", min=0,
                                                max=1000)) \
            .import_values(cols, amount)
        log(f"taxi: amount loaded {time.perf_counter()-t0:.1f}s")
        pickup = idx.create_field("pickup",
                                  FieldOptions(type="time",
                                               time_quantum="YMD"))
        from datetime import datetime
        days = rng.integers(0, 28, N_TIMED)  # kept for the numpy baseline
        pickup.import_bits(
            np.zeros(N_TIMED, np.uint64), cols[:N_TIMED],
            timestamps=[datetime(2019, 1, 1 + int(d)) for d in days])
        idx.add_existence(cols)
        load_s = time.perf_counter() - t0
        log(f"taxi: loaded in {load_s:.1f}s")

        # With an intermittent TPU tunnel, meet the chip at query time:
        # the load above is host-only, so (when enabled) wait here.
        from pilosa_tpu.utils.benchenv import hold_for_tpu, \
            measurement_context
        hold_for_tpu("taxi")
        # One quiet WAIT up front; each leg then re-stamps its own
        # record with a no-wait probe so the evidence describes the
        # conditions of THAT leg's timed loop, not the hold's.
        ctx = measurement_context()

        ex = Executor(holder)

        def p50(q):
            nonlocal ctx
            t0 = time.perf_counter()
            (want,) = ex.execute("taxi", q)  # warm
            log(f"taxi: warm {q[:40]!r} {time.perf_counter()-t0:.1f}s")
            ctx = measurement_context(wait_quiet_s=0)
            times = []
            for _ in range(ITERS):
                t0 = time.perf_counter()
                (got,) = ex.execute("taxi", q)
                times.append(time.perf_counter() - t0)
            return float(np.median(times)), want

        # 1. fused Count(Intersect) over two set fields
        t, got = p50("Count(Intersect(Row(cab_type=0), "
                     "Row(passenger_count=2)))")
        t0 = time.perf_counter()
        want = int(((cab == 0) & (pax == 2)).sum())
        c1 = time.perf_counter() - t0
        assert got == want
        emit("taxi_count_intersect_p50", t, c1, count=got, **ctx)

        # 2. BSI range count
        t, got = p50("Count(Row(dist < 50))")
        t0 = time.perf_counter()
        want = int((dist < 50).sum())
        c2 = time.perf_counter() - t0
        assert got == want
        emit("taxi_bsi_range_count_p50", t, c2, count=got, **ctx)

        # 3. Sum over a filtered row
        t, got = p50("Sum(Row(cab_type=1), field=amount)")
        t0 = time.perf_counter()
        want_v = int(amount[cab == 1].sum())
        want_c = int((cab == 1).sum())
        c3 = time.perf_counter() - t0
        assert (got.value, got.count) == (want_v, want_c)
        emit("taxi_sum_filtered_p50", t, c3, sum=got.value, **ctx)

        # 4. TopN over passenger_count
        t, got = p50("TopN(passenger_count, n=3)")
        t0 = time.perf_counter()
        counts = [(int(p), int((pax == p).sum())) for p in range(1, 7)]
        want_pairs = sorted(counts, key=lambda rc: (-rc[1], rc[0]))[:3]
        c4 = time.perf_counter() - t0
        assert got.pairs == want_pairs
        emit("taxi_topn_p50", t, c4, **ctx)

        # 5. GroupBy cab_type x passenger_count (batched expansion)
        t, got = p50("GroupBy(Rows(cab_type), Rows(passenger_count))")
        t0 = time.perf_counter()
        want_n = sum(1 for c in range(3) for p in range(1, 7)
                     if ((cab == c) & (pax == p)).any())
        c5 = time.perf_counter() - t0
        assert len(got) == want_n
        for gc in got:
            c, p = gc.group[0].row_id, gc.group[1].row_id
            assert gc.count == int(((cab == c) & (pax == p)).sum())
        emit("taxi_groupby_p50", t, c5, groups=len(got), **ctx)

        # 6. time-range row count. Baseline: the same [from, to) date
        # filter vectorized over the drawn days (this leg shipped with
        # emit(t, t) — i.e. no baseline at all — through r03, which is
        # why it sat at vs_baseline 1.0 in every record; VERDICT r3
        # item 10).
        t, got = p50("Count(Row(pickup=0, from='2019-01-05', "
                     "to='2019-01-12'))")
        t0 = time.perf_counter()
        want = int(((days >= 4) & (days < 11)).sum())  # days 5..11 Jan
        c6 = time.perf_counter() - t0
        assert got == want, (got, want)
        emit("taxi_time_range_count_p50", t, c6, count=got, **ctx)

        print(json.dumps({
            "metric": "taxi_workload_total",
            "value": N_RIDES, "unit": "rides",
            "vs_baseline": 1.0,
            "shards": (N_RIDES + (1 << 20) - 1) >> 20,
            "load_seconds": round(load_s, 1),
            **ctx,
        }))
        holder.close()


if __name__ == "__main__":
    main()
    # Real records are out; a late TERM during interpreter
    # teardown must not append a zero-value partial.
    import signal as _signal
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
