"""CPU microbench: serving-path throughput, coalescer off vs on.

64 client threads issue single-`Count` PQL queries over a shared view
bank through a live PilosaHTTPServer — the ISSUE's acceptance shape for
the cross-request coalescer. Phase 1 serves every request on the direct
path (no coalescer); phase 2 attaches a QueryCoalescer and repeats the
identical load. Responses are checked byte-identical across phases per
query string; aggregate qps and the coalescer's occupancy stats go to
stdout as ONE JSON line (progress chatter on stderr).

Two workloads:
- identical: every thread issues the same Count — the ISSUE's
  acceptance shape (64 concurrent single-Count requests over a shared
  bank) and the headline `value`; one window's worth of requests
  executes as ONE device sweep.
- mixed: threads spread over 8 distinct rows (dedup collapses repeats
  of the same row inside one window; the executor batch pipelines the
  distinct remainder) — the harder secondary number.

Clients hold ONE keep-alive connection each (http.client), the shape a
pooled production client presents — a fresh TCP connect + handler
thread per request costs ~4 ms on this box and would swamp what the
bench measures in both modes equally.

Env knobs: COALESCER_BENCH_THREADS (64), COALESCER_BENCH_QUERIES (25
per thread per phase), COALESCER_BENCH_ROWS (8 distinct rows),
COALESCER_BENCH_SHARDS (96).
"""

import http.client
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_THREADS = int(os.environ.get("COALESCER_BENCH_THREADS", 64))
N_QUERIES = int(os.environ.get("COALESCER_BENCH_QUERIES", 25))
N_ROWS = int(os.environ.get("COALESCER_BENCH_ROWS", 8))
N_SHARDS = int(os.environ.get("COALESCER_BENCH_SHARDS", 96))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build(tmp):
    """Dense shared bank (~30% density), written straight into
    container storage like bench.py's builder: Count(Row) then sweeps a
    [shards, words] row slice wide enough that per-query device+plan
    work, not connection churn, is what the phases compare."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    h = Holder(tmp)
    h.open()
    idx = h.create_index("b")
    f = idx.create_field("f")
    rng = np.random.default_rng(3)
    view = f.create_view_if_not_exists("standard")
    words_per_row = SHARD_WIDTH // 64
    for shard in range(N_SHARDS):
        frag = view.create_fragment_if_not_exists(shard)
        dense = rng.integers(0, 2**63, N_ROWS * words_per_row,
                             dtype=np.uint64)
        dense &= rng.integers(0, 2**63, N_ROWS * words_per_row,
                              dtype=np.uint64)
        frag.storage.set_dense_range(0, dense)
        for row in range(N_ROWS):
            frag._touch_row(row)
    return h


class Client:
    """One keep-alive connection, re-dialed on server-side close."""

    def __init__(self, host, port):
        self.host, self.port = host, port
        self.conn = http.client.HTTPConnection(host, port, timeout=60)

    def post(self, q):
        for attempt in (0, 1):
            try:
                self.conn.request("POST", "/index/b/query", body=q)
                return self.conn.getresponse().read()
            except (http.client.HTTPException, OSError):
                if attempt:
                    raise
                self.conn.close()
                self.conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=60)

    def close(self):
        self.conn.close()


def run_phase(host, port, queries):
    """N_THREADS keep-alive clients x N_QUERIES requests; returns
    (qps, responses) where responses maps query -> observed bodies."""
    observed = {q: set() for q in queries}
    obs_lock = threading.Lock()
    errors = []
    barrier = threading.Barrier(N_THREADS + 1)

    def worker(tid):
        local = {}
        client = Client(host, port)
        try:
            barrier.wait()
            for i in range(N_QUERIES):
                q = queries[(tid + i) % len(queries)]
                local.setdefault(q, set()).add(client.post(q))
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            client.close()
        with obs_lock:
            for q, bodies in local.items():
                observed[q].update(bodies)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return N_THREADS * N_QUERIES / dt, observed


def main():
    import tempfile

    from pilosa_tpu.server import API, serve
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient

    out = {"metric": "coalescer_serving_speedup", "unit": "x",
           "threads": N_THREADS, "queries_per_thread": N_QUERIES,
           "distinct_rows": N_ROWS, "shards": N_SHARDS,
           "platform": "cpu"}
    with tempfile.TemporaryDirectory() as tmp:
        log("bench: building holder")
        h = build(tmp)
        api = API(h, stats=MemStatsClient())
        srv = serve(api, "localhost", 0, background=True)
        host, port = "localhost", srv.server_address[1]
        mixed = [f"Count(Row(f={r}))".encode() for r in range(N_ROWS)]
        identical = [b"Count(Row(f=1))"]
        log("bench: warmup (bank upload + compile)")
        warm = Client(host, port)
        for q in mixed:
            warm.post(q)
        warm.close()

        results = {}
        for workload, queries in (("identical", identical),
                                  ("mixed", mixed)):
            log(f"bench: {workload}/direct")
            direct_qps, direct_obs = run_phase(host, port, queries)
            coal = QueryCoalescer(api.executor, window_s=0.002,
                                  max_batch=N_THREADS, max_queue=1024,
                                  stats=api.stats, tracer=api.tracer)
            coal.start()
            api.coalescer = coal
            log(f"bench: {workload}/coalesced")
            coal_qps, coal_obs = run_phase(host, port, queries)
            api.coalescer = None
            coal.stop()
            for q in queries:
                bodies = direct_obs[q] | coal_obs[q]
                assert len(bodies) == 1, \
                    f"responses diverged for {q!r}: {bodies}"
            results[workload] = {
                "direct_qps": round(direct_qps, 1),
                "coalesced_qps": round(coal_qps, 1),
                "speedup": round(coal_qps / direct_qps, 2),
            }
            log(f"bench: {workload}: direct {direct_qps:.0f} qps, "
                f"coalesced {coal_qps:.0f} qps "
                f"({coal_qps / direct_qps:.2f}x)")

        snap = api.stats.snapshot()
        # batch_size is a real cumulative histogram now (pow2 buckets);
        # report the mean + the bucket distribution.
        bs = snap["histograms"].get("coalescer.batch_size", {})
        out.update(results)
        out["value"] = results["identical"]["speedup"]
        out["batch_size_mean"] = (round(bs["sum"] / bs["count"], 2)
                                  if bs.get("count") else None)
        out["batch_size_buckets"] = bs.get("buckets")
        out["deduped"] = snap["counters"].get("coalescer.deduped", 0)
        out["flush_reasons"] = {
            k.split(".", 2)[2]: v for k, v in snap["counters"].items()
            if k.startswith("coalescer.flush.")}
        srv.shutdown()
        srv.server_close()
        h.close()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
