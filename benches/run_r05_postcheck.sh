#!/bin/bash
# Fourth-stage round-5 watcher: after ALL capture stages are done, run
# the driver's own checks (graft entry + 8-device dryrun, full pytest)
# so any breakage is known before the round closes. Never contends with
# a capture leg for the 1-vCPU box.
cd /root/repo
# Wait on EVERY upstream stage, not just the last: a crashed
# intermediate watcher must not release this stage into contention
# with a still-running capture leg.
for up in run_r05_orchestrator.sh run_r05_followup.sh \
          run_r05_probe_followup.sh run_r05_membership_followup.sh; do
  while pgrep -f "$up" > /dev/null; do sleep 60; done
done
echo "$(date -u +%H:%M:%S) postcheck: starting" >&2
timeout 900 env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g
import jax
fn, args = g.entry()
jax.jit(fn)(*args)
print('entry ok')
g.dryrun_multichip(8)
print('dryrun_multichip ok')
" > benches/postcheck_r05.log 2>&1
echo "graft rc=$?" >> benches/postcheck_r05.log
timeout 2400 python -m pytest tests/ -x -q >> benches/postcheck_r05.log 2>&1
echo "pytest rc=$?" >> benches/postcheck_r05.log
echo "$(date -u +%H:%M:%S) postcheck: done" >&2
