"""On-chip cost split of the PositionsBank TopN kernel at one-segment
scale (384M positions): gather-into-filter-table vs cumsum vs the
sparse-filter broadcast-compare alternative (no gather: the tanimoto
query fingerprint has ~48 set positions, so membership is a dense
[P] x [Q] compare-reduce, which is VPU-shaped instead of
gather-shaped). Times via the salted chain-slope harness so RTT
cancels.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

P = int(os.environ.get("PILOSA_PROBE_POSITIONS", 384 << 20))
R = int(os.environ.get("PILOSA_PROBE_ROWS", 8 << 20))
Q = 64  # padded sparse-filter slots


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    pos = jnp.asarray(rng.integers(0, 4096, P, dtype=np.uint16))
    starts = jnp.asarray(
        np.linspace(0, P, R + 1).astype(np.int32))
    fw = jnp.asarray(rng.integers(0, 2**32, 128, dtype=np.uint32))
    qpos = jnp.asarray(
        np.sort(rng.choice(4096, 48, replace=False))
        .astype(np.uint16))
    qpad = jnp.concatenate(
        [qpos, jnp.full((Q - 48,), 0xFFFF, jnp.uint16)])

    def timed(f, *args):
        f_j = jax.jit(f)
        out = jax.block_until_ready(f_j(*args))  # compile
        reps = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f_j(*args))
            reps.append(time.perf_counter() - t0)
        return float(np.median(reps)), out

    def k_gather(pos, fw):
        posi = pos.astype(jnp.int32)
        bits = (jnp.take(fw, posi >> 5, mode="fill", fill_value=0)
                >> (posi & 31).astype(jnp.uint32)) & jnp.uint32(1)
        return bits.astype(jnp.uint32).sum()

    def k_cumsum(pos):
        bits = (pos & jnp.uint16(1)).astype(jnp.uint32)
        s = jnp.concatenate(
            [jnp.zeros(1, jnp.uint32), jnp.cumsum(bits, dtype=jnp.uint32)])
        return s[-1]

    def k_rowdiff(pos, starts):
        bits = (pos & jnp.uint16(1)).astype(jnp.uint32)
        s = jnp.concatenate(
            [jnp.zeros(1, jnp.uint32), jnp.cumsum(bits, dtype=jnp.uint32)])
        c = s[starts[1:]] - s[starts[:-1]]
        return c.sum()

    def k_compare(pos, qpad):
        # membership against <=Q sparse filter positions, no gather:
        # [P] x [Q] broadcast compare, reduced over Q.
        m = (pos[:, None] == qpad[None, :]).any(axis=1)
        return m.astype(jnp.uint32).sum()

    def k_compare_rowsum(pos, qpad, starts):
        m = (pos[:, None] == qpad[None, :]).any(axis=1)
        bits = m.astype(jnp.uint32)
        s = jnp.concatenate(
            [jnp.zeros(1, jnp.uint32), jnp.cumsum(bits, dtype=jnp.uint32)])
        c = s[starts[1:]] - s[starts[:-1]]
        return c.sum()

    for name, f, args in [
        ("gather_only", k_gather, (pos, fw)),
        ("cumsum_only", k_cumsum, (pos,)),
        ("cumsum_rowdiff", k_rowdiff, (pos, starts)),
        ("compare_only", k_compare, (pos, qpad)),
        ("compare_rowsum_full", k_compare_rowsum, (pos, qpad, starts)),
    ]:
        t, out = timed(f, *args)
        print(f"{name}: {t*1000:.1f} ms  ({P/t/1e9:.2f} Gpos/s) out={out}",
              flush=True)


if __name__ == "__main__":
    main()
