"""Tanimoto similarity at scale — BASELINE.md config 4 (100M-fingerprint
class) via the CHUNKED TopN streaming path.

The reference workload (docs/examples.md:211-333): rows are molecules,
columns are 4096-bit Morgan fingerprint positions, and
TopN(fingerprint, Row(fingerprint=q), tanimotoThreshold=T) ranks
molecules by Tanimoto similarity to q. At this scale the full view bank
exceeds the TopN HBM budget, so the executor streams rows through
transient chunk banks with one-chunk lookahead
(executor/executor.py:_execute_topn) — the path whose throughput this
benchmark measures. Reported `mols_per_sec` is linear in N (each chunk
is independent), so `projected_100m_s` = 1e8 / mols_per_sec is the
honest extrapolation to the full BASELINE config.

Scale knob: PILOSA_TANIMOTO_N (default 1_000_000). Host memory per
molecule: one sorted-u16 array container (~100 B data+overhead; the
array encoding of SURVEY component #3, reference roaring.go:55-63) plus
~200 B of dict/row bookkeeping — 100M molecules ≈ 15-30 GB host RAM,
versus ~800 GB if containers were dense. The generation-side positions
array is uint16 (~9.6 GB at 100M), and the numpy baseline streams in
1M-row packed chunks, so no stage materializes O(N) dense data. The
device side is narrow too: banks trim to 128 u32 words/row
(max_columns=4096), and the chunked sweep touches only real
fingerprint bytes.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_MOLECULES = int(os.environ.get("PILOSA_TANIMOTO_N", 1_000_000))
FP_BITS = 4096
BITS_PER_MOL = 48
THRESHOLD = 60
QUERY_MOL = 12345
ITERS = int(os.environ.get("PILOSA_TANIMOTO_ITERS", 3))
CHUNK_ROWS = 65536


def build_positions(rng, n):
    """Sorted fingerprint bit positions [n, BITS_PER_MOL] (may repeat).
    uint16: at 100M molecules this array is ~9.6 GB, not the ~38 GB an
    int64 default would cost."""
    return np.sort(rng.integers(0, FP_BITS, (n, BITS_PER_MOL),
                                dtype=np.uint16), axis=1)


def pack_chunk(pos_chunk):
    """Packed u64 words [rows, FP_BITS//64] for a positions chunk."""
    n = len(pos_chunk)
    words = np.zeros((n, FP_BITS // 64), dtype=np.uint64)
    flat = words.reshape(-1)
    np.bitwise_or.at(
        flat,
        np.arange(n).repeat(BITS_PER_MOL) * (FP_BITS // 64)
        + (pos_chunk >> 6).reshape(-1),
        np.uint64(1) << (pos_chunk & 63).astype(np.uint64).reshape(-1))
    return words


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()

    from pilosa_tpu.utils.benchenv import \
        install_partial_record_handler
    install_partial_record_handler(
        "tanimoto_chunked_mols_per_sec", "molecules/sec")
    # Chunked path knobs must be set before the executor module loads.
    os.environ.setdefault("PILOSA_TPU_TOPN_CHUNK_ROWS", str(CHUNK_ROWS))
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    executor_mod.TOPN_CHUNK_ROWS = CHUNK_ROWS
    # Force the streaming path regardless of N so the measured number is
    # the chunked throughput (at 100M it engages on its own).
    executor_mod.TOPN_MAX_BANK_BYTES = 64 << 20

    rng = np.random.default_rng(11)
    t0 = time.perf_counter()
    positions = build_positions(rng, N_MOLECULES)
    gen_s = time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        from pilosa_tpu.core.field import FieldOptions
        idx = holder.create_index("mole")
        # Declared column bound: fingerprint banks trim to exactly
        # 4096 bits (512 B/row) instead of the 8 KiB container floor.
        f = idx.create_field("fingerprint",
                             FieldOptions(max_columns=FP_BITS))
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        # Direct array-encoded container writes (the ImportRoaring-class
        # fast path at bulk scale): molecule i's sorted fingerprint
        # positions become the u16 array container at the head of its
        # row span; ~100 B per molecule host-side, the memory story that
        # makes 100M molecules ~15 GB instead of ~800 GB dense.
        t0 = time.perf_counter()
        store = frag.storage
        containers = store.containers
        cpr = SHARD_WIDTH // 65536
        # Vectorized per-row dedup: rows are pre-sorted, so the unique
        # values are exactly the elements that differ from their left
        # neighbor. One boolean mask for the whole matrix replaces 100M
        # np.unique calls (~11 us each → the load dominated the 100M
        # leg's rebuild after a tunnel-outage kill; a retry pays this
        # full build again, so its constant matters).
        keep = np.empty(positions.shape, dtype=bool)
        keep[:, 0] = True
        np.not_equal(positions[:, 1:], positions[:, :-1], out=keep[:, 1:])
        for i in range(N_MOLECULES):
            containers[i * cpr] = positions[i][keep[i]]
        del keep  # ~4.8 GB at 100M; must not survive into the query phase
        for i in range(N_MOLECULES):
            frag._touch_row(i)
        converted = N_MOLECULES
        load_s = time.perf_counter() - t0

        # With an intermittent TPU tunnel, meet the chip at query time:
        # the build above is host-only, so (when enabled) wait here.
        from pilosa_tpu.utils.benchenv import hold_for_tpu
        hold_for_tpu("tanimoto_chunked")

        ex = Executor(holder)
        q = (f"TopN(fingerprint, Row(fingerprint={QUERY_MOL}), "
             f"n=50, tanimotoThreshold={THRESHOLD})")
        t0 = time.perf_counter()
        (want,) = ex.execute("mole", q)  # cold: includes compiles
        cold_s = time.perf_counter() - t0

        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            (got,) = ex.execute("mole", q)
            times.append(time.perf_counter() - t0)
            assert got.pairs == want.pairs
        tpu_t = float(np.median(times))

        # Exact numpy baseline over the same data (one core), streamed in
        # packed chunks so baseline memory stays bounded at any N.
        t0 = time.perf_counter()
        filt = pack_chunk(positions[QUERY_MOL:QUERY_MOL + 1])[0]
        src = int(np.bitwise_count(filt).sum())
        inter_parts, raw_parts = [], []
        for c0 in range(0, N_MOLECULES, 1_000_000):
            pw = pack_chunk(positions[c0:c0 + 1_000_000])
            inter_parts.append(np.bitwise_count(pw & filt).sum(axis=1))
            raw_parts.append(np.bitwise_count(pw).sum(axis=1))
        inter = np.concatenate(inter_parts)
        raw = np.concatenate(raw_parts)
        denom = raw + src - inter
        passing = (denom > 0) & ((inter * 100) // np.maximum(denom, 1)
                                 >= THRESHOLD) & (inter > 0)
        pairs = sorted(((int(m), int(inter[m]))
                        for m in np.nonzero(passing)[0]),
                       key=lambda rc: (-rc[1], rc[0]))[:50]
        cpu_t = time.perf_counter() - t0
        assert pairs == want.pairs, (pairs[:3], want.pairs[:3])

        mols_per_sec = N_MOLECULES / tpu_t
        print(json.dumps({
            "metric": "tanimoto_chunked_mols_per_sec",
            "value": mols_per_sec,
            "unit": "molecules/sec",
            "vs_baseline": (N_MOLECULES / cpu_t) and
                           mols_per_sec / (N_MOLECULES / cpu_t),
            "molecules": N_MOLECULES,
            "p50_query_s": tpu_t,
            "cold_query_s": round(cold_s, 2),
            "projected_100m_s": round(1e8 / mols_per_sec, 2),
            "chunk_rows": CHUNK_ROWS,
            "array_containers": converted,
            "gen_seconds": round(gen_s, 2),
            "load_seconds": round(load_s, 2),
        }))
        holder.close()


if __name__ == "__main__":
    main()
    # Real records are out; a late TERM during interpreter
    # teardown must not append a zero-value partial.
    import signal as _signal
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
