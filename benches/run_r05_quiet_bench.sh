#!/bin/bash
# Round-5 one-shot live bench.py capture: at the next tunnel up-window,
# pause the leg runner's whole process group (1-vCPU box — any
# competing process turns every device fetch into a ~70-100 ms
# scheduling stall) and run the official bench with exclusive use of
# the box. Promotes to BENCH_early_r05.json ONLY when the final JSON
# line is a real device record (no backend:cpu-fallback) — a failed
# attempt leaves no marker, so the loop retries at the next window.
cd /root/repo
probe() {
  timeout 170 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, _ = probe_device_once(150)
sys.exit(0 if ok else 1)" 2>/dev/null
}
while [ ! -e benches/.bench_live_r05_done ]; do
  until probe; do
    echo "$(date -u +%H:%M:%S) quiet-bench: waiting for TPU..." >&2
    sleep 45
  done
  LEGS_PID=$(pgrep -o -f run_r05_legs.sh)
  LEGS_PGID=""
  if [ -n "$LEGS_PID" ]; then
    LEGS_PGID=$(ps -o pgid= -p "$LEGS_PID" | tr -d ' ')
  fi
  echo "$(date -u +%H:%M:%S) quiet-bench: TPU up; pausing legs pgid=${LEGS_PGID:-none}" >&2
  [ -n "$LEGS_PGID" ] && kill -STOP -- "-$LEGS_PGID" 2>/dev/null
  resume() {
    [ -n "$LEGS_PGID" ] && kill -CONT -- "-$LEGS_PGID" 2>/dev/null
  }
  trap resume EXIT INT TERM HUP
  # Tunnel known up: a short probe hold inside bench.py suffices.
  timeout 2400 env PILOSA_BENCH_PROBE_HOLD_S=900 \
      PILOSA_BENCH_WAIT_QUIET_S=60 python bench.py \
      > BENCH_early_r05.json.tmp 2> bench_early_r05.err
  rc=$?
  resume
  trap - EXIT INT TERM HUP
  ok=$(python - <<'EOF'
import json
try:
    lines = open("BENCH_early_r05.json.tmp").read().strip().splitlines()
    rec = None
    for ln in reversed(lines):
        try:
            rec = json.loads(ln); break
        except ValueError:
            continue
    print(1 if rec and rec.get("backend") != "cpu-fallback"
          and not rec.get("provisional") and "value" in rec else 0)
except OSError:
    print(0)
EOF
)
  echo "$(date -u +%H:%M:%S) quiet-bench: rc=$rc ok=$ok" >&2
  if [ "$rc" -eq 0 ] && [ "$ok" = "1" ]; then
    mv BENCH_early_r05.json.tmp BENCH_early_r05.json
    touch benches/.bench_live_r05_done
    echo "$(date -u +%H:%M:%S) quiet-bench: live TPU record landed" >&2
  else
    rm -f BENCH_early_r05.json.tmp
    echo "$(date -u +%H:%M:%S) quiet-bench: attempt failed; will retry" >&2
    sleep 120
  fi
done
