"""Phase profiler for the headline TopN call (bench.py's workload).

bench.py's r04 sidecar shows device sweep 1.28 ms but 82 ms per
end-to-end call — ~80 ms of per-call overhead that a 22 us trivial-add
round trip (benches/tunnel_rtt_r04.json) cannot explain. This breaks
one TopN(f, n=10) call into phases and times each through the tunnel:

  probe     - trivial 1-element add fetch (tunnel health; must be quiet)
  dispatch  - _dispatch_counts only (async queue, no block)
  fetch     - np.asarray on the dispatched counts output
  execute   - the full production ex.execute per call (batched 8)
  fetch_eq  - np.asarray on a pre-existing device array of counts shape
  sweep_jit - raw jitted popcount sweep call+block on the same bank

Run only when nothing else is using the chip — contention inflates
every number (the suite's flagship legs upload GB-scale banks).
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SHARDS = int(os.environ.get("PILOSA_BENCH_SHARDS", 8))
N_ROWS = int(os.environ.get("PILOSA_BENCH_ROWS", 1023))
BATCH_CALLS = 8


def med(fn, n=7):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[n // 2], ts[0], ts[-1]


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, "/root/repo")
    import bench as bench_mod
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops.bitset import popcount

    out = {"platform": jax.devices()[0].platform, "phases": {}}

    def phase(name, fn, n=7):
        m, lo, hi = med(fn, n)
        out["phases"][name] = {"median_s": m, "min_s": lo, "max_s": hi}
        print(f"{name:<26} median {m*1e3:9.3f} ms  min {lo*1e3:9.3f}  "
              f"max {hi*1e3:9.3f}", file=sys.stderr, flush=True)

    one = jnp.zeros((1,), jnp.int32)
    tadd = jax.jit(lambda x: x + 1)
    np.asarray(tadd(one))
    phase("probe_trivial_fetch", lambda: np.asarray(tadd(one)))

    with tempfile.TemporaryDirectory() as tmp:
        holder = bench_mod.build_holder(tmp)
        ex = Executor(holder)
        (want,) = ex.execute("bench", "TopN(f, n=10)")  # warm upload+compile

        view = holder.index("bench").field("f").view()
        bank = view.device_bank(tuple(range(N_SHARDS)), trim=True)
        arr = bank.array
        print(f"bank {arr.shape} {arr.dtype} = {arr.nbytes >> 20} MiB",
              file=sys.stderr)

        # Raw sweep: same kernel family the counts dispatch runs.
        sweep = jax.jit(lambda a: popcount(a, axis=(-2, -1)))
        jax.block_until_ready(sweep(arr))
        phase("sweep_jit_block", lambda: jax.block_until_ready(sweep(arr)))
        phase("sweep_jit_fetch", lambda: np.asarray(sweep(arr)))

        # Executor dispatch vs fetch split.
        o = ex._dispatch_counts(arr, None)
        ex._fetch_counts(o, None)
        phase("dispatch_counts_only", lambda: ex._dispatch_counts(arr, None))
        phase("dispatch_plus_fetch",
              lambda: ex._fetch_counts(ex._dispatch_counts(arr, None), None))

        # Pre-existing device array of the counts shape: pure fetch cost.
        counts_dev = jax.block_until_ready(sweep(arr))
        phase("fetch_existing_counts", lambda: np.asarray(counts_dev))

        # Full production call, single and batched.
        phase("execute_single", lambda: ex.execute("bench", "TopN(f, n=10)"),
              n=5)
        q = " ".join("TopN(f, n=10)" for _ in range(BATCH_CALLS))
        ex.execute("bench", q)
        t0 = time.perf_counter()
        ex.execute("bench", q)
        out["phases"]["execute_batched_per_call"] = {
            "median_s": (time.perf_counter() - t0) / BATCH_CALLS}
        print(f"execute_batched_per_call   "
              f"{(time.perf_counter()-t0)/BATCH_CALLS*1e3:9.3f} ms",
              file=sys.stderr)
        holder.close()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
