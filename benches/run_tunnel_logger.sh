#!/bin/bash
# Tunnel-state logger: one timestamped line per state TRANSITION (and a
# heartbeat every ~30 min) in benches/tunnel_state_r05.log, probing via
# benchenv.probe_device_once (subprocess-isolated, bounded). Cheap
# enough to run for the whole round; the log is the round's tunnel
# uptime evidence.
cd /root/repo
LOG=benches/tunnel_state_r05.log
last=""
beats=0
while :; do
  if timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, _ = probe_device_once(80)
sys.exit(0 if ok else 1)" 2>/dev/null; then
    state=up
  else
    state=down
  fi
  beats=$((beats + 1))
  if [ "$state" != "$last" ] || [ $((beats % 10)) -eq 0 ]; then
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) $state" >> "$LOG"
    last=$state
  fi
  # Each probe costs ~5 s of host CPU (a jax import). While a capture
  # leg is alive, back off hard so a probe can't land inside a timed
  # query on this 1-vCPU box; the leg's own hold logs the down state.
  if pgrep -f "benches/tanimoto_chunked.py|benches/startrace.py|benches/bsi.py|benches/pbank_membership_probe.py|python bench.py" >/dev/null; then
    sleep 900
  else
    sleep 180
  fi
done
