#!/bin/bash
# Re-capture the tanimoto flagship legs with the final round-4 kernel
# (fixed-width segments + HBM/compile bounds) at the next tunnel
# window. Same wait/retry/done-marker mechanics as run_tpu_suite_r04b.
cd /root/repo
probe() {
  timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, _ = probe_device_once(80)
sys.exit(0 if ok else 1)" 2>/dev/null
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 45
  done
  echo "$(date -u +%H:%M:%S) TPU answered" >&2
}
run() {
  # No wait_tpu gate: the legs build host-side data during an outage
  # and hold at the build->query boundary (PILOSA_BENCH_HOLD_FOR_TPU),
  # so the next up-window is spent on compiles+queries, not builds.
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_final_done" ]; then
    echo "$(date -u +%H:%M:%S) $name already done, skipping" >&2
    return
  fi
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r04_tpu.jsonl" 2> "benches/${name}_r04_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) bench: $name rc=$rc" >&2
  if [ "$rc" -eq 0 ] && [ -s "benches/${name}_r04_tpu.jsonl" ]; then
    touch "benches/.${name}_final_done"
  fi
}
# Two passes so a mid-device death gets one retry window.
for pass in 1 2; do
  run tanimoto_chunked_100m 14400 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=9000 PILOSA_TANIMOTO_N=100000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
  run tanimoto_chunked_10m 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=2000 PILOSA_TANIMOTO_N=10000000 PILOSA_TANIMOTO_ITERS=5 python benches/tanimoto_chunked.py
done
echo "$(date -u +%H:%M:%S) recapture done" >&2
