#!/bin/bash
# Round-4 TPU suite: waits for the tunnel, then runs every bench
# serially — results land in benches/*_r04_tpu.jsonl. Order matters:
# bench.py first (persists the benches/last_good_tpu.json carry-forward
# sidecar so the round can never again lose its TPU evidence to a
# later tunnel outage — VERDICT r3 item 1), then micro (the validated
# AND+popcount roofline table + the Pallas-vs-XLA re-measurement,
# VERDICT r3 item 9), then the BASELINE suite configs, then the
# flagship-SCALE legs (VERDICT r3 item 2): tanimoto at 10M (safety
# leg, 3 iters) and the full 100M (1 iter), taxi at 100M rides
# (100 shards). Between benches it WAITS for the tunnel to return
# rather than aborting, so a mid-suite outage costs one leg, not the
# round.
cd /root/repo
probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
print(int(jnp.ones((8,), jnp.uint32).sum()))" >/dev/null 2>&1
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 120
  done
  echo "$(date -u +%H:%M:%S) TPU answered" >&2
}
run() {  # run <name> <timeout> <cmd...>
  local name=$1 to=$2; shift 2
  # Skip legs that already completed (marker file), so the watcher can
  # be restarted without redoing hours of work.
  if [ -e "benches/.${name}_r04_done" ]; then
    echo "$(date -u +%H:%M:%S) bench: $name already done, skipping" >&2
    return
  fi
  wait_tpu
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r04_tpu.jsonl" 2> "benches/${name}_r04_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) bench: $name rc=$rc" >&2
  # A leg counts as done when it produced at least one JSON record.
  if [ -s "benches/${name}_r04_tpu.jsonl" ]; then
    touch "benches/.${name}_r04_done"
  fi
}
wait_tpu
if [ ! -e benches/.bench_early_r04_done ]; then
  echo "$(date -u +%H:%M:%S) early bench.py (sidecar capture)" >&2
  timeout 1800 python bench.py > BENCH_early_r04.json 2> bench_early_r04.err
  echo "$(date -u +%H:%M:%S) bench.py rc=$?" >&2
  [ -s BENCH_early_r04.json ] && touch benches/.bench_early_r04_done
fi
run micro 3600 python benches/micro.py
run startrace 1200 python benches/startrace.py
run bsi 1800 python benches/bsi.py
run tanimoto_chunked_10m 3600 env PILOSA_TANIMOTO_N=10000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
run taxi_100m 7200 env PILOSA_TAXI_N=100000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run tanimoto_chunked_100m 14400 env PILOSA_TANIMOTO_N=100000000 PILOSA_TANIMOTO_ITERS=1 python benches/tanimoto_chunked.py
run tanimoto 1800 python benches/tanimoto.py
run taxi_10m 3600 env PILOSA_TAXI_N=10000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run topn_cache 1200 python benches/topn_cache.py
echo "$(date -u +%H:%M:%S) suite done" >&2
