"""Star-trace benchmark — BASELINE.md config 1: the getting-started
index (users star repositories), measured END-TO-END through the HTTP
server: POST /index/{i}/query with Row / Intersect / Count / TopN,
p50 latency per query. Baseline is the same computation on host numpy
sets (the serving overhead the reference's "sub-second" claim includes,
docs/faq.md:11).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_USERS = 2000
N_REPOS = 1_000_000
STARS_PER_USER = 2000
ITERS = 20
BATCH = int(os.environ.get("PILOSA_BENCH_BATCH", 16))
PORT = 10941


def post(path, body):
    req = urllib.request.Request(f"http://127.0.0.1:{PORT}{path}",
                                 data=body.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    from pilosa_tpu.utils.benchenv import \
        install_partial_record_handler
    install_partial_record_handler(
        "startrace_http_p50_latency", "seconds")
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server import API, serve

    rng = np.random.default_rng(3)
    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        api = API(holder)
        srv = serve(api, "127.0.0.1", PORT, background=True)
        try:
            post("/index/repository", "{}")
            post("/index/repository/field/stargazer", "{}")
            users = np.repeat(np.arange(N_USERS, dtype=np.uint64),
                              STARS_PER_USER)
            repos = rng.integers(0, N_REPOS, N_USERS * STARS_PER_USER,
                                 dtype=np.uint64)
            holder.index("repository").field("stargazer").import_bits(
                users, repos)

            # Meet an intermittent tunnel at query time (no-op unless
            # PILOSA_BENCH_HOLD_FOR_TPU is set).
            from pilosa_tpu.utils.benchenv import hold_for_tpu
            hold_for_tpu("startrace")

            q = ("Count(Intersect(Row(stargazer=14), Row(stargazer=19))) "
                 "TopN(stargazer, n=5)")
            want = post("/index/repository/query", q)  # warm
            from pilosa_tpu.utils.benchenv import measurement_context
            ctx = measurement_context()
            times = []
            for _ in range(ITERS):
                t0 = time.perf_counter()
                got = post("/index/repository/query", q)
                times.append(time.perf_counter() - t0)
                assert got == want
            tpu_t = float(np.median(times)) / 2  # per call

            # Batched serving shape: BATCH queries per /batch/query
            # request — one HTTP round trip, one pipelined device
            # drain (VERDICT r4 #3; the mitigation for the ~70 ms
            # tunnel fetch RTT that dominates 1 ms-class queries).
            batch_body = json.dumps({"queries": [
                {"index": "repository", "query": q}] * BATCH})
            got_b = post("/batch/query", batch_body)  # warm
            assert all(r == want for r in got_b["responses"])
            btimes = []
            for _ in range(max(3, ITERS // 4)):
                t0 = time.perf_counter()
                got_b = post("/batch/query", batch_body)
                btimes.append((time.perf_counter() - t0) / BATCH)
            batch_t = float(np.median(btimes)) / 2  # per call

            # numpy baseline: same answers (distinct (user,repo) pairs —
            # duplicates collapse in a bitmap) from the raw pair arrays.
            set14 = np.unique(repos[users == 14])
            set19 = np.unique(repos[users == 19])
            pairs = np.unique(np.stack([users, repos], axis=1), axis=0)
            t0 = time.perf_counter()
            cnt = len(np.intersect1d(set14, set19, assume_unique=True))
            counts = np.bincount(pairs[:, 0].astype(np.int64))
            order = np.argsort(-counts, kind="stable")[:5]
            top = [{"id": int(u), "count": int(counts[u])} for u in order]
            cpu_t = (time.perf_counter() - t0) / 2
            assert cnt == want["results"][0]
            got_top = want["results"][1]
            assert [p["count"] for p in top] == \
                [p["count"] for p in got_top], (top, got_top)
            print(json.dumps({
                "metric": "startrace_http_p50_latency",
                "value": tpu_t,
                "unit": "seconds",
                "vs_baseline": cpu_t / tpu_t,
                "batch_calls": BATCH,
                "batch_p50_per_call": batch_t,
                "batch_vs_baseline": cpu_t / batch_t,
                **ctx,
            }))
        finally:
            srv.shutdown()
            holder.close()


if __name__ == "__main__":
    main()
    # Real records are out; a late TERM during interpreter
    # teardown must not append a zero-value partial.
    import signal as _signal
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
