# Sourced helper: UTC HH:MM deadline -> epoch seconds.
#
# HH:MM string comparisons wrap at midnight (a chain armed in the
# evening with a past-midnight deadline never fires until the next
# day's HH:MM — ADVICE r5), so deadlines are compared as epoch seconds.
# Disambiguation rule: an HH:MM that passed within the last 6 h reads
# as an already-expired same-day deadline and stays past (a janitor
# restarted just after its deadline must wind the chain down NOW; a
# chain re-armed at 10:45 with cutoff 10:30 must NOT launch the
# multi-hour leg the cutoff exists to prevent); one that passed longer
# ago reads as "tomorrow" (arm at 21:00 for an 11:38 deadline, or the
# evening-arm past-midnight case). HH:MM alone cannot distinguish the
# two perfectly; 6 h separates every round-5 arming pattern.
deadline_epoch() {
  local t
  t=$(date -u -d "today $1" +%s 2>/dev/null) || t=$(date -u -d "$1" +%s)
  if [ $(( $(date -u +%s) - t )) -ge 21600 ]; then t=$((t + 86400)); fi
  echo "$t"
}
