#!/bin/bash
# Round-3 TPU suite: waits for the tunnel, then runs every bench
# serially, committing nothing itself — results land in benches/*.jsonl
# for the round record. Priority order: bench.py first (persists the
# last_good_tpu.json carry-forward sidecar), then micro (the validated
# AND+popcount roofline table — VERDICT r2 item 1), then the BASELINE
# suite configs (VERDICT r2 item 3). Between benches it WAITS for the
# tunnel to return rather than aborting.
cd /root/repo
probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
print(int(jnp.ones((8,), jnp.uint32).sum()))" >/dev/null 2>&1
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 120
  done
  echo "$(date -u +%H:%M:%S) TPU answered" >&2
}
run() {  # run <name> <timeout> <cmd...>
  local name=$1 to=$2; shift 2
  wait_tpu
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r03_tpu.jsonl" 2> "benches/${name}_r03_tpu.err"
  echo "$(date -u +%H:%M:%S) bench: $name rc=$?" >&2
}
wait_tpu
echo "$(date -u +%H:%M:%S) early bench.py (sidecar capture)" >&2
python bench.py > BENCH_early_r03.json 2> bench_early_r03.err
echo "$(date -u +%H:%M:%S) bench.py rc=$?" >&2
run micro 2400 python benches/micro.py
run startrace 1200 python benches/startrace.py
run bsi 1800 python benches/bsi.py
run tanimoto_chunked 2400 env PILOSA_TANIMOTO_N=1000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
run taxi 2400 env PILOSA_TAXI_N=2000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run tanimoto 1800 python benches/tanimoto.py
echo "$(date -u +%H:%M:%S) suite done" >&2
