"""Micro-benchmarks mirroring the reference's benchmark suites
(roaring/roaring_test.go:1392-1620 kernel ops;
fragment_internal_test.go:663-2280 import/snapshot/blocks).

Each line: {"metric", "value", "unit", ...}. Device numbers use the
default backend (TPU under axon; CPU otherwise); host numbers exercise
the native C++ codec and the numpy storage paths."""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, iters=5):
    fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def emit(metric, value, unit, **extra):
    print(json.dumps({"metric": metric, "value": value, "unit": unit,
                      **extra}))


def bench_roaring_kernels():
    """IntersectionCount / Union / serialization on the host paths
    (reference BenchmarkIntersectionCount*, BenchmarkUnion*)."""
    from pilosa_tpu.storage.roaring import Bitmap
    from pilosa_tpu import native

    rng = np.random.default_rng(0)
    n = 1 << 22  # 4M-bit universe
    a = Bitmap(np.unique(rng.integers(0, n, 500_000, dtype=np.uint64)))
    b = Bitmap(np.unique(rng.integers(0, n, 500_000, dtype=np.uint64)))

    t = timeit(lambda: a.intersection_count(b))
    emit("host_intersection_count", 1 / t, "ops/sec")
    t = timeit(lambda: a.union(b))
    emit("host_union", 1 / t, "ops/sec")
    data = a.write_bytes()
    t = timeit(lambda: a.write_bytes())
    emit("host_roaring_serialize", len(data) / t / 1e6, "MB/sec",
         native=native.available())
    t = timeit(lambda: Bitmap.from_bytes(data))
    emit("host_roaring_parse", len(data) / t / 1e6, "MB/sec",
         native=native.available())


def bench_fragment_paths():
    """Import / snapshot / block checksums (reference BenchmarkFragment_*).

    Two data shapes: 100 rows (dense containers, ~625 bits each — the
    round-2-comparable shape, dense-scatter import path) and 1000 rows
    (10 hash blocks, ~62 bits/container — array-encoded containers,
    sorted-group import path; also what makes the dirty-one-block
    checksum meaningfully incremental)."""
    from pilosa_tpu.core.fragment import Fragment

    rng = np.random.default_rng(1)
    n_bits = 1_000_000
    rows = rng.integers(0, 100, n_bits, dtype=np.uint64)
    wide_rows = rng.integers(0, 1000, n_bits, dtype=np.uint64)
    cols = rng.integers(0, 1 << 20, n_bits, dtype=np.uint64)

    with tempfile.TemporaryDirectory() as tmp:
        frag = Fragment(os.path.join(tmp, "f"), "i", "f", "standard", 0)
        frag.open()
        t0 = time.perf_counter()
        frag.bulk_import(rows, cols)
        emit("fragment_bulk_import", n_bits / (time.perf_counter() - t0),
             "bits/sec")
        t = timeit(lambda: frag._snapshot(), iters=3)
        emit("fragment_snapshot", 1 / t, "ops/sec")
        # Same shape as rounds 1-2 under the same key (cold full pass).
        t = timeit(lambda: (frag._invalidate_block_checksums(),
                            frag.checksum_blocks()), iters=3)
        emit("fragment_blocks_checksum", 1 / t, "ops/sec")
        frag.close()

        # reopen replays snapshot via the native codec
        frag2 = Fragment(os.path.join(tmp, "f"), "i", "f", "standard", 0)
        t = timeit(lambda: (frag2.open(), frag2.close()), iters=3)
        emit("fragment_open", 1 / t, "ops/sec")

        wide = Fragment(os.path.join(tmp, "w"), "i", "w", "standard", 0)
        wide.open()
        t0 = time.perf_counter()
        wide.bulk_import(wide_rows, cols)
        emit("fragment_bulk_import_wide",
             n_bits / (time.perf_counter() - t0), "bits/sec")
        t = timeit(lambda: wide._snapshot(), iters=3)
        emit("fragment_snapshot_sparse", 1 / t, "ops/sec")
        # Cold pass (cache invalidated each run: the reference's
        # every-sync cost, fragment.go:1259-1355) vs the incremental
        # path: idle (nothing dirty) and one dirty block of ten.
        t = timeit(lambda: (wide._invalidate_block_checksums(),
                            wide.checksum_blocks()), iters=3)
        emit("fragment_blocks_checksum_wide", 1 / t, "ops/sec")
        t = timeit(lambda: wide.checksum_blocks(), iters=3)
        emit("fragment_blocks_checksum_idle", 1 / t, "ops/sec")
        t = timeit(lambda: (wide.set_bit(1, 1), wide.clear_bit(1, 1),
                            wide.checksum_blocks()), iters=3)
        emit("fragment_blocks_checksum_dirty1", 1 / t, "ops/sec")
        wide._snapshot()
        wide.close()

        # Sparse-shape open: ~16k array-encoded containers through the
        # encoding-split native load.
        wide2 = Fragment(os.path.join(tmp, "w"), "i", "w", "standard", 0)
        t = timeit(lambda: (wide2.open(), wide2.close()), iters=3)
        emit("fragment_open_sparse", 1 / t, "ops/sec")


def bench_query_qps():
    """Warm end-to-end PQL dispatch rate (parse -> compiled-tree cache
    hit -> device exec -> fetch) for a small Count(Intersect) — the
    per-query host overhead floor (reference executor.Execute,
    executor.go:84)."""
    import tempfile
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    rng = np.random.default_rng(5)
    with tempfile.TemporaryDirectory() as tmp:
        h = Holder(tmp)
        h.open()
        idx = h.create_index("q")
        for name in ("f", "g"):
            fld = idx.create_field(name)
            cols = rng.integers(0, 4 << 20, 200_000, dtype=np.uint64)
            fld.import_bits(rng.integers(0, 50, len(cols), dtype=np.uint64),
                            cols)
        ex = Executor(h)
        q = "Count(Intersect(Row(f=3), Row(g=7)))"
        ex.execute("q", q)  # compile + bank upload
        t = timeit(lambda: ex.execute("q", q), iters=100)
        emit("pql_count_qps", 1 / t, "queries/sec")
        h.close()


def bench_device_kernels():
    """Fused device sweeps (the reference's per-container kernels land
    here as one XLA op)."""
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.ops.bitset import popcount, WORDS_PER_SHARD

    rng = np.random.default_rng(2)
    shape = (64, 4, WORDS_PER_SHARD)  # 64 rows x 4 shards
    a = jnp.asarray(rng.integers(0, 2**32, shape, dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 2**32, shape, dtype=np.uint32))
    jax.block_until_ready((a, b))
    nbytes = a.nbytes + b.nbytes

    f = jax.jit(lambda x, y: popcount(jnp.bitwise_and(x, y),
                                      axis=(-2, -1)))
    np.asarray(f(a, b))
    t = timeit(lambda: np.asarray(f(a, b)))
    emit("device_and_popcount", nbytes / t / 1e9, "GB/sec",
         backend=jax.devices()[0].platform)


def bench_device_time_table():
    """Pure device-side sweep rates via the chained-iteration slope
    method: per-sweep time = slope between fori_loop chain lengths,
    cancelling host<->device RTT — the number `device_and_popcount`
    above cannot give through a tunnel. Emits one GB/s line per kernel
    family, the roofline evidence table. Kernels match the reference's
    hot container loops: AND+popcount (roaring.go:2438), OR (:2654),
    XOR (:3400), ANDNOT (:3031).

    Validity (VERDICT r2): every iteration ADDS to EVERY operand bank a
    salt threaded from the previous iteration's popcount, so XLA cannot
    elide, hoist, or share any sweep's memory traffic (round 2's
    one-operand salt let the AND sweep report an impossible 3.5x the
    roofline; additive salting is used because XOR salts reassociate
    out of an XOR kernel). Per-iteration time is the Theil-Sen median
    over all chain-length pairs (min/median/max reported) and any
    median above roofline*1.05 is re-measured, then marked invalid=true
    rather than published as a number."""
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.ops.bitset import popcount, WORDS_PER_SHARD
    from pilosa_tpu.utils.benchenv import (make_salted_chain, timed_fetch,
                                           validated_chain_slope)

    rows = int(os.environ.get("PILOSA_MICRO_ROWS", 255))
    shards = int(os.environ.get("PILOSA_MICRO_SHARDS", 8))
    shape = (rows, shards, WORDS_PER_SHARD)
    # Operands are generated ON DEVICE: this is a pure kernel bench
    # (contents are random words either way), and uploading 2 x ~267 MB
    # through the tunnel costs 1-2 minutes of a ~6-minute up-window.
    ka, kb = jax.random.split(jax.random.key(3))
    a = jax.block_until_ready(jax.random.bits(ka, shape, jnp.uint32))
    b = jax.block_until_ready(jax.random.bits(kb, shape, jnp.uint32))

    kernels = {
        # bytes_read_factor: how many operand banks each sweep streams.
        "sweep_popcount": (1, lambda x, y, sx, sy: popcount(
            (x + sx), axis=(-2, -1))),
        "sweep_and_popcount": (2, lambda x, y, sx, sy: popcount(
            jnp.bitwise_and((x + sx), (y + sy)),
            axis=(-2, -1))),
        "sweep_or_popcount": (2, lambda x, y, sx, sy: popcount(
            jnp.bitwise_or((x + sx), (y + sy)),
            axis=(-2, -1))),
        "sweep_xor_popcount": (2, lambda x, y, sx, sy: popcount(
            jnp.bitwise_xor((x + sx), (y + sy)),
            axis=(-2, -1))),
        "sweep_andnot_popcount": (2, lambda x, y, sx, sy: popcount(
            jnp.bitwise_and((x + sx),
                            jnp.bitwise_not((y + sy))),
            axis=(-2, -1))),
    }

    from pilosa_tpu.ops import pallas_kernels
    if pallas_kernels.available():
        # Same sweeps through the hand-tiled Pallas kernels, so the
        # XLA-vs-Pallas call in ops/pallas_kernels.py's docstring rests
        # on device-time (slope) evidence, not tunnel-dominated timing.
        kernels["pallas_sweep_popcount"] = (1, lambda x, y, sx, sy: (
            pallas_kernels.bank_row_counts((x + sx))))
        # Filter-mask sweep: streams ONE bank plus a broadcast [S, W]
        # filter row (nbanks=1 — crediting two banks would inflate its
        # GB/s ~2x vs what it actually moves). Compare against the
        # XLA equivalent of the same workload below, not against the
        # two-full-bank sweep_and_popcount.
        kernels["pallas_sweep_filter_popcount"] = (1, lambda x, y, sx, sy: (
            pallas_kernels.bank_row_counts_masked(
                (x + sx),
                (y[0] + sy))[0]))
        kernels["sweep_filter_popcount"] = (1, lambda x, y, sx, sy: popcount(
            jnp.bitwise_and((x + sx),
                            (y[0] + sy)),
            axis=(-2, -1)))

    dev = jax.devices()[0]
    for name, (nbanks, kern) in kernels.items():
        chain = make_salted_chain(kern)
        try:
            r = validated_chain_slope(
                lambda k: timed_fetch(lambda: chain(a, b, k)),
                a.nbytes * nbanks, dev)
        except RuntimeError as e:
            emit(name, 0.0, "GB/sec", error=str(e))
            continue
        emit(name, r["gbps_median"], "GB/sec",
             backend=dev.platform, bank_mb=a.nbytes >> 20,
             method="salted-chain-slope", **{
                 k: r[k] for k in
                 ("gbps_min", "gbps_max", "slope_pairs", "roofline_frac",
                  "roofline_gbps_assumed", "device_kind")},
             **({"invalid": True, "error": r["error"]}
                if r.get("invalid") else {}))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    from pilosa_tpu.utils.benchenv import \
        install_partial_record_handler
    install_partial_record_handler(
        "micro_suite", "mixed")
    # Device-time table FIRST: with an intermittently-up TPU tunnel the
    # roofline evidence is the leg's most precious output — spend the
    # chip window on it before the host-side (tunnel-independent)
    # benches, so a mid-leg tunnel drop costs the cheap lines, not the
    # validated sweep table.
    bench_device_time_table()
    bench_device_kernels()
    bench_query_qps()
    bench_roaring_kernels()
    bench_fragment_paths()


if __name__ == "__main__":
    main()
    # Real records are out; a late TERM during interpreter
    # teardown must not append a zero-value partial.
    import signal as _signal
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
