"""Micro-benchmarks mirroring the reference's benchmark suites
(roaring/roaring_test.go:1392-1620 kernel ops;
fragment_internal_test.go:663-2280 import/snapshot/blocks).

Each line: {"metric", "value", "unit", ...}. Device numbers use the
default backend (TPU under axon; CPU otherwise); host numbers exercise
the native C++ codec and the numpy storage paths."""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, iters=5):
    fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def emit(metric, value, unit, **extra):
    print(json.dumps({"metric": metric, "value": value, "unit": unit,
                      **extra}))


def bench_roaring_kernels():
    """IntersectionCount / Union / serialization on the host paths
    (reference BenchmarkIntersectionCount*, BenchmarkUnion*)."""
    from pilosa_tpu.storage.roaring import Bitmap
    from pilosa_tpu import native

    rng = np.random.default_rng(0)
    n = 1 << 22  # 4M-bit universe
    a = Bitmap(np.unique(rng.integers(0, n, 500_000, dtype=np.uint64)))
    b = Bitmap(np.unique(rng.integers(0, n, 500_000, dtype=np.uint64)))

    t = timeit(lambda: a.intersection_count(b))
    emit("host_intersection_count", 1 / t, "ops/sec")
    t = timeit(lambda: a.union(b))
    emit("host_union", 1 / t, "ops/sec")
    data = a.write_bytes()
    t = timeit(lambda: a.write_bytes())
    emit("host_roaring_serialize", len(data) / t / 1e6, "MB/sec",
         native=native.available())
    t = timeit(lambda: Bitmap.from_bytes(data))
    emit("host_roaring_parse", len(data) / t / 1e6, "MB/sec",
         native=native.available())


def bench_fragment_paths():
    """Import / snapshot / block checksums (reference BenchmarkFragment_*)."""
    from pilosa_tpu.core.fragment import Fragment

    rng = np.random.default_rng(1)
    n_bits = 1_000_000
    rows = rng.integers(0, 100, n_bits, dtype=np.uint64)
    cols = rng.integers(0, 1 << 20, n_bits, dtype=np.uint64)

    with tempfile.TemporaryDirectory() as tmp:
        frag = Fragment(os.path.join(tmp, "f"), "i", "f", "standard", 0)
        frag.open()
        t0 = time.perf_counter()
        frag.bulk_import(rows, cols)
        emit("fragment_bulk_import", n_bits / (time.perf_counter() - t0),
             "bits/sec")
        t = timeit(lambda: frag._snapshot(), iters=3)
        emit("fragment_snapshot", 1 / t, "ops/sec")
        t = timeit(lambda: frag.checksum_blocks(), iters=3)
        emit("fragment_blocks_checksum", 1 / t, "ops/sec")
        frag.close()

        # reopen replays snapshot via the native codec
        frag2 = Fragment(os.path.join(tmp, "f"), "i", "f", "standard", 0)
        t = timeit(lambda: (frag2.open(), frag2.close()), iters=3)
        emit("fragment_open", 1 / t, "ops/sec")


def bench_device_kernels():
    """Fused device sweeps (the reference's per-container kernels land
    here as one XLA op)."""
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.ops.bitset import popcount, WORDS_PER_SHARD

    rng = np.random.default_rng(2)
    shape = (64, 4, WORDS_PER_SHARD)  # 64 rows x 4 shards
    a = jnp.asarray(rng.integers(0, 2**32, shape, dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 2**32, shape, dtype=np.uint32))
    jax.block_until_ready((a, b))
    nbytes = a.nbytes + b.nbytes

    f = jax.jit(lambda x, y: popcount(jnp.bitwise_and(x, y),
                                      axis=(-2, -1)))
    np.asarray(f(a, b))
    t = timeit(lambda: np.asarray(f(a, b)))
    emit("device_and_popcount", nbytes / t / 1e9, "GB/sec",
         backend=jax.devices()[0].platform)


def main():
    bench_roaring_kernels()
    bench_fragment_paths()
    bench_device_kernels()


if __name__ == "__main__":
    main()
