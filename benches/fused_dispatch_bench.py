"""CPU/TPU microbench: same-signature batch fusion, unfused vs fused.

The ISSUE's acceptance shape: B structurally identical Count queries
(different row ids over one shared view bank) served three ways —

- serial:    one `Executor.execute` per query (the un-batched serving
             baseline: one plan + one program dispatch + one drain
             each);
- pipelined: `Executor.execute_batch` with fusion disabled
             (PILOSA_TPU_FUSION semantics forced off) — the PR 1/PR 3
             state: one overlapped drain, but still one program
             dispatch per query;
- fused:     `Executor.execute_batch` with fusion on — one vmapped
             program dispatch for the whole signature group.

Results are checked identical across all three modes per B before any
number is reported. Aggregate queries/sec per (mode, B) goes to stdout
as ONE JSON line (progress chatter on stderr); run on TPU via the
benches/run_tpu_suite.sh pattern (JAX_PLATFORMS unset).

Columns confine to FUSED_BENCH_COL_SPAN (default 65536) low columns of
each shard so view banks width-trim to ~2k words: that makes each
query's device compute genuinely 1-ms-class, which is the north-star
shape — per-program HOST overhead (plan + dispatch + drain), the thing
fusion amortizes, then shows instead of drowning under a popcount that
is itself CPU-bound at full shard width. (On TPU the same full-width
sweep is microseconds while every dispatch costs a tunnel RTT, so
fusion's edge only grows with width there.)

Env knobs: FUSED_BENCH_B ("1,8,64,256"), FUSED_BENCH_REPS (30),
FUSED_BENCH_SHARDS (4), FUSED_BENCH_ROWS (256),
FUSED_BENCH_COL_SPAN (65536), FUSED_BENCH_SECONDS (1.0 max per timed
mode).
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCHES = [int(b) for b in
           os.environ.get("FUSED_BENCH_B", "1,8,64,256").split(",")]
REPS = int(os.environ.get("FUSED_BENCH_REPS", 30))
N_SHARDS = int(os.environ.get("FUSED_BENCH_SHARDS", 4))
N_ROWS = int(os.environ.get("FUSED_BENCH_ROWS", 256))
COL_SPAN = int(os.environ.get("FUSED_BENCH_COL_SPAN", 65536))
MAX_SECONDS = float(os.environ.get("FUSED_BENCH_SECONDS", 1.0))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build(tmp):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    h = Holder(tmp)
    h.open()
    idx = h.create_index("b")
    f = idx.create_field("f")
    rng = np.random.default_rng(42)
    n = 200_000
    rows = rng.integers(0, N_ROWS, n).astype(np.uint64)
    cols = (rng.integers(0, N_SHARDS, n).astype(np.uint64)
            * np.uint64(SHARD_WIDTH)
            + rng.integers(0, COL_SPAN, n).astype(np.uint64))
    f.import_bits(rows, cols)
    idx.add_existence(cols)
    return h


def timed_interleaved(mode_fns, reps):
    """Per-mode BEST single-batch time over `reps` interleaved rounds
    (mode A, mode B, ... per round). Interleaving + min is the noise
    shield for a shared box: a background burst taxes every mode's
    worst reps equally and the best rep approaches the true cost."""
    best = {fn.__name__: float("inf") for fn in mode_fns}
    done = {fn.__name__: 0 for fn in mode_fns}
    t_start = time.perf_counter()
    for _ in range(reps):
        for fn in mode_fns:
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            if dt < best[fn.__name__]:
                best[fn.__name__] = dt
            done[fn.__name__] += 1
        if time.perf_counter() - t_start > MAX_SECONDS * len(mode_fns):
            break
    return best, done


def main():
    import tempfile

    import jax

    from pilosa_tpu.executor import Executor, executor as executor_mod

    platform = jax.devices()[0].platform
    log(f"platform={platform} shards={N_SHARDS} rows={N_ROWS}")
    out = {"bench": "fused_dispatch", "platform": platform,
           "shards": N_SHARDS, "reps": REPS, "modes": {}}
    with tempfile.TemporaryDirectory() as tmp:
        h = build(tmp)
        ex = Executor(h)
        for B in BATCHES:
            queries = [f"Count(Row(f={r % N_ROWS}))" for r in range(B)]
            reqs = [("b", q, None) for q in queries]

            def serial():
                return [ex.execute("b", q)[0] for q in queries]

            def pipelined():
                prev = executor_mod.FUSION_ENABLED
                executor_mod.FUSION_ENABLED = False
                try:
                    return [r[0][0] for r in ex.execute_batch(reqs)]
                finally:
                    executor_mod.FUSION_ENABLED = prev

            def fused():
                return [r[0][0] for r in ex.execute_batch(reqs)]

            want = serial()  # also warms the single-program compile
            for mode_fn in (pipelined, fused):  # warm + verify
                got = mode_fn()
                assert got == want, (mode_fn.__name__, got[:4], want[:4])
            fd0 = ex.fused_dispatches
            fused()
            if B > 1:
                assert ex.fused_dispatches == fd0 + 1, \
                    "fused mode must be exactly one dispatch"
            row = {}
            best, done = timed_interleaved((serial, pipelined, fused),
                                           REPS)
            for name, dt in best.items():
                qps = B / dt
                row[name] = {"qps": round(qps, 1),
                             "s_per_batch": round(dt, 6)}
                log(f"B={B:4d} {name:9s} {qps:10.0f} q/s "
                    f"(best of {done[name]})")
            row["speedup_vs_serial"] = round(
                row["fused"]["qps"] / row["serial"]["qps"], 2)
            row["speedup_vs_pipelined"] = round(
                row["fused"]["qps"] / row["pipelined"]["qps"], 2)
            out["modes"][str(B)] = row
        h.close()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
