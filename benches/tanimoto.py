"""Tanimoto similarity benchmark — BASELINE.md config 4 (scaled): TopN
with tanimotoThreshold over molecule fingerprints (reference
docs/examples.md chemical-similarity workload; pruning
fragment.go:1087-1093).

Schema matches the reference's chem-usecase: ROWS are molecules
(chembl ids), COLUMNS are Morgan fingerprint bit positions, so
TopN(fingerprint, Row(fingerprint=<query mol>), tanimotoThreshold=T)
ranks molecules by similarity to the query molecule. The executor's
width-trimmed banks matter here: rows span only 4096 of the 2^20 shard
columns, so the sweep bank is 16x smaller than an untrimmed one.

Measures p50 similarity-search latency through the production executor
and validates against an exact bit-packed numpy Tanimoto on the same
data. Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_MOLECULES = 200_000
FP_BITS = 4096
BITS_PER_MOL = 48       # typical Morgan density
THRESHOLD = 60          # tanimoto percent
QUERY_MOL = 12345
ITERS = 5


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    rng = np.random.default_rng(11)
    # fingerprint bit positions per molecule (with possible repeats —
    # repeats collapse, as in real fingerprints)
    fp = rng.integers(0, FP_BITS, (N_MOLECULES, BITS_PER_MOL))
    rows = np.repeat(np.arange(N_MOLECULES, dtype=np.uint64), BITS_PER_MOL)
    cols = fp.reshape(-1).astype(np.uint64)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        idx = holder.create_index("mole")
        f = idx.create_field("fingerprint")
        t0 = time.perf_counter()
        f.import_bits(rows, cols)
        load_s = time.perf_counter() - t0

        ex = Executor(holder)
        q = (f"TopN(fingerprint, Row(fingerprint={QUERY_MOL}), "
             f"n=50, tanimotoThreshold={THRESHOLD})")
        (want,) = ex.execute("mole", q)  # warm: bank + compile

        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            (got,) = ex.execute("mole", q)
            times.append(time.perf_counter() - t0)
            assert got.pairs == want.pairs
        tpu_t = float(np.median(times))

        # Exact numpy baseline on bit-packed fingerprints [mol, 512 bytes]
        # (pack build excluded, matching the TPU side's cached bank).
        mat = np.zeros((N_MOLECULES, FP_BITS), dtype=bool)
        mat[rows.astype(np.int64), cols.astype(np.int64)] = True
        packed = np.packbits(mat, axis=1)
        t0 = time.perf_counter()
        filt = packed[QUERY_MOL]
        inter = np.bitwise_count(packed & filt).sum(axis=1)
        raw = np.bitwise_count(packed).sum(axis=1)
        src = int(np.bitwise_count(filt).sum())
        denom = raw + src - inter
        keep = (denom > 0) & ((inter * 100) // np.maximum(denom, 1)
                              >= THRESHOLD) & (inter > 0)
        pairs = sorted(((int(m), int(inter[m]))
                        for m in np.nonzero(keep)[0]),
                       key=lambda rc: (-rc[1], rc[0]))[:50]
        cpu_t = time.perf_counter() - t0
        assert pairs == want.pairs, (pairs[:3], want.pairs[:3])

        print(json.dumps({
            "metric": "tanimoto_molecule_topn_p50_latency",
            "value": tpu_t,
            "unit": "seconds",
            "vs_baseline": cpu_t / tpu_t,
            "molecules": N_MOLECULES,
            "load_seconds": round(load_s, 2),
        }))
        holder.close()


if __name__ == "__main__":
    main()
