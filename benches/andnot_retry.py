"""Standalone retry of the sweep_andnot_popcount device-time record.

The micro leg's andnot sweep refused a record during the 03:15 UTC
window (5/6 non-positive chain-slope pairs — tunnel too noisy), and
micro's done-marker keeps the other 23 records from re-running. This
re-measures ONLY the andnot family (reference ANDNOT container loops,
roaring/roaring.go:3031) with the identical salted-chain machinery, so
the roofline table in docs/perf.md has all four algebra kernels.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.ops.bitset import WORDS_PER_SHARD, popcount
    from pilosa_tpu.utils.benchenv import (make_salted_chain, timed_fetch,
                                           validated_chain_slope)

    rows = int(os.environ.get("PILOSA_MICRO_ROWS", 255))
    shards = int(os.environ.get("PILOSA_MICRO_SHARDS", 8))
    shape = (rows, shards, WORDS_PER_SHARD)
    ka, kb = jax.random.split(jax.random.key(3))
    a = jax.block_until_ready(jax.random.bits(ka, shape, jnp.uint32))
    b = jax.block_until_ready(jax.random.bits(kb, shape, jnp.uint32))

    chain = make_salted_chain(
        lambda x, y, sx, sy: popcount(
            jnp.bitwise_and((x + sx), jnp.bitwise_not((y + sy))),
            axis=(-2, -1)))
    dev = jax.devices()[0]
    try:
        r = validated_chain_slope(
            lambda k: timed_fetch(lambda: chain(a, b, k)),
            a.nbytes * 2, dev)
    except RuntimeError as e:
        print(json.dumps({"metric": "sweep_andnot_popcount", "value": 0.0,
                          "unit": "GB/sec", "error": str(e)}))
        return
    print(json.dumps({
        "metric": "sweep_andnot_popcount", "value": r["gbps_median"],
        "unit": "GB/sec", "backend": dev.platform,
        "bank_mb": a.nbytes >> 20, "method": "salted-chain-slope",
        **{k: r[k] for k in
           ("gbps_min", "gbps_max", "slope_pairs", "roofline_frac",
            "roofline_gbps_assumed", "device_kind")},
        **({"invalid": True, "error": r["error"]}
           if r.get("invalid") else {})}))


if __name__ == "__main__":
    main()
