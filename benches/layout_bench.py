"""Capacity bench for the adaptive hybrid bank layout (ISSUE 13).

Measures the capacity axis the hybrid layout exists for — resident
shards per byte of HBM — plus the guardrail the hot path must hold:

- **Corpus**: per shard, one "hot" field (a few well-filled rows; the
  serving hot set, must stay dense) and one "cold" field with a
  Zipfian density profile (row r carries ~``base / (r+1)^alpha`` set
  bits), the million-user shape where most rows are nearly empty.
- **Capacity lane**: ledgered device bytes per shard with the dense
  layout vs after the re-layout pass demotes the cold views —
  ``shardsPerGiB`` each way and their ratio (target: >= 2x).
- **Hot q/s lane**: a repeated Count burst over the HOT rows with the
  hybrid layout enabled (hot stays dense) vs the
  ``PILOSA_TPU_HYBRID_LAYOUT=0`` regime — the <5% regression gate.
- **Sparse rows/s lane**: Count throughput over the demoted sparse
  rows (the path OP_EXPAND serves).

Emits one JSON record per run on stdout (the repo's jsonl bench
convention); committed artifacts live beside this file as
``layout_bench_rNN_<backend>.jsonl``.

Usage::

    JAX_PLATFORMS=cpu python -m benches.layout_bench
    python -m benches.layout_bench --shards 4 --rows 4000 --iters 200
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import tempfile
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np


def build_corpus(holder, shards: int, rows: int, alpha: float,
                 base: int, seed: int = 7):
    """One index: `shards` shards, a hot field (8 dense rows) and a
    Zipfian cold field (`rows` rows, density ~ base/(r+1)^alpha)."""
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    rng = np.random.default_rng(seed)
    idx = holder.create_index("cap")
    hot = idx.create_field("hot")
    cold = idx.create_field("cold")
    all_cols = []
    for s in range(shards):
        col0 = s * SHARD_WIDTH
        # Hot: 8 rows x ~2500 bits inside a 4096-col window.
        hr = rng.integers(0, 8, 20000).astype(np.uint64)
        hc = (col0 + rng.integers(0, 4096, 20000)).astype(np.uint64)
        hot.import_bits(hr, hc)
        # Cold: Zipfian density, most rows nearly empty.
        counts = np.maximum(
            1, (base / np.power(np.arange(rows) + 1, alpha))
        ).astype(np.int64)
        cr = np.repeat(np.arange(rows, dtype=np.uint64), counts)
        cc = (col0 + rng.integers(0, 4096, int(counts.sum()))
              ).astype(np.uint64)
        cold.import_bits(cr, cc)
        all_cols.append(hc)
        all_cols.append(cc)
    idx.add_existence(np.unique(np.concatenate(all_cols)))
    return idx


def _qps(ex, queries, iters: int) -> float:
    """Median-of-3 queries/s over `iters` executions of the list."""
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(iters):
            ex.execute("cap", queries[i % len(queries)])
        samples.append(iters / (time.perf_counter() - t0))
    return statistics.median(samples)


def run(shards: int = 2, rows: int = 4000, alpha: float = 1.1,
        base: int = 64, iters: int = 200,
        seed: int = 7) -> Dict[str, Any]:
    from pilosa_tpu.core import layout as layout_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.layout import LayoutManager
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.utils.hotspots import WORKLOAD
    from pilosa_tpu.utils.memledger import LEDGER

    WORKLOAD.reset()
    rec: Dict[str, Any] = {
        "bench": "layout_capacity", "shards": shards, "rows": rows,
        "alpha": alpha, "base": base, "iters": iters,
    }
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        build_corpus(holder, shards, rows, alpha, base, seed)
        ex = Executor(holder)
        ex.result_cache.enabled = False  # measure the real path

        hot_qs = [f"Count(Row(hot={r}))" for r in range(8)]
        cold_qs = [f"Count(Row(cold={r}))" for r in range(64)]
        # Warm + materialize the dense banks (and keep hot HOT so the
        # re-layout pass leaves it dense).
        for q in hot_qs + cold_qs[:8]:
            ex.execute("cap", q)
        dense_bytes = LEDGER.total_bytes(device_only=True)
        hot_dense_qps = _qps(ex, hot_qs, iters)
        cold_dense_qps = _qps(ex, cold_qs, iters)

        # Re-layout under a fresh heat map where only HOT is hot (the
        # steady state a real deployment reaches once the cold field's
        # EWMA decays): cold demotes, hot must stay dense.
        WORKLOAD.reset()
        for q in hot_qs * 4:
            ex.execute("cap", q)
        mgr = LayoutManager(holder, min_bytes=1024)
        summary = mgr.relayout_once()
        rec["relayout"] = summary
        hybrid_bytes = LEDGER.total_bytes(device_only=True)
        # Touch the sparse path once so its (small) banks are resident
        # before the byte snapshot comparison is judged.
        for q in cold_qs[:8]:
            ex.execute("cap", q)
        hybrid_bytes = max(hybrid_bytes,
                           LEDGER.total_bytes(device_only=True))
        hot_hybrid_qps = _qps(ex, hot_qs, iters)
        cold_hybrid_qps = _qps(ex, cold_qs, iters)

        # Kill-switch q/s baseline (dense planning, same process).
        layout_mod.HYBRID_LAYOUT_ENABLED = False
        try:
            for q in hot_qs:
                ex.execute("cap", q)
            hot_kill_qps = _qps(ex, hot_qs, iters)
        finally:
            layout_mod.HYBRID_LAYOUT_ENABLED = True

        gib = 1 << 30
        rec.update({
            "denseDeviceBytes": dense_bytes,
            "hybridDeviceBytes": hybrid_bytes,
            "bytesPerShardDense": dense_bytes / shards,
            "bytesPerShardHybrid": hybrid_bytes / shards,
            "shardsPerGiBDense": gib / max(1, dense_bytes / shards),
            "shardsPerGiBHybrid": gib / max(1, hybrid_bytes / shards),
            "shardsPerByteRatio": dense_bytes / max(1, hybrid_bytes),
            "hotQpsDense": hot_dense_qps,
            "hotQpsHybrid": hot_hybrid_qps,
            "hotQpsKillSwitch": hot_kill_qps,
            "hotRegressionPct": 100.0 * (1.0 - hot_hybrid_qps
                                         / hot_dense_qps),
            "coldQpsDense": cold_dense_qps,
            "coldQpsHybrid": cold_hybrid_qps,
            "sparseRowsPerS": cold_hybrid_qps,  # 1 row counted/query
        })
        holder.close()
    return rec


def quick_capacity(shards: int = 2, rows: int = 2000,
                   iters: int = 50) -> Optional[Dict[str, Any]]:
    """Small-shape capacity stanza for bench.py's record (never
    raises: the main bench must not die on a capacity probe)."""
    try:
        rec = run(shards=shards, rows=rows, iters=iters)
        return {k: rec[k] for k in
                ("shardsPerByteRatio", "bytesPerShardDense",
                 "bytesPerShardHybrid", "hotQpsDense", "hotQpsHybrid",
                 "hotRegressionPct", "sparseRowsPerS", "relayout")}
    except Exception as e:  # pragma: no cover - probe guard
        return {"error": f"{type(e).__name__}: {e}"}


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="layout_bench")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--rows", type=int, default=4000)
    ap.add_argument("--alpha", type=float, default=1.1)
    ap.add_argument("--base", type=int, default=64)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    rec = run(shards=args.shards, rows=args.rows, alpha=args.alpha,
              base=args.base, iters=args.iters, seed=args.seed)
    import jax
    rec["backend"] = jax.devices()[0].platform
    rec["t"] = time.time()
    print(json.dumps(rec))
    ok = rec["shardsPerByteRatio"] >= 2.0 \
        and rec["hotRegressionPct"] < 5.0
    print(f"layout_bench: shards-per-byte x{rec['shardsPerByteRatio']:.1f}, "
          f"hot regression {rec['hotRegressionPct']:+.2f}% -> "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
