#!/bin/bash
# Round-5 capture legs, armed from round start: the flagship 100M
# tanimoto with the fixed-width-segment kernel (the capture that died
# to a tunnel outage mid-compile in round 4), then a 10M re-capture
# with the same final kernel. Each leg builds its host-side dataset
# while the tunnel is down and holds at the build->query boundary
# (PILOSA_BENCH_HOLD_FOR_TPU), so an up-window is spent on
# compiles+queries, not builds.
#
# Success detection (advisor r4): a leg writes to a .tmp and is
# promoted only on rc==0 && non-empty .tmp; the done marker is touched
# only at promotion — never inferred from a record that predates the
# leg (the r04 supervisor's `-s` check was satisfied by the restored
# previous-best record, so a dead leg skipped its retries).
cd /root/repo
run() {
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_r05_done" ]; then
    echo "$(date -u +%H:%M:%S) legs: $name already done, skipping" >&2
    return
  fi
  echo "$(date -u +%H:%M:%S) legs: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r05_tpu.jsonl.tmp" \
                   2> "benches/${name}_r05_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) legs: $name rc=$rc" >&2
  if [ "$rc" -eq 0 ] && [ -s "benches/${name}_r05_tpu.jsonl.tmp" ]; then
    mv "benches/${name}_r05_tpu.jsonl.tmp" "benches/${name}_r05_tpu.jsonl"
    touch "benches/.${name}_r05_done"
  else
    rm -f "benches/${name}_r05_tpu.jsonl.tmp"
  fi
}
# Three passes: a leg that dies mid-device (tunnel outage) rebuilds and
# holds for the next window. Timeouts cover build (~30 min at 100M) +
# hold (4 h) + query.
for pass in 1 2 3; do
  run tanimoto_chunked_100m 21600 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=14400 PILOSA_TANIMOTO_N=100000000 \
      PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
  run tanimoto_chunked_10m 7200 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=5400 PILOSA_TANIMOTO_N=10000000 \
      PILOSA_TANIMOTO_ITERS=5 python benches/tanimoto_chunked.py
done
echo "$(date -u +%H:%M:%S) legs: done" >&2
