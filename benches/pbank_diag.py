"""Diagnostic: does the tanimoto TopN warm path reuse the device-resident
PositionsBank, or rebuild/stream per query?

The r04 TPU suite measured 10M/100M tanimoto p50s that scale linearly
with N at ~tunnel bandwidth over the sparse (~2 B/set bit) size — the
signature of a per-query re-upload, while the CPU records demonstrably
ran the resident-bank path. This traces positions_bank cache hits,
segment builds, and the executor branch actually taken, at a scale just
above the forced 64 MB dense-bank threshold the bench uses.
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("PILOSA_DIAG_N", 8_000_000))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    os.environ.setdefault("PILOSA_TPU_TOPN_CHUNK_ROWS", "65536")
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH
    import pilosa_tpu.core.view as V

    executor_mod.TOPN_CHUNK_ROWS = 65536
    executor_mod.TOPN_MAX_BANK_BYTES = 64 << 20  # same forcing as the bench

    orig_pb = V.View.positions_bank
    def traced_pb(self, shard, width):
        t0 = time.perf_counter()
        pb = orig_pb(self, shard, width)
        print(f"[diag] positions_bank {1000 * (time.perf_counter() - t0):.0f} ms "
              f"none={pb is None}", flush=True)
        return pb
    V.View.positions_bank = traced_pb

    orig_build = V.View._build_pbank_segments
    def traced_build(self, frag, rows, width, row_lo0):
        t0 = time.perf_counter()
        r = orig_build(self, frag, rows, width, row_lo0)
        print(f"[diag] BUILD pbank segments {time.perf_counter() - t0:.1f} s "
              f"none={r is None}", flush=True)
        return r
    V.View._build_pbank_segments = traced_build

    orig_tp = executor_mod.Executor._topn_positions
    def traced_tp(self, *a, **kw):
        print("[diag] _topn_positions (resident-bank branch) taken", flush=True)
        return orig_tp(self, *a, **kw)
    executor_mod.Executor._topn_positions = traced_tp

    rng = np.random.default_rng(7)
    t0 = time.perf_counter()
    pos = np.sort(rng.integers(0, 4096, (N, 48), dtype=np.uint16), axis=1)
    print(f"[diag] gen {time.perf_counter() - t0:.1f} s", flush=True)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        idx = holder.create_index("mole")
        f = idx.create_field("fingerprint", FieldOptions(max_columns=4096))
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        containers = frag.storage.containers
        cpr = SHARD_WIDTH // 65536
        keep = np.empty(pos.shape, dtype=bool)
        keep[:, 0] = True
        np.not_equal(pos[:, 1:], pos[:, :-1], out=keep[:, 1:])
        t0 = time.perf_counter()
        for i in range(N):
            containers[i * cpr] = pos[i][keep[i]]
        for i in range(N):
            frag._touch_row(i)
        print(f"[diag] load {time.perf_counter() - t0:.1f} s", flush=True)

        ex = Executor(holder)
        q = ("TopN(fingerprint, Row(fingerprint=12345), n=50, "
             "tanimotoThreshold=60)")
        for it in range(4):
            t0 = time.perf_counter()
            (res,) = ex.execute("mole", q)
            print(f"[diag] query {it}: {time.perf_counter() - t0:.2f} s, "
                  f"pairs={len(res.pairs)}", flush=True)


if __name__ == "__main__":
    main()
