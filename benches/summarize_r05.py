"""Round-5 capture integrator: print a markdown-ready summary of every
landed r05 record (benches/*_r05_tpu.jsonl, BENCH_early_r05.json) with
the context fields that matter (p50, vs_baseline, batch amortization,
measurement context). Read-only; safe to run any time."""

import glob
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))


def last_record(path):
    rec = None
    try:
        for ln in open(path).read().strip().splitlines():
            try:
                c = json.loads(ln)
            except ValueError:
                continue
            if isinstance(c, dict) and ("value" in c or "metric" in c):
                rec = c
    except OSError:
        pass
    return rec


def fmt(v, nd=3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def main():
    rows = []
    for path in sorted(glob.glob(os.path.join(HERE, "*_r05_tpu.jsonl"))):
        rec = last_record(path)
        if rec is None:
            continue
        name = os.path.basename(path).replace("_r05_tpu.jsonl", "")
        rows.append((name, rec))
    for extra in ("membership_probe_r05_tpu.jsonl",):
        pass  # covered by the glob
    bench = last_record(os.path.join(HERE, os.pardir,
                                     "BENCH_early_r05.json"))
    if bench is not None:
        rows.append(("bench.py (live)", bench))

    if not rows:
        print("no r05 device records landed yet")
        return
    print("| leg | metric | value | unit | vs_baseline | p50 | batch/ctx |")
    print("|---|---|---|---|---|---|---|")
    for name, r in rows:
        ctx = []
        if "batch_vs_baseline" in r:
            ctx.append(f"batch {r.get('batch_requests') or r.get('batch_calls')}: "
                       f"{fmt(r['batch_vs_baseline'])}x")
        if "trivial_fetch_ms" in r:
            ctx.append(f"fetch {fmt(r['trivial_fetch_ms'])}ms")
        if "backend" in r:
            ctx.append(r["backend"])
        if r.get("partial"):
            ctx.append("PARTIAL")
        print(f"| {name} | {r.get('metric', '-')} | {fmt(r.get('value'))} "
              f"| {r.get('unit', '-')} | {fmt(r.get('vs_baseline'))} "
              f"| {fmt(r.get('p50_query_s'))} | {'; '.join(ctx) or '-'} |")


if __name__ == "__main__":
    main()
