"""Native import-path thread scaling (VERDICT r3 next #5).

Measures pn_import_build throughput (the fragment bulk-import hot path,
reference fragment.go:1494-1604 + errgroup-parallel forwarding
api.go:878-888) at PILOSA_NATIVE_THREADS = 1, 2, 4, 8 — each in a fresh
subprocess because the worker count latches on first native call.
Prints one JSON line per thread count plus a summary line.

On the 1-vCPU bench box the counts all share one core, so throughput is
flat (slightly lower at >1 from atomic-OR overhead) — the measurement
that matters runs on a multi-core host; this harness is how to take it.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os, time, sys
import numpy as np
sys.path.insert(0, %(repo)r)
from pilosa_tpu import native
assert native.available()
rng = np.random.default_rng(11)
n = 8_000_000
rows = rng.integers(0, 4, n, dtype=np.uint64)
cols = rng.integers(0, 1 << 20, n, dtype=np.uint64)
native.import_build(rows[:1000], cols[:1000], 20)  # warm lib load
best = None
for _ in range(3):
    t0 = time.perf_counter()
    keys, words, counts, payload, nbits = native.import_build(
        rows, cols, 20)
    dt = time.perf_counter() - t0
    best = dt if best is None else min(best, dt)
print(json.dumps({"pairs": n, "seconds": best,
                  "pairs_per_sec": n / best, "nbits": int(nbits)}))
"""


def main():
    results = {}
    for threads in (1, 2, 4, 8):
        env = {**os.environ, "PILOSA_NATIVE_THREADS": str(threads)}
        p = subprocess.run(
            [sys.executable, "-c", CHILD % {"repo": REPO}], env=env,
            capture_output=True, text=True, timeout=600)
        if p.returncode != 0:
            print(json.dumps({"metric": "import_build_pairs_per_sec",
                              "threads": threads, "value": 0.0,
                              "unit": "pairs/sec", "vs_baseline": 0.0,
                              "error": p.stderr[-300:]}), flush=True)
            continue
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        results[threads] = rec["pairs_per_sec"]
        print(json.dumps({"metric": "import_build_pairs_per_sec",
                          "threads": threads,
                          "value": rec["pairs_per_sec"],
                          "unit": "pairs/sec",
                          "vs_baseline": (rec["pairs_per_sec"]
                                          / results.get(1, 1.0)
                                          if 1 in results else 1.0)}),
              flush=True)
    if 1 in results:
        best_t = max(results, key=results.get)
        print(json.dumps({
            "metric": "import_build_thread_scaling",
            "value": results[best_t] / results[1],
            "unit": "x_vs_1_thread",
            "vs_baseline": results[best_t] / results[1],
            "best_threads": best_t,
            "host_cpus": os.cpu_count(),
        }))


if __name__ == "__main__":
    main()
