#!/bin/bash
# Round-5 LIVE capture chain (container-restart recovery). The original
# five-watcher chain died with the container restart at 08:28 UTC on
# 2026-08-01; this single sequential chain replaces it, re-ordered
# QUICK-FIRST because the tunnel was observed UP at 08:29 and windows
# have historically been short (~20 min to ~1 h):
#   1. live bench.py          (official headline; 3 rounds of cpu-fallback)
#   2. membership probe       (ns/position verdict for the default flip)
#   3. 10M tanimoto           (final-kernel flagship record)
#   4. startrace batch leg    (VERDICT #3: batch>=16 through the tunnel)
#   5. bsi batch leg          (same)
#   6. 10M with 'search' variant iff the probe says search wins >10%
#   7. 100M tanimoto          (long build; holds at the query boundary)
# Quick legs hold only ~25 min for a window so a mid-chain outage cannot
# starve later legs; the 100M leg holds 3 h as before. Promotion judges
# each leg by ITS OWN .tmp artifact (advisor r4 #1); markers only on
# promotion. Re-runnable: done markers skip landed legs.
cd /root/repo
log() { echo "$(date -u +%H:%M:%S) live-chain: $*" >&2; }

promote_tanimoto() {  # $1=tmp $2=final $3=marker $4=want_n
  python - "$1" "$2" "$3" "$4" <<'EOF'
import json, os, sys
tmp, final, marker, want_n = sys.argv[1:5]
rec = None
try:
    for ln in reversed(open(tmp).read().strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
ok = (rec is not None and not rec.get("partial")
      and rec.get("molecules") == int(want_n) and "p50_query_s" in rec)
if ok:
    with open(final, "w") as fh:
        fh.write(json.dumps(rec) + "\n")
    open(marker, "w").close()
    os.unlink(tmp)
    print("promoted:", rec.get("p50_query_s"))
sys.exit(0 if ok else 1)
EOF
}

promote_value() {  # $1=tmp $2=final $3=marker  (generic "value" record)
  python - "$1" "$2" "$3" <<'EOF'
import json, os, sys
tmp, final, marker = sys.argv[1:4]
rec = None
try:
    for ln in reversed(open(tmp).read().strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
ok = rec is not None and not rec.get("partial") and "value" in rec
if ok:
    os.replace(tmp, final)
    open(marker, "w").close()
sys.exit(0 if ok else 1)
EOF
}

# ---- 1. live bench.py -------------------------------------------------
if [ ! -e benches/.bench_live_r05_done ]; then
  log "bench.py live"
  timeout 3600 env PILOSA_BENCH_WAIT_QUIET_S=30 \
      PILOSA_BENCH_PROBE_HOLD_S=1500 python bench.py \
      > BENCH_early_r05.json.tmp 2> bench_early_r05.err
  rc=$?
  ok=$(python - <<'EOF'
import json
rec = None
try:
    for ln in reversed(open("BENCH_early_r05.json.tmp").read()
                       .strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
print(1 if rec and rec.get("backend") != "cpu-fallback"
      and not rec.get("provisional") and "value" in rec else 0)
EOF
)
  log "bench.py rc=$rc ok=$ok"
  if [ "$rc" -eq 0 ] && [ "$ok" = "1" ]; then
    mv BENCH_early_r05.json.tmp BENCH_early_r05.json
    touch benches/.bench_live_r05_done
    log "live TPU bench record landed"
  else
    rm -f BENCH_early_r05.json.tmp
  fi
fi

# ---- 2. membership probe ---------------------------------------------
if [ ! -e benches/.membership_probe_r05_done ]; then
  log "membership probe"
  timeout 2400 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=1500 \
      python benches/pbank_membership_probe.py \
      > benches/membership_probe_r05_tpu.jsonl.tmp \
      2> benches/membership_probe_r05_tpu.err
  rc=$?
  log "membership probe rc=$rc"
  if [ "$rc" -eq 0 ] && grep -q pbank_membership_best \
      benches/membership_probe_r05_tpu.jsonl.tmp; then
    mv benches/membership_probe_r05_tpu.jsonl.tmp \
       benches/membership_probe_r05_tpu.jsonl
    touch benches/.membership_probe_r05_done
  else
    rm -f benches/membership_probe_r05_tpu.jsonl.tmp
  fi
fi

# ---- 3. 10M tanimoto (final kernel, auto membership) ------------------
if [ ! -e benches/.tanimoto_chunked_10m_r05_done ]; then
  log "10M tanimoto"
  timeout 4500 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=1500 PILOSA_TANIMOTO_N=10000000 \
      PILOSA_TANIMOTO_ITERS=5 python benches/tanimoto_chunked.py \
      > benches/tanimoto_chunked_10m_r05_tpu.jsonl.tmp \
      2> benches/tanimoto_chunked_10m_r05_tpu.err
  log "10M rc=$?"
  promote_tanimoto benches/tanimoto_chunked_10m_r05_tpu.jsonl.tmp \
      benches/tanimoto_chunked_10m_r05_tpu.jsonl \
      benches/.tanimoto_chunked_10m_r05_done 10000000 >&2
  rm -f benches/tanimoto_chunked_10m_r05_tpu.jsonl.tmp
fi

# ---- 4+5. startrace / bsi batch legs ---------------------------------
for leg in startrace bsi; do
  if [ ! -e "benches/.${leg}_r05_done" ]; then
    log "$leg batch leg"
    timeout 2700 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
        PILOSA_BENCH_HOLD_MAX_S=1500 python "benches/${leg}.py" \
        > "benches/${leg}_r05_tpu.jsonl.tmp" \
        2> "benches/${leg}_r05_tpu.err"
    log "$leg rc=$?"
    promote_value "benches/${leg}_r05_tpu.jsonl.tmp" \
        "benches/${leg}_r05_tpu.jsonl" "benches/.${leg}_r05_done" >&2 \
      || rm -f "benches/${leg}_r05_tpu.jsonl.tmp"
  fi
done

# ---- 6. membership e2e leg (only if probe picked search) --------------
if [ -f benches/membership_probe_r05_tpu.jsonl ] && \
   [ ! -e benches/.membership_e2e_r05_done ]; then
  VARIANT=$(python - <<'EOF'
import json
best = None
for ln in open("benches/membership_probe_r05_tpu.jsonl"):
    try:
        rec = json.loads(ln)
    except ValueError:
        continue
    if rec.get("metric") == "pbank_membership_best":
        best = rec
if best and best.get("best") == "search" and \
        best.get("speedup_vs_compare", 0) > 1.10:
    print("search")
EOF
)
  if [ -n "$VARIANT" ]; then
    log "membership e2e leg with $VARIANT"
    timeout 4500 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
        PILOSA_BENCH_HOLD_MAX_S=1500 PILOSA_TANIMOTO_N=10000000 \
        PILOSA_TANIMOTO_ITERS=5 "PILOSA_TPU_PBANK_MEMBERSHIP=$VARIANT" \
        python benches/tanimoto_chunked.py \
        > "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" \
        2> "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.err"
    log "membership e2e rc=$?"
    promote_tanimoto \
        "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" \
        "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl" \
        benches/.membership_e2e_r05_done 10000000 >&2
    rm -f "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp"
  else
    log "probe verdict: compare stands; no e2e leg"
    touch benches/.membership_e2e_r05_done
  fi
fi

# ---- 7. 100M tanimoto (long build, holds at query boundary) -----------
for pass in 1 2 3; do
  [ -e benches/.tanimoto_chunked_100m_r05_done ] && break
  log "100M tanimoto pass $pass"
  timeout 18000 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=10800 PILOSA_TANIMOTO_N=100000000 \
      PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py \
      > benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp \
      2> benches/tanimoto_chunked_100m_r05_tpu.err
  log "100M rc=$?"
  promote_tanimoto benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp \
      benches/tanimoto_chunked_100m_r05_tpu.jsonl \
      benches/.tanimoto_chunked_100m_r05_done 100000000 >&2 && break
  rm -f benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp
done
log "chain done"
