#!/bin/bash
# One-shot: at the next tunnel up-window, capture the headline bench.py
# measurement and the TopN phase profile with EXCLUSIVE use of the box
# (the per-call floor is host scheduling — benches/README.md), by
# SIGSTOPping the main suite's WHOLE PROCESS GROUP (the nohup'd suite
# shell is its own group leader, so -PGID covers running leg children
# and probe subprocesses too) for the duration, then resuming it so its
# retry legs run next. The sidecar guard in bench.py means this can
# only upgrade the carried record, never downgrade it.
#
# probe() duplicates r04b's — those scripts are mid-execution and bash
# reads scripts incrementally, so they cannot be edited to source a
# shared file until they exit; dedup then.
cd /root/repo
probe() {
  timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, _ = probe_device_once(80)
sys.exit(0 if ok else 1)" 2>/dev/null
}
until probe; do
  echo "$(date -u +%H:%M:%S) quiet-capture: waiting for TPU..." >&2
  sleep 45
done
SUITE_PID=$(pgrep -o -f run_tpu_suite_r04b.sh)
SUITE_PGID=""
if [ -n "$SUITE_PID" ]; then
  SUITE_PGID=$(ps -o pgid= -p "$SUITE_PID" | tr -d ' ')
fi
echo "$(date -u +%H:%M:%S) quiet-capture: TPU answered; pausing suite pgid=${SUITE_PGID:-none}" >&2
[ -n "$SUITE_PGID" ] && kill -STOP -- "-$SUITE_PGID" 2>/dev/null
resume() {
  echo "$(date -u +%H:%M:%S) quiet-capture: resuming suite" >&2
  [ -n "$SUITE_PGID" ] && kill -CONT -- "-$SUITE_PGID" 2>/dev/null
}
# EXIT alone does not fire on untrapped signal death; cover the ways
# this script can be killed so the suite is never left stopped.
trap resume EXIT INT TERM HUP
echo "$(date -u +%H:%M:%S) quiet-capture: bench.py (full shape)" >&2
timeout 1800 env PILOSA_BENCH_WAIT_QUIET_S=60 python bench.py \
  > BENCH_quiet_r04.json 2> bench_quiet_r04.err
echo "$(date -u +%H:%M:%S) quiet-capture: bench.py rc=$?" >&2
echo "$(date -u +%H:%M:%S) quiet-capture: topn phase profile" >&2
timeout 600 python benches/topn_phase_profile.py \
  > benches/topn_phase_r04_tpu.jsonl 2> benches/topn_phase_r04_tpu.err
echo "$(date -u +%H:%M:%S) quiet-capture: profile rc=$?" >&2
