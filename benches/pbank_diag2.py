"""Phase breakdown of the warm positions-bank TopN query: preamble
(parse/translate/row-leaf) vs kernel dispatch vs device compute vs
result fetch. Follow-up to pbank_diag.py, which showed the resident
bank IS reused and a single-segment 8M warm query still costs ~5.6 s.
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("PILOSA_DIAG_N", 8_000_000))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    os.environ.setdefault("PILOSA_TPU_TOPN_CHUNK_ROWS", "65536")
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.executor.results import PairsResult
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    executor_mod.TOPN_CHUNK_ROWS = 65536
    executor_mod.TOPN_MAX_BANK_BYTES = 64 << 20

    import jax
    import jax.numpy as jnp

    def traced_tp(self, pb, filter_words, n, tanimoto, min_threshold,
                  src_dev):
        print(f"[diag]   _topn_positions enter; segments={len(pb.segments)}",
              flush=True)
        fw = filter_words[0] if filter_words is not None else None
        t0 = time.perf_counter()
        outs = []
        for row_lo, n_rows, pos, starts, _p in pb.segments:
            k = min(n, n_rows)
            if k == 0:
                continue
            kern = self._pbank_kernel(k, fw is not None,
                                      fixed=pos.ndim == 2)
            params = jnp.asarray(
                np.asarray([min_threshold, tanimoto, 0], np.uint32))
            if tanimoto and src_dev is not None:
                params = params.at[2].set(
                    jnp.asarray(src_dev).astype(jnp.uint32))
            outs.append((row_lo, kern(
                fw if fw is not None else jnp.zeros((1,), jnp.uint32),
                pos, starts, params)))
        print(f"[diag]   dispatch {time.perf_counter() - t0:.3f} s",
              flush=True)
        t0 = time.perf_counter()
        jax.block_until_ready([o for _, o in outs])
        print(f"[diag]   device  {time.perf_counter() - t0:.3f} s",
              flush=True)
        t0 = time.perf_counter()
        got = jax.device_get([(v, i) for _, (v, i) in outs])
        print(f"[diag]   fetch   {time.perf_counter() - t0:.3f} s",
              flush=True)

        def finalize():
            pairs = []
            for (row_lo, _), (v, ix) in zip(outs, got):
                for val, i in zip(v.tolist(), ix.tolist()):
                    if val > 0:
                        pairs.append((int(pb.row_ids[row_lo + i]),
                                      int(val)))
            pairs.sort(key=lambda rc: (-rc[1], rc[0]))
            return PairsResult(pairs[:n])

        return executor_mod._Pending(finalize)

    executor_mod.Executor._topn_positions = traced_tp

    rng = np.random.default_rng(7)
    pos = np.sort(rng.integers(0, 4096, (N, 48), dtype=np.uint16), axis=1)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        idx = holder.create_index("mole")
        f = idx.create_field("fingerprint", FieldOptions(max_columns=4096))
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        containers = frag.storage.containers
        cpr = SHARD_WIDTH // 65536
        keep = np.empty(pos.shape, dtype=bool)
        keep[:, 0] = True
        np.not_equal(pos[:, 1:], pos[:, :-1], out=keep[:, 1:])
        for i in range(N):
            containers[i * cpr] = pos[i][keep[i]]
        for i in range(N):
            frag._touch_row(i)
        print("[diag] loaded", flush=True)

        ex = Executor(holder)
        q = ("TopN(fingerprint, Row(fingerprint=12345), n=50, "
             "tanimotoThreshold=60)")
        for it in range(4):
            t0 = time.perf_counter()
            (res,) = ex.execute("mole", q)
            print(f"[diag] query {it}: {time.perf_counter() - t0:.2f} s "
                  f"pairs={len(res.pairs)}", flush=True)


if __name__ == "__main__":
    main()
