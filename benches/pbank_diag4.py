"""A/B the pbank kernel's row-count reduction at 32M molecules:
current flat jnp.cumsum over [P] vs a two-level blocked scan
([P/2^16, 2^16] inner cumsum + exclusive block offsets), both through
the real executor with the bank resident. Positions segments pad to
1M multiples, so the reshape is always valid; padding bits are zero
(sentinel positions match nothing), so prefix lookups clamp safely.
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("PILOSA_DIAG_N", 32_000_000))
ITERS = int(os.environ.get("PILOSA_DIAG_ITERS", 3))
INNER = 1 << 16


def variant_kernel(variant: str):
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.executor import executor as ex_mod

    def build(k: int, has_filter: bool):
        QCAP = ex_mod.PBANK_SPARSE_FILTER_BITS

        def bits_gather(fw, posi):
            return (jnp.take(fw, posi >> 5, mode="fill", fill_value=0)
                    >> (posi & 31).astype(jnp.uint32)) & jnp.uint32(1)

        def bits_compare(fw, posi):
            w = jnp.arange(fw.shape[0], dtype=jnp.int32)
            allpos = w[:, None] * 32 + jnp.arange(32, dtype=jnp.int32)
            setmask = ((fw[:, None] >> jnp.arange(32, dtype=jnp.uint32))
                       & jnp.uint32(1)).astype(bool)
            qpos = jnp.where(setmask, allpos, 1 << 30).reshape(-1)
            qk = min(QCAP, int(qpos.shape[0]))
            qtop = -jax.lax.top_k(-qpos, qk)[0]
            m = (posi[:, None] == qtop[None, :]).any(axis=1)
            return m.astype(jnp.uint32)

        def rowsum_flat(bits, starts):
            s = jnp.concatenate(
                [jnp.zeros(1, jnp.uint32),
                 jnp.cumsum(bits, dtype=jnp.uint32)])
            return (s[starts[1:]] - s[starts[:-1]]).astype(jnp.int32)

        def rowsum_two_level(bits, starts):
            nb = bits.shape[0] // INNER
            b2 = bits.reshape(nb, INNER)
            inner = jnp.cumsum(b2, axis=1, dtype=jnp.uint32)  # inclusive
            blk = jnp.concatenate(
                [jnp.zeros(1, jnp.uint32),
                 jnp.cumsum(inner[:, -1], dtype=jnp.uint32)])  # excl.

            def prefix(j):
                # sum of bits[:j]; padding bits are zero so clamping the
                # final j==P edge inside the last block is exact.
                jc = jnp.minimum(j, nb * INNER - 1)
                b = jc // INNER
                off = jc % INNER
                base = blk[b]
                innerv = jnp.where(off > 0, inner[b, off - 1],
                                   jnp.uint32(0))
                # j == nb*INNER: jc points at the last element, whose
                # bit is zero-padding, so prefix(j) == total.
                last = jnp.where(j == nb * INNER,
                                 inner[jc // INNER, INNER - 1] - innerv,
                                 jnp.uint32(0))
                return base + innerv + last

            hi = prefix(starts[1:])
            lo = prefix(starts[:-1])
            return (hi - lo).astype(jnp.int32)

        rowsum = rowsum_flat if variant == "flat" else rowsum_two_level

        @jax.jit
        def kernel(fw, pos, starts, params):
            raw = starts[1:] - starts[:-1]
            if has_filter:
                posi = pos.astype(jnp.int32)
                fwpop = jnp.sum(
                    jax.lax.population_count(fw)).astype(jnp.int32)
                bits = jax.lax.cond(
                    fwpop <= QCAP,
                    lambda: bits_compare(fw, posi),
                    lambda: bits_gather(fw, posi))
                c = rowsum(bits, starts)
            else:
                c = raw
            thresh, tani, src = (params[0].astype(jnp.int32),
                                 params[1].astype(jnp.int32),
                                 params[2].astype(jnp.int32))
            keep = c >= jnp.maximum(1, thresh)
            denom = raw + src - c
            keep &= jnp.where(tani > 0,
                              (denom > 0) & (c * 100 >= tani * denom),
                              True)
            score = jnp.where(keep, c, -1)
            return jax.lax.top_k(score, k)

        return kernel

    return build


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    os.environ.setdefault("PILOSA_TPU_TOPN_CHUNK_ROWS", "65536")
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as ex_mod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    ex_mod.TOPN_CHUNK_ROWS = 65536
    ex_mod.TOPN_MAX_BANK_BYTES = 64 << 20

    rng = np.random.default_rng(7)
    pos = np.sort(rng.integers(0, 4096, (N, 48), dtype=np.uint16), axis=1)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        idx = holder.create_index("mole")
        f = idx.create_field("fingerprint", FieldOptions(max_columns=4096))
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        containers = frag.storage.containers
        cpr = SHARD_WIDTH // 65536
        keep = np.empty(pos.shape, dtype=bool)
        keep[:, 0] = True
        np.not_equal(pos[:, 1:], pos[:, :-1], out=keep[:, 1:])
        for i in range(N):
            containers[i * cpr] = pos[i][keep[i]]
        for i in range(N):
            frag._touch_row(i)
        print("[diag] loaded", flush=True)

        ex = Executor(holder)
        q = ("TopN(fingerprint, Row(fingerprint=12345), n=50, "
             "tanimotoThreshold=60)")
        want = None
        for variant in ["flat", "two_level"]:
            ex_mod.Executor._PBANK_KERNELS.clear()
            build = variant_kernel(variant)
            ex_mod.Executor._pbank_kernel = classmethod(
                lambda cls, k, hf, _b=build: cls._PBANK_KERNELS.setdefault(
                    (k, hf), _b(k, hf)))
            times = []
            for it in range(ITERS + 1):
                t0 = time.perf_counter()
                (res,) = ex.execute("mole", q)
                dt = time.perf_counter() - t0
                if it > 0:
                    times.append(dt)
            if want is None:
                want = res.pairs
            assert res.pairs == want, f"{variant} results differ"
            print(f"[diag] {variant}: warm p50 "
                  f"{float(np.median(times)):.2f} s "
                  f"(all {[f'{t:.2f}' for t in times]})", flush=True)


if __name__ == "__main__":
    main()
