#!/bin/bash
# Round-5 sequential capture orchestrator. One process, strict order —
# no concurrent legs contending for the 1-vCPU box or for tunnel
# windows:
#   1. adopt the already-running 100M tanimoto leg (timeout pid $1,
#      writing benches/tanimoto_chunked_100m_r05_tpu.jsonl.tmp), or
#      start one; retry up to 3 total attempts. The flagship capture
#      owns the first tunnel window.
#   2. live bench.py capture (hold-for-window probe inside bench.py),
#      retried until a real device record lands in BENCH_early_r05.json.
#   3. 10M tanimoto re-capture with the final kernel.
# Promotion (advisor r4): a leg's success is judged from ITS OWN
# artifact — the .tmp it wrote — parsed for a complete (non-partial)
# record; the done marker is only ever touched at promotion.
cd /root/repo
REC=benches/tanimoto_chunked_100m_r05_tpu.jsonl

check_and_promote() {  # $1=tmpfile $2=final $3=marker $4=expected_n
  python - "$1" "$2" "$3" "$4" <<'EOF'
import json, os, sys
tmp, final, marker, want_n = sys.argv[1:5]
rec = None
try:
    for ln in reversed(open(tmp).read().strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
ok = (rec is not None and not rec.get("partial")
      and rec.get("molecules") == int(want_n) and "p50_query_s" in rec)
if ok:
    with open(final, "w") as fh:
        fh.write(json.dumps(rec) + "\n")
    with open(marker, "w") as fh:
        pass
    os.unlink(tmp)
    print("promoted:", rec.get("p50_query_s"))
sys.exit(0 if ok else 1)
EOF
}

ADOPT_PID=$1
if [ -n "$ADOPT_PID" ] && kill -0 "$ADOPT_PID" 2>/dev/null; then
  echo "$(date -u +%H:%M:%S) orch: adopting 100M leg pid $ADOPT_PID" >&2
  while kill -0 "$ADOPT_PID" 2>/dev/null; do sleep 30; done
  echo "$(date -u +%H:%M:%S) orch: adopted leg exited" >&2
  check_and_promote "$REC.tmp" "$REC" benches/.tanimoto_chunked_100m_r05_done \
      100000000 >&2 && echo "$(date -u +%H:%M:%S) orch: 100M landed (adopted)" >&2
  rm -f "$REC.tmp"
fi

run_leg() {  # $1=name $2=timeout $3=n $4=iters $5=hold_max
  local name=$1 to=$2 n=$3 iters=$4 hold=$5
  if [ -e "benches/.${name}_r05_done" ]; then return 0; fi
  echo "$(date -u +%H:%M:%S) orch: leg $name" >&2
  timeout "$to" env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      "PILOSA_BENCH_HOLD_MAX_S=$hold" "PILOSA_TANIMOTO_N=$n" \
      "PILOSA_TANIMOTO_ITERS=$iters" python benches/tanimoto_chunked.py \
      > "benches/${name}_r05_tpu.jsonl.tmp" \
      2> "benches/${name}_r05_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) orch: leg $name rc=$rc" >&2
  check_and_promote "benches/${name}_r05_tpu.jsonl.tmp" \
      "benches/${name}_r05_tpu.jsonl" "benches/.${name}_r05_done" "$n" >&2
  local ok=$?
  rm -f "benches/${name}_r05_tpu.jsonl.tmp"
  return $ok
}

for pass in 1 2 3; do
  [ -e benches/.tanimoto_chunked_100m_r05_done ] && break
  run_leg tanimoto_chunked_100m 18000 100000000 3 10800 && break
done

probe() {
  timeout 170 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, _ = probe_device_once(150)
sys.exit(0 if ok else 1)" 2>/dev/null
}
while [ ! -e benches/.bench_live_r05_done ]; do
  echo "$(date -u +%H:%M:%S) orch: bench.py live attempt" >&2
  # bench.py holds for a window itself (3 h default probe hold).
  timeout 14400 env PILOSA_BENCH_WAIT_QUIET_S=60 python bench.py \
      > BENCH_early_r05.json.tmp 2> bench_early_r05.err
  rc=$?
  ok=$(python - <<'EOF'
import json
rec = None
try:
    for ln in reversed(open("BENCH_early_r05.json.tmp").read()
                       .strip().splitlines()):
        try:
            rec = json.loads(ln)
            break
        except ValueError:
            continue
except OSError:
    pass
print(1 if rec and rec.get("backend") != "cpu-fallback"
      and not rec.get("provisional") and "value" in rec else 0)
EOF
)
  echo "$(date -u +%H:%M:%S) orch: bench.py rc=$rc ok=$ok" >&2
  if [ "$rc" -eq 0 ] && [ "$ok" = "1" ]; then
    mv BENCH_early_r05.json.tmp BENCH_early_r05.json
    touch benches/.bench_live_r05_done
    echo "$(date -u +%H:%M:%S) orch: live TPU bench record landed" >&2
  else
    rm -f BENCH_early_r05.json.tmp
    sleep 60
  fi
done

for pass in 1 2; do
  [ -e benches/.tanimoto_chunked_10m_r05_done ] && break
  run_leg tanimoto_chunked_10m 7200 10000000 5 5400 && break
done
echo "$(date -u +%H:%M:%S) orch: all done" >&2
