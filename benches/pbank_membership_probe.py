"""Membership-kernel probe (VERDICT r5 #2): measure ns/position on the
real device for each membership form over a fixed-layout positions bank
shape (R x L u16, ~48-bit sparse filter):

- compare: the [P] x [QCAP] equality fan-out (r4 default, ~1 ns/pos)
- search:  binary search in the sorted query positions (log2 QCAP)
- gather:  the filter-bit-table dynamic gather (r4's dense fallback)
- pallas:  fused compare+rowsum, VMEM-resident query positions
           (ops/pallas_kernels.pbank_membership_counts)

Timing: salted chains (identical-repeat timing is invalid on this
backend — docs/perf.md §4b); each iteration XORs a salt derived from
the previous result into the query positions so no sweep can be CSE'd.
Prints one JSON line per variant."""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = int(os.environ.get("PILOSA_PROBE_ROWS", 4_194_304))  # 4M rows
L = 48
QK = 48
ITERS = [4, 12]  # chain lengths for the slope


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    from pilosa_tpu.utils.benchenv import hold_for_tpu
    hold_for_tpu("membership_probe")
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    pos = np.sort(rng.integers(0, 4096, (R, L), dtype=np.uint16), axis=1)
    q = np.unique(rng.integers(0, 4096, QK * 2, dtype=np.uint16))[:QK]
    q32 = q.astype(np.int32)
    positions = R * L

    pos_dev = jnp.asarray(pos)
    qtop_dev = jnp.asarray(q32)
    grouped = jnp.asarray(pos.view(np.uint32).reshape(R // 16,
                                                      16 * (L // 2)))
    qpad = np.full((8, 128), -1, np.int32)
    qpad.reshape(-1)[:QK] = q32
    qpad_dev = jnp.asarray(qpad)
    # Filter bit table for the gather form: 4096 bits = 128 u32 words.
    fw = np.zeros(128, np.uint32)
    for p in q:
        fw[p >> 5] |= np.uint32(1) << (p & 31)
    fw_dev = jnp.asarray(fw)

    def counts_compare(p, qt):
        return (p[..., None].astype(jnp.int32) == qt).any(-1) \
            .sum(axis=1, dtype=jnp.int32)

    def counts_search(p, qt):
        idx = jnp.clip(jnp.searchsorted(qt, p.astype(jnp.int32)),
                       0, QK - 1)
        return (jnp.take(qt, idx) == p.astype(jnp.int32)) \
            .sum(axis=1, dtype=jnp.int32)

    def counts_gather(p, _qt):
        bits = (jnp.take(fw_dev, (p >> 5).astype(jnp.int32),
                         mode="fill", fill_value=0)
                >> (p & 31).astype(jnp.uint32)) & jnp.uint32(1)
        return bits.sum(axis=1, dtype=jnp.int32)

    def run_variant(name, fn, qarg):
        """Chain K sweeps, salt threaded through the query positions
        (XOR of a tiny salt keeps them valid i32s; counts feed the next
        salt so iterations serialize)."""
        @jax.jit
        def chain(qt, k):
            def body(_, carry):
                qt_c, acc = carry
                c = fn(pos_dev if name != "pallas" else grouped, qt_c)
                s = (c[0] & 1).astype(qt_c.dtype)
                return (qt_c ^ s, acc + c[-1])
            (_, acc) = jax.lax.fori_loop(
                0, k, body, (qt, jnp.int32(0)))
            return acc

        for k in ITERS:  # warm both shapes
            np.asarray(chain(qarg, k))
        times = {}
        for k in ITERS:
            reps = []
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(chain(qarg, k))
                reps.append(time.perf_counter() - t0)
            times[k] = min(reps)
        per_iter = (times[ITERS[1]] - times[ITERS[0]]) \
            / (ITERS[1] - ITERS[0])
        print(json.dumps({
            "metric": "pbank_membership_ns_per_position",
            "variant": name,
            "value": per_iter / positions * 1e9,
            "unit": "ns/position",
            "rows": R, "slots": L, "qk": QK,
            "per_sweep_s": per_iter,
        }), flush=True)
        return per_iter

    results = {}
    results["compare"] = run_variant("compare", counts_compare, qtop_dev)
    results["search"] = run_variant("search", counts_search, qtop_dev)
    results["gather"] = run_variant("gather", counts_gather, qtop_dev)

    from pilosa_tpu.ops import pallas_kernels as pk
    if pk.available():
        def counts_pallas(g, qt_pad):
            return pk.pbank_membership_counts(g, qt_pad, qk=QK)
        try:
            results["pallas"] = run_variant("pallas", counts_pallas,
                                            qpad_dev)
        except Exception as e:
            print(json.dumps({"variant": "pallas",
                              "error": repr(e)[:400]}), flush=True)
    else:
        print(json.dumps({"variant": "pallas",
                          "skipped": "no TPU backend"}), flush=True)

    best = min(results, key=results.get)
    print(json.dumps({"metric": "pbank_membership_best",
                      "best": best,
                      "value": results[best] / positions * 1e9,
                      "unit": "ns/position",
                      "speedup_vs_compare":
                      results["compare"] / results[best]}), flush=True)


if __name__ == "__main__":
    main()
