#!/bin/bash
# Round-5 follow-up captures: startrace + BSI end-to-end legs with the
# new batch mode (VERDICT r4 #3 wants batch>=16 measured through the
# tunnel), run AFTER the main orchestrator finishes so the box and the
# tunnel windows are never contended. Promotion mirrors the
# orchestrator: judge a leg by its own .tmp artifact, marker only on
# promotion.
cd /root/repo
while pgrep -f run_r05_orchestrator.sh > /dev/null; do sleep 60; done
echo "$(date -u +%H:%M:%S) followup: orchestrator done, starting" >&2
run() {
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_r05_done" ]; then
    echo "$(date -u +%H:%M:%S) followup: $name already done" >&2
    return
  fi
  echo "$(date -u +%H:%M:%S) followup: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r05_tpu.jsonl.tmp" \
                   2> "benches/${name}_r05_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) followup: $name rc=$rc" >&2
  if [ "$rc" -eq 0 ] && [ -s "benches/${name}_r05_tpu.jsonl.tmp" ] && \
     python - "benches/${name}_r05_tpu.jsonl.tmp" <<'EOF'
import json, sys
rec = None
for ln in reversed(open(sys.argv[1]).read().strip().splitlines()):
    try:
        rec = json.loads(ln); break
    except ValueError:
        continue
ok = rec is not None and not rec.get("partial") and "value" in rec
sys.exit(0 if ok else 1)
EOF
  then
    mv "benches/${name}_r05_tpu.jsonl.tmp" "benches/${name}_r05_tpu.jsonl"
    touch "benches/.${name}_r05_done"
  else
    rm -f "benches/${name}_r05_tpu.jsonl.tmp"
  fi
}
for pass in 1 2; do
  run startrace 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=3000 python benches/startrace.py
  run bsi 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=3000 python benches/bsi.py
done
echo "$(date -u +%H:%M:%S) followup: done" >&2
