#!/bin/bash
# Fifth-stage round-5 watcher: once the membership probe has landed,
# turn its ns/position verdict into END-TO-END evidence. If a
# production-selectable variant ("search") beat the default ("compare")
# by >10% on-device, run the 10M tanimoto leg with that variant so the
# default-flip decision rests on the full flagship path, not just the
# kernel microbenchmark. Probe-only variants (pallas/gather) are
# reported but cannot drive a leg.
cd /root/repo
for up in run_r05_orchestrator.sh run_r05_followup.sh \
          run_r05_probe_followup.sh; do
  while pgrep -f "$up" > /dev/null; do sleep 60; done
done
[ -e benches/.membership_e2e_r05_done ] && exit 0
if [ ! -f benches/membership_probe_r05_tpu.jsonl ]; then
  echo "membership probe never landed; nothing to act on" >&2
  exit 0
fi
VARIANT=$(python - <<'EOF'
import json
best = None
for ln in open("benches/membership_probe_r05_tpu.jsonl"):
    try:
        rec = json.loads(ln)
    except ValueError:
        continue
    if rec.get("metric") == "pbank_membership_best":
        best = rec
if best and best.get("best") == "search" and \
        best.get("speedup_vs_compare", 0) > 1.10:
    print("search")
EOF
)
if [ -z "$VARIANT" ]; then
  echo "probe verdict: default (compare) stands; no e2e leg needed" >&2
  touch benches/.membership_e2e_r05_done
  exit 0
fi
echo "$(date -u +%H:%M:%S) membership-followup: e2e leg with $VARIANT" >&2
for pass in 1 2; do
  timeout 7200 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=5400 PILOSA_TANIMOTO_N=10000000 \
      PILOSA_TANIMOTO_ITERS=5 "PILOSA_TPU_PBANK_MEMBERSHIP=$VARIANT" \
      python benches/tanimoto_chunked.py \
      > "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" \
      2> "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.err"
  rc=$?
  echo "$(date -u +%H:%M:%S) membership-followup: rc=$rc" >&2
  if python - "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" <<'EOF'
import json, sys
rec = None
for ln in reversed(open(sys.argv[1]).read().strip().splitlines()):
    try:
        rec = json.loads(ln); break
    except ValueError:
        continue
ok = (rec is not None and not rec.get("partial")
      and rec.get("molecules") == 10000000 and "p50_query_s" in rec)
sys.exit(0 if ok else 1)
EOF
  then
    mv "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp" \
       "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl"
    touch benches/.membership_e2e_r05_done
    break
  fi
  rm -f "benches/tanimoto_chunked_10m_${VARIANT}_r05_tpu.jsonl.tmp"
done
echo "$(date -u +%H:%M:%S) membership-followup: done" >&2
