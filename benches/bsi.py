"""BSI benchmark — BASELINE.md config 3: int field over 10M columns,
16 shards; Range/Sum/Min/Max through the production executor vs an exact
numpy host baseline on the same planes.

Prints one JSON line per op: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_COLS = 10_000_000
N_SHARDS = 16
VMIN, VMAX = 0, 100_000
ITERS = 5
BATCH = int(os.environ.get("PILOSA_BENCH_BATCH", 16))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    from pilosa_tpu.utils.benchenv import \
        install_partial_record_handler
    install_partial_record_handler(
        "bsi_ops_per_sec", "ops/sec")
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    rng = np.random.default_rng(7)
    cols = np.arange(N_COLS, dtype=np.uint64)
    vals = rng.integers(VMIN, VMAX, N_COLS, dtype=np.int64)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        from pilosa_tpu.core.field import FieldOptions
        idx = holder.create_index("bsi")
        f = idx.create_field("v", FieldOptions(type="int", min=VMIN,
                                               max=VMAX))
        t0 = time.perf_counter()
        f.import_values(cols, vals)
        load_s = time.perf_counter() - t0

        # Meet an intermittent tunnel at query time (no-op unless
        # PILOSA_BENCH_HOLD_FOR_TPU is set).
        from pilosa_tpu.utils.benchenv import hold_for_tpu
        hold_for_tpu("bsi")
        ex = Executor(holder)

        queries = {
            "range_gt": (f"Count(Range(v > {VMAX // 2}))",
                         lambda: int((vals > VMAX // 2).sum())),
            "sum": ('Sum(field="v")', lambda: {"value": int(vals.sum()),
                                       "count": len(vals)}),
            "min": ('Min(field="v")', lambda: {"value": int(vals.min()),
                                       "count": int((vals == vals.min())
                                                    .sum())}),
            "max": ('Max(field="v")', lambda: {"value": int(vals.max()),
                                       "count": int((vals == vals.max())
                                                    .sum())}),
        }
        out = {"metric": "bsi_ops_per_sec", "unit": "ops/sec",
               "loaded_cols": N_COLS, "load_seconds": round(load_s, 2)}
        batched = " ".join(q for q, _ in queries.values())
        ex.execute("bsi", batched)  # warm compile
        from pilosa_tpu.utils.benchenv import measurement_context
        out.update(measurement_context())
        # correctness
        results = ex.execute("bsi", batched)
        for (name, (_, ref)), got in zip(queries.items(), results):
            want = ref()
            if isinstance(want, dict):
                assert got.value == want["value"] and \
                    got.count == want["count"], (name, got, want)
            else:
                assert got == want, (name, got, want)
        # TPU timing (batched — dispatches pipeline before fetch)
        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            ex.execute("bsi", batched)
            times.append((time.perf_counter() - t0) / len(queries))
        tpu_t = float(np.median(times))
        # Cross-request batch (execute_batch): BATCH requests of the
        # 4-op query share ONE overlapped device->host drain — the
        # serving amortization for high-RTT links (VERDICT r4 #3).
        reqs = [("bsi", batched, None)] * BATCH
        ex.execute_batch(reqs)  # warm
        btimes = []
        for _ in range(max(2, ITERS // 2)):
            t0 = time.perf_counter()
            got = ex.execute_batch(reqs)
            btimes.append((time.perf_counter() - t0)
                          / (len(queries) * BATCH))
            assert not any(isinstance(r, Exception) for r in got)
        batch_t = float(np.median(btimes))
        # host baseline: same predicates on the raw values
        t0 = time.perf_counter()
        for _, ref in queries.values():
            ref()
        cpu_t = (time.perf_counter() - t0) / len(queries)
        out["value"] = 1.0 / tpu_t
        out["vs_baseline"] = cpu_t / tpu_t
        out["batch_requests"] = BATCH
        out["batch_p50_per_call"] = batch_t
        out["batch_vs_baseline"] = cpu_t / batch_t
        print(json.dumps(out))
        holder.close()


if __name__ == "__main__":
    main()
    # Real records are out; a late TERM during interpreter
    # teardown must not append a zero-value partial.
    import signal as _signal
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
