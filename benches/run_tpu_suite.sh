#!/bin/bash
# Waits for the TPU tunnel to answer, then runs every bench serially,
# recording outputs. Between benches it WAITS for the tunnel to return
# rather than aborting — it must survive the tunnel's known flakiness.
cd /root/repo
probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
print(int(jnp.ones((8,), jnp.uint32).sum()))" >/dev/null 2>&1
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 120
  done
}
run() {  # run <name> <timeout> <cmd...>
  local name=$1 to=$2; shift 2
  wait_tpu
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r02_tpu.jsonl" 2> "benches/${name}_r02_tpu.err"
  echo "$(date -u +%H:%M:%S) bench: $name rc=$?" >&2
}
run tanimoto_chunked 2400 env PILOSA_TANIMOTO_N=2000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
run taxi 2400 env PILOSA_TAXI_N=2000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run micro 1800 python benches/micro.py
run startrace 1200 python benches/startrace.py
run bsi 1800 python benches/bsi.py
wait_tpu
echo "$(date -u +%H:%M:%S) final bench.py" >&2
python bench.py > BENCH_late.json 2> bench_late.err
echo "$(date -u +%H:%M:%S) suite done rc=$?" >&2
# Appended mid-round: retry tanimoto_chunked (its first slot hit a hung
# tunnel) at a smaller N that fits the window, then refresh micro.
run tanimoto_chunked_retry 2000 env PILOSA_TANIMOTO_N=1000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
echo "$(date -u +%H:%M:%S) appended-retry done" >&2
