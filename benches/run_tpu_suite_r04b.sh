#!/bin/bash
# Round-4 TPU suite, revision b — tuned for an INTERMITTENT tunnel
# (observed up-windows of ~6 minutes between multi-hour outages):
#
#  * bench.py first (persists benches/last_good_tpu.json — captured
#    01:05 UTC this round, marker prevents a rerun);
#  * micro next (device-time roofline table now runs FIRST inside the
#    leg), then the remaining legs FAST-FIRST so each up-window banks
#    the most records;
#  * the two 100M flagship legs run LAST with nowait+hold: they build
#    their host-side data while the tunnel is DOWN and hold at the
#    build->query boundary (benchenv.hold_for_tpu) until the chip
#    answers, instead of burning the up-window on data generation;
#  * a leg is marked done ONLY when its process exits 0 — a leg that
#    emitted host-side lines and then died on the first device op (or
#    was killed by the leg timeout, rc=124) reruns on restart.
cd /root/repo
# Single probe definition: benchenv.probe_device_once (also used by the
# in-leg hold_for_tpu), so the shell gate and the python hold can never
# drift in what "tunnel is up" means.
probe() {
  timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, detail = probe_device_once(80)
if not ok:
    print(detail, file=sys.stderr)
sys.exit(0 if ok else 1)" 2>/dev/null
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 45
  done
  echo "$(date -u +%H:%M:%S) TPU answered" >&2
}
run() {  # run [--nowait] <name> <timeout> <cmd...>
  local nowait=""
  if [ "$1" = "--nowait" ]; then nowait=1; shift; fi
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_r04_done" ]; then
    echo "$(date -u +%H:%M:%S) bench: $name already done, skipping" >&2
    return
  fi
  if [ -z "$nowait" ]; then wait_tpu; fi
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r04_tpu.jsonl" 2> "benches/${name}_r04_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) bench: $name rc=$rc" >&2
  # Done = clean exit AND at least one record: rc=124 (leg timeout) or
  # a device-op crash must leave the leg eligible for a retry pass.
  if [ "$rc" -eq 0 ] && [ -s "benches/${name}_r04_tpu.jsonl" ]; then
    touch "benches/.${name}_r04_done"
  fi
}
if [ ! -e benches/.bench_early_r04_done ]; then
  wait_tpu
  echo "$(date -u +%H:%M:%S) early bench.py (sidecar capture)" >&2
  timeout 1800 python bench.py > BENCH_early_r04.json 2> bench_early_r04.err
  echo "$(date -u +%H:%M:%S) bench.py rc=$?" >&2
  [ -s BENCH_early_r04.json ] && touch benches/.bench_early_r04_done
fi
run micro 3600 python benches/micro.py
run startrace 1200 python benches/startrace.py
run bsi 1800 python benches/bsi.py
run topn_cache 1200 python benches/topn_cache.py
run tanimoto 1800 python benches/tanimoto.py
run --nowait tanimoto_chunked_10m 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=2000 PILOSA_TANIMOTO_N=10000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
run --nowait taxi_10m 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=2000 PILOSA_TAXI_N=10000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run --nowait taxi_100m 14400 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=9000 PILOSA_TAXI_N=100000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run --nowait tanimoto_chunked_100m 21600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=12000 PILOSA_TANIMOTO_N=100000000 PILOSA_TANIMOTO_ITERS=1 python benches/tanimoto_chunked.py
# Retry pass: anything that failed mid-device gets one more window.
run micro 3600 python benches/micro.py
run startrace 1200 python benches/startrace.py
run bsi 1800 python benches/bsi.py
run topn_cache 1200 python benches/topn_cache.py
run tanimoto 1800 python benches/tanimoto.py
run --nowait tanimoto_chunked_10m 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=2000 PILOSA_TANIMOTO_N=10000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
run --nowait taxi_10m 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=2000 PILOSA_TAXI_N=10000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run --nowait taxi_100m 14400 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=9000 PILOSA_TAXI_N=100000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run --nowait tanimoto_chunked_100m 21600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=12000 PILOSA_TANIMOTO_N=100000000 PILOSA_TANIMOTO_ITERS=1 python benches/tanimoto_chunked.py
echo "$(date -u +%H:%M:%S) suite done" >&2
