"""Summarize benches/*_r0N_{tpu,cpu}.jsonl records into one markdown
table (for docs/perf.md and the round notes).

Usage: python benches/summarize.py [round] [backend]
       (defaults: round 4, backend tpu)

Skips partial records (a leg killed mid-run leaves {"partial": true});
flags invalid device-time rows (above-roofline measurements are stored
with "invalid": true rather than suppressed)."""

import glob
import json
import os
import sys


def load(path):
    recs = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue  # severed line from a mid-print TERM
            if isinstance(r, dict) and r.get("metric"):
                recs.append(r)
    return recs


def fmt(v):
    if isinstance(v, float):
        if v >= 1000:
            return f"{v:,.0f}"
        if v >= 1:
            return f"{v:.2f}"
        return f"{v:.4g}"
    return str(v)


def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else "4"
    backend = sys.argv[2] if len(sys.argv) > 2 else "tpu"
    base = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(glob.glob(
        os.path.join(base, f"*_r0{rnd}_{backend}.jsonl")))
    if not paths:
        print(f"(no *_r0{rnd}_{backend}.jsonl records yet)")
        return
    print(f"| Leg | Metric | Value | Unit | vs_baseline | Notes |")
    print(f"|---|---|---|---|---|---|")
    for p in paths:
        leg = os.path.basename(p).replace(f"_r0{rnd}_{backend}.jsonl", "")
        for r in load(p):
            if r.get("partial"):
                continue
            notes = []
            if r.get("invalid"):
                notes.append("INVALID (above roofline)")
            if r.get("error"):
                notes.append(str(r["error"])[:60])
            for k in ("roofline_frac", "gbps_min", "gbps_max", "p50_query_s",
                      "backend", "platform", "device_kind"):
                if k in r:
                    notes.append(f"{k}={fmt(r[k])}")
            print(f"| {leg} | {r['metric']} | {fmt(r.get('value', ''))} | "
                  f"{r.get('unit', '')} | "
                  f"{fmt(r.get('vs_baseline', ''))} | "
                  f"{'; '.join(notes)} |")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # `| head` closed the pipe; not an error
        pass
