"""Threshold (N-of-M) bench: the OP_THRESH thermometer lowering vs
the classical union-of-k-subsets expansion a client would otherwise
send (PR 16 acceptance lane).

``Threshold(r1..rn, k=K)`` lowers to ~K*N plan rows (K thermometer
accumulators swept once per operand); the equivalent
``Union(Intersect(...k-subset...) for every subset)`` lowers to
C(N,K) intersect chains plus the final union — combinatorial in the
plan buffer, identical in the answer. Both forms run as megakernel
batches on the same index; the record carries measured plan entries,
plan bytes, and wall time for each, plus the bit-identity check. The
expansion leg runs with the optimizer ON too, so the comparison is
"best possible expansion" vs the opcode — CSE already dedupes the
shared subsets, and the gap that remains is the point of the opcode.

One JSON line per (n, k) shape on stdout, appended to
``thresh_r01_cpu.jsonl``. Env knobs: THRESH_BENCH_BITS (400000),
THRESH_BENCH_ROWS (16), THRESH_BENCH_QUERIES (8 per leg),
THRESH_BENCH_REPEATS (3).
"""

import itertools
import json
import os
import statistics
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_BITS = int(os.environ.get("THRESH_BENCH_BITS", 400_000))
N_ROWS = int(os.environ.get("THRESH_BENCH_ROWS", 16))
N_QUERIES = int(os.environ.get("THRESH_BENCH_QUERIES", 8))
REPEATS = int(os.environ.get("THRESH_BENCH_REPEATS", 3))
SHAPES = ((4, 2), (6, 3), (8, 4))  # (n operands, k threshold)
ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "thresh_r01_cpu.jsonl")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def emit(rec):
    line = json.dumps(rec)
    print(line, flush=True)
    with open(ARTIFACT, "a") as fh:
        fh.write(line + "\n")


def operand_rows(q, n):
    """n distinct Row() atoms per query index q, overlapping across
    queries so the cross-request CSE has real work on both legs."""
    return [f"Row({'f' if (q + i) % 2 else 'g'}={(q + i) % N_ROWS})"
            for i in range(n)]


def thresh_pql(rows, k):
    return f"Count(Threshold({', '.join(rows)}, k={k}))"


def expansion_pql(rows, k):
    subsets = [f"Intersect({', '.join(s)})"
               for s in itertools.combinations(rows, k)]
    return f"Count(Union({', '.join(subsets)}))"


def run_leg(ex, reqs):
    from pilosa_tpu.executor import megakernel as megamod
    assert megamod.MEGAKERNEL_ENABLED
    entries0 = ex.mega_plan_entries
    pbytes0 = ex.mega_plan_bytes
    launches0 = ex.mega_launches
    walls, out = [], None
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = ex.execute_batch_shaped(reqs)
        walls.append(time.perf_counter() - t0)
    reps = ex.mega_launches - launches0
    return out, {
        "wall_ms": round(1e3 * statistics.median(walls), 3),
        "mega_launches": reps,
        "plan_entries": (ex.mega_plan_entries - entries0)
        // max(1, reps),
        "plan_bytes": (ex.mega_plan_bytes - pbytes0) // max(1, reps),
    }


def main():
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import megakernel as megamod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    log(f"thresh-bench: building holder ({N_BITS} bits, {N_ROWS} rows)")
    if os.path.exists(ARTIFACT):
        os.remove(ARTIFACT)
    with tempfile.TemporaryDirectory() as tmp:
        h = Holder(tmp)
        h.open()
        idx = h.create_index("bench")
        f = idx.create_field("f")
        g = idx.create_field("g")
        rng = np.random.default_rng(42)
        rows = rng.integers(0, N_ROWS, N_BITS).astype(np.uint64)
        cols = rng.integers(0, 2 * SHARD_WIDTH, N_BITS).astype(np.uint64)
        f.import_bits(rows, cols)
        g.import_bits(rows[::2], cols[::2])
        idx.add_existence(cols)
        ex = Executor(h)
        ex.result_cache.enabled = False
        prev = megamod.MEGAKERNEL_ENABLED
        megamod.MEGAKERNEL_ENABLED = True
        try:
            for n, k in SHAPES:
                ops = [operand_rows(q, n) for q in range(N_QUERIES)]
                treqs = [("bench", thresh_pql(r, k), None) for r in ops]
                ereqs = [("bench", expansion_pql(r, k), None)
                         for r in ops]
                for rq in (treqs, ereqs):  # warm compiled variants
                    ex.execute_batch_shaped(rq)
                t_out, t_stats = run_leg(ex, treqs)
                e_out, e_stats = run_leg(ex, ereqs)
                assert t_out == e_out, \
                    f"Threshold != expansion at n={n} k={k}"
                emit({
                    "bench": "thresh_vs_expansion",
                    "n": n, "k": k, "subsets": len(
                        list(itertools.combinations(range(n), k))),
                    "queries": N_QUERIES,
                    "repeats": REPEATS,
                    "threshold": t_stats,
                    "expansion": e_stats,
                    "plan_entry_ratio": round(
                        e_stats["plan_entries"]
                        / max(1, t_stats["plan_entries"]), 2),
                    "bit_identical": True,
                    "backend": "cpu",
                })
        finally:
            megamod.MEGAKERNEL_ENABLED = prev
        h.close()


if __name__ == "__main__":
    main()
