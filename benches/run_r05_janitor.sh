#!/bin/bash
# Round-5 janitor: the driver runs the OFFICIAL bench.py at round end
# (~12h after round start). Any still-holding capture watcher would
# contend with it for the tunnel window and the 1-vCPU box — worse
# than losing the remaining legs. Wind the whole chain down at the
# deadline (default: 11:50 UTC, ~75 min before the expected driver
# bench) unless it finished on its own.
cd /root/repo
DEADLINE_UTC=${1:-"11:50"}
# Epoch-second deadline with the shared midnight-wrap rule (ADVICE
# r5; see benches/deadline_epoch.sh for the 6 h disambiguation — a
# janitor restarted just after its deadline winds the chain down
# immediately, not a day later).
. benches/deadline_epoch.sh
DEADLINE_EPOCH=$(deadline_epoch "$DEADLINE_UTC")
while :; do
  [ "$(date -u +%s)" -ge "$DEADLINE_EPOCH" ] && break
  pgrep -f "run_r05_orchestrator.sh|run_r05_followup.sh|run_r05_probe_followup.sh|run_r05_membership_followup.sh|run_r05_live_chain.sh|run_r05_chain2.sh" \
      > /dev/null || exit 0   # chain finished by itself
  sleep 120
done
echo "$(date -u +%H:%M:%S) janitor: deadline passed, winding down" >&2
pkill -f run_r05_orchestrator.sh
pkill -f run_r05_followup.sh
pkill -f run_r05_probe_followup.sh
pkill -f run_r05_membership_followup.sh
pkill -f run_r05_live_chain.sh
pkill -f run_r05_chain2.sh
sleep 2
# Kill leg payloads (python benches) still holding for a window; their
# partial-record handlers write what they have. The postcheck stage is
# left alone — it only runs when everything above is gone.
pkill -f "benches/tanimoto_chunked.py"
pkill -f "benches/startrace.py"
pkill -f "benches/bsi.py"
pkill -f "benches/pbank_membership_probe.py"
pkill -f "python bench.py"
echo "$(date -u +%H:%M:%S) janitor: done" >&2
