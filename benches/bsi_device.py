"""BSI device-time bench — the chain-slope companion to benches/bsi.py.

benches/bsi.py measures BASELINE config 3 (int field, 10M columns)
END-TO-END through the executor, which through the bench tunnel is
dominated by per-dispatch RPC latency and contention, not device work
(a trivial device add round-trips in 22 us, yet end-to-end ops measure
~100+ ms when the tunnel is busy — see benches/tunnel_rtt_r04.json).
This harness measures the DEVICE time of the same four fused BSI query
programs (Range >, Sum, Min, Max — reference fragment.go:767,794,827,
857-1035) with the salted-chain slope method (utils/benchenv.py), which
cancels all host<->device round trips. On co-located hardware the
device time is the serving ceiling; together the two benches bracket
reality from both sides.

Bank shape matches config 3: depth+1 planes x 10 shards x 32768 words
(10M columns of a 0..100k int field). Operands are generated on device
— a pure kernel bench, contents are random either way, and the upload
would burn a tunnel up-window. bytes_per_iter credits ONE full bank
read per sweep; Sum/Min/Max stream some planes more than once, so
their GB/s under-reports (conservative, same convention as micro.py).

Prints one JSON line per op plus a combined bsi_device_ops_per_sec.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEPTH = 17          # bit depth of a 0..100k int field (config 3)
# 10M columns / 2^20 shard width; overridable because the 23 MB bank
# the config-3 shape implies can leave the longest chain's device time
# (~3 ms) inside the tunnel's RTT jitter — a wider bank (e.g. 96
# shards = 226 MB) lifts the slope signal clear of the noise without
# changing the per-byte rate being measured.
N_SHARDS = int(os.environ.get("PILOSA_BSI_DEVICE_SHARDS", "10"))
VALUE = 50_000


def emit(rec):
    print(json.dumps(rec), flush=True)


def make_plane_chain(kern):
    """One-bank variant of benchenv.make_salted_chain: kern(planes)
    -> array/scalar of counts. Every iteration ADDS a carry-derived
    salt to the whole bank (addition does not distribute over the
    bitwise ops being measured), so no iteration's memory traffic can
    be elided or hoisted — the validity rules of benchenv apply."""
    import jax
    import jax.numpy as jnp

    def chain_impl(x, k):
        def body(_, carry):
            acc, salt = carry
            sx = salt ^ jnp.uint32(0x9E3779B9)
            tot = jnp.sum(kern(x + sx)).astype(jnp.uint32)
            return acc + tot, tot ^ salt
        acc, _ = jax.lax.fori_loop(0, k, body,
                                   (jnp.uint32(0), jnp.uint32(0)))
        return acc

    jitted = jax.jit(chain_impl)
    return lambda x, k: jitted(x, np.int32(k))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.executor import bsi as B
    from pilosa_tpu.ops.bitset import WORDS_PER_SHARD, popcount
    from pilosa_tpu.utils.benchenv import timed_fetch, validated_chain_slope

    shape = (DEPTH + 1, N_SHARDS, WORDS_PER_SHARD)
    planes = jax.block_until_ready(
        jax.random.bits(jax.random.key(5), shape, jnp.uint32))

    axes = (-2, -1)
    kernels = {
        "bsi_device_range_gt": lambda p: popcount(
            B.gt(p, VALUE), axis=axes),
        "bsi_device_sum": lambda p: B.sum_count(p)[0].sum()
        + B.sum_count(p)[1],
        "bsi_device_min": lambda p: popcount(
            B.min_mask(p)[1], axis=axes) + B.min_mask(p)[0].sum(),
        "bsi_device_max": lambda p: popcount(
            B.max_mask(p)[1], axis=axes) + B.max_mask(p)[0].sum(),
    }

    dev = jax.devices()[0]
    op_seconds = {}
    for name, kern in kernels.items():
        chain = make_plane_chain(kern)
        try:
            r = validated_chain_slope(
                lambda k: timed_fetch(lambda: chain(planes, k)),
                planes.nbytes, dev)
        except RuntimeError as e:
            emit({"metric": name, "value": 0.0, "unit": "GB/sec",
                  "error": str(e)})
            continue
        op_seconds[name] = planes.nbytes / (r["gbps_median"] * 1e9)
        emit({"metric": name, "value": r["gbps_median"],
              "unit": "GB/sec", "backend": dev.platform,
              "bank_mb": planes.nbytes >> 20,
              "device_op_seconds": op_seconds[name],
              "method": "salted-chain-slope",
              **{k: r[k] for k in
                 ("gbps_min", "gbps_max", "slope_pairs", "roofline_frac",
                  "roofline_gbps_assumed", "device_kind")},
              **({"invalid": True, "error": r["error"]}
                 if r.get("invalid") else {})})

    if op_seconds:
        mean_s = sum(op_seconds.values()) / len(op_seconds)
        emit({"metric": "bsi_device_ops_per_sec", "value": 1.0 / mean_s,
              "unit": "ops/sec", "backend": dev.platform,
              "note": "device time only (chain slope); end-to-end with "
              "dispatch is benches/bsi.py", "ops_measured":
              sorted(op_seconds)})


if __name__ == "__main__":
    main()
