#!/bin/bash
# Round-4 flagship-SCALE records on the host CPU (VERDICT r3 item 2:
# "measured rows at >=10x current scale"). These are measurements of
# the production executor path at scale — the TPU suite
# (run_tpu_suite_r04.sh) carries the same configs on hardware when the
# tunnel answers; this script guarantees the scale evidence exists
# either way. Niced: the box has 1 vCPU shared with the build.
cd /root/repo
run() {  # run <name> <timeout> <cmd...>
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_done" ]; then return; fi
  echo "$(date -u +%H:%M:%S) cpu-scale: $name" >&2
  timeout "$to" nice -n 15 "$@" \
    > "benches/${name}.jsonl" 2> "benches/${name}.err"
  echo "$(date -u +%H:%M:%S) cpu-scale: $name rc=$?" >&2
  [ -s "benches/${name}.jsonl" ] && touch "benches/.${name}_done"
}
export PILOSA_BENCH_PLATFORM=cpu
run taxi_100m_r04_cpu 21600 env PILOSA_TAXI_N=100000000 PILOSA_TAXI_ITERS=3 python benches/taxi.py
run tanimoto_chunked_10m_r04_cpu 14400 env PILOSA_TANIMOTO_N=10000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
echo "$(date -u +%H:%M:%S) cpu-scale done" >&2
