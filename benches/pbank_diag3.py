"""A/B the PositionsBank TopN kernel through the REAL executor path at
one-segment scale (8M molecules, bank resident between queries):

  current — gather bits + cumsum rowdiff + flat lax.top_k
  A       — same, but exact two-stage (blocked) top-k
  B       — A + gather-free membership for sparse filters: the query
            fingerprint's <=64 set positions are extracted on device
            (nonzero over 4096 bits) and membership is a dense
            [P]x[64] compare-reduce; lax.cond falls back to the gather
            form when the filter is denser than 64 bits.

Each variant replaces Executor._pbank_kernel, clears the kernel cache,
and runs ITERS warm queries; results must match the current kernel's.
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("PILOSA_DIAG_N", 8_000_000))
ITERS = int(os.environ.get("PILOSA_DIAG_ITERS", 3))
BLOCK = 8192
QCAP = 64


def variant_kernel(variant: str):
    import jax
    import jax.numpy as jnp

    def build(k: int, has_filter: bool):
        def topk_flat(score):
            return jax.lax.top_k(score, k)

        def topk_two_stage(score):
            r = score.shape[0]
            pad = (-r) % BLOCK
            sp = jnp.pad(score, (0, pad), constant_values=-1)
            nb = sp.shape[0] // BLOCK
            kb = min(k, BLOCK)
            v, i = jax.lax.top_k(sp.reshape(nb, BLOCK), kb)
            base = (jnp.arange(nb, dtype=jnp.int32) * BLOCK)[:, None]
            cv = v.reshape(-1)
            ci = (i.astype(jnp.int32) + base).reshape(-1)
            gv, gi = jax.lax.top_k(cv, k)
            return gv, jnp.take(ci, gi)

        topk = topk_flat if variant == "current" else topk_two_stage

        def bits_gather(fw, posi):
            return (jnp.take(fw, posi >> 5, mode="fill", fill_value=0)
                    >> (posi & 31).astype(jnp.uint32)) & jnp.uint32(1)

        def bits_compare(fw, posi):
            # fw: [W] u32 words; set positions -> [QCAP] i32 (pad 2^30)
            w = jnp.arange(fw.shape[0], dtype=jnp.int32)
            allpos = w[:, None] * 32 + jnp.arange(32, dtype=jnp.int32)
            setmask = ((fw[:, None] >> jnp.arange(32, dtype=jnp.uint32))
                       & jnp.uint32(1)).astype(bool)
            qpos = jnp.where(
                setmask, allpos, 1 << 30).reshape(-1)
            qtop = -jax.lax.top_k(-qpos, QCAP)[0]  # QCAP smallest
            m = (posi[:, None] == qtop[None, :]).any(axis=1)
            return m.astype(jnp.uint32)

        @jax.jit
        def kernel(fw, pos, starts, params):
            raw = starts[1:] - starts[:-1]
            if has_filter:
                posi = pos.astype(jnp.int32)
                if variant == "B":
                    fwpop = jnp.sum(
                        jax.lax.population_count(fw)).astype(jnp.int32)
                    bits = jax.lax.cond(
                        fwpop <= QCAP,
                        lambda: bits_compare(fw, posi),
                        lambda: bits_gather(fw, posi))
                else:
                    bits = bits_gather(fw, posi)
                s = jnp.concatenate(
                    [jnp.zeros(1, jnp.uint32),
                     jnp.cumsum(bits, dtype=jnp.uint32)])
                c = (s[starts[1:]] - s[starts[:-1]]).astype(jnp.int32)
            else:
                c = raw
            thresh, tani, src = (params[0].astype(jnp.int32),
                                 params[1].astype(jnp.int32),
                                 params[2].astype(jnp.int32))
            keep = c >= jnp.maximum(1, thresh)
            denom = raw + src - c
            keep &= jnp.where(tani > 0,
                              (denom > 0) & (c * 100 >= tani * denom),
                              True)
            score = jnp.where(keep, c, -1)
            return topk(score)

        return kernel

    return build


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    os.environ.setdefault("PILOSA_TPU_TOPN_CHUNK_ROWS", "65536")
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as executor_mod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    executor_mod.TOPN_CHUNK_ROWS = 65536
    executor_mod.TOPN_MAX_BANK_BYTES = 64 << 20

    rng = np.random.default_rng(7)
    pos = np.sort(rng.integers(0, 4096, (N, 48), dtype=np.uint16), axis=1)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp)
        holder.open()
        idx = holder.create_index("mole")
        f = idx.create_field("fingerprint", FieldOptions(max_columns=4096))
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        containers = frag.storage.containers
        cpr = SHARD_WIDTH // 65536
        keep = np.empty(pos.shape, dtype=bool)
        keep[:, 0] = True
        np.not_equal(pos[:, 1:], pos[:, :-1], out=keep[:, 1:])
        for i in range(N):
            containers[i * cpr] = pos[i][keep[i]]
        for i in range(N):
            frag._touch_row(i)
        print("[diag] loaded", flush=True)

        ex = Executor(holder)
        q = ("TopN(fingerprint, Row(fingerprint=12345), n=50, "
             "tanimotoThreshold=60)")
        want = None
        for variant in ["current", "A", "B"]:
            executor_mod.Executor._PBANK_KERNELS.clear()
            build = variant_kernel(variant)
            executor_mod.Executor._pbank_kernel = classmethod(
                lambda cls, k, hf, _b=build: cls._PBANK_KERNELS.setdefault(
                    (k, hf), _b(k, hf)))
            times = []
            for it in range(ITERS + 1):
                t0 = time.perf_counter()
                (res,) = ex.execute("mole", q)
                dt = time.perf_counter() - t0
                if it > 0:  # it 0 pays the variant's compile
                    times.append(dt)
            if want is None:
                want = res.pairs
            assert res.pairs == want, f"{variant} results differ"
            print(f"[diag] {variant}: warm p50 "
                  f"{float(np.median(times)):.2f} s "
                  f"(all {[f'{t:.2f}' for t in times]})", flush=True)


if __name__ == "__main__":
    main()
