#!/bin/bash
# Adopts the already-holding 100M tanimoto leg (pid passed as $1):
# waits for it to finish; promotes or restores the record; then runs
# the 10M leg with atomic promotion via the fixed recapture script's
# conventions.
cd /root/repo
LEG_PID=$1
GOOD_100M_COMMIT=08e305a
if [ -n "$LEG_PID" ]; then
  echo "$(date -u +%H:%M:%S) supervising 100M leg pid $LEG_PID" >&2
  while kill -0 "$LEG_PID" 2>/dev/null; do sleep 60; done
  if [ -s benches/tanimoto_chunked_100m_r04_tpu.jsonl ]; then
    echo "$(date -u +%H:%M:%S) 100M record landed" >&2
    touch benches/.tanimoto_chunked_100m_final_done
  else
    echo "$(date -u +%H:%M:%S) 100M attempt failed; restoring best" >&2
    git show "$GOOD_100M_COMMIT":benches/tanimoto_chunked_100m_r04_tpu.jsonl \
      > benches/tanimoto_chunked_100m_r04_tpu.jsonl
  fi
fi
run() {
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_final_done" ]; then
    echo "$(date -u +%H:%M:%S) $name already done, skipping" >&2
    return
  fi
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r04_tpu.jsonl.tmp" \
                   2> "benches/${name}_r04_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) bench: $name rc=$rc" >&2
  if [ "$rc" -eq 0 ] && [ -s "benches/${name}_r04_tpu.jsonl.tmp" ]; then
    mv "benches/${name}_r04_tpu.jsonl.tmp" "benches/${name}_r04_tpu.jsonl"
    touch "benches/.${name}_final_done"
  else
    rm -f "benches/${name}_r04_tpu.jsonl.tmp"
  fi
}
for pass in 1 2; do
  run tanimoto_chunked_100m 14400 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=9000 PILOSA_TANIMOTO_N=100000000 PILOSA_TANIMOTO_ITERS=3 python benches/tanimoto_chunked.py
  run tanimoto_chunked_10m 3600 env PILOSA_BENCH_HOLD_FOR_TPU=1 PILOSA_BENCH_HOLD_MAX_S=2000 PILOSA_TANIMOTO_N=10000000 PILOSA_TANIMOTO_ITERS=5 python benches/tanimoto_chunked.py
done
echo "$(date -u +%H:%M:%S) supervisor done" >&2
