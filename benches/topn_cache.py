"""BASELINE config 2: TopN over a 1M-column set field, single shard,
warm ranked cache vs numpy exact recount (reference rankCache,
cache.go:136 + fragment.top, fragment.go:1067)."""
import json, os, sys, tempfile, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from pilosa_tpu.utils.benchenv import apply_bench_platform
apply_bench_platform()
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor

rng = np.random.default_rng(2)
with tempfile.TemporaryDirectory() as tmp:
    h = Holder(tmp); h.open()
    idx = h.create_index("c2")
    f = idx.create_field("f")  # default ranked cache, 50k
    rows = rng.integers(0, 5000, 4_000_000).astype(np.uint64)
    cols = rng.integers(0, 1 << 20, 4_000_000).astype(np.uint64)
    t0 = time.perf_counter()
    f.import_bits(rows, cols)
    load_s = time.perf_counter() - t0
    ex = Executor(h)
    (want,) = ex.execute("c2", "TopN(f, n=10)")  # warm
    from pilosa_tpu.utils.benchenv import measurement_context
    ctx = measurement_context()
    times = []
    for _ in range(200):
        t0 = time.perf_counter()
        (got,) = ex.execute("c2", "TopN(f, n=10)")
        times.append(time.perf_counter() - t0)
    assert got.pairs == want.pairs
    p50 = float(np.median(times))
    assert ex.topn_cache_hits > 0  # really the warm ranked-cache path
    # numpy baseline: exact recount + top-k over the same bits
    per_row = {}
    t0 = time.perf_counter()
    u, c = np.unique((rows << np.uint64(20)) + cols, return_counts=False), None
    counts = np.bincount((u >> np.uint64(20)).astype(np.int64), minlength=5000)
    order = np.argsort(-counts, kind="stable")[:10]
    base_s = time.perf_counter() - t0
    base_pairs = [(int(r), int(counts[r])) for r in order]
    assert base_pairs == want.pairs, (base_pairs[:3], want.pairs[:3])
    h.close()
print(json.dumps({"metric": "topn_ranked_cache_p50_latency", "value": p50,
                  "unit": "seconds", "vs_baseline": base_s / p50,
                  "columns": 1 << 20, "distinct_rows": 5000,
                  "cache_hits": True, "load_seconds": round(load_s, 2),
                  **ctx}))
