"""Salted-chain timing of the PositionsBank TopN kernel stages at one-
segment scale. Repeat-identical-call timing is invalid on this backend
(identical executions get cached/elided somewhere between jax and the
tunnel — observed as 0.0 ms lax.top_k over 8M rows), so every stage is
measured the way benchenv measures sweeps: K iterations chained in one
fori_loop, every iteration's input perturbed by a salt carried from the
previous iteration's output, per-iteration time = Theil-Sen slope
across chain lengths (RTT and dispatch cancel).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

P = int(os.environ.get("PILOSA_PROBE_POSITIONS", 384 << 20))
R = int(os.environ.get("PILOSA_PROBE_ROWS", 8 << 20))
K = 50
BLOCK = int(os.environ.get("PILOSA_PROBE_BLOCK", 8192))
Q = 64


def main():
    from pilosa_tpu.utils.benchenv import (apply_bench_platform,
                                           timed_fetch,
                                           validated_chain_slope)
    apply_bench_platform()
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    pos = jnp.asarray(rng.integers(0, 4096, P, dtype=np.uint16))
    starts = jnp.asarray(np.linspace(0, P, R + 1).astype(np.int32))
    fw = jnp.asarray(rng.integers(0, 2**32, 128, dtype=np.uint32))
    qpad = jnp.asarray(np.concatenate(
        [np.sort(rng.choice(4096, 48, replace=False)),
         np.full(Q - 48, 0xFFFF)]).astype(np.uint16))
    score0 = jnp.asarray(rng.integers(-1, 60, R, dtype=np.int32))
    dev = jax.devices()[0]

    def chain(stage):
        """stage(salt) -> u32 scalar; chained k times."""
        def impl(k):
            def body(_, carry):
                acc, salt = carry
                out = stage(salt)
                return acc + out, out ^ salt
            acc, _ = jax.lax.fori_loop(
                0, k, body, (jnp.uint32(0), jnp.uint32(1)))
            return acc
        jit = jax.jit(impl, static_argnums=())
        return lambda k: jit(np.int32(k))

    def report(name, stage, nbytes):
        c = chain(stage)
        try:
            r = validated_chain_slope(
                lambda k: timed_fetch(lambda: c(k)), nbytes, dev,
                ks=(2, 6, 12, 20), reps=3)
            per_iter = nbytes / (r["gbps_median"] * 1e9)
            print(f"{name}: {per_iter*1000:.1f} ms/iter "
                  f"(spread {nbytes/(r['gbps_max']*1e9)*1000:.1f}-"
                  f"{nbytes/(r['gbps_min']*1e9)*1000:.1f} ms)", flush=True)
        except RuntimeError as e:
            print(f"{name}: REFUSED ({e})", flush=True)

    # Stage definitions; each consumes the salt so no iteration can be
    # shared, and returns a u32 scalar the next iteration depends on.
    def s_gather(salt):
        p2 = pos + salt.astype(jnp.uint16)  # shifts every position
        posi = (p2 & jnp.uint16(4095)).astype(jnp.int32)
        bits = (jnp.take(fw, posi >> 5, mode="fill", fill_value=0)
                >> (posi & 31).astype(jnp.uint32)) & jnp.uint32(1)
        return bits.sum().astype(jnp.uint32)

    def s_cumsum_rowdiff(salt):
        bits = ((pos + salt.astype(jnp.uint16)) & jnp.uint16(1))\
            .astype(jnp.uint32)
        s = jnp.concatenate(
            [jnp.zeros(1, jnp.uint32), jnp.cumsum(bits, dtype=jnp.uint32)])
        c = s[starts[1:]] - s[starts[:-1]]
        return c.sum().astype(jnp.uint32)

    def s_compare(salt):
        p2 = (pos + salt.astype(jnp.uint16)) & jnp.uint16(4095)
        m = (p2[:, None] == qpad[None, :]).any(axis=1)
        return m.astype(jnp.uint32).sum()

    def s_flat_topk(salt):
        s2 = score0 + salt.astype(jnp.int32)
        v, i = jax.lax.top_k(s2, K)
        return (v.sum() + i.sum()).astype(jnp.uint32)

    def s_two_stage_topk(salt):
        s2 = score0 + salt.astype(jnp.int32)
        nb = R // BLOCK
        sb = s2.reshape(nb, BLOCK)
        v, i = jax.lax.top_k(sb, K)
        base = (jnp.arange(nb, dtype=jnp.int32) * BLOCK)[:, None]
        cand_v = v.reshape(-1)
        cand_i = (i.astype(jnp.int32) + base).reshape(-1)
        gv, gi = jax.lax.top_k(cand_v, K)
        return (gv.sum() + jnp.take(cand_i, gi).sum()).astype(jnp.uint32)

    def s_full_kernel(salt):
        # the production kernel shape: gather bits, cumsum rowdiff,
        # threshold/tanimoto filter, flat top_k
        p2 = (pos + salt.astype(jnp.uint16)) & jnp.uint16(4095)
        posi = p2.astype(jnp.int32)
        bits = (jnp.take(fw, posi >> 5, mode="fill", fill_value=0)
                >> (posi & 31).astype(jnp.uint32)) & jnp.uint32(1)
        s = jnp.concatenate(
            [jnp.zeros(1, jnp.uint32), jnp.cumsum(bits, dtype=jnp.uint32)])
        raw = (starts[1:] - starts[:-1]).astype(jnp.int32)
        c = (s[starts[1:]] - s[starts[:-1]]).astype(jnp.int32)
        keep = c >= 1
        denom = raw + 48 - c
        keep &= (denom > 0) & (c * 100 >= 60 * denom)
        sc = jnp.where(keep, c, -1)
        v, i = jax.lax.top_k(sc, K)
        return (v.sum() + i.sum()).astype(jnp.uint32)

    report("gather_only", s_gather, P * 2)
    report("cumsum_rowdiff", s_cumsum_rowdiff, P * 2)
    report("compare_only", s_compare, P * 2)
    report("flat_topk_8M", s_flat_topk, R * 4)
    report("two_stage_topk_8M", s_two_stage_topk, R * 4)
    report("full_kernel", s_full_kernel, P * 2)


if __name__ == "__main__":
    main()
