# Shared tunnel probe for suite scripts: source this file, then use
# probe / wait_tpu. Single definition so shell gates and the in-leg
# hold (benchenv.probe_device_once — the same probe, called here) can
# never drift in what "tunnel is up" means.
#
# run_tpu_suite_r04b.sh carries an inline copy because it was
# mid-execution when this file was extracted (bash reads scripts
# incrementally — editing a running script corrupts it); new suite
# scripts should `source benches/probe.sh` instead.
probe() {
  timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, detail = probe_device_once(80)
if not ok:
    print(detail, file=sys.stderr)
sys.exit(0 if ok else 1)" 2>/dev/null
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 45
  done
  echo "$(date -u +%H:%M:%S) TPU answered" >&2
}
