"""CPU microbench: repeated-traffic serving throughput, result cache
off vs on (ISSUE 10 acceptance: >=3x on a >=80%-repeat workload).

64 client threads issue single-`Count` PQL queries drawn from a
Zipfian mix over N_ROWS distinct rows through a live PilosaHTTPServer
— the heavy-repetition shape PR 6's workload plane measures
(`coalescer.window_repeat`, cache-opportunity `estSavedS`) and the
generation-keyed result cache (executor/result_cache.py) now acts on.
Phase 1 serves every request with the cache disabled (the
PILOSA_TPU_RESULT_CACHE=0 regime); phase 2 enables it and repeats the
IDENTICAL schedule. Responses are checked byte-identical across
phases per query string; aggregate qps, the observed hit ratio, and
the speedup go to stdout as ONE JSON line (progress chatter on
stderr).

The Zipfian mix (pmf ~ 1/rank^ZIPF_S over N_ROWS rows) concentrates
~half the traffic on a handful of hot queries while keeping a long
tail of colder ones — the cache must win on the hot set while the
tail churns through it, a harsher shape than all-identical. The
schedule is precomputed per thread so both phases replay exactly the
same request sequence; its repeat fraction (1 - distinct/total) is
recorded and asserted >= 0.8.

Clients hold ONE keep-alive connection each (http.client), the shape
a pooled production client presents (see coalescer_bench.py).

Env knobs: RESULT_CACHE_BENCH_THREADS (64),
RESULT_CACHE_BENCH_QUERIES (25 per thread per phase),
RESULT_CACHE_BENCH_ROWS (64 distinct rows),
RESULT_CACHE_BENCH_SHARDS (192), RESULT_CACHE_BENCH_ZIPF_S (1.1).
"""

import http.client
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_THREADS = int(os.environ.get("RESULT_CACHE_BENCH_THREADS", 64))
N_QUERIES = int(os.environ.get("RESULT_CACHE_BENCH_QUERIES", 25))
N_ROWS = int(os.environ.get("RESULT_CACHE_BENCH_ROWS", 64))
N_SHARDS = int(os.environ.get("RESULT_CACHE_BENCH_SHARDS", 192))
ZIPF_S = float(os.environ.get("RESULT_CACHE_BENCH_ZIPF_S", 1.1))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build(tmp):
    """Dense shared bank (~30% density), written straight into
    container storage (the coalescer_bench builder): each Count(Row)
    miss sweeps a [shards, words] row slice wide enough that per-query
    plan+dispatch+device work, not connection churn, is what the cache
    elides."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    h = Holder(tmp)
    h.open()
    idx = h.create_index("b")
    f = idx.create_field("f")
    rng = np.random.default_rng(3)
    view = f.create_view_if_not_exists("standard")
    words_per_row = SHARD_WIDTH // 64
    for shard in range(N_SHARDS):
        frag = view.create_fragment_if_not_exists(shard)
        dense = rng.integers(0, 2**63, N_ROWS * words_per_row,
                             dtype=np.uint64)
        dense &= rng.integers(0, 2**63, N_ROWS * words_per_row,
                              dtype=np.uint64)
        frag.storage.set_dense_range(0, dense)
        for row in range(N_ROWS):
            frag._touch_row(row)
    return h


def zipf_schedule():
    """One fixed Zipfian request schedule per thread (replayed by both
    phases): pmf ~ 1/rank^ZIPF_S over N_ROWS rows."""
    rng = np.random.default_rng(7)
    p = 1.0 / np.arange(1, N_ROWS + 1) ** ZIPF_S
    p /= p.sum()
    sched = [
        [f"Count(Row(f={r}))".encode()
         for r in rng.choice(N_ROWS, size=N_QUERIES, p=p)]
        for _ in range(N_THREADS)
    ]
    total = N_THREADS * N_QUERIES
    distinct = len({q for ts in sched for q in ts})
    return sched, 1.0 - distinct / total


class Client:
    """One keep-alive connection, re-dialed on server-side close."""

    def __init__(self, host, port):
        self.host, self.port = host, port
        self.conn = http.client.HTTPConnection(host, port, timeout=60)

    def post(self, q):
        for attempt in (0, 1):
            try:
                self.conn.request("POST", "/index/b/query", body=q)
                return self.conn.getresponse().read()
            except (http.client.HTTPException, OSError):
                if attempt:
                    raise
                self.conn.close()
                self.conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=60)

    def close(self):
        self.conn.close()


def run_phase(host, port, schedule):
    """N_THREADS keep-alive clients replaying the fixed schedule;
    returns (qps, observed) where observed maps query -> bodies."""
    observed = {}
    obs_lock = threading.Lock()
    errors = []
    barrier = threading.Barrier(N_THREADS + 1)

    def worker(tid):
        local = {}
        client = Client(host, port)
        try:
            barrier.wait()
            for q in schedule[tid]:
                local.setdefault(q, set()).add(client.post(q))
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            client.close()
        with obs_lock:
            for q, bodies in local.items():
                observed.setdefault(q, set()).update(bodies)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return N_THREADS * N_QUERIES / dt, observed


def main():
    import tempfile

    from pilosa_tpu.server import API, serve
    from pilosa_tpu.utils.stats import MemStatsClient

    schedule, repeat_fraction = zipf_schedule()
    assert repeat_fraction >= 0.8, \
        f"workload must be >=80% repeats, got {repeat_fraction:.3f}"
    out = {"metric": "result_cache_serving_speedup", "unit": "x",
           "threads": N_THREADS, "queries_per_thread": N_QUERIES,
           "distinct_rows": N_ROWS, "shards": N_SHARDS,
           "zipf_s": ZIPF_S,
           "repeat_fraction": round(repeat_fraction, 4),
           "platform": "cpu"}
    with tempfile.TemporaryDirectory() as tmp:
        log("bench: building holder")
        h = build(tmp)
        api = API(h, stats=MemStatsClient())
        srv = serve(api, "localhost", 0, background=True)
        host, port = "localhost", srv.server_address[1]
        rc = api.executor.result_cache
        log("bench: warmup (bank upload + compile)")
        rc.enabled = False
        warm = Client(host, port)
        for r in range(N_ROWS):
            warm.post(f"Count(Row(f={r}))".encode())
        warm.close()

        log("bench: phase 1 (cache OFF — the "
            "PILOSA_TPU_RESULT_CACHE=0 regime)")
        off_qps, off_obs = run_phase(host, port, schedule)
        log(f"bench: cache-off {off_qps:.0f} qps")

        rc.enabled = True
        rc.clear()
        log("bench: phase 2 (cache ON)")
        on_qps, on_obs = run_phase(host, port, schedule)
        log(f"bench: cache-on {on_qps:.0f} qps "
            f"({on_qps / off_qps:.2f}x)")

        for q, bodies in on_obs.items():
            merged = bodies | off_obs.get(q, set())
            assert len(merged) == 1, \
                f"responses diverged for {q!r}: {merged}"

        snap = rc.snapshot()
        out.update({
            "value": round(on_qps / off_qps, 2),
            "cache_off_qps": round(off_qps, 1),
            "cache_on_qps": round(on_qps, 1),
            "hit_ratio": round(snap["hitRatio"], 4),
            "hits": snap["hits"],
            "misses": snap["misses"],
            "cache_bytes": snap["bytes"],
            "cache_entries": snap["entries"],
        })
        srv.shutdown()
        srv.server_close()
        h.close()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
