"""Cost of the top-k stage of the PositionsBank kernel at 8M rows:
flat lax.top_k vs two-stage blocked exact top-k vs approx_max_k.
Exactness note: the two-stage form is exact for k<=block top-k — every
global top-k element is in its block's top-k candidates.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = int(os.environ.get("PILOSA_PROBE_ROWS", 8 << 20))
K = 50
BLOCK = int(os.environ.get("PILOSA_PROBE_BLOCK", 8192))


def main():
    from pilosa_tpu.utils.benchenv import apply_bench_platform
    apply_bench_platform()
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    score = jnp.asarray(rng.integers(-1, 60, R, dtype=np.int32))

    def timed(f, *args):
        f_j = jax.jit(f)
        jax.block_until_ready(f_j(*args))
        reps = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f_j(*args))
            reps.append(time.perf_counter() - t0)
        return float(np.median(reps))

    def flat_topk(s):
        return jax.lax.top_k(s, K)

    def two_stage(s):
        nb = R // BLOCK
        sb = s.reshape(nb, BLOCK)
        v, i = jax.lax.top_k(sb, K)              # [nb, K] per block
        base = (jnp.arange(nb, dtype=jnp.int32) * BLOCK)[:, None]
        cand_v = v.reshape(-1)
        cand_i = (i.astype(jnp.int32) + base).reshape(-1)
        gv, gi = jax.lax.top_k(cand_v, K)        # over nb*K candidates
        return gv, jnp.take(cand_i, gi)

    def approx(s):
        return jax.lax.approx_max_k(s.astype(jnp.float32), K)

    t = timed(flat_topk, score)
    print(f"flat_topk: {t*1000:.1f} ms", flush=True)
    t = timed(two_stage, score)
    print(f"two_stage(block={BLOCK}): {t*1000:.1f} ms", flush=True)
    t = timed(approx, score)
    print(f"approx_max_k: {t*1000:.1f} ms", flush=True)

    # equivalence check (values must match exactly; ties may reorder)
    fv, fi = jax.jit(flat_topk)(score)
    tv, ti = jax.jit(two_stage)(score)
    assert np.array_equal(np.asarray(fv), np.asarray(tv)), "top-k values differ"
    print("two_stage values == flat values", flush=True)


if __name__ == "__main__":
    main()
