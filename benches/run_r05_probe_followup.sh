#!/bin/bash
# Third-stage round-5 watcher: after the main followup (startrace/bsi
# batch legs) finishes, run the pbank membership-kernel probe
# (VERDICT r5 #2) at the next tunnel window.
cd /root/repo
while pgrep -f "run_r05_followup.sh" > /dev/null; do sleep 60; done
echo "$(date -u +%H:%M:%S) probe-followup: starting" >&2
for pass in 1 2; do
  [ -e benches/.membership_probe_r05_done ] && break
  timeout 5400 env PILOSA_BENCH_HOLD_FOR_TPU=1 \
      PILOSA_BENCH_HOLD_MAX_S=4500 \
      python benches/pbank_membership_probe.py \
      > benches/membership_probe_r05_tpu.jsonl.tmp \
      2> benches/membership_probe_r05_tpu.err
  rc=$?
  echo "$(date -u +%H:%M:%S) probe-followup: rc=$rc" >&2
  if [ "$rc" -eq 0 ] && grep -q pbank_membership_best \
      benches/membership_probe_r05_tpu.jsonl.tmp; then
    mv benches/membership_probe_r05_tpu.jsonl.tmp \
       benches/membership_probe_r05_tpu.jsonl
    touch benches/.membership_probe_r05_done
  else
    rm -f benches/membership_probe_r05_tpu.jsonl.tmp
  fi
done
echo "$(date -u +%H:%M:%S) probe-followup: done" >&2
