"""Mixed-signature burst bench: heterogeneous megakernel +
RTT-hiding pipelined dispatch vs the PR 10 serving path (ISSUE 11
acceptance: device idle ratio under a 64-thread mixed burst measurably
drops — target <= half — with responses bit-identical under both kill
switches).

Two lanes, each one JSON line on stdout and one record in the JSONL
artifact (progress chatter on stderr):

* ``mixed``: 64 client threads fire single-query PQL drawn from four
  signature families (Count(Row), Row, Count(Intersect),
  Count(Union)) through an in-process QueryCoalescer — the realistic
  mixed flood PR 4's same-signature fusion cannot collapse (one XLA
  launch per distinct shape). The identical schedule replays under
  four configs {megakernel, pipeline} x {off, on}; responses must be
  BYTE-IDENTICAL across all four, and the dispatch-gap analyzer's
  ``pilosa_device_idle_ratio`` is recorded per config (median over
  REPEATS bursts — the enqueue-interval analyzer is scheduler-noisy
  on CPU).

* ``tanimoto``: the BASELINE.json chemical-similarity scenario as a
  *serving-path* top-K: 64 threads issue the Count(Row(fp=c)) /
  Count(Intersect(Row(fp=Q), Row(fp=c))) probes of a Tanimoto top-K
  over molecule fingerprints — a fused AND+popcount flood of exactly
  two signatures that the megakernel runs as single plan-buffer
  launches. The client-side top-K is validated bit-exactly against a
  packed-numpy Tanimoto on the same data.

* ``opt``: the PR 16 cost-based plan optimizer lane — a 64-thread
  shared-subtree burst (every query reuses Intersect/Threshold
  subtrees across requests) replayed with the megakernel forced ON
  under ``PILOSA_TPU_PLAN_OPT`` on vs off. Responses must be
  BYTE-IDENTICAL; the record carries the measured plan-entry and
  plan+slab byte reduction plus the optimizer counters (cse hits,
  folds reordered) that /metrics exports as
  ``pilosa_executor_opt_*_total``.

* ``multichip``: the serving-path lane over an N-device mesh (the
  MULTICHIP dryrun promoted to a first-class record). A fresh BOUNDED
  child — the PR 11 probe_device_once reaper shape: subprocess +
  timeout + stderr tail, because the forced device count latches at
  first jax init — runs the mixed burst against a mesh-sharded
  executor: one SPMD cohort launch per flush, Count lanes psum'd
  in-kernel, rows all-gathered. The record carries mesh q/s, the
  collective-reduce bytes and the profiler-asserted d2h accounting
  (4 bytes per Count — the final answer, ZERO host bytes of per-shard
  partials), with responses byte-identical to PILOSA_TPU_MESH=0.

Env knobs: MEGA_BENCH_THREADS (64), MEGA_BENCH_QUERIES (256 total),
MEGA_BENCH_ROWS (16), MEGA_BENCH_BITS (400000), MEGA_BENCH_REPEATS
(5), MEGA_BENCH_BATCH (16), MEGA_BENCH_MOLECULES (20000),
MEGA_BENCH_CANDIDATES (192), MEGA_BENCH_TOPK (50),
MEGA_BENCH_MESH_DEVICES (8), MEGA_BENCH_MESH_TIMEOUT_S (900).
"""

import json
import os
import statistics
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_THREADS = int(os.environ.get("MEGA_BENCH_THREADS", 64))
N_QUERIES = int(os.environ.get("MEGA_BENCH_QUERIES", 256))
N_ROWS = int(os.environ.get("MEGA_BENCH_ROWS", 16))
N_BITS = int(os.environ.get("MEGA_BENCH_BITS", 400_000))
REPEATS = int(os.environ.get("MEGA_BENCH_REPEATS", 5))
MAX_BATCH = int(os.environ.get("MEGA_BENCH_BATCH", 16))
N_MOLECULES = int(os.environ.get("MEGA_BENCH_MOLECULES", 20_000))
N_CANDIDATES = int(os.environ.get("MEGA_BENCH_CANDIDATES", 192))
TOPK = int(os.environ.get("MEGA_BENCH_TOPK", 50))
MESH_DEVICES = int(os.environ.get("MEGA_BENCH_MESH_DEVICES", 8))
MESH_TIMEOUT_S = float(os.environ.get("MEGA_BENCH_MESH_TIMEOUT_S", 900))
FP_BITS = 4096
BITS_PER_MOL = 48
ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "mega_burst_r01_cpu.jsonl")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def emit(rec):
    line = json.dumps(rec)
    print(line, flush=True)
    with open(ARTIFACT, "a") as fh:
        fh.write(line + "\n")


def burst(co, queries):
    """Fire the queries from N_THREADS client threads (each worker
    submits its slice sequentially — the pooled-client shape); returns
    (responses dict, wall seconds)."""
    n_workers = min(N_THREADS, len(queries))
    results, errors = {}, []
    barrier = threading.Barrier(n_workers + 1)

    def worker(w):
        try:
            barrier.wait()
            for i in range(w, len(queries), n_workers):
                results[i] = co.submit("bench", queries[i])
        except Exception as e:  # noqa: BLE001
            errors.append((w, e))

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_workers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join(timeout=120)
    wall = time.perf_counter() - t0
    assert not errors, errors[:3]
    assert len(results) == len(queries)
    return results, wall


def run_config(ex, queries, mega, pipeline):
    """One measured burst under a (megakernel, pipeline) setting;
    median idle ratio over REPEATS replays."""
    from pilosa_tpu.executor import megakernel as megamod
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient
    from pilosa_tpu.utils.timeline import TIMELINE

    prev = megamod.MEGAKERNEL_ENABLED
    megamod.MEGAKERNEL_ENABLED = mega
    try:
        ratios, walls = [], []
        launches0 = ex.mega_launches
        fused0 = ex.fused_dispatches
        results = None
        for _ in range(REPEATS):
            TIMELINE.reset()
            co = QueryCoalescer(ex, window_s=0.002, max_batch=MAX_BATCH,
                                max_queue=4 * len(queries),
                                stats=MemStatsClient(),
                                pipeline=pipeline)
            co.start()
            try:
                results, wall = burst(co, queries)
            finally:
                co.stop()
            ratios.append(TIMELINE.gap_summary()["idleRatio"])
            walls.append(wall)
        return {
            "idle_ratio": statistics.median(ratios),
            "idle_ratios": [round(r, 4) for r in ratios],
            "qps": len(queries) / statistics.median(walls),
            "mega_launches": ex.mega_launches - launches0,
            "fused_dispatches": ex.fused_dispatches - fused0,
        }, results
    finally:
        megamod.MEGAKERNEL_ENABLED = prev


def lane_mixed():
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    log(f"mega-bench: building mixed-burst holder ({N_BITS} bits, "
        f"{N_ROWS} rows)")
    with tempfile.TemporaryDirectory() as tmp:
        h = Holder(tmp)
        h.open()
        idx = h.create_index("bench")
        f = idx.create_field("f")
        g = idx.create_field("g")
        rng = np.random.default_rng(42)
        rows = rng.integers(0, N_ROWS, N_BITS).astype(np.uint64)
        cols = rng.integers(0, 2 * SHARD_WIDTH, N_BITS).astype(np.uint64)
        f.import_bits(rows, cols)
        g.import_bits(rows[::2], cols[::2])
        idx.add_existence(cols)
        ex = Executor(h)
        # Distinct queries throughout: the result cache and read-dedup
        # would otherwise absorb the very launches under measurement.
        ex.result_cache.enabled = False
        queries = []
        for k in range(N_QUERIES):
            r = k % N_ROWS
            form = (k // N_ROWS) % 4
            queries.append([
                f"Count(Row(f={r}))",
                f"Row(g={r})",
                f"Count(Intersect(Row(f={r}), Row(g={r})))",
                f"Count(Union(Row(f={r}), Row(g={r})))"][form])
        queries = queries[:N_QUERIES]
        # Shuffle the submission order (fixed seed): pooled workers
        # that resolve in one flush submit their next queries together,
        # so any structured order phase-locks flushes onto a single
        # signature family and the megakernel never sees a mixed batch.
        perm = np.random.default_rng(3).permutation(len(queries))
        queries = [queries[int(p)] for p in perm]
        for q in queries:  # warm every compiled variant
            ex.execute_full("bench", q)

        configs = [("baseline", False, False), ("mega", True, False),
                   ("pipeline", False, True), ("mega+pipeline", True, True)]
        stats, shapes = {}, {}
        for name, mega, pipe in configs:
            log(f"mega-bench: config {name}")
            stats[name], shapes[name] = run_config(ex, queries, mega,
                                                   pipe)
        base = shapes["baseline"]
        for name in ("mega", "pipeline", "mega+pipeline"):
            assert shapes[name] == base, \
                f"config {name} responses differ from baseline"
        rec = {
            "bench": "mega_burst_mixed",
            "threads": min(N_THREADS, N_QUERIES),
            "queries": len(queries),
            "signatures": 4,
            "max_batch": MAX_BATCH,
            "repeats": REPEATS,
            "configs": stats,
            "idle_ratio_baseline": stats["baseline"]["idle_ratio"],
            "idle_ratio_mega_pipeline":
                stats["mega+pipeline"]["idle_ratio"],
            "idle_drop_factor": round(
                stats["baseline"]["idle_ratio"]
                / max(1e-9, stats["mega+pipeline"]["idle_ratio"]), 3),
            "bit_identical_all_configs": True,
            "backend": "cpu",
            "note": ("CPU XLA launches cost ~20us, so collapsing them "
                     "trades qps for launch count here; the default is "
                     "therefore PILOSA_TPU_MEGAKERNEL=auto (TPU-only), "
                     "where the 22us-70ms tunnel launch floor is what "
                     "the collapse eliminates (docs/perf.md S11)"),
        }
        emit(rec)
        h.close()


def lane_tanimoto():
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import megakernel as megamod
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient

    log(f"mega-bench: building tanimoto holder ({N_MOLECULES} molecules)")
    rng = np.random.default_rng(11)
    fp = rng.integers(0, FP_BITS, (N_MOLECULES, BITS_PER_MOL))
    rows = np.repeat(np.arange(N_MOLECULES, dtype=np.uint64),
                     BITS_PER_MOL)
    cols = fp.reshape(-1).astype(np.uint64)
    with tempfile.TemporaryDirectory() as tmp:
        h = Holder(tmp)
        h.open()
        idx = h.create_index("bench")
        f = idx.create_field("fp")
        f.import_bits(rows, cols)
        ex = Executor(h)
        ex.result_cache.enabled = False

        q_mol = 12345
        cands = rng.choice(N_MOLECULES, N_CANDIDATES, replace=False)
        cands = [int(c) for c in cands if c != q_mol]
        # The serving-path Tanimoto probe mix: numerator |Q ∧ c| per
        # candidate (fused AND+popcount) + cardinalities |c|, |Q| —
        # exactly two heterogeneous signatures, INTERLEAVED so every
        # coalescer flush carries both (the mixed shape the megakernel
        # collapses; a family-sorted list phase-aligns the worker pool
        # into same-signature flushes the vmap path already handles).
        queries = []
        for c in cands:
            queries.append(
                f"Count(Intersect(Row(fp={q_mol}), Row(fp={c})))")
            queries.append(f"Count(Row(fp={c}))")
        queries.append(f"Count(Row(fp={q_mol}))")
        # Shuffled submission order, un-shuffled on read-back (see
        # lane_mixed: structured orders phase-lock the worker pool
        # into same-signature flushes).
        perm = np.random.default_rng(3).permutation(len(queries))
        shuffled = [queries[int(p)] for p in perm]
        launches0 = ex.mega_launches
        # Force the megakernel ON for this lane (default `auto` is
        # TPU-only): the lane's point is the fused AND+popcount flood
        # running as plan-buffer launches.
        prev_mega = megamod.MEGAKERNEL_ENABLED
        megamod.MEGAKERNEL_ENABLED = True
        co = QueryCoalescer(ex, window_s=0.002, max_batch=MAX_BATCH,
                            max_queue=4 * len(queries),
                            stats=MemStatsClient(), pipeline=True)
        co.start()
        try:
            shuffled_res, wall = burst(co, shuffled)
        finally:
            co.stop()
            megamod.MEGAKERNEL_ENABLED = prev_mega
        results = {int(perm[i]): r for i, r in shuffled_res.items()}
        n = len(cands)
        inter = [results[2 * i]["results"][0] for i in range(n)]
        card = [results[2 * i + 1]["results"][0] for i in range(n)]
        q_card = results[2 * n]["results"][0]
        sims = [(i_qc / (q_card + c - i_qc) if (q_card + c - i_qc) else 0.0)
                for i_qc, c in zip(inter, card)]
        order = sorted(range(n), key=lambda i: (-sims[i], cands[i]))
        got = [(cands[i], round(sims[i], 6)) for i in order[:TOPK]]

        # Exact packed-numpy Tanimoto over the same candidate set.
        packed = np.zeros((N_MOLECULES, FP_BITS // 8), np.uint8)
        mol_idx = np.repeat(np.arange(N_MOLECULES), BITS_PER_MOL)
        flat = fp.reshape(-1)
        np.bitwise_or.at(packed, (mol_idx, flat // 8),
                         (1 << (flat % 8)).astype(np.uint8))
        pop = np.unpackbits(packed, axis=1).sum(axis=1)
        qv = packed[q_mol]
        want = []
        for c in cands:
            i_qc = int(np.unpackbits(packed[c] & qv).sum())
            denom = int(pop[q_mol]) + int(pop[c]) - i_qc
            want.append((c, round(i_qc / denom if denom else 0.0, 6)))
        want = sorted(want, key=lambda t: (-t[1], t[0]))[:TOPK]
        assert got == want, "serving-path Tanimoto top-K != exact numpy"

        emit({
            "bench": "mega_burst_tanimoto_topk",
            "molecules": N_MOLECULES,
            "fp_bits": FP_BITS,
            "candidates": n,
            "topk": TOPK,
            "probe_queries": len(queries),
            "wall_s": round(wall, 4),
            "probes_per_sec": round(len(queries) / wall, 1),
            "mega_launches": ex.mega_launches - launches0,
            "topk_exact_match": True,
            "backend": "cpu",
        })
        h.close()


def lane_opt():
    """Plan-optimizer on/off over a shared-subtree burst: same
    schedule, megakernel forced ON both times, PLAN_OPT toggled.
    Responses byte-identical; plan entries / plan+slab bytes drop."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import megakernel as megamod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient

    log(f"mega-bench: building opt-lane holder ({N_BITS} bits)")
    with tempfile.TemporaryDirectory() as tmp:
        h = Holder(tmp)
        h.open()
        idx = h.create_index("bench")
        f = idx.create_field("f")
        g = idx.create_field("g")
        rng = np.random.default_rng(42)
        rows = rng.integers(0, N_ROWS, N_BITS).astype(np.uint64)
        cols = rng.integers(0, 2 * SHARD_WIDTH,
                            N_BITS).astype(np.uint64)
        f.import_bits(rows, cols)
        g.import_bits(rows[::2], cols[::2])
        idx.add_existence(cols)
        ex = Executor(h)
        ex.result_cache.enabled = False
        # Shared-subtree families: every query around row r reuses the
        # Intersect(Row(f=r), Row(g=r)) subtree (once commuted — the
        # canonicalized fingerprint must still hit), plus a Threshold
        # whose top rung is that same AND. This is the cross-request
        # shape the CSE pass exists for.
        queries = []
        for k in range(N_QUERIES):
            r = k % N_ROWS
            r2 = (r + 1) % N_ROWS
            queries.append([
                f"Count(Intersect(Row(f={r}), Row(g={r})))",
                f"Intersect(Row(g={r}), Row(f={r}))",
                f"Count(Union(Intersect(Row(f={r}), Row(g={r})), "
                f"Row(f={r2})))",
                f"Count(Threshold(Row(f={r}), Row(g={r}), "
                f"Row(f={r2}), k=2))"][(k // N_ROWS) % 4])
        perm = np.random.default_rng(3).permutation(len(queries))
        queries = [queries[int(p)] for p in perm]
        for q in queries:  # warm every compiled variant
            ex.execute_full("bench", q)

        prev_mega = megamod.MEGAKERNEL_ENABLED
        prev_opt = megamod.PLAN_OPT_ENABLED
        megamod.MEGAKERNEL_ENABLED = True
        stats, shapes = {}, {}
        try:
            for name, opt_on in (("opt-off", False), ("opt-on", True)):
                log(f"mega-bench: config {name}")
                megamod.PLAN_OPT_ENABLED = opt_on
                entries0 = ex.mega_plan_entries
                pbytes0 = ex.mega_plan_bytes
                launches0 = ex.mega_launches
                c0 = (ex.opt_cse_hits, ex.opt_entries_eliminated,
                      ex.opt_folds_reordered, ex.opt_bytes_saved)
                walls, results = [], None
                for _ in range(REPEATS):
                    co = QueryCoalescer(
                        ex, window_s=0.002, max_batch=MAX_BATCH,
                        max_queue=4 * len(queries),
                        stats=MemStatsClient(), pipeline=True)
                    co.start()
                    try:
                        results, wall = burst(co, queries)
                    finally:
                        co.stop()
                    walls.append(wall)
                stats[name] = {
                    "qps": len(queries) / statistics.median(walls),
                    "mega_launches": ex.mega_launches - launches0,
                    "plan_entries": ex.mega_plan_entries - entries0,
                    "plan_bytes": ex.mega_plan_bytes - pbytes0,
                    "cse_hits": ex.opt_cse_hits - c0[0],
                    "entries_eliminated":
                        ex.opt_entries_eliminated - c0[1],
                    "folds_reordered": ex.opt_folds_reordered - c0[2],
                    "bytes_saved": ex.opt_bytes_saved - c0[3],
                }
                shapes[name] = results
        finally:
            megamod.MEGAKERNEL_ENABLED = prev_mega
            megamod.PLAN_OPT_ENABLED = prev_opt
        assert shapes["opt-on"] == shapes["opt-off"], \
            "optimizer responses differ from kill-switch path"
        off, on = stats["opt-off"], stats["opt-on"]
        assert on["cse_hits"] > 0, "shared-subtree burst must CSE"
        assert off["cse_hits"] == 0 and off["bytes_saved"] == 0, \
            "kill switch must keep the optimizer fully out"
        emit({
            "bench": "mega_burst_opt",
            "threads": min(N_THREADS, N_QUERIES),
            "queries": len(queries),
            "repeats": REPEATS,
            "configs": stats,
            "plan_entry_reduction": round(
                1 - on["plan_entries"] / max(1, off["plan_entries"]),
                4),
            "plan_byte_reduction": round(
                1 - on["plan_bytes"] / max(1, off["plan_bytes"]), 4),
            "slab_bytes_saved": on["bytes_saved"],
            "bit_identical_opt_on_off": True,
            "backend": "cpu",
        })
        h.close()


def _multichip_child():
    """In-child body of the multichip lane (the parent spawned us with
    the device-count XLA flag — it latches at first jax init, so the
    mesh size can never be set from an already-warm bench process).
    Prints ONE JSON record on stdout."""
    import jax

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import megakernel as megamod
    from pilosa_tpu.ops.bitset import SHARD_WIDTH
    from pilosa_tpu.parallel import MeshContext
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.profile import QueryProfile
    from pilosa_tpu.utils.stats import MemStatsClient

    devs = jax.devices()
    n_mesh = min(MESH_DEVICES, len(devs))
    with tempfile.TemporaryDirectory() as tmp:
        h = Holder(tmp)
        h.open()
        idx = h.create_index("bench")
        f = idx.create_field("f")
        g = idx.create_field("g")
        rng = np.random.default_rng(42)
        rows = rng.integers(0, N_ROWS, N_BITS).astype(np.uint64)
        cols = rng.integers(0, 2 * SHARD_WIDTH, N_BITS).astype(np.uint64)
        f.import_bits(rows, cols)
        g.import_bits(rows[::2], cols[::2])
        idx.add_existence(cols)

        queries = []
        for k in range(N_QUERIES):
            r = k % N_ROWS
            queries.append([
                f"Count(Row(f={r}))",
                f"Row(g={r})",
                f"Count(Intersect(Row(f={r}), Row(g={r})))",
                f"Count(Union(Row(f={r}), Row(g={r})))"][
                    (k // N_ROWS) % 4])
        perm = np.random.default_rng(3).permutation(len(queries))
        queries = [queries[int(p)] for p in perm]

        megamod.MEGAKERNEL_ENABLED = True

        def serving_qps(executor):
            executor.result_cache.enabled = False
            for q in queries[:8]:  # warm the cohort programs
                executor.execute_full("bench", q)
            walls, results = [], None
            for _ in range(REPEATS):
                co = QueryCoalescer(executor, window_s=0.002,
                                    max_batch=MAX_BATCH,
                                    max_queue=4 * len(queries),
                                    stats=MemStatsClient(),
                                    pipeline=True)
                co.start()
                try:
                    results, wall = burst(co, queries)
                finally:
                    co.stop()
                walls.append(wall)
            return len(queries) / statistics.median(walls), results

        mesh_ex = Executor(h, mesh=MeshContext(devs[:n_mesh]))
        mesh_qps, mesh_res = serving_qps(mesh_ex)
        collective = mesh_ex.mesh_collective_bytes
        launches = mesh_ex.mesh_launches
        assert launches > 0, "burst never took the mesh cohort path"

        # Kill-switch twin on the same sharded banks: PILOSA_TPU_MESH=0
        # semantics, byte-identical responses required.
        megamod.MESH_ENABLED = False
        off_qps, off_res = serving_qps(Executor(h, mesh=MeshContext(
            devs[:n_mesh])))
        megamod.MESH_ENABLED = True
        assert mesh_res == off_res, \
            "mesh responses differ from PILOSA_TPU_MESH=0 path"

        # The zero-host-bytes claim on the Count/Sum reduce path: the
        # profiler's d2h accounting must see ONE uint32 (the psum'd
        # final answer) per count lane, never the [S] partial vector.
        count_qs = [("bench", q, None) for q in queries
                    if q.startswith("Count")][:16]
        profs = [QueryProfile("bench", q) for _, q, _ in count_qs]
        out = mesh_ex.execute_batch(count_qs, profiles=profs)
        assert not any(isinstance(r, Exception) for r in out), out[:3]
        d2h = [p.d2h_bytes for p in profs]
        assert all(b == 4 for b in d2h), f"host partials on reduce: {d2h}"

        print(json.dumps({
            "bench": "mega_burst_multichip",
            "mesh_devices": n_mesh,
            "threads": min(N_THREADS, N_QUERIES),
            "queries": len(queries),
            "repeats": REPEATS,
            "mesh_qps": mesh_qps,
            "qps_mesh_off": off_qps,
            "mesh_launches": launches,
            "collective_bytes": collective,
            "d2h_bytes_per_count": 4,
            "bit_identical_mesh_on_off": True,
            "backend": jax.devices()[0].platform,
            "note": ("on forced-host CPU the N 'devices' share one "
                     "socket, so the collective epilogue only adds "
                     "emulation overhead; the lane's subject is the "
                     "record shape + the zero-host-bytes reduce "
                     "assertion, the speedup is the ICI fabric's on "
                     "real chips"),
        }, sort_keys=True), flush=True)
        h.close()


def lane_multichip():
    """Serving-path lane over an N-device mesh: one SPMD cohort launch
    per flush, Count/Sum reduced in-kernel (psum), rows all-gathered.
    Runs in a BOUNDED fresh child — the probe_device_once reaper shape
    (subprocess + timeout + stderr tail) — because the forced device
    count latches at first jax init and a dead backend stalls rather
    than errors."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in env.get(
            "XLA_FLAGS", "") and env["JAX_PLATFORMS"] == "cpu":
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{MESH_DEVICES}").strip()
    log(f"mega-bench: multichip lane in bounded child "
        f"({MESH_DEVICES} devices, timeout {MESH_TIMEOUT_S:.0f}s)")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--multichip-child"],
            timeout=MESH_TIMEOUT_S, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    except subprocess.TimeoutExpired:
        emit({"bench": "mega_burst_multichip", "partial": True,
              "error": f"child timed out after {MESH_TIMEOUT_S:.0f}s"})
        return
    if r.returncode != 0:
        tail = (r.stderr or b"").decode("utf-8", "replace")[-500:]
        emit({"bench": "mega_burst_multichip", "partial": True,
              "error": f"child rc={r.returncode}: {tail}"})
        return
    for line in r.stdout.decode().splitlines():
        line = line.strip()
        if line.startswith("{"):
            emit(json.loads(line))


def main():
    if "--multichip-child" in sys.argv[1:]:
        _multichip_child()
        return
    lanes = sys.argv[1:] or ["mixed", "tanimoto", "opt", "multichip"]
    # A full run regenerates the artifact; a single-lane rerun appends
    # to the committed record set instead of destroying it.
    if not sys.argv[1:] and os.path.exists(ARTIFACT):
        os.remove(ARTIFACT)
    if "mixed" in lanes:
        lane_mixed()
    if "tanimoto" in lanes:
        lane_tanimoto()
    if "opt" in lanes:
        lane_opt()
    if "multichip" in lanes:
        lane_multichip()


if __name__ == "__main__":
    main()
