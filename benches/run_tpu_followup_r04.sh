#!/bin/bash
# Follow-up legs added after the r04b suite launched: waits for the
# main suite (if running) so chain-slope measurements don't time-share
# the chip with flagship legs, then captures the BSI device-time table
# and the andnot retry with the same wait/retry mechanics as r04b.
cd /root/repo
while pgrep -f run_tpu_suite_r04b.sh > /dev/null; do
  echo "$(date -u +%H:%M:%S) waiting for main suite to finish..." >&2
  sleep 120
done
probe() {
  timeout 100 python -c "
from pilosa_tpu.utils.benchenv import probe_device_once
import sys
ok, detail = probe_device_once(80)
if not ok:
    print(detail, file=sys.stderr)
sys.exit(0 if ok else 1)" 2>/dev/null
}
wait_tpu() {
  until probe; do
    echo "$(date -u +%H:%M:%S) waiting for TPU..." >&2
    sleep 45
  done
  echo "$(date -u +%H:%M:%S) TPU answered" >&2
}
run() {
  local name=$1 to=$2; shift 2
  if [ -e "benches/.${name}_r04_done" ]; then
    echo "$(date -u +%H:%M:%S) bench: $name already done, skipping" >&2
    return
  fi
  wait_tpu
  echo "$(date -u +%H:%M:%S) bench: $name" >&2
  timeout "$to" "$@" > "benches/${name}_r04_tpu.jsonl" 2> "benches/${name}_r04_tpu.err"
  local rc=$?
  echo "$(date -u +%H:%M:%S) bench: $name rc=$rc" >&2
  if [ "$rc" -eq 0 ] && [ -s "benches/${name}_r04_tpu.jsonl" ]; then
    touch "benches/.${name}_r04_done"
  fi
}
run bsi_device 1800 python benches/bsi_device.py
run andnot_retry 1200 python benches/andnot_retry.py
# One more pass in case a leg died mid-device.
run bsi_device 1800 python benches/bsi_device.py
run andnot_retry 1200 python benches/andnot_retry.py
